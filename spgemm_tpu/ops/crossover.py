"""Measured exact-vs-MXU crossover gate for hybrid dispatch (ops/spgemm.py).

Round-3 hardware data (benchmarks/ROUND3_NOTES.md): the MXU limb kernel's
best measured rate (7.0 GFLOP/s at (64, 256)) is far below the exact VPU
kernel (~45 GFLOP/s) at every swept shape -- so an exactness proof alone
must not route a round MXU-ward; `--backend hybrid` would then be *slower*
than `--backend pallas` while producing identical bits.  This module is the
missing half of the gate: a one-time micro-measurement of both kernels at
the round's shape, persisted to disk, consulted per round by
_hybrid_setup's choose_numeric.

Policy (SPGEMM_TPU_HYBRID_GATE):
  * "auto"  -- measure once per (kernel config, round shape), cache, route
               to the measured winner.  Default on TPU.
  * "proof" -- route on the exactness proof alone (the pre-round-4
               behavior).  Default off-TPU, where the CPU 'mxu' lowering is
               an XLA oracle whose relative speed says nothing about the
               chip and where tests pin proof-based routing.

The measurement itself doubles as the compile warmup for whichever kernel
wins.  Timing inputs are synthetic random planes: both kernels' wall time
is value-independent (fixed limb grids, fixed fold lengths), so garbage
values time exactly like real ones.
"""

from __future__ import annotations

import json
import logging
import os
import time

from spgemm_tpu.utils import knobs

log = logging.getLogger("spgemm_tpu.crossover")

# In-memory cache keyed by resolved cache-file path: if
# SPGEMM_TPU_CROSSOVER_CACHE changes mid-process (tests, tooling), entries
# from the old path must not leak into, or shadow, the new one.
_CACHE: dict[str, dict] = {}


def entries(prefix: str | None = None) -> dict:
    """Read-only copy of the measured crossover cache, optionally
    filtered by key prefix.  `cli tune --status` lists the `dense-v1:`
    keys here: an autotuner ACCUM_ROUTE trial leg running under the
    "auto" gate policy on-chip measures ladder-vs-dense at every round
    shape the class reaches, and those captures persist into this cache
    exactly like a real job's would -- idle trials pre-pay the
    first-contact measurement cost for live traffic."""
    cache = dict(_load())
    if prefix:
        cache = {k: v for k, v in cache.items() if k.startswith(prefix)}
    return cache


def gate_policy(platform: str | None = None) -> str:
    """'auto' or 'proof' (see module docstring).

    platform None resolves from the live jax backend (a backend touch --
    main thread only).  Host-only callers (the plan-side hybrid split in
    ops/spgemm, planner worker threads) pass the platform they resolved up
    front, keeping this a pure env+string function there."""
    env = knobs.get("SPGEMM_TPU_HYBRID_GATE")
    if env is not None:
        return env
    if platform is None:
        import jax  # noqa: PLC0415

        platform = jax.devices()[0].platform
    return "auto" if platform == "tpu" else "proof"


def _cache_path() -> str:
    root = (knobs.get("SPGEMM_TPU_CROSSOVER_CACHE")
            or os.path.expanduser("~/.cache/jax_bench"))
    os.makedirs(root, exist_ok=True)
    return os.path.join(root, "hybrid_crossover.json")


def _load() -> dict:
    path = _cache_path()
    if path not in _CACHE:
        try:
            with open(path) as f:
                _CACHE[path] = json.load(f)
        except (OSError, ValueError):
            _CACHE[path] = {}
    return _CACHE[path]


def _save() -> None:
    # merge the on-disk state first: concurrent processes (multi-host runs)
    # each measure their own missing keys, and a whole-dict dump would lose
    # the other writers' entries (last-writer-wins); measured-first-wins per
    # key is fine -- any process's measurement is equally valid
    path = _cache_path()
    entries = _CACHE.get(path, {})
    try:
        with open(path) as f:
            on_disk = json.load(f)
    except (OSError, ValueError):
        on_disk = {}
    entries.update({k: v for k, v in on_disk.items() if k not in entries})
    tmp = path + f".tmp{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(entries, f, indent=0, sort_keys=True)
    os.replace(tmp, path)


def _digest(out) -> int:
    """8-byte completion fetch of each output leaf.  This environment's TPU
    tunnel acknowledges block_until_ready at ENQUEUE (benchmarks/
    kernel_sweep.py), so a real D2H scalar read must sit inside the timed
    region or the timer spans dispatch latency, not kernel wall time --
    and the bogus verdict would be persisted by _save."""
    import jax  # noqa: PLC0415
    import jax.numpy as jnp  # noqa: PLC0415

    total = 0
    for leaf in jax.tree.leaves(out):
        total ^= int(jnp.asarray(leaf).ravel()[0])
    return total


def _time_call(fn, args, repeats: int = 2) -> float:
    def once() -> float:
        t0 = time.perf_counter()
        _digest(fn(*args))
        return time.perf_counter() - t0

    once()  # compile + warmup
    return min(once() for _ in range(repeats))


def mxu_wins(numeric_exact, numeric_mxu, *, key: str, k: int, K: int,
             P: int, nnzb: int) -> bool:
    """True iff the MXU kernel measured faster than the exact kernel at this
    round shape.  First call per key measures both (and persists); later
    calls are a dict lookup."""
    cache = _load()
    hit = cache.get(key)
    if hit is None:
        import jax.numpy as jnp  # noqa: PLC0415
        import numpy as np  # noqa: PLC0415

        # round-batched dispatch merges whole fanout classes, so key axes
        # now reach 8192; both kernels' per-key cost is shape-stationary
        # beyond a few thousand keys, so cap the one-time measurement shape
        # while still keying the cache on the true class -- the ranking is
        # what is persisted, and it is K-stable in that regime.
        K = min(K, 4096)
        rng = np.random.default_rng(0)
        plane = rng.integers(0, 1 << 32, size=(nnzb + 1, k, k),
                             dtype=np.int64).astype(np.uint32)
        plane[-1] = 0  # sentinel zero tile, as the engine guarantees
        hi = jnp.asarray(plane)
        lo = jnp.asarray(plane)
        pa = jnp.asarray(rng.integers(0, nnzb, size=(K, P), dtype=np.int32))
        pb = jnp.asarray(rng.integers(0, nnzb, size=(K, P), dtype=np.int32))
        hit = {
            "exact_s": _time_call(numeric_exact, (hi, lo, hi, lo, pa, pb)),
            "mxu_s": _time_call(numeric_mxu, (hi, lo, hi, lo, pa, pb)),
        }
        cache[key] = hit
        _save()
        log.info("crossover %s: exact=%.4fs mxu=%.4fs -> %s", key,
                 hit["exact_s"], hit["mxu_s"],
                 "mxu" if hit["mxu_s"] < hit["exact_s"] else "exact")
    return hit["mxu_s"] < hit["exact_s"]


# Structural dense-route threshold for the "proof" gate policy: with no
# measured crossover available (off-TPU default), the auto accumulator
# route takes the dense stream only where the ladder's padded-MAC ratio
# clears this -- the padding tax is the one cost the structure alone can
# prove, and below ~1.25x the stream fold's per-pair overhead is not
# reliably amortized.
DENSE_RATIO_GATE = 1.25


def dense_wins(numeric_ladder, numeric_dense, *, key: str, k: int, K: int,
               P: int, stream_len: int, nnzb: int = 2048,
               policy: str = "auto", padded_ratio: float = 1.0) -> bool:
    """True iff the dense segmented-fold kernel should replace the ladder
    kernel for a round of this shape (the auto accumulator route's speed
    gate, SPGEMM_TPU_ACCUM_ROUTE) -- the exact analog of mxu_wins: both
    routes produce identical bits, so this is ONLY a wall-clock ranking.

    Under the "auto" policy the first call per key measures both kernels
    at the round's (K, P) / stream shape and persists {"ladder_s",
    "dense_s"} into the shared crossover cache; later calls are a dict
    lookup.  Under "proof" (the off-TPU default, where tests pin
    deterministic routing and a CPU measurement says nothing about the
    chip) the gate is structural: dense wins iff the ladder layout's
    padded-MAC ratio clears DENSE_RATIO_GATE."""
    if policy != "auto":
        return padded_ratio >= DENSE_RATIO_GATE
    cache = _load()
    hit = cache.get(key)
    if hit is None:
        import jax.numpy as jnp  # noqa: PLC0415
        import numpy as np  # noqa: PLC0415

        K = min(K, 4096)
        # multiple of 8, like every real stream (symbolic._stream_pad)
        stream_len = -(-min(stream_len, 4096 * P) // 8) * 8
        rng = np.random.default_rng(0)
        plane = rng.integers(0, 1 << 32, size=(nnzb + 1, k, k),
                             dtype=np.int64).astype(np.uint32)
        plane[-1] = 0  # sentinel zero tile, as the engine guarantees
        hi = jnp.asarray(plane)
        lo = jnp.asarray(plane)
        pa = jnp.asarray(rng.integers(0, nnzb, size=(K, P), dtype=np.int32))
        pb = jnp.asarray(rng.integers(0, nnzb, size=(K, P), dtype=np.int32))
        # the dense leg times the STREAM the ladder round would flatten to
        # (same real-MAC count lives in stream_len; rows cycle the K keys
        # so the accumulator traffic pattern matches a real chunk)
        spa = jnp.asarray(rng.integers(0, nnzb, size=stream_len,
                                       dtype=np.int32))
        spb = jnp.asarray(rng.integers(0, nnzb, size=stream_len,
                                       dtype=np.int32))
        seg = jnp.asarray(np.arange(stream_len, dtype=np.int32) % K)
        zeros = jnp.zeros((K + 1, k, k), jnp.uint32)
        hit = {
            "ladder_s": _time_call(numeric_ladder, (hi, lo, hi, lo, pa, pb)),
            "dense_s": _time_call(numeric_dense,
                                  (hi, lo, hi, lo, spa, spb, seg,
                                   zeros, zeros)),
        }
        cache[key] = hit
        _save()
        log.info("crossover %s: ladder=%.4fs dense=%.4fs -> %s", key,
                 hit["ladder_s"], hit["dense_s"],
                 "dense" if hit["dense_s"] < hit["ladder_s"] else "ladder")
    return hit["dense_s"] < hit["ladder_s"]
