"""uint64 modular arithmetic on TPU via (hi, lo) uint32 limb pairs.

TPUs have no native 64-bit integer multiply (SURVEY.md section 7 "hard parts"),
so every uint64 value is carried as two uint32 planes (hi, lo) and all
arithmetic is synthesized from wrapping uint32 ops, which the VPU supports
natively.  These functions are pure jax.numpy, shape-polymorphic, and work
identically under jit on TPU, on the CPU backend, and inside Pallas kernels.

Semantics implemented: the reference's wrap-then-mod sequence
(sparse_matrix_mult.cu:48,59-61; SURVEY.md section 2.9):

    mulmod(a, b) = ((a*b) mod 2^64) mod (2^64-1)     -- LOW 64 bits of the
                                                        product, then the
                                                        ==MAX -> 0 collapse
    addmod(a, b) = ((a+b) mod 2^64) mod (2^64-1)

For x < 2^64:  x mod (2^64-1) == 0 if x == 2^64-1 else x, so "mod" is an
equality test, never a division.
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

_M16 = 0xFFFF
_M32 = 0xFFFFFFFF
# As a typed scalar: the bare python literal would overflow JAX's default
# int32 canonicalization when mixed with uint32 arrays under jit.
_M32_U32 = np.uint32(_M32)


# ---------------------------------------------------------------------------
# Host-side packing between numpy uint64 and (hi, lo) uint32 planes.
# ---------------------------------------------------------------------------

def u64_to_hilo(x: np.ndarray):
    """Split a numpy uint64 array into (hi, lo) uint32 arrays."""
    x = np.asarray(x, dtype=np.uint64)
    hi = (x >> np.uint64(32)).astype(np.uint32)
    lo = (x & np.uint64(_M32)).astype(np.uint32)
    return hi, lo


def hilo_to_u64(hi, lo) -> np.ndarray:
    """Reassemble numpy uint64 from (hi, lo) uint32 arrays (device or host)."""
    hi = np.asarray(hi, dtype=np.uint64)
    lo = np.asarray(lo, dtype=np.uint64)
    return (hi << np.uint64(32)) | lo


# ---------------------------------------------------------------------------
# Device-side limb arithmetic (wrapping uint32 ops only).
# ---------------------------------------------------------------------------

def mul32_wide(a, b):
    """Exact 32x32 -> 64 bit product of uint32 arrays, as (hi, lo) uint32.

    16-bit limb decomposition; every intermediate provably fits in uint32
    (max value of `mid` is exactly 2^32 - 1), so no partial sum ever wraps.
    """
    al = a & _M16
    ah = a >> 16
    bl = b & _M16
    bh = b >> 16
    ll = al * bl  # <= (2^16-1)^2 < 2^32
    lh = al * bh
    hl = ah * bl
    hh = ah * bh
    mid = lh + (hl & _M16) + (ll >> 16)  # <= 2^32 - 1 exactly: no wrap
    hi = hh + (hl >> 16) + (mid >> 16)
    lo = (mid << 16) | (ll & _M16)
    return hi, lo


def mul64_lo(a_hi, a_lo, b_hi, b_lo):
    """Low 64 bits of the u64 x u64 product -- i.e. (a*b) mod 2^64.

    Mirrors the hardware wraparound the reference's `elem1*elem2` performs
    (sparse_matrix_mult.cu:59): the high 64 bits are discarded, so only
    al*bl (full) and the low halves of the cross terms contribute.
    """
    hi, lo = mul32_wide(a_lo, b_lo)
    hi = hi + a_lo * b_hi + a_hi * b_lo  # wrapping u32: only low 32 of cross terms
    return hi, lo


def add64(a_hi, a_lo, b_hi, b_lo):
    """(a + b) mod 2^64 on (hi, lo) pairs, with carry propagation."""
    lo = a_lo + b_lo
    carry = (lo < a_lo).astype(jnp.uint32)
    hi = a_hi + b_hi + carry
    return hi, lo


def mod_max(hi, lo):
    """x mod (2^64 - 1) for x < 2^64: collapse x == 2^64-1 to 0.

    (hi & lo) == 0xFFFFFFFF iff both words are all-ones -- one op cheaper
    than two compares, and this runs twice per MAC in the hot kernel."""
    is_max = (hi & lo) == _M32_U32
    zero = jnp.zeros_like(hi)
    return jnp.where(is_max, zero, hi), jnp.where(is_max, zero, lo)


def mulmod(a_hi, a_lo, b_hi, b_lo):
    """The reference's product step: ((a*b) mod 2^64) mod (2^64-1)."""
    return mod_max(*mul64_lo(a_hi, a_lo, b_hi, b_lo))


def addmod(a_hi, a_lo, b_hi, b_lo):
    """The reference's accumulate step: ((a+b) mod 2^64) mod (2^64-1).

    NOT associative (SURVEY.md section 2.9): when the u64 sum wraps, the
    result is one less than the clean mod-(2^64-1) sum.  Callers must fold
    terms in the reference's order.
    """
    return mod_max(*add64(a_hi, a_lo, b_hi, b_lo))


def mac(acc_hi, acc_lo, a_hi, a_lo, b_hi, b_lo):
    """acc = addmod(acc, mulmod(a, b)) -- one contraction step."""
    p_hi, p_lo = mulmod(a_hi, a_lo, b_hi, b_lo)
    return addmod(acc_hi, acc_lo, p_hi, p_lo)


def mac_nomod(acc_hi, acc_lo, a_hi, a_lo, b_hi, b_lo):
    """mac with both mod_max collapses elided: 28 vector ops vs 36.

    BIT-EXACT ONLY under a proof obligation (mxu_spgemm.safe_exact_bound):
    every product and every partial sum stays strictly below 2^64 - 1, so
    each `x mod (2^64-1)` is the identity and the wrap-then-mod sequence
    degenerates to plain u64 arithmetic.  This is the same proof that
    licenses the MXU field-mode route in hybrid dispatch -- the dispatcher
    uses this variant for proven rounds the speed gate keeps on the VPU
    (benchmarks/ROOFLINE.md section 1: the MAC op count is the ceiling-
    setting quantity once layouts plateau)."""
    p_hi, p_lo = mul64_lo(a_hi, a_lo, b_hi, b_lo)
    return add64(acc_hi, acc_lo, p_hi, p_lo)


# ---------------------------------------------------------------------------
# Clean ring arithmetic mod (2^64 - 1) -- "field mode".
#
# The reference's wrap-then-mod sequence above is order-dependent, which
# forbids reducing partial products across devices.  Partitioning the
# *contraction* dimension (parallel/innershard.py, the north star's
# "MPI -> psum" mapping) therefore uses clean mod-(2^64-1) arithmetic, which
# is associative and commutative: 2^64 === 1 (mod 2^64-1), so the high word
# of any overflow folds back in as +1.  Results agree with reference mode
# whenever no product or accumulation crosses 2^64 (e.g. values < 2^32);
# they are the mathematically-correct residues everywhere.
# ---------------------------------------------------------------------------

def add64_carry(a_hi, a_lo, b_hi, b_lo):
    """(a + b) exactly, as (carry, hi, lo) -- 65-bit result."""
    lo = a_lo + b_lo
    c_lo = (lo < a_lo).astype(jnp.uint32)
    hi1 = a_hi + b_hi
    c_hi1 = (hi1 < a_hi).astype(jnp.uint32)
    hi = hi1 + c_lo
    c_hi2 = (hi < hi1).astype(jnp.uint32)
    return c_hi1 + c_hi2, hi, lo


def addmod_field(a_hi, a_lo, b_hi, b_lo):
    """(a + b) mod (2^64 - 1) for a, b <= 2^64 - 1.  Associative."""
    carry, hi, lo = add64_carry(a_hi, a_lo, b_hi, b_lo)
    # fold the 2^64 carry back as +1 (2^64 === 1); cannot re-overflow because
    # carry=1 implies the low 64 bits are <= 2^64 - 2
    lo2 = lo + carry
    c2 = (lo2 < lo).astype(jnp.uint32)
    return mod_max(hi + c2, lo2)


def mul64_full(a_hi, a_lo, b_hi, b_lo):
    """Exact 64x64 -> 128 bit product as four uint32 limbs (p3, p2, p1, p0)."""
    h00, l00 = mul32_wide(a_lo, b_lo)
    h01, l01 = mul32_wide(a_lo, b_hi)
    h10, l10 = mul32_wide(a_hi, b_lo)
    h11, l11 = mul32_wide(a_hi, b_hi)

    p0 = l00
    p1 = h00 + l01
    c1a = (p1 < h00).astype(jnp.uint32)
    p1b = p1 + l10
    c1b = (p1b < p1).astype(jnp.uint32)
    carry1 = c1a + c1b

    p2 = h01 + h10
    c2a = (p2 < h01).astype(jnp.uint32)
    p2b = p2 + l11
    c2b = (p2b < p2).astype(jnp.uint32)
    p2c = p2b + carry1
    c2c = (p2c < p2b).astype(jnp.uint32)

    p3 = h11 + c2a + c2b + c2c  # h11 <= 2^32 - 2^17 + 1: cannot wrap
    return p3, p2c, p1b, p0


def mulmod_field(a_hi, a_lo, b_hi, b_lo):
    """(a * b) mod (2^64 - 1), full 128-bit product folded (2^64 === 1)."""
    p3, p2, p1, p0 = mul64_full(a_hi, a_lo, b_hi, b_lo)
    return addmod_field(p3, p2, p1, p0)  # hi64 + lo64 (mod 2^64-1)


def mac_field(acc_hi, acc_lo, a_hi, a_lo, b_hi, b_lo):
    """acc = (acc + a*b) mod (2^64 - 1), clean ring semantics."""
    p_hi, p_lo = mulmod_field(a_hi, a_lo, b_hi, b_lo)
    return addmod_field(acc_hi, acc_lo, p_hi, p_lo)


def operands_below_2_32(*mats) -> bool:
    """True when every operand's values are provably < 2^32 -- the gate that
    licenses mac_field_b32 (duck-typed over .nnzb/.tiles so both host
    BlockSparseMatrix and device wrappers work).  Single-sourced here so the
    ring and inner engines can never diverge on when the b32 route is legal."""
    return all(m.nnzb == 0 or int(np.asarray(m.tiles).max()) < 1 << 32
               for m in mats)


def mac_field_b32(acc_hi, acc_lo, a_lo, b_lo):
    """mac_field for PROVEN a, b < 2^32: ~21 vector ops instead of ~128.

    With both operands below 2^32 the product is a*b <= (2^32-1)^2 =
    2^64 - 2^33 + 1 < 2^64 - 1, so (i) the full 128-bit mul64_full folds
    to a single exact mul32_wide, and (ii) the product's mod-(2^64-1)
    collapse is the identity.  Only the accumulate needs field reduction
    (the accumulator spans the full residue range).  Callers gate on the
    operands' val_bound -- exactly the proof discipline of mac_nomod, but
    for field mode.  The hi operand planes are not even read (callers drop
    those gathers: half the gather traffic)."""
    p_hi, p_lo = mul32_wide(a_lo, b_lo)
    return addmod_field(acc_hi, acc_lo, p_hi, p_lo)
