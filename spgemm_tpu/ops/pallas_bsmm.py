"""Pallas block-sparse matmul for the float/MXU path (models/ffn).

Dense activations x (M, d_in) times a column-major block-sparse weight
(each output block-column owns `rpc` nonzero k x k tiles): the classic
TPU block-sparse matmul -- grid (M tiles, output block-cols, pairs), the
scalar-prefetched `rows` table steering which x block each step reads, a
float32 VMEM scratch accumulator, and `jnp.dot` on the MXU per step.  This is
the Pallas counterpart of models/ffn.bsmm_gather's gather-einsum, with the
gather folded into the pipeline's DMAs (no (M, nbc, rpc, k) materialization).

k = 128 tiles are MXU-native; any multiple of the dtype tile works.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from spgemm_tpu.utils import jaxcompat


def _kernel(rows_ref, x_ref, w_ref, out_ref, acc_ref, *, rpc: int, k: int,
            fuse_gelu: bool, resident: bool):
    """Shared body for the streaming and resident layouts: only the x-slice
    expression differs (full streamed block vs a dynamic k-slice of the
    VMEM-resident panel), so the init/accumulate/epilogue logic -- and with
    it the two kernels' bit-exactness contract -- cannot drift."""
    r = pl.program_id(2)

    @pl.when(r == 0)
    def _init():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    if resident:
        c = pl.program_id(1)
        row = rows_ref[c, r]  # which k-slice of the resident panel
        x_slice = x_ref[:, pl.ds(row * k, k)]
    else:
        x_slice = x_ref[...]
    acc_ref[...] += jnp.dot(
        x_slice, w_ref[0, 0],
        preferred_element_type=jnp.float32)

    @pl.when(r == rpc - 1)
    def _flush():
        acc = acc_ref[...]
        if fuse_gelu:  # epilogue on the f32 accumulator: saves one HBM
            acc = jax.nn.gelu(acc)  # round-trip of h vs a separate gelu op
        out_ref[...] = acc.astype(out_ref.dtype)


@partial(jax.jit, static_argnames=("block_m", "interpret", "fuse_gelu"))
def bsmm_pallas(x, rows, tiles, *, block_m: int = 128, interpret=None,
                fuse_gelu: bool = False):
    """x (M, d_in) @ column-major block-sparse W -> (M, nbc * k).

    rows  : (nbc, rpc) int32 -- nonzero input block-rows per output block-col.
    tiles : (nbc, rpc, k, k) -- weight tiles, same dtype as x.
    M must be a multiple of block_m; d_in a multiple of k.
    fuse_gelu applies gelu to the f32 accumulator in the kernel epilogue
    (activation fusion: h never round-trips HBM in full precision).
    """
    M, d_in = x.shape
    nbc, rpc, k, _ = tiles.shape
    if M % block_m:
        raise ValueError(f"M={M} not a multiple of block_m={block_m}")
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # rows
        grid=(M // block_m, nbc, rpc),
        in_specs=[
            pl.BlockSpec((block_m, k), lambda m, c, r, rows: (m, rows[c, r])),
            pl.BlockSpec((1, 1, k, k), lambda m, c, r, rows: (c, r, 0, 0)),
        ],
        out_specs=pl.BlockSpec((block_m, k), lambda m, c, r, rows: (m, c)),
        scratch_shapes=[pltpu.VMEM((block_m, k), jnp.float32)],
    )
    return pl.pallas_call(
        partial(_kernel, rpc=rpc, k=k, fuse_gelu=fuse_gelu, resident=False),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((M, nbc * k), x.dtype),
        interpret=interpret,
        compiler_params=jaxcompat.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(rows, x, tiles)


# VMEM budget for the resident x panel (bytes).  The chip has ~16 MB of
# VMEM, Pallas DOUBLE-BUFFERS input blocks whose index map varies over the
# grid (the panel changes with m), and the pipeline still needs room for
# weight tiles, the accumulator, and the output block -- so the single-copy
# panel budget is 4 MB (8 MB with its double buffer).
_RESIDENT_PANEL_BUDGET = 4 * 1024 * 1024


@partial(jax.jit, static_argnames=("block_m", "interpret", "fuse_gelu"))
def bsmm_pallas_resident(x, rows, tiles, *, block_m: int = 128,
                         interpret=None, fuse_gelu: bool = False):
    """bsmm_pallas with the x row-panel VMEM-RESIDENT across block-columns.

    The streaming kernel re-DMAs one (block_m, k) x block per (col, pair)
    grid step: x HBM traffic is nbc*rpc*M*k bytes -- the HBM-bound regime
    ROOFLINE_FFN.md section 3 derives (~64 FLOP/byte per step).  Here the
    x BlockSpec is the full (block_m, d_in) panel whose index map depends
    only on m, so Pallas DMAs it ONCE per M-panel and keeps it in VMEM
    while the (c, r) grid sweeps all output columns; the kernel selects
    each pair's k-slice with a dynamic lane-dim slice steered by the
    scalar-prefetched rows table.  x traffic drops to M*d_in bytes --
    nbc*rpc/nb_in times less (12x on BASELINE config 5) -- lifting the
    kernel into the compute-bound regime.  Same contract/bits as
    bsmm_pallas; caller gates on the panel fitting VMEM
    (resident_panel_fits)."""
    M, d_in = x.shape
    nbc, rpc, k, _ = tiles.shape
    if M % block_m:
        raise ValueError(f"M={M} not a multiple of block_m={block_m}")
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # rows
        grid=(M // block_m, nbc, rpc),
        in_specs=[
            # full row-panel; index map ignores (c, r) => one DMA per m
            pl.BlockSpec((block_m, d_in), lambda m, c, r, rows: (m, 0)),
            pl.BlockSpec((1, 1, k, k), lambda m, c, r, rows: (c, r, 0, 0)),
        ],
        out_specs=pl.BlockSpec((block_m, k), lambda m, c, r, rows: (m, c)),
        scratch_shapes=[pltpu.VMEM((block_m, k), jnp.float32)],
    )
    return pl.pallas_call(
        partial(_kernel, rpc=rpc, k=k, fuse_gelu=fuse_gelu, resident=True),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((M, nbc * k), x.dtype),
        interpret=interpret,
        compiler_params=jaxcompat.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
    )(rows, x, tiles)


def resident_panel_fits(d_in: int, block_m: int, dtype_bytes: int = 2,
                        k: int = 128) -> bool:
    """Whether the resident kernel is safe to AUTO-pick: the (block_m, d_in)
    x panel fits the VMEM budget (double-buffering included in the budget
    constant) AND the dynamic lane slice stays 128-lane-aligned on chip
    (k % 128 == 0 -- interpret-mode tests may still force resident=True at
    smaller k).  Callers fall back to the streaming bsmm_pallas otherwise."""
    return (block_m * d_in * dtype_bytes <= _RESIDENT_PANEL_BUDGET
            and k % 128 == 0)


def w2_to_column_major(cols, tiles, nb_out: int):
    """Row-major W2 (each input block-row owns block-cols) -> column-major
    (each output block-col owns block-rows), for the pallas forward path.

    Column fan-in can be ragged; pads with an appended zero tile.  Host-side,
    done once per weight."""
    import numpy as np

    cols_np = np.asarray(cols)
    tiles_np = np.asarray(tiles)
    nbr, cpc, k, _ = tiles_np.shape
    fan = np.zeros(nb_out, np.int64)
    for r in range(nbr):
        for c in cols_np[r]:
            fan[c] += 1
    rpc = max(1, int(fan.max()))
    # index of an all-zero pad tile appended at flat slot nbr*cpc
    flat_tiles = np.concatenate(
        [tiles_np.reshape(nbr * cpc, k, k),
         np.zeros((1, k, k), tiles_np.dtype)], axis=0)
    rows_out = np.zeros((nb_out, rpc), np.int32)       # x block-row to read
    tile_idx = np.full((nb_out, rpc), nbr * cpc, np.int64)  # pad tile default
    fill = np.zeros(nb_out, np.int64)
    for r in range(nbr):
        for ci, c in enumerate(cols_np[r]):
            slot = fill[c]
            rows_out[c, slot] = r
            tile_idx[c, slot] = r * cpc + ci
            fill[c] += 1
    tiles_out = flat_tiles[tile_idx]                   # (nb_out, rpc, k, k)
    return jnp.asarray(rows_out), jnp.asarray(tiles_out)
