"""MXU numeric phase: limb-decomposed integer SpGEMM on the systolic array.

The exact-parity kernels (ops/pallas_spgemm.py, ops/spgemm.py) are VPU-bound:
the reference's wrap-then-mod fold (SURVEY.md section 2.9) is order-dependent,
so it cannot be expressed as a sum and cannot ride the MXU.  Clean
mod-(2^64-1) "field mode" (ops/u64.py) *is* a sum, and this module computes
it where the FLOPs belong on a TPU: the MXU.

Method -- exact integer arithmetic via 7-bit limbs:

  * every uint64 value splits into 10 limbs of 7 bits (int8-safe: 0..127);
  * the full 128-bit products and their sum over a pair list decompose into
    limb-pair convolutions  S[la, lb] = sum_{p, j} A_la[i, j] * B_lb[j, n];
  * ALL 100 limb-pair blocks come from ONE batched int8 matmul by packing
    limbs into the matrix dimensions:  (K, 10k, P*k) @ (K, P*k, 10k) ->
    (K, 10k, 10k) int32 -- MXU-shaped (>= 128 on both output axes at k=32),
    no wasted flops, exact in int32 for P*k <= 2^17 accumulated terms;
  * a VPU epilogue folds S[la, lb] * 2^(7*(la+lb)) into a 128-bit
    accumulator (four uint32 limbs, carry chains) and reduces it
    mod (2^64-1) via 2^64 === 1.

Semantics: associative field mode -- identical to the reference's fold
whenever no intermediate product or partial sum crosses 2^64-1 (the
`safe_exact_bound` predicate below proves this per multiply from host-known
value bounds, enabling the "hybrid" backend: MXU speed with bit-exact
reference parity on real-world value ranges, VPU exact-mode fallback
otherwise).  Cross-device reductions (parallel/innershard.py, parallel/ring.py)
already use field mode for the same associativity reason.

Reference equivalent: matrix_multiplyKernel (sparse_matrix_mult.cu:44-66).

Measured reality on this repo's v5e-lite (single chip, k=32): the batched
limb matmul runs at ~2.5 TOPS, not the ~78 TOPS the same chip reaches on
>= 1280-wide dense int8 matmuls -- per-item overhead of small batched
matmuls (~250 us/item via XLA, ~30 us/dot via a Pallas grid) dominates, and
no packing of 32x32-tile sparse work reaches MXU-efficient shapes without
prohibitive padding.  At 100x limb-pair flops over value flops, the MXU
path lands at ~16 effective GFLOP/s vs ~45 for the VPU exact kernel
(ops/pallas_spgemm.py).  It is kept as a correct, property-tested backend:
on hardware/toolchains where batched int8 matmul is lowered efficiently
(larger k, newer Mosaic), the crossover favors this path, and it is the
only backend whose semantics admit contraction-dimension sharding.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from spgemm_tpu.ops import u64
from spgemm_tpu.ops.symbolic import accept_round_stack

N_LIMBS = 10  # ceil(64 / 7)
_M32 = np.uint32(0xFFFFFFFF)


def limbs7(hi, lo, n_limbs: int = N_LIMBS, dtype=jnp.int8):
    """Split (hi, lo) uint32 planes into n_limbs limbs of 7 bits each.

    Limb l covers bits [7l, 7l+7) of the 64-bit value; limb 9 is 1 bit.
    n_limbs < 10 is valid when every value is < 2^(7*n_limbs) (the dropped
    high planes would be all zero).  dtype is the output cast: int8 for the
    XLA batched matmul here, bf16 (via int32/f32) for the Pallas kernel.
    """
    out = []
    for l in range(n_limbs):
        o = 7 * l
        if o + 7 <= 32:
            v = lo >> o
        elif o < 32:
            v = (lo >> o) | (hi << (32 - o))
        else:
            v = hi >> (o - 32)
        v = (v & np.uint32(0x7F)).astype(jnp.int32)
        if dtype == jnp.bfloat16:
            # u32 -> i32 -> f32 -> bf16: 0..127 is exact at every step
            out.append(v.astype(jnp.float32).astype(jnp.bfloat16))
        else:
            out.append(v.astype(dtype))
    return out


def _add_carry(x, y):
    """u32 wrapping add returning (sum, carry)."""
    s = x + y
    return s, (s < y).astype(jnp.uint32)


def _combine_mod_m(S, k: int):
    """Fold (K, 10k, 10k) int32 limb products into u64 residues mod 2^64-1.

    S[:, la*k + i, lb*k + n] = sum of 7-bit limb products for (la, lb);
    each entry < 127^2 * (P*k) <= 2^31 (asserted by the caller's P*k cap).
    Returns (hi, lo) uint32 of shape (K, k, k).
    """
    K = S.shape[0]
    S6 = S.reshape(K, N_LIMBS, k, N_LIMBS, k).astype(jnp.uint32)

    # group limb pairs by diagonal d = la + lb (same 2^(7d) weight); the
    # group sum can reach 10 * 2^31, so accumulate it as a u32 (hi, lo) pair
    diag_lo = [None] * (2 * N_LIMBS - 1)
    diag_hi = [None] * (2 * N_LIMBS - 1)
    for la in range(N_LIMBS):
        for lb in range(N_LIMBS):
            d = la + lb
            s = S6[:, la, :, lb, :]
            if diag_lo[d] is None:
                diag_lo[d], diag_hi[d] = s, jnp.zeros_like(s)
            else:
                diag_lo[d], c = _add_carry(diag_lo[d], s)
                diag_hi[d] = diag_hi[d] + c

    # accumulate sum_d diag[d] * 2^(7d mod 64) into a 128-bit value
    # (2^64 === 1 mod 2^64-1 folds the weight exponent); each diag value is
    # < 2^35, shifted by < 64, so the total stays far below 2^128
    acc = [None] * 4  # little-endian u32 limbs
    zero = jnp.zeros((K, k, k), jnp.uint32)
    for i in range(4):
        acc[i] = zero
    for d in range(2 * N_LIMBS - 1):
        sh = 7 * d
        if sh >= 64:
            sh -= 64
        q, r = divmod(sh, 32)
        dl, dh = diag_lo[d], diag_hi[d]
        if r == 0:
            parts = [dl, dh]
        else:
            parts = [dl << r,
                     (dl >> (32 - r)) | (dh << r),
                     dh >> (32 - r)]
        for off, p in enumerate(parts):
            i = q + off
            acc[i], c = _add_carry(acc[i], p)
            for j in range(i + 1, 4):  # propagate; carry out of limb 3 is
                acc[j], c = _add_carry(acc[j], c)  # impossible (total < 2^128)

    # 128-bit -> mod (2^64-1): x = hi64 * 2^64 + lo64 === hi64 + lo64
    return u64.addmod_field(acc[3], acc[2], acc[1], acc[0])


@accept_round_stack
@jax.jit
def numeric_round_mxu(a_hi, a_lo, b_hi, b_lo, pa, pb):
    """Same contract as ops.spgemm.numeric_round_impl, field-mode semantics.

    a_*/b_* : (nnzb + 1, k, k) uint32 slabs (sentinel zero tile last).
    pa, pb  : (K, P) int32 slab indices, sentinel-padded (zero tiles
              contribute exactly 0 in field mode too).  A stacked (R, K, P)
              batch of same-shape rounds is also accepted and returns
              (R, K, k, k) (symbolic.accept_round_stack).
    Returns (out_hi, out_lo): (K, k, k) uint32, residues mod 2^64-1.
    """
    K, P = pa.shape
    k = a_hi.shape[-1]
    if P * k > 1 << 17:
        # int32 accumulator bound: 127^2 * P * k < 2^31
        raise ValueError(f"P*k = {P * k} exceeds the int32-exact bound 2^17")

    ah, al = a_hi[pa], a_lo[pa]  # (K, P, k, k)
    bh, bl = b_hi[pb], b_lo[pb]

    # limbs into the matrix dims: A rows (la, i), B cols (lb, n)
    la_planes = limbs7(ah, al)   # 10 x (K, P, k, k)
    lb_planes = limbs7(bh, bl)
    A = jnp.stack(la_planes, axis=0)            # (10, K, P, i, j)
    A = A.transpose(1, 0, 3, 2, 4).reshape(K, N_LIMBS * k, P * k)
    B = jnp.stack(lb_planes, axis=0)            # (10, K, P, j, n)
    B = B.transpose(1, 2, 3, 0, 4).reshape(K, P * k, N_LIMBS * k)

    S = jnp.matmul(A, B, preferred_element_type=jnp.int32)  # (K, 10k, 10k)
    return _combine_mod_m(S, k)


def safe_exact_bound(a_bound: int, b_bound: int, max_fanout: int, k: int):
    """Prove field mode == reference mode for one SpGEMM.

    If every scalar of A is <= a_bound and of B is <= b_bound, each product
    is <= a_bound * b_bound and each output element's full sum is
    <= a_bound * b_bound * max_fanout * k.  When that stays below 2^64 - 1,
    no product wraps, no partial sum wraps, and no mod-collapse fires -- the
    reference's wrap-then-mod fold degenerates to a plain sum, which is
    exactly what field mode computes.  Returns the propagated output bound,
    or None if safety cannot be proven.
    """
    out_bound = a_bound * b_bound * max(max_fanout, 1) * k
    return out_bound if out_bound < (1 << 64) - 1 else None
