"""Pallas TPU kernel for the SpGEMM numeric phase (L1 -- the reference's C7).

The reference's CUDA kernel (matrix_multiplyKernel, sparse_matrix_mult.cu:44-66)
launches one thread block per output tile with k x k threads, each thread
folding its pair list sequentially.  The TPU-native shape of the same work:

  * grid = (key_groups, max_pairs): the pair axis is the innermost grid
    dimension, and TPU grids execute sequentially, so each output tile's
    pairs accumulate in exactly the reference's order (SURVEY.md section 2.9
    -- the arithmetic is non-associative, so this ordering is load-bearing).
  * scalar-prefetched index arrays pa/pb drive the BlockSpec index_maps:
    the pipeline DMAs exactly the (A, B) tile pairs each step needs from HBM
    into VMEM -- the TPU equivalent of the reference's host-side pack+H2D
    staging (sparse_matrix_mult.cu:189-238), with zero host involvement.
  * lane packing: a k x k tile only fills k of the VPU's 128 lanes, so each
    grid step processes a GROUP of G = min(16, 512 // k) output tiles side
    by side in a (k, G*k) accumulator (512 lanes at k = 32) -- wider groups
    amortize per-grid-step overhead, measured ~10% over G = 4.
  * the k x k tile contraction is k unrolled VPU steps of (hi, lo) uint32
    limb arithmetic (ops/u64.py) -- TPUs have no native u64, and the MXU
    cannot do exact wrap-then-mod integer arithmetic, so this is VPU work
    by design (SURVEY.md section 7).
  * the output block revisits the same VMEM buffer across the pair axis
    (accumulator-in-output pattern); it is initialized at pair 0.

Sentinel pairs (padding) index an all-zero tile, contributing exactly 0.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from spgemm_tpu.ops import u64
from spgemm_tpu.ops.symbolic import accept_round_stack
from spgemm_tpu.utils import jaxcompat


def _kernel(pa_ref, pb_ref, *refs, k: int, G: int, algo: str, PB: int = 1,
            no_mod: bool = False):
    # refs layout, pb-major: for pb in range(PB): ah x G; then al, bh, bl
    # blocks in the same order; finally out_hi, out_lo.  PB > 1 folds
    # pair_block consecutive pairs per grid step (pair-axis blocking --
    # amortizes per-step fixed cost over PB pair slots; fold order stays
    # pair-ascending, so SURVEY.md 2.9 ordering is preserved).
    n = G * PB
    all_ah = [r[0] for r in refs[0 * n : 1 * n]]       # each (k, k) uint32
    all_al = [r[0] for r in refs[1 * n : 2 * n]]
    all_bh = [r[0] for r in refs[2 * n : 3 * n]]
    all_bl = [r[0] for r in refs[3 * n : 4 * n]]
    out_hi_ref, out_lo_ref = refs[4 * n], refs[4 * n + 1]

    pair = pl.program_id(1)

    @pl.when(pair == 0)
    def _init():
        out_hi_ref[...] = jnp.zeros_like(out_hi_ref)
        out_lo_ref[...] = jnp.zeros_like(out_lo_ref)

    acc_h = out_hi_ref[0]                              # (k, G*k)
    acc_l = out_lo_ref[0]
    for pb in range(PB):
        ahs = all_ah[pb * G : (pb + 1) * G]
        als = all_al[pb * G : (pb + 1) * G]
        bhs = all_bh[pb * G : (pb + 1) * G]
        bls = all_bl[pb * G : (pb + 1) * G]
        acc_h, acc_l = _fold_pair(acc_h, acc_l, ahs, als, bhs, bls,
                                  k=k, G=G, algo=algo, no_mod=no_mod)

    out_hi_ref[0] = acc_h
    out_lo_ref[0] = acc_l


def _fold_pair(acc_h, acc_l, ahs, als, bhs, bls, *, k: int, G: int, algo: str,
               no_mod: bool = False):
    # no_mod: elide both mod_max collapses per MAC (28 ops vs 36) -- bit-
    # exact ONLY under the safe_exact_bound proof (u64.mac_nomod docstring)
    mac_fn = u64.mac_nomod if no_mod else u64.mac
    mul_fn = u64.mul64_lo if no_mod else u64.mulmod
    add_fn = u64.add64 if no_mod else u64.addmod
    if algo == "colbcast":
        # B rows pack once per step: group tiles side by side along lanes.
        bh_cat = jnp.concatenate(bhs, axis=1)          # (k, G*k)
        bl_cat = jnp.concatenate(bls, axis=1)

        # The reference's j-loop (sparse_matrix_mult.cu:56-62), unrolled (k
        # is static): fold the outer product of A's column j with B's row j.
        for j in range(k):
            a_h = jnp.concatenate(
                [jnp.broadcast_to(t[:, j : j + 1], (k, k)) for t in ahs], axis=1)
            a_l = jnp.concatenate(
                [jnp.broadcast_to(t[:, j : j + 1], (k, k)) for t in als], axis=1)
            b_h = jnp.broadcast_to(bh_cat[j : j + 1, :], (k, G * k))
            b_l = jnp.broadcast_to(bl_cat[j : j + 1, :], (k, G * k))
            acc_h, acc_l = mac_fn(acc_h, acc_l, a_h, a_l, b_h, b_l)
    elif algo == "vecj":
        # Vectorized-j layout: compute a BLOCK of j's products at once in a
        # ((j, i) sublanes, (g, n) lanes) arrangement, then fold the j axis
        # with cheap sublane slices.  The colbcast variant runs 2*G*k
        # lane-extract+broadcast ops per step (A's column j per key per
        # plane) -- the dominant instruction count; here A is transposed
        # once per tile and every per-j access is a sublane slice.  The j
        # axis is chunked (JB) so the six (JB*k, G*k) uint32 intermediates
        # plus mulmod's limb temporaries stay well under VMEM (~3 MB at
        # k=32, G=16, JB=8, vs ~12+ MB unchunked).  The mod fold stays
        # sequential over j (SURVEY.md 2.9).
        # (JB*k, G*k) uint32 <= 512 KB per intermediate
        JB = max(1, min(k, 131072 // (k * G * k)))
        ats_h = [t.T for t in ahs]                     # (j, i), once per tile
        ats_l = [t.T for t in als]

        def expand_a(at, j0):
            c = at[j0:j0 + JB]                         # (JB, i) sublane slice
            return jnp.broadcast_to(c[:, :, None], (JB, k, k)).reshape(JB * k, k)

        def expand_b(t, j0):
            c = t[j0:j0 + JB]                          # (JB, n) sublane slice
            return jnp.broadcast_to(c[:, None, :], (JB, k, k)).reshape(JB * k, k)

        for j0 in range(0, k, JB):
            a_h = jnp.concatenate([expand_a(t, j0) for t in ats_h], axis=1)
            a_l = jnp.concatenate([expand_a(t, j0) for t in ats_l], axis=1)
            b_h = jnp.concatenate([expand_b(t, j0) for t in bhs], axis=1)
            b_l = jnp.concatenate([expand_b(t, j0) for t in bls], axis=1)
            prod_h, prod_l = mul_fn(a_h, a_l, b_h, b_l)  # (JB*k, G*k)
            for jj in range(min(JB, k - j0)):
                acc_h, acc_l = add_fn(
                    acc_h, acc_l,
                    prod_h[jj * k:(jj + 1) * k, :], prod_l[jj * k:(jj + 1) * k, :])
    else:
        raise ValueError(f"unknown algo {algo!r}")
    return acc_h, acc_l


def validate_vpu_config(algo: str, pair_block: int, *, platform: str,
                        interpret: bool = False) -> None:
    """Reject knob combinations that are known-broken BEFORE they reach
    Mosaic.

    SPGEMM_TPU_VPU_ALGO=vecj and SPGEMM_TPU_VPU_PB>1 die on TPU hardware
    with a bare JaxRuntimeError at default-adjacent shapes (RESULTS.md
    kernel-variant rows; round-5 VERDICT "What's weak" #2) -- an advertised
    whole-engine A/B hook must fail with the knob named, not a Mosaic
    stack trace.  Both remain available in interpret mode, where the
    parity tests exercise them.
    """
    if algo not in ("colbcast", "vecj"):
        raise ValueError(
            f"unknown VPU algo {algo!r} (SPGEMM_TPU_VPU_ALGO): valid values "
            "are 'colbcast' and 'vecj'")
    if pair_block < 1:
        raise ValueError(
            f"SPGEMM_TPU_VPU_PB must be >= 1, got {pair_block}")
    if platform == "tpu" and not interpret:
        if algo == "vecj":
            raise ValueError(
                "SPGEMM_TPU_VPU_ALGO=vecj is not supported on TPU hardware "
                "(Mosaic miscompiles it to a JaxRuntimeError at "
                "default-adjacent shapes; RESULTS.md kernel-variant rows) "
                "-- use the default 'colbcast', or interpret mode for "
                "testing")
        if pair_block > 1:
            raise ValueError(
                f"SPGEMM_TPU_VPU_PB={pair_block} is not supported on TPU "
                "hardware (pair-axis blocking > 1 crashes in Mosaic at "
                "default-adjacent shapes; RESULTS.md kernel-variant rows) "
                "-- use the default 1, or interpret mode for testing")


def resolve_group(k: int, K: int, group: int | None = None) -> int:
    """The key-group width G the kernel will actually run.

    Default 16, bounded by 512 accumulator lanes (1024 for an explicit
    override) and by K.  Exposed so benchmark labels report the RESOLVED
    width, not the requested one (they differ when lane caps clamp)."""
    lane_cap = 1024 if group else 512
    return max(1, min(group or 16, lane_cap // k, K))


@accept_round_stack
@partial(jax.jit, static_argnames=("interpret", "algo", "group", "pair_block",
                                   "no_mod"))
def numeric_round_pallas(a_hi, a_lo, b_hi, b_lo, pa, pb, interpret=None,
                         algo: str = "colbcast", group: int | None = None,
                         pair_block: int = 1, no_mod: bool = False):
    """Same contract as ops.spgemm.numeric_round_impl, as a Pallas kernel.

    a_*/b_* : (nnzb + 1, k, k) uint32 slabs (sentinel zero tile last).
    pa, pb  : (K, P) int32 slab indices, per-key j-ascending, sentinel-padded.
    group   : override the key-group width G (benchmarks/kernel_sweep.py
              measures the ladder; default below is the tuned value).
    pair_block : pairs folded per grid step (PB).  PB > 1 shrinks the grid's
              pair axis PB-fold, amortizing per-step fixed cost, at the price
              of 4*G*PB input refs per step.  Sentinel padding of the pair
              axis keeps results exact; fold order stays pair-ascending.
    no_mod  : elide the mod_max collapses (u64.mac_nomod; 28 vs 36 ops per
              MAC) -- callers must hold the safe_exact_bound proof, exactly
              as for the MXU field-mode route (hybrid dispatch supplies it).
    Returns (out_hi, out_lo): (K, k, k) uint32.

    A stacked (R, K, P) pa/pb is also accepted and returns (R, K, k, k)
    (symbolic.accept_round_stack -- round-batched dispatch).
    """
    k = a_hi.shape[-1]
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"
    validate_vpu_config(algo, pair_block,
                        platform=jax.devices()[0].platform,
                        interpret=bool(interpret))
    K, P = pa.shape

    # group width: wider groups amortize per-grid-step overhead (~10% win
    # from G=4 to G=16 at k=32, measured); bounded by the accumulator lane
    # cap and 4*G input refs per step
    G = resolve_group(k, K, group)
    PB = max(1, min(int(pair_block), P))
    K_pad = -(-K // G) * G      # key axis: whole groups
    P_pad = -(-P // PB) * PB    # pair axis: whole pair blocks
    if (K_pad, P_pad) != (K, P):
        widths = ((0, K_pad - K), (0, P_pad - P))
        pa = jnp.pad(pa, widths, constant_values=a_hi.shape[0] - 1)
        pb = jnp.pad(pb, widths, constant_values=b_hi.shape[0] - 1)
    KG = K_pad // G

    # Prefetch arrays are SMEM-resident, lane-padded to 128 in the last
    # dimension and sublane-padded to 8 in the first: ship whichever
    # orientation has the smaller footprint (normally (P, K) -- the long key
    # axis rides the lane padding; for huge fanout classes P > K the
    # untransposed (K, P) wins).
    def pad8(x):
        return -(-x // 8) * 8

    transpose = pad8(P_pad) * max(K_pad, 128) <= pad8(K_pad) * max(P_pad, 128)
    if transpose:
        pa_t, pb_t = pa.T, pb.T

        def a_map(g, pbi):
            return lambda kg, p, pa, pb: (pa[p * PB + pbi, kg * G + g], 0, 0)

        def b_map(g, pbi):
            return lambda kg, p, pa, pb: (pb[p * PB + pbi, kg * G + g], 0, 0)
    else:
        pa_t, pb_t = pa, pb

        def a_map(g, pbi):
            return lambda kg, p, pa, pb: (pa[kg * G + g, p * PB + pbi], 0, 0)

        def b_map(g, pbi):
            return lambda kg, p, pa, pb: (pb[kg * G + g, p * PB + pbi], 0, 0)

    # pb-major ref order -- the kernel slices G-wide runs per pair slot
    tile_spec_a = [pl.BlockSpec((1, k, k), a_map(g, pbi))
                   for pbi in range(PB) for g in range(G)]
    tile_spec_b = [pl.BlockSpec((1, k, k), b_map(g, pbi))
                   for pbi in range(PB) for g in range(G)]
    out_spec = pl.BlockSpec((1, k, G * k), lambda kg, p, pa, pb: (kg, 0, 0))

    n = G * PB
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # pa, pb
        grid=(KG, P_pad // PB),
        in_specs=tile_spec_a + tile_spec_a + tile_spec_b + tile_spec_b,
        out_specs=[out_spec, out_spec],
    )
    out_shape = [
        jax.ShapeDtypeStruct((KG, k, G * k), jnp.uint32),
        jax.ShapeDtypeStruct((KG, k, G * k), jnp.uint32),
    ]
    packed_hi, packed_lo = pl.pallas_call(
        partial(_kernel, k=k, G=G, algo=algo, PB=PB, no_mod=no_mod),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        compiler_params=jaxcompat.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),  # sequential: order matters
        ),
    )(pa_t, pb_t,
      *([a_hi] * n), *([a_lo] * n), *([b_hi] * n), *([b_lo] * n))

    def unpack(x):
        # (KG, ty, g*k+tx) -> (K, ty, tx)
        return (x.reshape(KG, k, G, k)
                 .transpose(0, 2, 1, 3)
                 .reshape(K_pad, k, k)[:K])

    return unpack(packed_hi), unpack(packed_lo)
