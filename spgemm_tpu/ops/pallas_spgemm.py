"""Pallas TPU kernel for the SpGEMM numeric phase (L1 -- the reference's C7).

The reference's CUDA kernel (matrix_multiplyKernel, sparse_matrix_mult.cu:44-66)
launches one thread block per output tile with k x k threads, each thread
folding its pair list sequentially.  The TPU-native shape of the same work:

  * grid = (num_keys, max_pairs): the pair axis is the innermost grid
    dimension, and TPU grids execute sequentially, so each output tile's
    pairs accumulate in exactly the reference's order (SURVEY.md section 2.9
    -- the arithmetic is non-associative, so this ordering is load-bearing).
  * scalar-prefetched index arrays pa/pb drive the BlockSpec index_maps:
    the pipeline DMAs exactly the (A, B) tile pair each step needs from HBM
    into VMEM -- the TPU equivalent of the reference's host-side pack+H2D
    staging (sparse_matrix_mult.cu:189-238), with zero host involvement.
  * the k x k tile contraction is k unrolled VPU steps of (hi, lo) uint32
    limb arithmetic (ops/u64.py) -- TPUs have no native u64, and the MXU
    cannot do exact wrap-then-mod integer arithmetic, so this is VPU work
    by design (SURVEY.md section 7).
  * the output block revisits the same VMEM buffer across the pair axis
    (accumulator-in-output pattern); it is initialized at pair 0.

Sentinel pairs (padding) index an all-zero tile, contributing exactly 0.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from spgemm_tpu.ops import u64


def _kernel(pa_ref, pb_ref, a_hi_ref, a_lo_ref, b_hi_ref, b_lo_ref,
            out_hi_ref, out_lo_ref, *, k: int):
    pair = pl.program_id(1)

    @pl.when(pair == 0)
    def _init():
        out_hi_ref[...] = jnp.zeros_like(out_hi_ref)
        out_lo_ref[...] = jnp.zeros_like(out_lo_ref)

    ah = a_hi_ref[0]  # (k, k) uint32
    al = a_lo_ref[0]
    bh = b_hi_ref[0]
    bl = b_lo_ref[0]
    acc_h = out_hi_ref[0]
    acc_l = out_lo_ref[0]

    # The reference's j-loop (sparse_matrix_mult.cu:56-62), unrolled (k is
    # static): fold the outer product of A's column j with B's row j.
    for j in range(k):
        acc_h, acc_l = u64.mac(
            acc_h, acc_l,
            ah[:, j : j + 1], al[:, j : j + 1],
            bh[j : j + 1, :], bl[j : j + 1, :],
        )

    out_hi_ref[0] = acc_h
    out_lo_ref[0] = acc_l


@partial(jax.jit, static_argnames=("interpret",))
def numeric_round_pallas(a_hi, a_lo, b_hi, b_lo, pa, pb, interpret=None):
    """Same contract as ops.spgemm.numeric_round_impl, as a Pallas kernel.

    a_*/b_* : (nnzb + 1, k, k) uint32 slabs (sentinel zero tile last).
    pa, pb  : (K, P) int32 slab indices, per-key j-ascending, sentinel-padded.
    Returns (out_hi, out_lo): (K, k, k) uint32.
    """
    K, P = pa.shape
    k = a_hi.shape[-1]
    if interpret is None:
        interpret = jax.devices()[0].platform == "cpu"

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,  # pa, pb
        grid=(K, P),
        in_specs=[
            pl.BlockSpec((1, k, k), lambda ki, pi, pa, pb: (pa[ki, pi], 0, 0)),
            pl.BlockSpec((1, k, k), lambda ki, pi, pa, pb: (pa[ki, pi], 0, 0)),
            pl.BlockSpec((1, k, k), lambda ki, pi, pa, pb: (pb[ki, pi], 0, 0)),
            pl.BlockSpec((1, k, k), lambda ki, pi, pa, pb: (pb[ki, pi], 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, k, k), lambda ki, pi, pa, pb: (ki, 0, 0)),
            pl.BlockSpec((1, k, k), lambda ki, pi, pa, pb: (ki, 0, 0)),
        ],
    )
    out_shape = [
        jax.ShapeDtypeStruct((K, k, k), jnp.uint32),
        jax.ShapeDtypeStruct((K, k, k), jnp.uint32),
    ]
    out_hi, out_lo = pl.pallas_call(
        partial(_kernel, k=k),
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary"),  # sequential: order matters
        ),
    )(pa, pb, a_hi, a_lo, b_hi, b_lo)
    return out_hi, out_lo
