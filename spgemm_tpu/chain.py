"""Chain scheduler (L3): order-preserving pairwise reduction of a matrix chain.

The reference's helper2() (sparse_matrix_mult.cu:287-327) halves the array each
pass, multiplying adjacent pairs left-to-right and carrying the odd trailing
element; correctness for the non-commutative product relies only on preserving
left-to-right adjacency, but because the arithmetic is also non-*associative*
(SURVEY.md section 2.9), parity requires this exact reduction tree, not just
any ordered fold.

Dispatch is a plain Python loop: each multiply is a jitted device program, so
host-side control flow costs nothing by comparison (SURVEY.md C11).
"""

from __future__ import annotations

import logging
import queue as queue_mod
import threading

from spgemm_tpu.utils import knobs
from spgemm_tpu.utils.backend_probe import host_only
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils.timers import ENGINE

log = logging.getLogger("spgemm_tpu.chain")


def _to_host(m):
    return m.to_host() if hasattr(m, "to_host") else m


class _PlanAheadWorker:
    """Bounded host planner worker for one reduction pass.

    All pairs of a pass are independent, so while the device executes pair
    i the worker plans pairs i+1..i+ahead (SPGEMM_TPU_PLAN_AHEAD, default
    2) -- the OOC pipeline's worker discipline applied to the planner.
    Plans are consumed strictly in pair order; the semaphore bounds the
    unconsumed-plan backlog (each plan holds padded index arrays on host
    RAM).  The worker must never touch a backend (the BKD contract --
    utils/backend_probe.host_only): the caller resolves backend/platform
    on the main thread and the worker plans pure numpy from there.
    """

    def __init__(self, pairs, planner, ahead: int):
        self._outq: queue_mod.Queue = queue_mod.Queue()
        self._stop = threading.Event()
        self._sem = threading.Semaphore(ahead)
        # captured on the CALLING thread: the worker's plan phases, cache
        # counters, and spans must attribute to the job that spawned it
        # (per-job PhaseScope + flight-recorder tags), not to the worker
        # thread's anonymous context
        self._attr = ENGINE.attribution()
        self._thread = threading.Thread(
            target=self._work, args=(list(pairs), planner),
            name="chain-planner", daemon=True)
        self._thread.start()

    @host_only
    def _work(self, pairs, planner):
        try:
            with ENGINE.attributed(self._attr):
                for i, (a, b) in enumerate(pairs):
                    while not self._sem.acquire(timeout=0.2):
                        if self._stop.is_set():
                            return
                    if self._stop.is_set():
                        return
                    self._outq.put((i, planner(a, b), None))
                    pairs[i] = None  # drop operand refs as soon as planned
        except Exception as e:  # noqa: BLE001 -- re-raised on the consumer
            self._outq.put((None, None, e))

    def get(self):
        """Next pair's plan, in order; re-raises a worker failure.  The
        blocking span is the pipeline's honest 'planner was late' cost --
        the caller times it as plan_wait."""
        with ENGINE.phase("plan_wait"):
            i, plan, err = self._outq.get()
        self._sem.release()
        if err is not None:
            raise err
        return i, plan

    def close(self):
        """Shut the worker down and wait for it (also on a mid-pass
        failure: a planner left running would pin the pass's operands,
        compete with a failover retry for CPU, and bleed its plan phase /
        cache counters into ENGINE mid-retry).  The worker notices the
        stop flag within 0.2 s unless inside planner() -- the bounded
        join covers one in-flight plan (host numpy, ms-scale); the
        daemon flag keeps a pathological plan from pinning exit."""
        self._stop.set()
        self._thread.join(timeout=30.0)


def _plan_ahead_depth() -> int:
    """SPGEMM_TPU_PLAN_AHEAD (default 2): 0 = legacy inline planning."""
    return knobs.get("SPGEMM_TPU_PLAN_AHEAD")


def _make_planner(multiply, kwargs):
    """A (a, b) -> SpgemmPlan closure for the plan-ahead worker, or None
    when the pipeline does not apply: planning only exists for the
    plan/execute-split engine (ops.spgemm.spgemm_device), and the
    backend/platform must resolve on the MAIN thread (the one allowed to
    touch -- and hang on -- a backend) before any worker starts."""
    from spgemm_tpu.ops import spgemm as spgemm_mod  # noqa: PLC0415

    if multiply is not spgemm_mod.spgemm_device:
        return None
    import jax  # noqa: PLC0415

    platform = jax.devices()[0].platform
    backend = spgemm_mod.resolve_backend(kwargs.get("backend"))
    round_size = kwargs.get("round_size")

    def planner(a, b):
        p = spgemm_mod.plan(a, b, round_size=round_size,
                            backend=backend, platform=platform)
        # an estimator-routed plan (ops/estimate) returns fast with the
        # exact symbolic join deferred: complete it HERE, on the worker
        # thread, so the join's cost overlaps device execution instead of
        # landing on the dispatch critical path (host-pure numpy -- the
        # @host_only contract holds)
        p.ensure_exact()
        # delta planning rides the same worker: the per-tile-row content
        # digests (ops/delta -- the diff's hash cost on host-reachable
        # operands) are memoized on the operand objects here, so the
        # dispatch-side diff is a lookup, not a hash pass (hashlib+numpy,
        # host-pure like the rest of the planner)
        from spgemm_tpu.ops import delta  # noqa: PLC0415
        if delta.enabled():
            delta.stash_digests(a)
            delta.stash_digests(b)
        return p

    return planner


def oracle_multiply(a: BlockSparseMatrix, b: BlockSparseMatrix,
                    **_ignored) -> BlockSparseMatrix:
    """Host-only multiply with reference semantics (utils/semantics oracle).

    The failover path: needs no accelerator, no XLA backend -- survives a
    dead device.  Slow; correctness over speed by construction.
    """
    from spgemm_tpu.utils.semantics import spgemm_oracle  # noqa: PLC0415

    a, b = _to_host(a), _to_host(b)
    return BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))


def chain_product(matrices: list[BlockSparseMatrix], multiply=None,
                  checkpoint_dir: str | None = None, resume: bool = True,
                  keep_device: bool = False, failover: bool = False,
                  heartbeat=None, **kwargs) -> BlockSparseMatrix:
    """Reduce [M1, ..., MN] to M1 x M2 x ... x MN with helper2's pairing.

    multiply: binary op (defaults to ops.spgemm.spgemm_device, which keeps
    every partial product in HBM -- tile data crosses the host boundary only
    at the final result, or never with keep_device=True); kwargs forwarded.
    checkpoint_dir: if set, snapshot the surviving partials after each pass
    (utils/checkpoint.py) and resume from the newest snapshot on restart.
    failover: failure detection + recovery (SURVEY.md section 5.3; the
    reference has none -- any rank failure kills the MPI job).  If a
    multiply raises (device/tunnel death mid-chain), restart the current
    pass from the newest checkpoint -- or from the last completed pass's
    host copies -- on the host-only oracle, which needs no device at all.
    heartbeat: optional zero-arg progress callback invoked after every
    completed multiply -- the serving daemon's liveness signal (its
    watchdog must tell a slow-but-progressing job from an executor wedged
    inside a hung backend call, which never raises).  Must be cheap; must
    not raise Exception, but MAY raise a BaseException-derived abort
    signal (serve.queue.JobAbandoned) to stop an abandoned chain at a
    multiply boundary -- BaseException so it deliberately pierces the
    failover catch below, which must not mistake an abort for device
    loss.  Never forwarded to multiply.
    """
    if multiply is None:
        from spgemm_tpu.ops.spgemm import spgemm_device as multiply  # noqa: PLC0415
    if not matrices:
        raise ValueError("empty chain")
    arr = list(matrices)
    pass_idx = 0
    if checkpoint_dir and resume:
        from spgemm_tpu.utils import checkpoint  # noqa: PLC0415
        found = checkpoint.latest_pass(checkpoint_dir)
        if found is not None:
            pass_idx, arr = found
            log.info("resumed from checkpoint pass %d (%d partials)",
                     pass_idx, len(arr))
    # Host-side copies of the current pass input: the failover restart point
    # (device partials are unfetchable once the device is gone, so copies
    # must be taken while it is alive -- inside the try, one D2H per pass,
    # shared with the checkpoint writer and the final return).
    need_host = failover or bool(checkpoint_dir)
    arr_host = [_to_host(m) for m in arr] if failover else None
    # plan-ahead pipeline (read the knob once up front so an invalid value
    # raises before any multiply): a bounded host planner worker plans pair
    # i+1..i+ahead while the device executes pair i.  0 = legacy inline
    # planning -- bit-identical either way (planning is deterministic and
    # dispatch order is unchanged), so the knob is a whole-engine A/B.
    ahead = _plan_ahead_depth()
    while len(arr) > 1:
        try:
            nxt = []
            odd_carry = arr[-1] if len(arr) % 2 == 1 else None
            pairs = [(arr[i], arr[i + 1]) for i in range(0, len(arr) - 1, 2)]
            planner = _make_planner(multiply, kwargs) \
                if ahead > 0 and len(pairs) > 1 else None
            worker = _PlanAheadWorker(pairs, planner, ahead) \
                if planner is not None else None
            try:
                for p, (ma, mb) in enumerate(pairs):
                    i = 2 * p
                    # the reference's :301 progress line -- printed
                    # unconditionally to stdout, as sparse_matrix_mult.cu does
                    print(f"multiplying {i} {i + 1}", flush=True)
                    if worker is not None:
                        got, pln = worker.get()
                        assert got == p  # the worker plans strictly in order
                        nxt.append(multiply(ma, mb, plan=pln, **kwargs))
                    else:
                        nxt.append(multiply(ma, mb, **kwargs))
                    if heartbeat is not None:
                        heartbeat()
                    # drop consumed partials so their HBM frees as soon as
                    # the dependent computations drain (pass >= 1 operands
                    # are device-resident and otherwise pinned for the whole
                    # pass; failover restarts from arr_host, never these)
                    arr[i] = arr[i + 1] = None
                    pairs[p] = None
            finally:
                if worker is not None:
                    worker.close()
            if odd_carry is not None:
                nxt.append(odd_carry)  # odd element carried (:315-321)
            nxt_host = [_to_host(m) for m in nxt] if need_host else None
        except Exception as e:  # noqa: BLE001 -- device loss is the use case
            if not failover or multiply is oracle_multiply:
                raise
            # arr_host snapshots the exact input of the failed pass (within
            # a run it equals the newest checkpoint, and unlike the on-disk
            # dir it cannot belong to a previous unrelated run)
            log.warning("multiply failed (%r); failing over to the host "
                        "oracle from pass %d", e, pass_idx)
            # the event log's view of the same transition (job/trace tags
            # ride along automatically under spgemmd)
            from spgemm_tpu.obs import events  # noqa: PLC0415
            events.emit("chain_failover", error=repr(e),
                        pass_idx=pass_idx)
            # copy, not alias: the retry pass Nones out consumed entries of
            # its working list, which must never corrupt the snapshot
            arr = list(arr_host)
            multiply, kwargs, keep_device = oracle_multiply, {}, False
            continue
        arr, arr_host = nxt, nxt_host
        pass_idx += 1
        if checkpoint_dir:
            from spgemm_tpu.utils import checkpoint  # noqa: PLC0415
            checkpoint.save_pass(checkpoint_dir, pass_idx, arr_host)
    if arr_host is not None and not keep_device:
        return arr_host[0]
    return arr[0] if keep_device else _to_host(arr[0])
