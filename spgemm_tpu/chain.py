"""Chain scheduler (L3): order-preserving pairwise reduction of a matrix chain.

The reference's helper2() (sparse_matrix_mult.cu:287-327) halves the array each
pass, multiplying adjacent pairs left-to-right and carrying the odd trailing
element; correctness for the non-commutative product relies only on preserving
left-to-right adjacency, but because the arithmetic is also non-*associative*
(SURVEY.md section 2.9), parity requires this exact reduction tree, not just
any ordered fold.

Dispatch is a plain Python loop: each multiply is a jitted device program, so
host-side control flow costs nothing by comparison (SURVEY.md C11).
"""

from __future__ import annotations

import logging

from spgemm_tpu.utils.blockcsr import BlockSparseMatrix

log = logging.getLogger("spgemm_tpu.chain")


def _to_host(m):
    return m.to_host() if hasattr(m, "to_host") else m


def oracle_multiply(a: BlockSparseMatrix, b: BlockSparseMatrix,
                    **_ignored) -> BlockSparseMatrix:
    """Host-only multiply with reference semantics (utils/semantics oracle).

    The failover path: needs no accelerator, no XLA backend -- survives a
    dead device.  Slow; correctness over speed by construction.
    """
    from spgemm_tpu.utils.semantics import spgemm_oracle  # noqa: PLC0415

    a, b = _to_host(a), _to_host(b)
    return BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))


def chain_product(matrices: list[BlockSparseMatrix], multiply=None,
                  checkpoint_dir: str | None = None, resume: bool = True,
                  keep_device: bool = False, failover: bool = False,
                  **kwargs) -> BlockSparseMatrix:
    """Reduce [M1, ..., MN] to M1 x M2 x ... x MN with helper2's pairing.

    multiply: binary op (defaults to ops.spgemm.spgemm_device, which keeps
    every partial product in HBM -- tile data crosses the host boundary only
    at the final result, or never with keep_device=True); kwargs forwarded.
    checkpoint_dir: if set, snapshot the surviving partials after each pass
    (utils/checkpoint.py) and resume from the newest snapshot on restart.
    failover: failure detection + recovery (SURVEY.md section 5.3; the
    reference has none -- any rank failure kills the MPI job).  If a
    multiply raises (device/tunnel death mid-chain), restart the current
    pass from the newest checkpoint -- or from the last completed pass's
    host copies -- on the host-only oracle, which needs no device at all.
    """
    if multiply is None:
        from spgemm_tpu.ops.spgemm import spgemm_device as multiply  # noqa: PLC0415
    if not matrices:
        raise ValueError("empty chain")
    arr = list(matrices)
    pass_idx = 0
    if checkpoint_dir and resume:
        from spgemm_tpu.utils import checkpoint  # noqa: PLC0415
        found = checkpoint.latest_pass(checkpoint_dir)
        if found is not None:
            pass_idx, arr = found
            log.info("resumed from checkpoint pass %d (%d partials)",
                     pass_idx, len(arr))
    # Host-side copies of the current pass input: the failover restart point
    # (device partials are unfetchable once the device is gone, so copies
    # must be taken while it is alive -- inside the try, one D2H per pass,
    # shared with the checkpoint writer and the final return).
    need_host = failover or bool(checkpoint_dir)
    arr_host = [_to_host(m) for m in arr] if failover else None
    while len(arr) > 1:
        try:
            nxt = []
            odd_carry = arr[-1] if len(arr) % 2 == 1 else None
            for i in range(0, len(arr) - 1, 2):
                # the reference's :301 progress line -- printed
                # unconditionally to stdout, as sparse_matrix_mult.cu does
                print(f"multiplying {i} {i + 1}", flush=True)
                nxt.append(multiply(arr[i], arr[i + 1], **kwargs))
                # drop consumed partials so their HBM frees as soon as the
                # dependent computations drain (pass >= 1 operands are
                # device-resident and otherwise pinned for the whole pass;
                # failover restarts from arr_host, never from these)
                arr[i] = arr[i + 1] = None
            if odd_carry is not None:
                nxt.append(odd_carry)  # odd element carried (:315-321)
            nxt_host = [_to_host(m) for m in nxt] if need_host else None
        except Exception as e:  # noqa: BLE001 -- device loss is the use case
            if not failover or multiply is oracle_multiply:
                raise
            # arr_host snapshots the exact input of the failed pass (within
            # a run it equals the newest checkpoint, and unlike the on-disk
            # dir it cannot belong to a previous unrelated run)
            log.warning("multiply failed (%r); failing over to the host "
                        "oracle from pass %d", e, pass_idx)
            # copy, not alias: the retry pass Nones out consumed entries of
            # its working list, which must never corrupt the snapshot
            arr = list(arr_host)
            multiply, kwargs, keep_device = oracle_multiply, {}, False
            continue
        arr, arr_host = nxt, nxt_host
        pass_idx += 1
        if checkpoint_dir:
            from spgemm_tpu.utils import checkpoint  # noqa: PLC0415
            checkpoint.save_pass(checkpoint_dir, pass_idx, arr_host)
    if arr_host is not None and not keep_device:
        return arr_host[0]
    return arr[0] if keep_device else _to_host(arr[0])
