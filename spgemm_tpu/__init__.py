"""tpu-spgemm: a TPU-native block-sparse matrix multiplication framework.

Built from scratch in JAX/XLA/Pallas with the capabilities of the reference
OpenMP+MPI+CUDA implementation (see SURVEY.md): chain products of block-sparse
matrices whose nonzeros are dense k x k tiles of uint64, with the reference's
exact wrap-then-mod-(2^64-1) arithmetic (SURVEY.md section 2.9, reference
sparse_matrix_mult.cu:48,59-61), read/written in the reference's text directory
format, scaled over a TPU device mesh with `shard_map` + XLA collectives in
place of the reference's MPI layer.

Layering (mirrors SURVEY.md section 1, redesigned TPU-first):

  cli            -- the `a4`-compatible driver (folder -> ./matrix)   [L6]
  parallel/      -- mesh partitioning + collectives (replaces MPI)    [L5]
  utils/io_text  -- reference text format reader/writer               [L4]
  chain          -- order-preserving pairwise chain reduction         [L3]
  ops/spgemm     -- two-phase SpGEMM engine (symbolic + numeric)      [L2]
  ops/pallas_*   -- Pallas TPU kernels (numeric phase)                [L1]
  (memory: JAX/HBM managed -- the reference's 8 GB arena disappears)  [L0]

Top-level imports are lazy so that importing the package does not pull in
jax -- the CLI must be able to pin JAX_PLATFORMS before jax is imported.
"""

__version__ = "0.1.0"

__all__ = ["BlockSparseMatrix", "spgemm", "spgemm_outofcore", "chain_product",
           "__version__"]


def __getattr__(name):
    if name == "BlockSparseMatrix":
        from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
        return BlockSparseMatrix
    if name == "spgemm":
        from spgemm_tpu.ops.spgemm import spgemm
        return spgemm
    if name == "spgemm_outofcore":
        from spgemm_tpu.ops.spgemm import spgemm_outofcore
        return spgemm_outofcore
    if name == "chain_product":
        from spgemm_tpu.chain import chain_product
        return chain_product
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
