"""FPT: failpoint-name registry discipline for utils/failpoints.check.

The chaos harness arms injection points BY NAME (SPGEMM_TPU_FAILPOINTS),
so a `failpoints.check("...")` site whose name is not declared in the
`utils/failpoints.py` registry is dead chaos surface -- unarmed forever,
silently -- and a computed name cannot be audited at all.  Symmetrically,
a REGISTRY entry with no live call site is a failpoint an operator can
arm that injects nothing: the chaos run "passes" without ever faulting
that path.  This rule makes the registry binding both ways, the MET
pattern applied to fault injection:

  * per file (`check_fpt`): the name argument of every
    `failpoints.check(...)` call must be a string literal declared in
    the registry;
  * package level (`check_fpt_registry`, run by core.lint_report when
    the registry module itself is in the linted unit set): every
    registry entry must have at least one literal call site somewhere in
    the unit set -- a stale entry is a finding at its declaration line.

Receiver resolution is import-based like MET: any alias of the
failpoints module (`from spgemm_tpu.utils import failpoints [as fp]`,
`import spgemm_tpu.utils.failpoints as f`) or of the function itself
(`from ...failpoints import check [as c]`) counts.
"""

from __future__ import annotations

import ast

from spgemm_tpu.analysis.core import Finding
from spgemm_tpu.analysis.rules import dotted_name
from spgemm_tpu.utils.failpoints import REGISTRY

FAILPOINTS_MODULE = "spgemm_tpu.utils.failpoints"
FAILPOINTS_SUFFIX = "/utils/failpoints.py"


def _receivers(tree: ast.AST) -> tuple[set[str], set[str]]:
    """(dotted module-spellings whose `.check` is the failpoint check,
    bare function-name spellings that ARE the check)."""
    modules: set[str] = set()
    funcs: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and node.module.endswith("utils.failpoints"):
                for alias in node.names:
                    if alias.name == "check":
                        funcs.add(alias.asname or alias.name)
            elif node.module and node.module.endswith("utils"):
                # `from spgemm_tpu.utils import failpoints [as fp]`
                for alias in node.names:
                    if alias.name == "failpoints":
                        modules.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == FAILPOINTS_MODULE or \
                        alias.name.endswith("utils.failpoints"):
                    modules.add(alias.asname or alias.name)
    return modules, funcs


def _check_calls(tree: ast.AST):
    """Yield (call node, name argument node) for every failpoint check
    call in the module."""
    modules, funcs = _receivers(tree)
    if not modules and not funcs:
        return
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        hit = (isinstance(f, ast.Attribute) and f.attr == "check"
               and dotted_name(f.value) in modules) \
            or (isinstance(f, ast.Name) and f.id in funcs)
        if not hit:
            continue
        arg = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords if kw.arg == "name"), None)
        yield node, arg


def check_fpt(tree: ast.AST, file: str) -> list[Finding]:
    """FPT over one module: undeclared or non-literal failpoint names."""
    findings: list[Finding] = []
    for node, arg in _check_calls(tree):
        if arg is None:
            continue
        if not (isinstance(arg, ast.Constant)
                and isinstance(arg.value, str)):
            findings.append(Finding(
                file, node.lineno, "FPT",
                "failpoints.check() name must be a string literal "
                "declared in the spgemm_tpu/utils/failpoints.py registry: "
                "a computed name cannot be audited against the registry "
                "(and can never be armed deliberately)"))
        elif arg.value not in REGISTRY:
            findings.append(Finding(
                file, node.lineno, "FPT",
                f"undeclared failpoint {arg.value!r} in "
                "failpoints.check(): declare it in the "
                "spgemm_tpu/utils/failpoints.py registry (name, kind, "
                "site module, doc) so the chaos spec, the triggered "
                "metric and the FPT stale-entry check stay in sync"))
    return findings


def literal_names(tree: ast.AST) -> set[str]:
    """The string-literal failpoint names checked in one module (the
    package-level stale-entry pass's per-unit contribution)."""
    names: set[str] = set()
    for _, arg in _check_calls(tree):
        if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
            names.add(arg.value)
    return names


def check_fpt_registry(units) -> list[Finding]:
    """The reverse direction, over the whole unit set: a registry entry
    no `failpoints.check` site names is a stale failpoint (armable,
    injects nothing).  Runs only when the registry module itself is
    among the linted units (the default self-lint scope) -- fixture runs
    over partial trees must not see every entry as stale."""
    registry_unit = next(
        (u for u in units
         if u.path.replace("\\", "/").endswith(FAILPOINTS_SUFFIX)), None)
    if registry_unit is None or registry_unit.tree is None:
        return []
    seen: set[str] = set()
    for u in units:
        if u.tree is not None and u is not registry_unit:
            seen |= literal_names(u.tree)
    findings: list[Finding] = []
    src_lines = registry_unit.source.splitlines()
    for name in sorted(set(REGISTRY) - seen):
        line = next((i + 1 for i, text in enumerate(src_lines)
                     if f'"{name}"' in text), 1)
        findings.append(Finding(
            registry_unit.file, line, "FPT",
            f"stale failpoint registry entry {name!r}: no "
            "failpoints.check() site names it anywhere in the package -- "
            "arming it injects nothing; wire the site (module "
            f"{REGISTRY[name].module}) or delete the entry"))
    return findings
