"""THR: lock discipline for shared mutable state, annotation-enforced.

PRs 4-5 made the engine genuinely multi-threaded (chain plan-ahead worker,
OOC staging/landing pipeline, spgemmd executor/watchdog/conn handlers) --
exactly the shape where the multi-threaded SpGEMM literature says
accumulator/ordering bugs live.  The lock discipline used to exist only as
comments ("# ids, journal file, degrade state"); this rule makes it a
machine-checked contract:

    self._jobs = []        # spgemm-lint: guarded-by(_lock)
    _CACHE = OrderedDict() # spgemm-lint: guarded-by(_LOCK)

declares that every read/write of the attribute (instance attribute via
`self.X`, or module global via bare `X`) must happen inside a
`with self._lock:` / `with _LOCK:` block.  Accesses outside one are THR
findings.  The rule understands:

  * lock ALIASES: `self._avail = threading.Condition(self._lock)` makes
    `with self._avail:` hold the same lock (condition variables share
    their lock by construction);
  * `__init__` is exempt -- construction happens-before publication to
    any other thread;
  * methods named `*_locked` are exempt -- the suffix is the repo's
    caller-holds-the-lock convention (the caller's `with` is the guard);
  * a NESTED def or lambda inside a `with` block does NOT inherit the
    guard: its body runs later, usually on another thread (Thread targets,
    callbacks), so held locks reset to none inside it;
  * the escape hatch `# spgemm-lint: thr-ok(<reason>)` on the access line
    (or the line above) for accesses that are provably safe lock-free --
    the reason is the reviewable proof.

The annotation is deliberately opt-in per attribute: single-writer
handoff protocols (spgemmd's _current/_reaped slots) are lock-free by
design and stay unannotated, with their ordering argument in comments.
"""

from __future__ import annotations

import ast

from spgemm_tpu.analysis.core import Finding, LintUnit
from spgemm_tpu.analysis.rules import dotted_name

GUARD_MARKER = "spgemm-lint: guarded-by("

_CONDITION_WRAPPERS = {"Condition"}  # threading.Condition(lock) aliases lock


def guard_on_assignment(ann: dict[int, str],
                        node: ast.AST) -> str | None:
    """The guard name an annotation binds to `node` -- on ANY line the
    (possibly wrapped) assignment spans: a multi-line dict literal
    carries its comment on the closing line, and an annotation that
    silently fails to bind is worse than no annotation.  THE one
    binding rule: THR (enforcement) and TSI (the annotated-state
    exemption) must agree on it, so both call this."""
    for ln in range(node.lineno,
                    (getattr(node, "end_lineno", None) or node.lineno) + 1):
        if ln in ann:
            return ann[ln]
    return None


def _guard_annotations(comments: dict[int, str]) -> dict[int, str]:
    """1-indexed line -> declared lock name (leading `self.` stripped).
    Scans real comments only (core.comment_map), so a quoted marker in a
    docstring or message string never declares a guard."""
    out: dict[int, str] = {}
    for i, text in comments.items():
        pos = text.find(GUARD_MARKER)
        if pos < 0:
            continue
        lock = text[pos + len(GUARD_MARKER):].split(")", 1)[0].strip()
        if lock.startswith("self."):
            lock = lock[len("self."):]
        if lock:
            out[i] = lock
    return out


def _assign_targets(node: ast.AST):
    if isinstance(node, ast.Assign):
        return node.targets
    if isinstance(node, (ast.AnnAssign, ast.AugAssign)):
        return [node.target]
    return []


class _Scope:
    """Guarded names + lock aliases for one class (attr access via self.X)
    or one module (bare-name globals)."""

    def __init__(self):
        self.guards: dict[str, str] = {}  # name -> lock name
        self.alias: dict[str, str] = {}   # lock alias -> lock name

    def rep(self, lock: str) -> str:
        seen = set()
        while lock in self.alias and lock not in seen:
            seen.add(lock)
            lock = self.alias[lock]
        return lock

    def collect(self, body_walk, ann: dict[int, str], *,
                attr_of_self: bool) -> None:
        """Pick up guard annotations and Condition aliases from an AST
        walk (class body or module top level)."""
        def name_of(target: ast.expr) -> str | None:
            if attr_of_self:
                if (isinstance(target, ast.Attribute)
                        and isinstance(target.value, ast.Name)
                        and target.value.id == "self"):
                    return target.attr
                return None
            return target.id if isinstance(target, ast.Name) else None

        for node in body_walk:
            targets = _assign_targets(node)
            if not targets:
                continue
            names = [n for n in map(name_of, targets) if n is not None]
            if not names:
                continue
            guard = guard_on_assignment(ann, node)
            if guard is not None:
                for n in names:
                    self.guards[n] = guard
            value = getattr(node, "value", None)
            if (isinstance(value, ast.Call)
                    and (dotted_name(value.func) or "").rsplit(".", 1)[-1]
                    in _CONDITION_WRAPPERS and value.args):
                arg = value.args[0]
                arg_name = name_of(arg)
                if arg_name is not None:
                    for n in names:
                        self.alias[n] = arg_name


def _local_shadows(fn: ast.AST, guarded: set[str]) -> frozenset:
    """Guarded names this function binds LOCALLY (a parameter, or assigned
    in its body without a `global` declaration): Python scoping makes
    every use of such a name refer to the local, never the guarded module
    global, so the THR check must not fire on it.  Nested defs are
    excluded -- they have their own scopes, handled on entry."""
    declared_global: set[str] = set()
    assigned: set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        assigned.update(a.arg for a in (
            args.posonlyargs + args.args + args.kwonlyargs
            + [a for a in (args.vararg, args.kwarg) if a is not None]))

    def rec(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Global):
                declared_global.update(child.names)
            elif isinstance(child, ast.Name) and isinstance(
                    child.ctx, (ast.Store, ast.Del)):
                assigned.add(child.id)
            rec(child)

    rec(fn)
    return frozenset((assigned & guarded) - declared_global)


class _AccessChecker:
    """Walk function bodies tracking held locks; report unguarded accesses
    of guarded names."""

    def __init__(self, unit: LintUnit, scope: _Scope, escapes: set[int],
                 *, attr_of_self: bool):
        self.unit = unit
        self.scope = scope
        self.escapes = escapes
        self.attr_of_self = attr_of_self
        self.findings: list[Finding] = []
        self._seen: set[tuple[int, str]] = set()
        self._shadow: frozenset = frozenset()

    def _acquired(self, item: ast.withitem) -> str | None:
        expr = item.context_expr
        if self.attr_of_self:
            if (isinstance(expr, ast.Attribute)
                    and isinstance(expr.value, ast.Name)
                    and expr.value.id == "self"):
                return self.scope.rep(expr.attr)
            return None
        if isinstance(expr, ast.Name):
            return self.scope.rep(expr.id)
        return None

    def _accessed_name(self, node: ast.AST) -> str | None:
        if self.attr_of_self:
            if (isinstance(node, ast.Attribute)
                    and isinstance(node.value, ast.Name)
                    and node.value.id == "self"
                    and node.attr in self.scope.guards):
                return node.attr
            return None
        if (isinstance(node, ast.Name) and node.id in self.scope.guards
                and node.id not in self._shadow):
            return node.id
        return None

    def check_function(self, fn: ast.AST) -> None:
        if not self.attr_of_self:
            self._shadow = _local_shadows(fn, set(self.scope.guards))
        for stmt in fn.body:
            self._visit(stmt, frozenset())

    def _visit(self, node: ast.AST, held: frozenset) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            acquired = set()
            for item in node.items:
                self._visit(item.context_expr, held)
                lock = self._acquired(item)
                if lock is not None:
                    acquired.add(lock)
            inner = held | acquired
            for stmt in node.body:
                self._visit(stmt, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            # a nested def/lambda runs LATER, usually on another thread
            # (Thread target, callback): the enclosing `with` does not
            # protect it -- held locks reset to none inside.  Shadowing
            # accumulates: a name local to ANY enclosing scope (or bound
            # here) is a closure variable, not the guarded global
            for dec in getattr(node, "decorator_list", ()):
                self._visit(dec, held)
            outer_shadow = self._shadow
            if not self.attr_of_self:
                self._shadow = outer_shadow | _local_shadows(
                    node, set(self.scope.guards))
            body = node.body if isinstance(node.body, list) else [node.body]
            for stmt in body:
                self._visit(stmt, frozenset())
            self._shadow = outer_shadow
            return
        name = self._accessed_name(node)
        if name is not None:
            lock = self.scope.rep(self.scope.guards[name])
            line = node.lineno
            if (lock not in held and (line, name) not in self._seen
                    and line not in self.escapes
                    and line - 1 not in self.escapes):
                self._seen.add((line, name))
                spelled = f"self.{name}" if self.attr_of_self else name
                lock_spelled = f"self.{lock}" if self.attr_of_self else lock
                self.findings.append(Finding(
                    self.unit.file, line, "THR",
                    f"`{spelled}` is declared guarded-by({lock}) but is "
                    f"accessed outside a `with {lock_spelled}:` block "
                    "(worker/watchdog/handler threads share this state); "
                    "hold the lock, or escape with "
                    "`# spgemm-lint: thr-ok(<reason>)` if lock-free access "
                    "is provably safe here"))
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)


def _exempt(fn_name: str, *, attr_of_self: bool) -> bool:
    # *_locked is the caller-holds-the-lock convention (both scopes);
    # __init__ is exempt ONLY for the instance's own attributes -- it runs
    # before the OBJECT is published to any other thread, but a module
    # global is already published to every thread while __init__ runs
    if fn_name.endswith("_locked"):
        return True
    return attr_of_self and fn_name == "__init__"


def check_thr(unit: LintUnit, escapes: set[int]) -> list[Finding]:
    """THR over one unit: class-attribute guards and module-global guards."""
    tree = unit.tree
    ann = _guard_annotations(unit.comments)
    findings: list[Finding] = []
    if not ann:
        return findings

    # ---- class-attribute guards (self.X) --------------------------------
    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        scope = _Scope()
        scope.collect(ast.walk(cls), ann, attr_of_self=True)
        if not scope.guards:
            continue
        for item in cls.body:
            if (isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and not _exempt(item.name, attr_of_self=True)):
                checker = _AccessChecker(unit, scope, escapes,
                                         attr_of_self=True)
                checker.check_function(item)
                findings += checker.findings

    # ---- module-global guards (bare names) ------------------------------
    scope = _Scope()
    scope.collect(ast.iter_child_nodes(tree), ann, attr_of_self=False)
    if scope.guards:
        for node in _outer_functions(tree):
            if not _exempt(node.name, attr_of_self=False):
                checker = _AccessChecker(unit, scope, escapes,
                                         attr_of_self=False)
                checker.check_function(node)
                findings += checker.findings
    return findings


def _outer_functions(tree: ast.AST) -> list[ast.AST]:
    """Outermost function defs (module level, class methods, any nesting
    of classes/ifs -- but NOT defs nested in other defs: the access
    checker recurses into those itself, with held locks reset)."""
    out: list[ast.AST] = []

    def rec(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(child)
            else:
                rec(child)

    rec(tree)
    return out
