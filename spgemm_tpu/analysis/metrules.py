"""MET: metric-name registry discipline for the ENGINE timer registry.

The Prometheus surface (obs/metrics.py) and the span flight recorder both
key on the names passed to `ENGINE.phase(...)` / `ENGINE.record(...)` /
`ENGINE.incr(...)` -- an ad-hoc name at a call site would mint a new
time series that no dashboard, no generated doc table, and no alert knows
about.  This rule makes the registry binding the same way KNB does for
knobs:

  * the name argument must be a STRING LITERAL (a computed name cannot be
    audited against the registry, and per-item dynamic names are exactly
    the cardinality explosion Prometheus forbids);
  * phase()/record() names must be declared in
    `obs/metrics.ENGINE_PHASES`, incr() names in
    `obs/metrics.ENGINE_COUNTERS`.

Receiver resolution is import-based: any local alias of
`spgemm_tpu.utils.timers.ENGINE` counts (`from ... import ENGINE`,
`from ... import ENGINE as timers`, `import spgemm_tpu.utils.timers as t`
+ `t.ENGINE...`).  Ad-hoc PhaseTimers INSTANCES (the CLI's local driver
timers, test registries) are deliberately out of scope: only the
process-wide ENGINE feeds the scrape/trace surface.
"""

from __future__ import annotations

import ast

from spgemm_tpu.analysis.core import Finding
from spgemm_tpu.analysis.rules import dotted_name
from spgemm_tpu.obs.metrics import ENGINE_COUNTERS, ENGINE_PHASES

TIMERS_MODULE = "spgemm_tpu.utils.timers"

# method name -> (registry, registry spelling for the message)
_METHODS = {
    "phase": (ENGINE_PHASES, "obs/metrics.ENGINE_PHASES"),
    "record": (ENGINE_PHASES, "obs/metrics.ENGINE_PHASES"),
    "incr": (ENGINE_COUNTERS, "obs/metrics.ENGINE_COUNTERS"),
}


def _engine_receivers(tree: ast.AST) -> set[str]:
    """Every dotted spelling that refers to the ENGINE registry in this
    module: direct/aliased `from ...timers import ENGINE`, plus
    `<module-alias>.ENGINE` for any import of the timers module."""
    receivers: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            if node.module and node.module.endswith("utils.timers"):
                for alias in node.names:
                    if alias.name == "ENGINE":
                        receivers.add(alias.asname or alias.name)
            elif node.module and node.module.endswith("utils"):
                # `from spgemm_tpu.utils import timers [as t]`
                for alias in node.names:
                    if alias.name == "timers":
                        receivers.add(f"{alias.asname or alias.name}.ENGINE")
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == TIMERS_MODULE or \
                        alias.name.endswith("utils.timers"):
                    receivers.add(f"{alias.asname or alias.name}.ENGINE")
    return receivers


def check_met(tree: ast.AST, file: str) -> list[Finding]:
    """MET over one module: undeclared or non-literal ENGINE metric
    names."""
    receivers = _engine_receivers(tree)
    if not receivers:
        return []
    findings: list[Finding] = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in _METHODS):
            continue
        recv = dotted_name(node.func.value)
        if recv not in receivers:
            continue
        # the name rides as the first positional OR as name= -- both
        # spellings mint the series, so both are in scope
        arg = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords if kw.arg == "name"), None)
        if arg is None:
            continue
        registry, spelled = _METHODS[node.func.attr]
        if not (isinstance(arg, ast.Constant) and isinstance(arg.value, str)):
            findings.append(Finding(
                file, node.lineno, "MET",
                f"ENGINE.{node.func.attr}() metric name must be a string "
                f"literal declared in {spelled}: a computed name mints an "
                "unauditable time series (and dynamic label-by-name is "
                "the cardinality explosion the metrics registry exists "
                "to prevent)"))
        elif arg.value not in registry:
            findings.append(Finding(
                file, node.lineno, "MET",
                f"undeclared metric name {arg.value!r} in "
                f"ENGINE.{node.func.attr}(): declare it in {spelled} "
                "(spgemm_tpu/obs/metrics.py) so the Prometheus surface, "
                "the flight recorder, and the generated ARCHITECTURE.md "
                "table stay in sync -- no ad-hoc series names"))
    return findings
