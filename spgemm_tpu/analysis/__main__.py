"""CLI: `python -m spgemm_tpu.analysis [paths...] [--json|--sarif F]`.

Default run (no paths): self-lint the whole spgemm_tpu package plus the
repo doc-drift checks (CLAUDE.md knob table, CLI help coverage, the rule-id
coverage of this very --help).  Explicit paths lint just those files/dirs;
the doc checks then run only when --claude-md is passed (fixture testing
drives this).

Exit status: 0 = clean, 1 = findings (CI-gateable).  --json emits one
machine-readable report object on stdout:
  {"findings": [{"file", "line", "rule", "message"}, ...],
   "counts": {<rule id>: n for every registered rule},
   "suppressions": [{"file", "line", "rule", "reason", "stale"}, ...],
   "cache": {"enabled", "dir"?, "hits"?, "misses"?, "invalidations"?},
   "clean": bool}
(the suppression inventory lists EVERY escape-hatch comment in the run --
fld-proof / thr-ok / exc-ok / lck-ok / blk-ok / tsi-ok / drf-ok -- with
stale=true
for an escape that no longer suppresses anything; a stale escape is also a
SUP finding).  --sarif F additionally writes a SARIF 2.1.0 log to F
(`make lint-sarif`), with suppressed findings carried as results bearing
SARIF `suppressions` objects.

Per-file results are content-hash cached under `.lint_cache/` by default
(the linter is proven env-independent and jax-free, so a file's findings
are a pure function of its bytes + the analysis package's bytes): a warm
`make lint` re-runs only changed files.  `--no-cache` disables it,
`--cache-dir` relocates it (tests), `make lint-cache-clean` empties it.
"""

from __future__ import annotations

import argparse
import collections
import json
import os
import sys

from spgemm_tpu.analysis import core, docrules, sarif


def build_parser() -> argparse.ArgumentParser:
    """The analysis CLI parser.  The epilog is generated from the rule-id
    registry (core.RULES) so docrules.check_analysis_help can hold this
    --help to covering every rule id without a hand-maintained list."""
    epilog = "rule ids:\n" + "\n".join(
        f"  {rule_id:6s}{doc}" for rule_id, doc in core.RULES.items())
    p = argparse.ArgumentParser(
        prog="spgemm_tpu.analysis",
        formatter_class=argparse.RawDescriptionHelpFormatter,
        description="spgemm-lint: package-level invariant checker (FLD fold "
                    "order incl. interprocedural taint, KNB knob registry, "
                    "BKD import-time backend touch, THR lock discipline, "
                    "LCK lock-order deadlock detection, BLK blocking-under-"
                    "lock, TSI thread-shared inference, EXC exception "
                    "contracts, MET metric registry, FPT failpoint "
                    "registry, PRO wire-protocol registry, EVT event-kind "
                    "registry, DRF registry drift, SUP stale "
                    "suppressions, DOC doc drift)",
        epilog=epilog)
    p.add_argument("paths", nargs="*",
                   help="files/dirs to lint (default: the spgemm_tpu "
                        "package, bench.py, benchmarks/, the graft entry, "
                        "+ repo doc checks)")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="emit the machine-readable findings + suppression-"
                        "inventory report")
    p.add_argument("--sarif", default=None, metavar="FILE",
                   help="also write a SARIF 2.1.0 log to FILE "
                        "(`make lint-sarif` writes lint.sarif)")
    p.add_argument("--claude-md", default=None, metavar="PATH",
                   help="CLAUDE.md to diff the knob table against "
                        "(default: the repo's, on a default run; the "
                        "metrics table is checked in the ARCHITECTURE.md "
                        "beside it)")
    p.add_argument("--architecture-md", default=None, metavar="PATH",
                   help="ARCHITECTURE.md for --write-metrics-table "
                        "(default: the repo's)")
    p.add_argument("--no-doc", action="store_true",
                   help="skip the DOC drift checks")
    p.add_argument("--write-knob-table", action="store_true",
                   help="regenerate the CLAUDE.md knob-table block from "
                        "the registry and exit")
    p.add_argument("--write-metrics-table", action="store_true",
                   help="regenerate the ARCHITECTURE.md metrics-table "
                        "block from the obs/metrics.py registry and exit")
    p.add_argument("--write-thread-inventory", action="store_true",
                   help="regenerate the ARCHITECTURE.md thread-inventory "
                        "block from the concurrency pass (LCK/BLK/TSI) "
                        "over the default scope and exit")
    p.add_argument("--write-protocol-table", action="store_true",
                   help="regenerate the ARCHITECTURE.md wire-protocol "
                        "table block from the serve/protocol.py registry "
                        "and exit")
    p.add_argument("--write-event-table", action="store_true",
                   help="regenerate the ARCHITECTURE.md event-kind table "
                        "block from the obs/events.py EVENT_KINDS "
                        "registry and exit")
    p.add_argument("--no-cache", action="store_true",
                   help="disable the content-hash per-file result cache "
                        "(.lint_cache/; the default run caches)")
    p.add_argument("--cache-dir", default=None, metavar="DIR",
                   help="cache directory (default: <repo>/.lint_cache)")
    return p


def _write_block(path: str, begin_marker: str, end_marker: str,
                 block: str, what: str) -> int:
    """Regenerate one marked generated-doc block in place."""
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        print(f"cannot read {path}", file=sys.stderr)
        return 1
    begin = text.find(begin_marker)
    end = text.find(end_marker)
    if begin < 0 or end < 0 or end < begin:
        print(f"{path}: {what} markers missing; paste this block where "
              f"the {what} belongs:\n\n" + block, file=sys.stderr)
        return 1
    new = text[:begin] + block + text[end + len(end_marker):]
    if new != text:
        with open(path, "w", encoding="utf-8") as f:
            f.write(new)
        print(f"updated {what} in {path}")
    else:
        print(f"{what} in {path} already current")
    return 0


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)

    root = core.repo_root()
    default_claude = os.path.join(root, "CLAUDE.md")
    if args.write_knob_table or args.write_metrics_table \
            or args.write_thread_inventory or args.write_protocol_table \
            or args.write_event_table:
        # the flags compose: "regenerate everything" must not silently
        # leave a later table stale behind an earlier early return
        rc = 0
        if args.write_knob_table:
            rc = max(rc, _write_block(
                args.claude_md or default_claude,
                docrules.KNOB_TABLE_BEGIN, docrules.KNOB_TABLE_END,
                docrules.render_knob_block(), "knob table"))
        if args.write_metrics_table:
            rc = max(rc, _write_block(
                args.architecture_md or os.path.join(root,
                                                     "ARCHITECTURE.md"),
                docrules.METRICS_TABLE_BEGIN, docrules.METRICS_TABLE_END,
                docrules.render_metrics_block(), "metrics table"))
        if args.write_thread_inventory:
            rc = max(rc, _write_block(
                args.architecture_md or os.path.join(root,
                                                     "ARCHITECTURE.md"),
                docrules.THREAD_TABLE_BEGIN, docrules.THREAD_TABLE_END,
                docrules.render_thread_block(), "thread inventory"))
        if args.write_protocol_table:
            rc = max(rc, _write_block(
                args.architecture_md or os.path.join(root,
                                                     "ARCHITECTURE.md"),
                docrules.PROTOCOL_TABLE_BEGIN, docrules.PROTOCOL_TABLE_END,
                docrules.render_protocol_block(), "protocol table"))
        if args.write_event_table:
            rc = max(rc, _write_block(
                args.architecture_md or os.path.join(root,
                                                     "ARCHITECTURE.md"),
                docrules.EVENT_TABLE_BEGIN, docrules.EVENT_TABLE_END,
                docrules.render_event_block(), "event table"))
        return rc

    if args.paths:
        paths = args.paths
        claude_md = args.claude_md  # None = no doc checks on custom runs
    else:
        paths = core.default_paths()
        claude_md = args.claude_md or default_claude
    cache = None if args.no_cache else core.LintCache(args.cache_dir)
    # the DOC half (knob table + CLI/analysis help) runs only when a
    # CLAUDE.md is in play: default runs always, explicit-path runs only
    # with --claude-md
    report = core.lint_run(
        paths, claude_md=claude_md,
        doc=not args.no_doc and claude_md is not None, cache=cache)
    findings, suppressions = report.findings, report.suppressions

    if args.sarif:
        sarif.write(args.sarif, findings, report.suppressed)
    if args.as_json:
        counts = collections.Counter(f.rule for f in findings)
        print(json.dumps({
            "findings": [f.to_dict() for f in findings],
            "counts": {rule: counts.get(rule, 0) for rule in core.RULES},
            "suppressions": [s.to_dict() for s in suppressions],
            "cache": report.cache or {"enabled": False},
            "clean": not findings,
        }, indent=2))
    else:
        for f in findings:
            print(f"{f.file}:{f.line}: [{f.rule}] {f.message}")
        print(f"spgemm-lint: {len(findings)} finding(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
