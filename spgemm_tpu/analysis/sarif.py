"""SARIF 2.1.0 emitter: CI/editor-annotatable lint output.

`python -m spgemm_tpu.analysis --sarif lint.sarif` (or `make lint-sarif`)
writes one run with the full rule-id registry as tool.driver.rules and one
result per finding -- the shape GitHub code scanning and SARIF-aware
editors consume.  The contract test (tests/test_lint.py) pins the schema
shape; stale suppressions travel as ordinary SUP results, and the full
escape inventory stays a --json feature (SARIF's per-result suppressions
model suppressed results, not escape comments)."""

from __future__ import annotations

import json

from spgemm_tpu.analysis.core import RULES, Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"


def render(findings: list[Finding]) -> dict:
    """The SARIF log object (plain dict, json.dump-ready)."""
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                # no informationUri: SARIF 2.1.0 requires an ABSOLUTE URI
                # there and this repo has no canonical hosted URL -- the
                # property is optional, and strict consumers (GitHub code
                # scanning) reject relative ones.  The human pointer is
                # ARCHITECTURE.md "Enforced invariants (spgemm-lint)".
                "name": "spgemm-lint",
                "rules": [{
                    "id": rule_id,
                    "shortDescription": {"text": doc},
                } for rule_id, doc in RULES.items()],
            }},
            "results": [{
                "ruleId": f.rule,
                "level": "error",
                "message": {"text": f.message},
                "locations": [{
                    "physicalLocation": {
                        "artifactLocation": {"uri": f.file},
                        "region": {"startLine": f.line},
                    },
                }],
            } for f in findings],
        }],
    }


def write(path: str, findings: list[Finding]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(render(findings), f, indent=2)
        f.write("\n")
