"""SARIF 2.1.0 emitter: CI/editor-annotatable lint output.

`python -m spgemm_tpu.analysis --sarif lint.sarif` (or `make lint-sarif`)
writes one run with the full rule-id registry as tool.driver.rules and one
result per finding -- the shape GitHub code scanning and SARIF-aware
editors consume.  The contract test (tests/test_lint.py) pins the schema
shape; stale suppressions travel as ordinary SUP results.

Escaped findings are NOT dropped: each suppressed finding is emitted as a
result carrying a SARIF `suppressions` object (`kind: "inSource"`, the
escape comment's reason as the `justification`), so code scanning can
audit every escape instead of watching findings silently vanish.  An
active finding carries an explicit empty `suppressions` array -- the
SARIF 2.1.0 convention that lets a consumer distinguish "not suppressed"
from "suppression state unknown"."""

from __future__ import annotations

import json

from spgemm_tpu.analysis.core import RULES, Finding

SARIF_VERSION = "2.1.0"
SARIF_SCHEMA = "https://json.schemastore.org/sarif-2.1.0.json"


def _result(f: Finding, suppressions: list[dict]) -> dict:
    return {
        "ruleId": f.rule,
        "level": "error",
        "message": {"text": f.message},
        "locations": [{
            "physicalLocation": {
                "artifactLocation": {"uri": f.file},
                "region": {"startLine": f.line},
            },
        }],
        "suppressions": suppressions,
    }


def render(findings: list[Finding],
           suppressed: list[tuple[Finding, str]] = ()) -> dict:
    """The SARIF log object (plain dict, json.dump-ready).

    suppressed: (finding, justification) pairs for findings an in-source
    escape comment suppressed -- emitted as results with a populated
    `suppressions` array."""
    return {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                # no informationUri: SARIF 2.1.0 requires an ABSOLUTE URI
                # there and this repo has no canonical hosted URL -- the
                # property is optional, and strict consumers (GitHub code
                # scanning) reject relative ones.  The human pointer is
                # ARCHITECTURE.md "Enforced invariants (spgemm-lint)".
                "name": "spgemm-lint",
                "rules": [{
                    "id": rule_id,
                    "shortDescription": {"text": doc},
                } for rule_id, doc in RULES.items()],
            }},
            "results": [_result(f, []) for f in findings] + [
                _result(f, [{"kind": "inSource",
                             "justification": reason}])
                for f, reason in suppressed],
        }],
    }


def write(path: str, findings: list[Finding],
          suppressed: list[tuple[Finding, str]] = ()) -> None:
    with open(path, "w", encoding="utf-8") as f:
        json.dump(render(findings, suppressed), f, indent=2)
        f.write("\n")
