"""spgemm-lint: package-level invariant checker for the repo's machine-enforced contracts.

The reference semantics (SURVEY.md section 2.9) make the wrap-then-mod u64
arithmetic non-associative, so fold order is a correctness invariant; the
dispatch layers (round batching, ring overlap) additionally require every
engine knob to be jit-static discipline-clean; the flaky-TPU environment
requires that no module touches a backend at import time (a dead TPU hangs,
never raises); and the threaded runtime (plan-ahead worker, OOC pipeline,
spgemmd) requires its lock and exception contracts to hold.  Reviewer
memory does not scale to those contracts -- this package checks them
structurally:

  FLD  ordered-fold rule: unordered reductions (jnp.sum / lax.psum /
       segment_sum / functools.reduce / array .sum()) are findings inside
       the numeric modules unless escaped with a reasoned fld-proof
       comment (the proof-gated MXU / no_mod routes).  v2 adds the
       INTERPROCEDURAL pass (callgraph.py): a numeric-module call into a
       non-numeric helper that transitively performs an unordered
       reduction is flagged at the call site, closing the "hide the
       jnp.sum in utils/" hole.
  KNB  knob rule: every SPGEMM_TPU_* environment read must go through the
       central registry (spgemm_tpu/utils/knobs.py); raw os.environ /
       os.getenv reads are findings.
  BKD  backend rule: no module-import-time jax.devices()/backend-touching
       calls outside utils/backend_probe.py (nor anywhere in an
       @host_only worker body).
  THR  lock rule (thrrules.py): an attribute annotated
       `# spgemm-lint: guarded-by(<lock>)` accessed outside a
       `with <lock>:` block is a finding (__init__, *_locked methods,
       Condition aliases exempt; escape: reasoned thr-ok comment).
  LCK  lock-order rule (lockrules.py): v3 builds an interprocedural
       lock-acquisition-order graph from `with <lock>:` nests over the
       call graph; a cycle (two paths acquiring registered locks in
       opposite orders) or a non-reentrant re-acquisition is a
       potential-deadlock finding with both witness chains (RLock is
       exempt from the self-edge but participates in cycles; escape:
       reasoned lck-ok comment).
  BLK  blocking-under-lock rule (lockrules.py): a blocking operation
       (sleep, subprocess, flock/fsync, socket accept/recv/sendall,
       Queue.get/put, Thread.join, Event/Condition.wait,
       block_until_ready) reached transitively while a registered lock
       is held is a finding with the witness chain down to the blocking
       call (escape: reasoned blk-ok comment, at the call site or at the
       blocking source).
  TSI  thread-shared inference (lockrules.py): functions passed to
       threading.Thread(target=...) are thread roots -- nested defs
       included (no inherited __init__ write exemption), and a root
       spawned in a loop or from >= 2 sites counts as two threads by
       itself; an instance attribute or module global written from
       >= 2 root-weighted threads without a guarded-by(<lock>)
       annotation is a finding -- THR's opt-in hole, closed (escape:
       reasoned tsi-ok comment).
  EXC  exception rule (excrules.py): a broad `except Exception` needs the
       `# noqa: BLE001 -- <reason>` justification; a bare `except:` /
       `except BaseException` must end its handler in `raise` (the
       JobAbandoned-must-pierce contract; escape: reasoned exc-ok).
  SUP  suppression audit: every escape comment is inventoried (--json),
       and one whose underlying finding no longer exists is itself a
       finding (like an unused noqa).
  DOC  drift rule: the CLAUDE.md knob table, the CLI help, and the
       analysis --help rule-id epilog must cover exactly what the
       registries generate.

Run `python -m spgemm_tpu.analysis [--json] [--sarif F]` (`make lint`,
`make lint-sarif`); the repo self-lints in tier-1 (tests/test_lint.py).
Everything is stdlib-only: the linter never imports jax, so it can never
hang on a dead TPU.
"""

from spgemm_tpu.analysis.core import (RULES, Finding, LintCache, Report,
                                      Suppression, is_numeric_module,
                                      lint_file, lint_paths, lint_report,
                                      lint_repo, lint_run, repo_root)
from spgemm_tpu.analysis.docrules import (KNOB_TABLE_BEGIN, KNOB_TABLE_END,
                                          check_analysis_help,
                                          check_claude_md, check_cli_help)

__all__ = [
    "Finding", "LintCache", "Report", "Suppression", "RULES", "lint_file",
    "lint_paths", "lint_report", "lint_repo", "lint_run", "repo_root",
    "is_numeric_module", "check_analysis_help", "check_claude_md",
    "check_cli_help", "KNOB_TABLE_BEGIN", "KNOB_TABLE_END",
]
