"""spgemm-lint: AST invariant checker for the repo's machine-enforced contracts.

The reference semantics (SURVEY.md section 2.9) make the wrap-then-mod u64
arithmetic non-associative, so fold order is a correctness invariant; the
dispatch layers (round batching, ring overlap) additionally require every
engine knob to be jit-static discipline-clean, and the flaky-TPU environment
requires that no module touches a backend at import time (a dead TPU hangs,
never raises).  Reviewer memory does not scale to those contracts -- this
package checks them structurally:

  FLD  ordered-fold rule: unordered reductions (jnp.sum / lax.psum /
       segment_sum / functools.reduce / array .sum()) are findings inside
       the numeric modules unless escaped with
       `# spgemm-lint: fld-proof(<reason>)` (the proof-gated MXU / no_mod
       routes).
  KNB  knob rule: every SPGEMM_TPU_* environment read must go through the
       central registry (spgemm_tpu/utils/knobs.py); raw os.environ /
       os.getenv reads are findings.
  BKD  backend rule: no module-import-time jax.devices()/backend-touching
       calls outside utils/backend_probe.py.
  DOC  drift rule: the CLAUDE.md knob table and the CLI help must cover
       exactly the registry's knobs (generated-vs-committed diff is a
       finding).

Run `python -m spgemm_tpu.analysis [--json]` (or `make lint`); the repo
self-lints in tier-1 (tests/test_lint.py).
"""

from spgemm_tpu.analysis.core import (Finding, is_numeric_module, lint_file,
                                      lint_paths, lint_repo, repo_root)
from spgemm_tpu.analysis.docrules import (KNOB_TABLE_BEGIN, KNOB_TABLE_END,
                                          check_claude_md, check_cli_help)

__all__ = [
    "Finding", "lint_file", "lint_paths", "lint_repo", "repo_root",
    "is_numeric_module", "check_claude_md", "check_cli_help",
    "KNOB_TABLE_BEGIN", "KNOB_TABLE_END",
]
