"""PRO / EVT / DRF: wire-contract and registry-drift enforcement.

PRO -- the serve wire protocol's declarative registry
(serve/protocol.py: ENVELOPE_FIELDS / REQUEST_FIELDS / RESPONSE_FIELDS
/ ERROR_CODES) is binding at every call site that speaks the protocol
(any module importing serve.protocol or serve.client):

  * a literal field key read from a request (`msg.get("...")`,
    `msg["..."]`) or read from / written into a response
    (`protocol.ok(field=...)`, `protocol.error(code, msg, field=...)`,
    `resp[...]` / `resp.get(...)`, subscripts of a direct
    `request(...)` call) must be declared for the op in play.  Op
    context resolves from the enclosing function (the daemon's
    `_op_<name>` handlers) and from `{"op": "..."}` dict literals in
    the same scope; with no context the union of every op's table
    applies (cross-op helpers stay checkable without false positives);
  * an `{"op": ...}` dict literal must name a declared op, and its
    literal keys must be declared request fields FOR that op;
  * every structured-error code raised (`protocol.error` /
    `ProtocolError` / `ServeError` first argument) or compared
    (`....code == "..."`, `["code"] in (...)`) must be a declared
    ERROR_CODES value, and a `protocol.E_*` attribute must name a
    declared constant;
  * a dict literal stamping a hardcoded integer `"v"` is a
    rolling-upgrade hazard: version stamping belongs to
    protocol.version_for() over the derived FIELD_MIN_VERSION table;
  * package level (check_pro_registry, self-gated on protocol.py being
    in the linted unit set): the tables themselves must cohere --
    request/response op sets agree, min versions sit within
    1..PROTOCOL_VERSION, a field spelled in several request ops
    carries ONE min version (FIELD_MIN_VERSION flattens by name), every
    post-v1 request field lands in FIELD_MIN_VERSION (the
    rolling-upgrade-hazard half), and the E_* constants match
    ERROR_CODES both ways.

EVT -- the MET discipline applied to the structured event log: every
`emit(...)` / `LOG.emit(...)` kind (import-alias-resolved receivers of
obs/events: the module, its LOG singleton, or the bare emit function)
must be a string literal declared in events.EVENT_KINDS.

DRF -- the reverse audit over the whole unit set (escapable with
`# spgemm-lint: drf-ok(<reason>)` at the registry declaration line,
SUP-inventoried): a declared knob never read through knobs.get(), an
ENGINE phase/counter or metric family never referenced, an event kind
never emitted, or a protocol field / error code never referenced
anywhere in the package is dead registry surface -- the operator can
name it, the engine never honors it.  Each sub-audit self-gates on its
registry module being in the linted unit set, so fixture runs over
partial trees stay quiet.  Failpoints are deliberately NOT re-audited
here: FPT already owns that registry's stale direction, and one
finding per drift keeps escapes unambiguous.
"""

from __future__ import annotations

import ast

from spgemm_tpu.analysis.core import Finding
from spgemm_tpu.analysis.rules import dotted_name
from spgemm_tpu.obs.events import EVENT_KINDS
from spgemm_tpu.obs.metrics import ENGINE_COUNTERS, ENGINE_PHASES
from spgemm_tpu.obs.metrics import REGISTRY as METRIC_REGISTRY
from spgemm_tpu.serve import protocol
from spgemm_tpu.utils.knobs import REGISTRY as KNOB_REGISTRY

PROTOCOL_SUFFIX = "/serve/protocol.py"
EVENTS_SUFFIX = "/obs/events.py"
KNOBS_SUFFIX = "/utils/knobs.py"
METRICS_SUFFIX = "/obs/metrics.py"

# the wire-variable naming convention the serve code already follows:
# requests travel as `msg`, responses as `resp` (plus direct subscripts
# of a `request(...)` call); other receiver names are out of scope --
# unauditable, and renaming a wire dict away from the convention is
# exactly the obscurity the rule exists to prevent
_REQUEST_NAMES = frozenset({"msg"})
_RESPONSE_NAMES = frozenset({"resp"})

_ENVELOPE = frozenset(protocol.ENVELOPE_FIELDS)
_ALL_REQUEST = frozenset(
    f for fields in protocol.REQUEST_FIELDS.values() for f in fields)
_ALL_RESPONSE = frozenset(
    f for fields in protocol.RESPONSE_FIELDS.values() for f in fields)
_CODES = frozenset(protocol.ERROR_CODES)
_E_NAMES = frozenset(
    n for n in dir(protocol)
    if n.startswith("E_") and isinstance(getattr(protocol, n), str))


def _str(node) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ------------------------------------------------------------ PRO -----
def _protocol_imports(tree: ast.AST):
    """(dotted spellings of the protocol module, {local name: 'ok' |
    'error'} for functions imported from it, True iff serve.client is
    imported).  Any of them puts the module in PRO scope."""
    modules: set[str] = set()
    funcs: dict[str, str] = {}
    client_imported = False
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.endswith("serve.protocol"):
                for alias in node.names:
                    if alias.name in ("ok", "error"):
                        funcs[alias.asname or alias.name] = alias.name
            elif mod == "serve" or mod.endswith(".serve"):
                for alias in node.names:
                    if alias.name == "protocol":
                        modules.add(alias.asname or alias.name)
                    elif alias.name == "client":
                        client_imported = True
            elif mod.endswith("serve.client"):
                client_imported = True
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.endswith("serve.protocol"):
                    modules.add(alias.asname or alias.name)
                elif alias.name.endswith("serve.client"):
                    client_imported = True
    return modules, funcs, client_imported


def _scope_roots(tree: ast.AST) -> list[ast.AST]:
    """Top-level functions and methods (class bodies included, nested
    defs excluded -- they share the enclosing root's op context)."""
    roots: list[ast.AST] = []

    def collect(body):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                roots.append(node)
            elif isinstance(node, ast.ClassDef):
                collect(node.body)

    collect(tree.body)
    return roots


def _context_ops(fn) -> set[str]:
    """The ops a scope provably speaks: its `_op_<name>` handler name
    plus every literal `{"op": "..."}` it builds."""
    ops: set[str] = set()
    if fn.name.startswith("_op_") and fn.name[4:] in protocol.OPS:
        ops.add(fn.name[4:])
    for node in ast.walk(fn):
        if isinstance(node, ast.Dict):
            for key, value in zip(node.keys, node.values):
                if _str(key) == "op" and _str(value) in protocol.OPS:
                    ops.add(_str(value))
    return ops


def _request_allowed(ops: set[str]) -> frozenset:
    if not ops:
        return _ALL_REQUEST | _ENVELOPE
    out = set(_ENVELOPE)
    for op in ops:
        out |= set(protocol.REQUEST_FIELDS.get(op, {}))
    return frozenset(out)


def _response_allowed(ops: set[str]) -> frozenset:
    if not ops:
        return _ALL_RESPONSE | _ENVELOPE
    out = set(_ENVELOPE)
    for op in ops:
        out |= set(protocol.RESPONSE_FIELDS.get(op, {}))
    return frozenset(out)


def _is_request_call(node) -> bool:
    """A direct `request(...)` / `x.request(...)` call -- its value IS a
    wire response, whatever it gets bound to."""
    if not isinstance(node, ast.Call):
        return False
    name = dotted_name(node.func)
    return name is not None and name.split(".")[-1] == "request"


def _wire_key_access(node):
    """('request'|'response', key node) for a literal field access on a
    conventional wire dict, else None: `msg.get("k")` / `msg["k"]` on
    the request side, `resp.get("k")` / `resp["k"]` /
    `request(...)["k"]` on the response side (reads and writes both --
    the client builds requests by subscript assignment)."""
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute) \
            and node.func.attr == "get" and node.args:
        recv = node.func.value
        if isinstance(recv, ast.Name) and recv.id in _REQUEST_NAMES:
            return "request", node.args[0]
        if (isinstance(recv, ast.Name) and recv.id in _RESPONSE_NAMES) \
                or _is_request_call(recv):
            return "response", node.args[0]
    elif isinstance(node, ast.Subscript):
        recv = node.value
        if isinstance(recv, ast.Name) and recv.id in _REQUEST_NAMES:
            return "request", node.slice
        if (isinstance(recv, ast.Name) and recv.id in _RESPONSE_NAMES) \
                or _is_request_call(recv):
            return "response", node.slice
    return None


def _code_flavored(node) -> bool:
    """An expression that reads a structured error code: `x.code`,
    `...["code"]`, or `....get("code")`."""
    if isinstance(node, ast.Attribute) and node.attr == "code":
        return True
    if isinstance(node, ast.Subscript) and _str(node.slice) == "code":
        return True
    return (isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "get" and node.args
            and _str(node.args[0]) == "code")


def _check_pro_nodes(nodes, ops: set[str], file: str,
                     modules: set[str], funcs: dict[str, str],
                     findings: list[Finding]) -> None:
    req_allowed = _request_allowed(ops)
    resp_allowed = _response_allowed(ops)
    ctx = "/".join(sorted(ops)) if ops \
        else "any op (no op context in this scope)"
    tables = "serve/protocol.py REQUEST_FIELDS/RESPONSE_FIELDS"

    def field_finding(line, direction, key):
        table = "REQUEST_FIELDS" if direction == "request" \
            else "RESPONSE_FIELDS"
        findings.append(Finding(
            file, line, "PRO",
            f"undeclared wire {direction} field {key!r} for op {ctx}: "
            f"declare it in serve/protocol.py {table} (with its min "
            "protocol version) so the wire contract, version "
            "negotiation, and the generated ARCHITECTURE.md protocol "
            "table stay in sync"))

    def code_check(line, node):
        code = _str(node)
        if code is not None and code not in _CODES:
            findings.append(Finding(
                file, line, "PRO",
                f"undeclared error code {code!r}: every structured-"
                "error code raised or compared must be a declared "
                "serve/protocol.py ERROR_CODES value (use the E_* "
                "constant)"))

    for node in nodes:
        access = _wire_key_access(node)
        if access is not None:
            direction, key_node = access
            key = _str(key_node)
            allowed = req_allowed if direction == "request" \
                else resp_allowed
            if key is not None and key not in allowed:
                field_finding(node.lineno, direction, key)
            continue
        if isinstance(node, ast.Dict):
            keys = {_str(k): v for k, v in zip(node.keys, node.values)
                    if _str(k) is not None}
            if "op" not in keys and "v" not in keys:
                continue  # not a wire-message literal
            if "v" in keys and isinstance(keys["v"], ast.Constant) \
                    and isinstance(keys["v"].value, int):
                findings.append(Finding(
                    file, node.lineno, "PRO",
                    "hardcoded protocol version in a message literal: "
                    "stamp protocol.version_for(msg) (the "
                    "FIELD_MIN_VERSION capability table) so rolling "
                    "upgrades keep negotiating instead of pinning a "
                    "version a peer may not speak"))
            if "op" not in keys:
                continue
            op = _str(keys["op"])
            if op is None:
                continue  # computed op: runtime-validated by the daemon
            if op not in protocol.OPS:
                findings.append(Finding(
                    file, node.lineno, "PRO",
                    f"unknown op {op!r} in a wire-message literal "
                    f"(declared ops: {', '.join(protocol.OPS)})"))
                continue
            op_fields = (set(protocol.REQUEST_FIELDS[op]) | _ENVELOPE)
            for key in keys:
                if key not in op_fields:
                    findings.append(Finding(
                        file, node.lineno, "PRO",
                        f"undeclared wire request field {key!r} for op "
                        f"{op!r}: declare it in serve/protocol.py "
                        "REQUEST_FIELDS (with its min protocol version) "
                        "-- an undeclared field never negotiates and an "
                        "older daemon silently drops it"))
            continue
        if isinstance(node, ast.Call):
            f = node.func
            kind = None
            if isinstance(f, ast.Attribute) and f.attr in ("ok", "error") \
                    and dotted_name(f.value) in modules:
                kind = f.attr
            elif isinstance(f, ast.Name) and f.id in funcs:
                kind = funcs[f.id]
            if kind is not None:
                if kind == "error" and node.args:
                    code_check(node.lineno, node.args[0])
                for kw in node.keywords:
                    if kw.arg is not None and kw.arg not in resp_allowed:
                        field_finding(node.lineno, "response", kw.arg)
                continue
            ctor = dotted_name(f)
            if ctor is not None and ctor.split(".")[-1] in (
                    "ProtocolError", "ServeError") and node.args:
                code_check(node.lineno, node.args[0])
            continue
        if isinstance(node, ast.Attribute) and node.attr.startswith("E_") \
                and dotted_name(node.value) in modules:
            if node.attr not in _E_NAMES:
                findings.append(Finding(
                    file, node.lineno, "PRO",
                    f"undeclared error-code constant protocol."
                    f"{node.attr}: declare it (and its code value) in "
                    "serve/protocol.py ERROR_CODES"))
            continue
        if isinstance(node, ast.Compare) and any(
                isinstance(o, (ast.Eq, ast.NotEq, ast.In, ast.NotIn))
                for o in node.ops):
            sides = [node.left, *node.comparators]
            if not any(_code_flavored(s) for s in sides):
                continue
            for side in sides:
                candidates = side.elts if isinstance(
                    side, (ast.Tuple, ast.List, ast.Set)) else [side]
                for cand in candidates:
                    code_check(node.lineno, cand)


def check_pro(tree: ast.AST, file: str) -> list[Finding]:
    """PRO over one module: wire field / op / error-code literals at
    every call site that speaks the serve protocol."""
    modules, funcs, client_imported = _protocol_imports(tree)
    if not modules and not funcs and not client_imported:
        return []
    findings: list[Finding] = []
    covered: set[int] = set()
    for fn in _scope_roots(tree):
        nodes = list(ast.walk(fn))
        covered.update(id(n) for n in nodes)
        _check_pro_nodes(nodes, _context_ops(fn), file, modules, funcs,
                         findings)
    module_nodes = [n for n in ast.walk(tree) if id(n) not in covered]
    _check_pro_nodes(module_nodes, set(), file, modules, funcs, findings)
    return findings


def _registry_unit(units, suffix):
    return next((u for u in units
                 if u.path.replace("\\", "/").endswith(suffix)
                 and u.tree is not None), None)


def _decl_line(source: str, name: str) -> int:
    """The first source line spelling `name` as a quoted literal (the
    registry declaration anchor; 1 when not found)."""
    return next((i + 1 for i, text in enumerate(source.splitlines())
                 if f'"{name}"' in text or f"'{name}'" in text), 1)


def check_pro_registry(units) -> list[Finding]:
    """The registry-coherence half of PRO, over serve/protocol.py itself
    (self-gated like the FPT stale-entry pass)."""
    unit = _registry_unit(units, PROTOCOL_SUFFIX)
    if unit is None:
        return []
    findings: list[Finding] = []

    def at(name: str) -> int:
        return _decl_line(unit.source, name)

    for op in sorted(set(protocol.REQUEST_FIELDS)
                     ^ set(protocol.RESPONSE_FIELDS)):
        findings.append(Finding(
            unit.file, at(op), "PRO",
            f"op {op!r} is declared in only one of REQUEST_FIELDS/"
            "RESPONSE_FIELDS: every op needs both halves of its wire "
            "contract (an empty dict is an explicit 'no fields')"))
    for table_name, table in (
            ("REQUEST_FIELDS", protocol.REQUEST_FIELDS),
            ("RESPONSE_FIELDS", protocol.RESPONSE_FIELDS)):
        for op, fields in table.items():
            for fname, ver in fields.items():
                if not (isinstance(ver, int)
                        and 1 <= ver <= protocol.PROTOCOL_VERSION):
                    findings.append(Finding(
                        unit.file, at(fname), "PRO",
                        f"{table_name}[{op!r}][{fname!r}] min version "
                        f"{ver!r} is outside 1..PROTOCOL_VERSION "
                        f"({protocol.PROTOCOL_VERSION})"))
    flat: dict[str, int] = {}
    for op, fields in protocol.REQUEST_FIELDS.items():
        for fname, ver in fields.items():
            if fname in flat and flat[fname] != ver:
                findings.append(Finding(
                    unit.file, at(fname), "PRO",
                    f"request field {fname!r} carries two min versions "
                    f"({flat[fname]} and {ver}) across ops: "
                    "FIELD_MIN_VERSION flattens by field name, so one "
                    "name must mean one version everywhere"))
            flat[fname] = ver
            if ver > 1 and protocol.FIELD_MIN_VERSION.get(fname) != ver:
                findings.append(Finding(
                    unit.file, at(fname), "PRO",
                    f"rolling-upgrade hazard: post-v1 request field "
                    f"{fname!r} (v{ver}+) is missing from "
                    "FIELD_MIN_VERSION -- version_for() would stamp a "
                    "version too low to carry it and an older daemon "
                    "would silently drop it"))
    const_values = {getattr(protocol, n) for n in _E_NAMES}
    for code in sorted(_CODES - const_values):
        findings.append(Finding(
            unit.file, at(code), "PRO",
            f"ERROR_CODES entry {code!r} has no E_* constant: call "
            "sites spell codes through the constants, so an entry "
            "without one is unreachable by construction"))
    for n in sorted(_E_NAMES):
        if getattr(protocol, n) not in _CODES:
            findings.append(Finding(
                unit.file, at(getattr(protocol, n)), "PRO",
                f"constant {n} = {getattr(protocol, n)!r} is not a "
                "declared ERROR_CODES entry: the registry is the one "
                "source for the code set and its docs"))
    return findings


def wire_literals(tree: ast.AST) -> tuple[set[str], set[str]]:
    """(field names, error-code values) one module references -- the
    DRF protocol sub-audit's per-unit contribution.  Scope-gated like
    check_pro; E_* attribute references count as their code values."""
    modules, funcs, client_imported = _protocol_imports(tree)
    fields: set[str] = set()
    codes: set[str] = set()
    if not modules and not funcs and not client_imported:
        return fields, codes
    for node in ast.walk(tree):
        access = _wire_key_access(node)
        if access is not None:
            key = _str(access[1])
            if key is not None:
                fields.add(key)
            continue
        if isinstance(node, ast.Dict):
            keys = [_str(k) for k in node.keys]
            if "op" in keys or "v" in keys:
                fields.update(k for k in keys if k is not None)
            continue
        if isinstance(node, ast.Call):
            f = node.func
            kind = None
            if isinstance(f, ast.Attribute) and f.attr in ("ok", "error") \
                    and dotted_name(f.value) in modules:
                kind = f.attr
            elif isinstance(f, ast.Name) and f.id in funcs:
                kind = funcs[f.id]
            if kind is not None:
                fields.update(kw.arg for kw in node.keywords
                              if kw.arg is not None)
                if kind == "error" and node.args \
                        and _str(node.args[0]) is not None:
                    codes.add(_str(node.args[0]))
                continue
            ctor = dotted_name(f)
            if ctor is not None and ctor.split(".")[-1] in (
                    "ProtocolError", "ServeError") and node.args \
                    and _str(node.args[0]) is not None:
                codes.add(_str(node.args[0]))
            continue
        if isinstance(node, ast.Attribute) and node.attr in _E_NAMES \
                and dotted_name(node.value) in modules:
            codes.add(getattr(protocol, node.attr))
            continue
        if isinstance(node, ast.Compare):
            sides = [node.left, *node.comparators]
            if not any(_code_flavored(s) for s in sides):
                continue
            for side in sides:
                candidates = side.elts if isinstance(
                    side, (ast.Tuple, ast.List, ast.Set)) else [side]
                codes.update(c for c in map(_str, candidates)
                             if c is not None)
    return fields, codes


# ------------------------------------------------------------ EVT -----
def _event_receivers(tree: ast.AST):
    """(dotted spellings of the events module, dotted spellings of its
    LOG singleton, bare names of the imported emit function)."""
    modules: set[str] = set()
    logs: set[str] = set()
    funcs: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.endswith("obs.events"):
                for alias in node.names:
                    if alias.name == "emit":
                        funcs.add(alias.asname or alias.name)
                    elif alias.name == "LOG":
                        logs.add(alias.asname or alias.name)
            elif mod == "obs" or mod.endswith(".obs"):
                for alias in node.names:
                    if alias.name == "events":
                        modules.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.endswith("obs.events"):
                    modules.add(alias.asname or alias.name)
    return modules, logs, funcs


def _emit_calls(tree: ast.AST):
    """Yield (call node, kind argument node) for every event-log emit
    in the module (module alias, LOG singleton, or bare imported emit;
    locally-defined emit helpers never resolve -- receiver resolution
    is import-gated, the MET discipline)."""
    modules, logs, funcs = _event_receivers(tree)
    if not modules and not logs and not funcs:
        return
    log_spellings = logs | {f"{m}.LOG" for m in modules}
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Attribute) and f.attr == "emit":
            recv = dotted_name(f.value)
            if recv not in modules and recv not in log_spellings:
                continue
        elif not (isinstance(f, ast.Name) and f.id in funcs):
            continue
        arg = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords if kw.arg == "kind"), None)
        yield node, arg


def check_evt(tree: ast.AST, file: str) -> list[Finding]:
    """EVT over one module: undeclared or non-literal event kinds."""
    findings: list[Finding] = []
    for node, arg in _emit_calls(tree):
        if arg is None:
            continue
        kind = _str(arg)
        if kind is None:
            findings.append(Finding(
                file, node.lineno, "EVT",
                "event kind must be a string literal declared in "
                "obs/events.EVENT_KINDS: a computed kind mints an "
                "unauditable event stream no dashboard or postmortem "
                "tooling knows about"))
        elif kind not in EVENT_KINDS:
            findings.append(Finding(
                file, node.lineno, "EVT",
                f"undeclared event kind {kind!r} in emit(): declare it "
                "in obs/events.EVENT_KINDS (spgemm_tpu/obs/events.py) "
                "so the event log, the DRF drift audit, and the "
                "generated ARCHITECTURE.md event table stay in sync"))
    return findings


def emit_kind_literals(tree: ast.AST) -> set[str]:
    """The string-literal event kinds one module emits (the DRF event
    sub-audit's per-unit contribution)."""
    kinds: set[str] = set()
    for _, arg in _emit_calls(tree):
        kind = _str(arg)
        if kind is not None:
            kinds.add(kind)
    return kinds


# ------------------------------------------------------------ DRF -----
def _knob_read_literals(tree: ast.AST) -> set[str]:
    """The knob names one module reads through the registry accessors
    (knobs.get / knobs.pin, module- or function-imported)."""
    modules: set[str] = set()
    funcs: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            mod = node.module or ""
            if mod.endswith("utils.knobs"):
                for alias in node.names:
                    if alias.name in ("get", "pin"):
                        funcs.add(alias.asname or alias.name)
            elif mod == "utils" or mod.endswith(".utils"):
                for alias in node.names:
                    if alias.name == "knobs":
                        modules.add(alias.asname or alias.name)
        elif isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.endswith("utils.knobs"):
                    modules.add(alias.asname or alias.name)
    names: set[str] = set()
    if not modules and not funcs:
        return names
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        hit = (isinstance(f, ast.Attribute) and f.attr in ("get", "pin")
               and dotted_name(f.value) in modules) \
            or (isinstance(f, ast.Name) and f.id in funcs)
        if not hit or not node.args:
            continue
        name = _str(node.args[0])
        if name is not None:
            names.add(name)
    return names


def _engine_name_literals(tree: ast.AST) -> tuple[set[str], set[str]]:
    """(phase/record names, incr names) one module spells at ENGINE
    call sites -- the metrules receiver resolution, reference-collection
    direction."""
    from spgemm_tpu.analysis.metrules import _engine_receivers  # noqa: PLC0415

    receivers = _engine_receivers(tree)
    phases: set[str] = set()
    counters: set[str] = set()
    if not receivers:
        return phases, counters
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("phase", "record", "incr")
                and dotted_name(node.func.value) in receivers):
            continue
        arg = node.args[0] if node.args else next(
            (kw.value for kw in node.keywords if kw.arg == "name"), None)
        name = _str(arg)
        if name is None:
            continue
        (counters if node.func.attr == "incr" else phases).add(name)
    return phases, counters


def _string_constants(tree: ast.AST, exclude_assigns: tuple[str, ...] = ()
                      ) -> set[str]:
    """Every string constant in the module EXCEPT docstrings and the
    subtrees of the named top-level assignments (a registry's own
    declaration block must not count as a reference to itself)."""
    excluded: set[int] = set()
    for node in ast.walk(tree):
        # docstrings: the leading Expr-of-Constant of any body
        if isinstance(node, (ast.Module, ast.ClassDef, ast.FunctionDef,
                             ast.AsyncFunctionDef)):
            body = node.body
            if body and isinstance(body[0], ast.Expr) and isinstance(
                    body[0].value, ast.Constant):
                excluded.update(id(n) for n in ast.walk(body[0]))
        if isinstance(node, (ast.Assign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            names = {t.id for t in targets if isinstance(t, ast.Name)}
            if names & set(exclude_assigns):
                excluded.update(id(n) for n in ast.walk(node))
    out: set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and id(node) not in excluded:
            out.add(node.value)
    return out


def check_drf(units) -> list[Finding]:
    """The registry-drift audit (RAW findings -- core applies the
    drf-ok escape filter): declared-but-never-referenced entries of the
    knob, metric, event-kind, and protocol registries, each sub-audit
    gated on its registry module being in the unit set and anchored at
    the entry's declaration line."""
    findings: list[Finding] = []
    live = [u for u in units if u.tree is not None]

    knobs_unit = _registry_unit(units, KNOBS_SUFFIX)
    if knobs_unit is not None:
        read: set[str] = set()
        for u in live:
            if u is not knobs_unit:
                read |= _knob_read_literals(u.tree)
        for name in sorted(set(KNOB_REGISTRY) - read):
            findings.append(Finding(
                knobs_unit.file, _decl_line(knobs_unit.source, name),
                "DRF",
                f"declared knob {name} is never read through "
                "knobs.get() anywhere in the package: dead registry "
                "surface (setting it changes nothing) -- wire the "
                "reader, delete the entry, or escape with "
                "drf-ok(<reason>) if it is read outside Python"))

    metrics_unit = _registry_unit(units, METRICS_SUFFIX)
    if metrics_unit is not None:
        phases: set[str] = set()
        counters: set[str] = set()
        strings: set[str] = set()
        for u in live:
            ph, ct = _engine_name_literals(u.tree)
            phases |= ph
            counters |= ct
            if u is metrics_unit:
                strings |= _string_constants(
                    u.tree, ("_METRICS", "ENGINE_PHASES",
                             "ENGINE_COUNTERS"))
            else:
                strings |= _string_constants(u.tree)
        for name in sorted(set(ENGINE_PHASES) - phases - strings):
            findings.append(Finding(
                metrics_unit.file,
                _decl_line(metrics_unit.source, name), "DRF",
                f"declared ENGINE phase {name!r} has no ENGINE.phase/"
                "record site anywhere in the package: a time series "
                "that can never move -- wire the site or delete the "
                "entry (escape: drf-ok(<reason>))"))
        for name in sorted(set(ENGINE_COUNTERS) - counters - strings):
            findings.append(Finding(
                metrics_unit.file,
                _decl_line(metrics_unit.source, name), "DRF",
                f"declared ENGINE counter {name!r} has no ENGINE.incr "
                "site anywhere in the package: a counter that can "
                "never move -- wire the site or delete the entry "
                "(escape: drf-ok(<reason>))"))
        for name in sorted(set(METRIC_REGISTRY) - strings):
            findings.append(Finding(
                metrics_unit.file,
                _decl_line(metrics_unit.source, name), "DRF",
                f"declared metric family {name!r} is never referenced "
                "outside its registry entry: nothing renders it, so "
                "the scrape can never carry it -- wire the emitter or "
                "delete the entry (escape: drf-ok(<reason>))"))

    events_unit = _registry_unit(units, EVENTS_SUFFIX)
    if events_unit is not None:
        emitted: set[str] = set()
        for u in live:
            if u is not events_unit:
                emitted |= emit_kind_literals(u.tree)
        for name in sorted(set(EVENT_KINDS) - emitted):
            findings.append(Finding(
                events_unit.file,
                _decl_line(events_unit.source, name), "DRF",
                f"declared event kind {name!r} is never emitted "
                "anywhere in the package: dead event surface -- wire "
                "the emit site or delete the entry (escape: "
                "drf-ok(<reason>))"))

    protocol_unit = _registry_unit(units, PROTOCOL_SUFFIX)
    if protocol_unit is not None:
        fields: set[str] = set()
        codes: set[str] = set()
        for u in live:
            if u is protocol_unit:
                continue
            fl, cd = wire_literals(u.tree)
            fields |= fl
            codes |= cd
        declared_fields: dict[str, str] = {}
        for op in protocol.OPS:
            for fname in protocol.REQUEST_FIELDS[op]:
                declared_fields.setdefault(fname, f"op {op!r} request")
            for fname in protocol.RESPONSE_FIELDS[op]:
                declared_fields.setdefault(fname, f"op {op!r} response")
        for fname in protocol.ENVELOPE_FIELDS:
            declared_fields.setdefault(fname, "envelope")
        for fname in sorted(set(declared_fields) - fields):
            findings.append(Finding(
                protocol_unit.file,
                _decl_line(protocol_unit.source, fname), "DRF",
                f"declared wire field {fname!r} "
                f"({declared_fields[fname]}) is never referenced at "
                "any call site: dead wire surface -- wire the "
                "reader/writer or delete the entry (escape: "
                "drf-ok(<reason>))"))
        for code in sorted(_CODES - codes):
            findings.append(Finding(
                protocol_unit.file,
                _decl_line(protocol_unit.source, code), "DRF",
                f"declared error code {code!r} is never raised or "
                "compared at any call site: dead error surface -- "
                "wire the site or delete the entry (escape: "
                "drf-ok(<reason>))"))
    return findings
