"""Whole-program concurrency soundness: LCK / BLK / TSI.

PRs 12-13 multiplied spgemmd's thread population (per-slice executors,
watchdog, recovery probes, drain handlers, the event-log writer, the
plan-ahead and OOC workers) and the THR rule only protects attributes
someone remembered to annotate; nothing checked lock ACQUISITION ORDER or
what runs WHILE a lock is held -- exactly the hang/deadlock class the
chaos harness (PR 13) can only probe dynamically, one seed at a time.
This pass closes it statically, over the same jax-free call graph the
interprocedural FLD taint uses (analysis/callgraph.py):

  LCK  lock-order deadlock detection.  Every `with <lock>:` on a
       REGISTERED lock (an attribute/global assigned threading.Lock /
       RLock / Condition / Semaphore; Condition(lock) aliases its lock,
       like THR) is an acquisition; an acquisition while another
       registered lock is held -- directly nested, or transitively
       through resolved call edges -- is an order edge.  A cycle in the
       order graph is a potential deadlock, reported with the witness
       chains that acquire the locks in opposite orders; a SELF-edge
       (re-acquiring a lock already held) is the non-reentrant
       threading.Lock self-deadlock -- RLock is exempt from the
       self-edge (same-thread re-entry is its documented use-case) but
       still participates in order cycles.  Lock identity is per class
       attribute / module global (two instances of one class share a
       node -- the deliberate over-approximation every static lock-order
       tool makes).  Escape: `# spgemm-lint: lck-ok(<reason>)` on the
       finding's anchor line.

  BLK  blocking-under-lock.  A blocking operation -- time.sleep,
       subprocess.run/call/check_*, fcntl.flock, os.fsync,
       select.select, socket accept/recv/sendall, jax
       block_until_ready, and (via the registered-resource map)
       Queue.get/put, Thread.join, Event/Condition.wait and
       Lock/Semaphore.acquire -- reached while a registered lock is held
       is a finding with the witness chain down to the blocking call.
       `Condition.wait` is exempt for the condition's OWN lock (wait
       releases it); every OTHER held lock stays held across the wait
       and counts.  Plain file read/write is deliberately NOT in the set
       (the journal writes under the daemon lock are the durability
       contract); fsync/flock are.  Escape:
       `# spgemm-lint: blk-ok(<reason>)` -- on the blocking line itself
       (a source escape: callers stop seeing the op, like fld-proof at a
       reduction) or on the call site the finding lands on.

  TSI  thread-shared inference -- THR's opt-in hole, closed.  Functions
       passed to `threading.Thread(target=...)` (including through the
       repo's loop-over-(target, name)-tuples spelling, and including
       NESTED defs, which get their own records -- a closure spawned
       from `__init__` does not inherit its happens-before-publication
       write exemption) are THREAD ROOTS; a root spawned inside a loop
       that does not rebind the target, or from >= 2 distinct sites,
       is MULTI-INSTANCE and counts as two threads by itself (the
       accept loop's per-connection handler).  An instance attribute or
       module global WRITTEN (outside `__init__`) from functions
       reached by >= 2 root-weighted threads without a
       `# spgemm-lint: guarded-by(<lock>)` annotation is a finding: the
       state is demonstrably multi-thread-written, so it must either be
       annotated (and THR then enforces the lock) or carry a reasoned
       `# spgemm-lint: tsi-ok(<reason>)` on the write line (the
       single-writer-handoff argument, made reviewable).  Registered
       synchronization resources themselves are exempt.

Resolution is the call graph's name-based trade (spelled forms resolve;
attribute calls on arbitrary objects do not), extended with module-level
singleton instances (`ENGINE = PhaseTimers()`) and class instantiation
(`Cls(...)` -> `Cls.__init__`) so the process-wide registries' locks are
visible through their real spellings.  Everything is stdlib ast -- no
imports execute, no environment is read.

The thread-inventory table in ARCHITECTURE.md (between the
`<!-- thread-inventory:begin/end -->` markers) is GENERATED from this
pass over the default lint scope -- root function, spawner, locks it may
hold, shared attrs it writes -- and held current by the DOC rule exactly
like the knob and metrics tables.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from spgemm_tpu.analysis import callgraph
from spgemm_tpu.analysis.core import Finding, LintUnit
from spgemm_tpu.analysis.core import escape_at as core_escape_at
from spgemm_tpu.analysis.rules import dotted_name
from spgemm_tpu.analysis.thrrules import (_guard_annotations,
                                          guard_on_assignment)

# ------------------------------------------------ registered resources ----
# factory last-name -> resource kind (namespace-agnostic, like the THR
# Condition detection: `threading.Lock()`, `Lock()` after a from-import,
# and `mp.Lock()` all register)
_FACTORY_KINDS = {
    "Lock": "lock", "RLock": "rlock",
    "Condition": "cond",
    "Semaphore": "sem", "BoundedSemaphore": "sem",
    "Event": "event",
    "Queue": "queue", "LifoQueue": "queue", "PriorityQueue": "queue",
    "SimpleQueue": "queue",
    "Thread": "thread",
    "socket": "socket",
    # threading.local(): per-thread by construction -- registered so
    # TSI exempts writes through it like the other sync resources
    "local": "tlocal",
}
# `with <x>:` acquires these; rlock participates in ORDER edges (an
# RLock in a cycle deadlocks like any lock) but is exempt from the
# self-edge finding (same-thread re-acquisition is its documented
# use-case, never a deadlock)
_ACQUIRABLE = ("lock", "rlock", "cond", "sem")

# always-blocking calls by exact spelled name
BLOCKING_CALLS = {
    "time.sleep",
    "subprocess.run", "subprocess.call", "subprocess.check_call",
    "subprocess.check_output",
    "fcntl.flock",
    "os.fsync",
    "select.select",
}
# blocking method names on ANY base object (unambiguous spellings only:
# `.get`/`.put`/`.join`/`.wait`/`.acquire` need a typed base -- dict.get,
# str.join and os.path.join would drown the rule in false positives)
BLOCKING_METHODS = {"accept", "recv", "recv_into", "recvfrom", "sendall",
                    "block_until_ready"}
# blocking methods gated on the base resolving to a registered resource
_TYPED_BLOCKING = {
    "queue": {"get", "put"},
    "thread": {"join"},
    "event": {"wait"},
    "cond": {"wait"},
    "lock": {"acquire"},
    "rlock": {"acquire"},
    "sem": {"acquire"},
    "socket": {"accept", "recv", "sendall", "connect"},
}


@dataclass
class _Resource:
    kind: str
    alias: str | None = None   # Condition(lock): the aliased lock attr


@dataclass
class _FnInfo:
    """Per-function concurrency facts.  Named nested defs get their own
    records (labeled `outer.name`) so they can be thread roots and keep
    their own write/escape context; lambdas fold into the outer record
    with held locks reset."""

    module: str
    label: str
    file: str
    # (line, lock id, held-before tuple)
    acquisitions: list = field(default_factory=list)
    # (line, spelled name, enclosing class, held tuple)
    calls: list = field(default_factory=list)
    # (line, op spelling, effective-held tuple, escape line | None,
    #  released lock id | None)
    blocks: list = field(default_factory=list)
    # (line, attr key, escaped) -- shared-state writes outside __init__
    writes: list = field(default_factory=list)


@dataclass
class _RootSite:
    """One resolved-or-not thread-entry reference: the spelled target of
    a threading.Thread(...) call, with the spawning function."""

    spelled: str
    cls: str | None
    spawner: str        # label of the function creating the thread
    file: str
    line: int
    # pre-resolved intra-module label (a NESTED def passed as target:
    # the call graph cannot name it, the walker that saw the def can)
    label: str | None = None
    # the spawn sits inside a loop whose iteration does not rebind the
    # target: the SAME function runs on many threads (the accept loop's
    # per-connection handler), so one root already means >= 2 threads
    multi: bool = False


class _ModInfo:
    """One module's resource registry + per-function facts."""

    def __init__(self, unit: LintUnit, module: str):
        self.unit = unit
        self.module = module
        self.file = unit.file
        self.class_res: dict[str, dict[str, _Resource]] = {}
        self.module_res: dict[str, _Resource] = {}
        self.module_globals: set[str] = set()
        # import aliases: local name -> canonical dotted prefix, so
        # `from time import sleep` / `import subprocess as sp` still
        # hit the always-blocking set (BLOCKING_CALLS stores canonical
        # spellings)
        self.aliases: dict[str, str] = {}
        self.fns: dict[str, _FnInfo] = {}
        self.roots: list[_RootSite] = []
        # guard-annotated attr names, per scope (class name or None for
        # module globals) -- TSI skips them (THR owns annotated state)
        self.annotated: dict[str | None, set[str]] = {}
        self.used_escapes: set[tuple[str, int]] = set()  # (rule, line)

    # ---------------------------------------------------- lock identity --
    def _rep_attr(self, scope: dict[str, _Resource], name: str) -> str:
        seen = set()
        while name in scope and scope[name].alias and name not in seen:
            seen.add(name)
            name = scope[name].alias
        return name

    def lock_id(self, cls: str | None, name: str) -> str | None:
        """Global id for an acquirable resource spelled `self.<name>` (in
        class cls) or bare `<name>` (module global); None if unregistered."""
        scope = self.class_res.get(cls, {}) if cls is not None \
            else self.module_res
        res = scope.get(name)
        if res is None or res.kind not in _ACQUIRABLE:
            return None
        rep = self._rep_attr(scope, name)
        owner = f"{self.module}.{cls}" if cls is not None else self.module
        return f"{owner}.{rep}"

    def resource_of(self, cls: str | None, base: str,
                    local_kinds: dict[str, str]) -> _Resource | None:
        """Resource record for a call base: `self.X` (class attr), bare
        `X` (function local, then module global)."""
        if base.startswith("self.") and cls is not None:
            return self.class_res.get(cls, {}).get(base[len("self."):])
        if "." not in base:
            kind = local_kinds.get(base)
            if kind is not None:
                return _Resource(kind)
            return self.module_res.get(base)
        return None


def _res_of_value(value: ast.expr) -> _Resource | None:
    if not isinstance(value, ast.Call):
        return None
    name = dotted_name(value.func)
    if name is None:
        return None
    kind = _FACTORY_KINDS.get(name.rsplit(".", 1)[-1])
    if kind is None:
        return None
    alias = None
    if kind == "cond" and value.args:
        arg = value.args[0]
        arg_name = dotted_name(arg)
        if arg_name is not None:
            alias = arg_name[len("self."):] \
                if arg_name.startswith("self.") else arg_name
    return _Resource(kind, alias)


def _assign_pairs(node: ast.AST):
    """(target, value) pairs for Assign/AnnAssign nodes."""
    if isinstance(node, ast.Assign) and node.value is not None:
        return [(t, node.value) for t in node.targets]
    if isinstance(node, ast.AnnAssign) and node.value is not None:
        return [(node.target, node.value)]
    return []


def _self_attr(node: ast.expr) -> str | None:
    if (isinstance(node, ast.Attribute) and isinstance(node.value, ast.Name)
            and node.value.id == "self"):
        return node.attr
    return None


def _collect_resources(mod: _ModInfo) -> None:
    """Registered synchronization resources + guard annotations, per class
    and at module level."""
    tree = mod.unit.tree
    ann = _guard_annotations(mod.unit.comments)

    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.asname:
                    mod.aliases[a.asname] = a.name
        elif isinstance(node, ast.ImportFrom) and node.module \
                and node.level == 0:
            for a in node.names:
                mod.aliases[a.asname or a.name] = \
                    f"{node.module}.{a.name}"

    def spans_annotation(node: ast.AST) -> bool:
        # the SAME binding rule THR enforces with (thrrules): TSI's
        # annotated-state exemption and THR's guard binding must agree
        return guard_on_assignment(ann, node) is not None

    for cls in [n for n in ast.walk(tree) if isinstance(n, ast.ClassDef)]:
        attrs: dict[str, _Resource] = {}
        annotated: set[str] = set()
        for node in ast.walk(cls):
            for target, value in _assign_pairs(node):
                name = _self_attr(target)
                if name is None:
                    continue
                res = _res_of_value(value)
                if res is not None:
                    attrs[name] = res
                if spans_annotation(node):
                    annotated.add(name)
        mod.class_res[cls.name] = attrs
        mod.annotated[cls.name] = annotated

    def module_scope(node: ast.AST):
        # every statement executed at MODULE scope: descend through
        # try/if/with nesting (conditionally-defined locks and guarded
        # globals are real), never into function or class bodies
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.ClassDef, ast.Lambda)):
                continue
            yield child
            yield from module_scope(child)

    annotated_mod: set[str] = set()
    for node in module_scope(tree):
        for target, value in _assign_pairs(node):
            if not isinstance(target, ast.Name):
                continue
            mod.module_globals.add(target.id)
            res = _res_of_value(value)
            if res is not None:
                mod.module_res[target.id] = res
            if spans_annotation(node):
                annotated_mod.add(target.id)
    mod.annotated[None] = annotated_mod


def _local_binds(fn: ast.AST) -> set[str]:
    """Names bound locally in fn (params + assignments, nested defs
    excluded) -- a bare-name write to one of these is a local, never a
    module global."""
    out: set[str] = set()
    args = getattr(fn, "args", None)
    if args is not None:
        out.update(a.arg for a in (
            args.posonlyargs + args.args + args.kwonlyargs
            + [a for a in (args.vararg, args.kwarg) if a is not None]))

    def rec(node: ast.AST) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                continue
            if isinstance(child, ast.Name) and isinstance(
                    child.ctx, (ast.Store, ast.Del)):
                out.add(child.id)
            rec(child)

    rec(fn)
    return out


def _declared_globals(fn: ast.AST) -> set[str]:
    return {name for node in ast.walk(fn) if isinstance(node, ast.Global)
            for name in node.names}


def _harvest_refs(expr: ast.expr) -> list[str]:
    """Every spelled Attribute/Name reference inside expr (Load ctx) --
    the thread-target candidates hiding in a tuple literal the spawn loop
    iterates (`for target, name in ((self._accept_loop, ...), ...)`).
    A call's FUNCTION is skipped: in `t = pick(worker_a, worker_b)` the
    candidates are the arguments, not `pick` itself (which runs
    synchronously on the spawning thread, never as a thread)."""
    out: list[str] = []

    def rec(node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            for child in list(node.args) \
                    + [kw.value for kw in node.keywords]:
                rec(child)
            return
        if isinstance(node, (ast.Attribute, ast.Name)) \
                and isinstance(getattr(node, "ctx", None), ast.Load):
            name = dotted_name(node)
            if name is not None and name not in out:
                out.append(name)
        for child in ast.iter_child_nodes(node):
            rec(child)

    rec(expr)
    return out


def _binds_name(target: ast.expr, name: str) -> bool:
    for node in ast.walk(target):
        if isinstance(node, ast.Name) and node.id == name:
            return True
    return False


def _flatten_targets(target: ast.expr):
    """The elementary write targets inside a possibly tuple/list/starred
    unpacking target -- `self.a, (self.b, *rest) = ...` writes each."""
    if isinstance(target, (ast.Tuple, ast.List)):
        for elt in target.elts:
            yield from _flatten_targets(elt)
    elif isinstance(target, ast.Starred):
        yield from _flatten_targets(target.value)
    else:
        yield target


# the ONE spelling of the escape-attachment rule lives in core
_escape_at = core_escape_at


class _FnWalker:
    """Walk one outer function tracking held registered locks; record
    acquisitions, calls-with-held, blocking ops and shared-state writes.
    Named nested defs become their OWN _FnInfo records (their bodies run
    later, usually on another thread -- a nested def passed to
    Thread(target=...) is a thread root in its own right, and a closure
    defined in __init__ must NOT inherit the happens-before-publication
    write exemption); lambdas still fold in with held locks reset."""

    def __init__(self, mod: _ModInfo, info: _FnInfo, fn: ast.AST,
                 cls: str | None, blk_escapes: dict[int, str],
                 tsi_escapes: dict[int, str]):
        self.mod = mod
        self.info = info
        self.fn = fn
        self.cls = cls
        self.blk_escapes = blk_escapes
        self.tsi_escapes = tsi_escapes
        self.local_kinds: dict[str, str] = {}
        self.locals = _local_binds(fn)
        self.globals_declared = _declared_globals(fn)
        self.is_init = getattr(fn, "name", "") == "__init__"
        self.nested: dict[str, str] = {}   # local def name -> full label
        self._loops: list[set[str]] = []   # enclosing loops' bound names

    def run(self) -> None:
        for stmt in self.fn.body:
            self._visit(stmt, frozenset())

    # ---------------------------------------------------------- helpers --
    def _lock_of_expr(self, expr: ast.expr) -> str | None:
        name = _self_attr(expr)
        if name is not None:
            return self.mod.lock_id(self.cls, name)
        if isinstance(expr, ast.Name):
            if expr.id in self.locals \
                    and expr.id not in self.globals_declared:
                # a parameter/local shadowing a registered lock's name
                # is NOT the module lock: misattributing it would
                # fabricate order edges and blocking-under-lock findings
                return None
            return self.mod.lock_id(None, expr.id)
        return None

    def _escaped(self, line: int, escapes: dict[int, str]) -> int | None:
        """The escape line covering `line` (itself or the line above)."""
        return _escape_at(escapes, line)

    def _record_block(self, line: int, op: str, held: frozenset,
                      released: str | None = None) -> None:
        # the escape line rides the record; whether it is USED is
        # decided by the analysis (an escape on an op no caller ever
        # reaches under a lock suppresses nothing and must go stale).
        # released: the lock the op gives up while blocking
        # (Condition.wait's own lock) -- callers discharge it from
        # their held set too
        esc = self._escaped(line, self.blk_escapes)
        self.info.blocks.append((line, op, tuple(sorted(held)), esc,
                                 released))

    def _classify_call(self, node: ast.Call, name: str,
                       held: frozenset) -> None:
        head, _, rest = name.partition(".")
        full = self.mod.aliases.get(head)
        canon = (f"{full}.{rest}" if rest else full) if full else name
        if name in BLOCKING_CALLS or canon in BLOCKING_CALLS:
            self._record_block(node.lineno, name, held)
            return
        base, _, meth = name.rpartition(".")
        if not base:
            return
        if meth in BLOCKING_METHODS:
            self._record_block(node.lineno, name, held)
            return
        res = self.mod.resource_of(self.cls, base, self.local_kinds)
        if res is None:
            return
        if meth in _TYPED_BLOCKING.get(res.kind, ()):
            effective = set(held)
            released = None
            if res.kind == "cond":
                # Condition.wait releases the condition's own lock; every
                # other held lock stays held across the wait
                attr = base[len("self."):] if base.startswith("self.") \
                    else base
                released = self.mod.lock_id(
                    self.cls if base.startswith("self.") else None, attr)
                if released is not None:
                    effective.discard(released)
            self._record_block(node.lineno, name, frozenset(effective),
                               released)

    def _thread_targets(self, node: ast.Call) -> None:
        target = next((kw.value for kw in node.keywords
                       if kw.arg == "target"), None)
        if target is None:
            return
        spelled = dotted_name(target)
        candidates: list[str] = []
        if spelled is not None:
            if isinstance(target, ast.Name) and spelled in self.locals:
                # `Thread(target=target)` where `target` is bound by a
                # local assignment or a for over a tuple of entry points:
                # harvest the function references from the binding exprs
                for n in ast.walk(self.fn):
                    for tgt, value in _assign_pairs(n):
                        if _binds_name(tgt, spelled):
                            candidates.extend(_harvest_refs(value))
                    if isinstance(n, ast.For) \
                            and _binds_name(n.target, spelled):
                        candidates.extend(_harvest_refs(n.iter))
            else:
                candidates.append(spelled)
        # a spawn inside a loop whose iteration does NOT rebind the
        # target runs the SAME function on many threads (the accept
        # loop's per-connection handler); a loop-variable target (the
        # repo's for-over-(target, name)-tuples start()) spawns each
        # bound function once and stays single-instance
        loop_vars: set[str] = set().union(*self._loops) \
            if self._loops else set()
        multi = bool(self._loops) and not any(
            isinstance(n, ast.Name) and n.id in loop_vars
            for n in ast.walk(target))
        for cand in candidates:
            label = self.nested.get(cand) if "." not in cand else None
            self.mod.roots.append(_RootSite(cand, self.cls,
                                            self.info.label,
                                            self.mod.file, node.lineno,
                                            label=label, multi=multi))

    def _record_write(self, line: int, scope_cls: str | None,
                      attr: str) -> None:
        if self.is_init:
            return  # construction happens-before publication
        scope = self.mod.class_res.get(scope_cls, {}) if scope_cls \
            else self.mod.module_res
        res = scope.get(attr)
        if res is not None:
            return  # the synchronization resources themselves are exempt
        esc = self._escaped(line, self.tsi_escapes)
        owner = f"{self.mod.module}.{scope_cls}" if scope_cls \
            else self.mod.module
        self.info.writes.append((line, (scope_cls, attr, owner), esc))

    def _mutation_base(self, target: ast.expr) -> tuple[str | None,
                                                        str] | None:
        """(class scope, attr) for a write target: `self.X` (and any
        deeper `self.X.y`/`self.X[k]` mutation, recorded as a write of
        X), bare global `X` (with a `global` declaration), or
        `X[k]`/`X.attr` mutation of a module-level name."""
        node = target
        mutated = False  # stripped at least one Subscript/Attribute
        while isinstance(node, (ast.Subscript, ast.Attribute)):
            if isinstance(node, ast.Attribute):
                name = _self_attr(node)
                if name is not None:
                    return (self.cls, name)
            node = node.value
            mutated = True
        if isinstance(node, ast.Name):
            if node.id in self.locals \
                    and node.id not in self.globals_declared:
                return None  # a local (or parameter) shadow
            if mutated:
                if node.id in self.mod.module_globals:
                    return (None, node.id)
                return None
            if node.id in self.globals_declared:
                return (None, node.id)
        return None

    # ------------------------------------------------------------- walk --
    def _visit(self, node: ast.AST, held: frozenset) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            # items of one `with A, B:` acquire left-to-right exactly
            # like nested withs: each later item sees the earlier ones
            # held, so the A->B order edge exists in either spelling
            acquired: set[str] = set()
            for item in node.items:
                self._visit(item.context_expr, held | acquired)
                lid = self._lock_of_expr(item.context_expr)
                if lid is not None:
                    self.info.acquisitions.append(
                        (item.context_expr.lineno, lid,
                         tuple(sorted(held | acquired))))
                    acquired.add(lid)
                if item.optional_vars is not None:
                    # `with open() as self.x:` binds (writes) the target
                    for t in _flatten_targets(item.optional_vars):
                        based = self._mutation_base(t)
                        if based is not None:
                            self._record_write(
                                item.context_expr.lineno, *based)
            inner = held | acquired
            for stmt in node.body:
                self._visit(stmt, inner)
            return
        if isinstance(node, ast.Lambda):
            # lambda: runs later, held locks reset, folds into the outer
            self._visit(node.body, frozenset())
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # named nested def: its own record (thread-root candidate;
            # no inherited __init__ exemption; synchronous calls to it
            # resolve through the intra-module nested-label edge)
            for dec in node.decorator_list:
                self._visit(dec, held)
            label = f"{self.info.label}.{node.name}"
            self.nested[node.name] = label
            sub = _FnInfo(self.mod.module, label, self.info.file)
            self.mod.fns[label] = sub
            _FnWalker(self.mod, sub, node, self.cls, self.blk_escapes,
                      self.tsi_escapes).run()
            return
        if isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
            # track the enclosing-loop context (and which names the
            # loop rebinds) for the multi-instance thread-spawn signal
            names: set[str] = set()
            if isinstance(node, (ast.For, ast.AsyncFor)):
                for nd in ast.walk(node.target):
                    if isinstance(nd, ast.Name):
                        names.add(nd.id)
                # `for self.cur in ...:` writes the attribute each
                # iteration -- a shared-state write like any other
                for t in _flatten_targets(node.target):
                    based = self._mutation_base(t)
                    if based is not None:
                        self._record_write(node.lineno, *based)
                self._visit(node.iter, held)
            else:
                self._visit(node.test, held)
            self._loops.append(names)
            for stmt in node.body:
                self._visit(stmt, held)
            self._loops.pop()
            # the else block runs ONCE, after the loop: a thread spawned
            # there is not multi-instance
            for stmt in node.orelse:
                self._visit(stmt, held)
            return
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name is not None:
                last = name.rsplit(".", 1)[-1]
                if last == "Thread":
                    self._thread_targets(node)
                self.info.calls.append((node.lineno, name, self.cls,
                                        tuple(sorted(held))))
                self._classify_call(node, name, held)
            for child in ast.iter_child_nodes(node):
                self._visit(child, held)
            return
        for target, value in _assign_pairs(node):
            res = _res_of_value(value)
            if res is not None and isinstance(target, ast.Name):
                self.local_kinds[target.id] = res.kind
        if isinstance(node, (ast.Assign, ast.AugAssign)) \
                or (isinstance(node, ast.AnnAssign)
                    and node.value is not None):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            for target in targets:
                for t in _flatten_targets(target):
                    based = self._mutation_base(t)
                    if based is not None:
                        self._record_write(node.lineno, based[0],
                                           based[1])
        for child in ast.iter_child_nodes(node):
            self._visit(child, held)


def _outer_functions(tree: ast.AST):
    """(fn node, enclosing class name, label) for every outermost def."""
    out = []

    def rec(node: ast.AST, cls: str | None) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.ClassDef):
                rec(child, child.name)
            elif isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                label = f"{cls}.{child.name}" if cls else child.name
                out.append((child, cls, label))
            else:
                rec(child, cls)

    rec(tree, None)
    return out


def _collect(unit: LintUnit, module: str) -> _ModInfo:
    mod = _ModInfo(unit, module)
    _collect_resources(mod)
    blk = unit.escapes.get("BLK", {})
    tsi = unit.escapes.get("TSI", {})
    for fn, cls, label in _outer_functions(unit.tree):
        info = _FnInfo(module, label, unit.file)
        mod.fns[label] = info
        _FnWalker(mod, info, fn, cls, blk, tsi).run()
    return mod


# ============================================================== analysis ==
class _Analysis:
    """The package-level pass: summaries by fixpoint over resolved call
    edges (cycle-tolerant by construction), then LCK edges/cycles, BLK
    witnesses and TSI root reachability."""

    def __init__(self, units: list[LintUnit],
                 prebuilt: tuple | None = None):
        self.units = [u for u in units if u.tree is not None]
        if prebuilt is None:
            prebuilt = callgraph.build(self.units)
        self.cg_modules, self.graph = prebuilt
        self.mods: dict[str, _ModInfo] = {}
        for u, cgm in zip(self.units, self.cg_modules):
            self.mods[cgm.module] = _collect(u, cgm.module)
        self.infos: dict[tuple[str, str], _FnInfo] = {
            (m.module, label): info
            for m in self.mods.values() for label, info in m.fns.items()}
        self.unit_by_file = {u.file: u for u in self.units}
        # (finding, escape reason) pairs whose escapes sit away from the
        # finding's own anchor line (a tsi-ok on a non-anchor write, a
        # blk-ok at the blocking SOURCE suppressing a caller's finding)
        # -- the anchor-based split cannot recover these, so they feed
        # the SARIF suppressions surface directly
        self.tsi_suppressed: list[tuple[Finding, str]] = []
        self.blk_suppressed: list[tuple[Finding, str]] = []
        # lock id -> kind (the representative's kind: a Condition(lock)
        # alias deadlocks, or not, like the lock it wraps)
        self.lock_kinds: dict[str, str] = {}
        for mod in self.mods.values():
            for cls, scope in list(mod.class_res.items()) \
                    + [(None, mod.module_res)]:
                for name in scope:
                    lid = mod.lock_id(cls, name)
                    if lid is None:
                        continue
                    rep = mod._rep_attr(scope, name)
                    rep_res = scope.get(rep, scope[name])
                    self.lock_kinds.setdefault(lid, rep_res.kind)
        self._resolve_edges()
        self._fixpoint()

    # ------------------------------------------------------- resolution --
    def _resolve_edges(self) -> None:
        self.callees: dict[tuple[str, str], list] = {}
        by_name = {m.module: m for m in self.cg_modules}
        for key, info in self.infos.items():
            cgm = by_name[key[0]]
            edges = []
            for line, name, cls, held in info.calls:
                if "." not in name:
                    # a synchronous call to a nested def visible from
                    # this scope: the caller's own children first, then
                    # siblings by ascending through enclosing FUNCTION
                    # scopes (never past one -- a bare name inside a
                    # method must not resolve to a sibling method)
                    prefix, nkey = key[1], None
                    while True:
                        cand = (key[0], f"{prefix}.{name}")
                        if cand in self.infos:
                            nkey = cand
                            break
                        if "." not in prefix:
                            break
                        parent = prefix.rsplit(".", 1)[0]
                        if (key[0], parent) not in self.infos:
                            break
                        prefix = parent
                    if nkey is not None:
                        edges.append((line, name, held, nkey))
                        continue
                callee = self.graph.resolve(cgm, name, cls)
                if callee is None:
                    continue
                ckey = (callee.module, callee.label)
                # self-edges stay: `with self._lock: self.step(...)`
                # recursing into itself is the one-edge re-acquisition
                # deadlock (the fixpoint merges are no-ops on them)
                if ckey not in self.infos:
                    continue
                edges.append((line, name, held, ckey))
            self.callees[key] = edges

    # -------------------------------------------------------- summaries --
    def _fixpoint(self) -> None:
        # acquires[f]: lock id -> (chain labels, acq file, acq line)
        self.acquires: dict[tuple[str, str], dict] = {}
        # blocks[f]: released-lock -> (chain labels, file, line, op) --
        # first UNESCAPED blocking op reachable from f, kept PER
        # released lock (Condition.wait gives up its own lock while
        # blocking, so a caller discharge of that lock must not hide a
        # plain sleep behind the same call edge)
        self.blocks: dict[tuple[str, str], dict] = {}
        # blocks_raw[f]: same with escapes ignored, each witness
        # carrying its own escape (module, line) (feeds raw findings
        # and the SARIF justification); block_escapes[f]: every source
        # blk-ok's (module, line) on a blocking op reachable from f --
        # a lock-held call marks ALL of them used (each suppresses its
        # own route), so an escape on an op no caller reaches under a
        # lock goes stale
        self.blocks_raw: dict[tuple[str, str], dict] = {}
        self.block_escapes: dict[tuple[str, str], set] = {}
        for key, info in self.infos.items():
            acq = {}
            for line, lid, _held in info.acquisitions:
                acq.setdefault(lid, ([info.label], info.file, line))
            self.acquires[key] = acq
            blk: dict = {}
            blk_raw: dict = {}
            esc_set = set()
            for line, op, _held, esc, released in info.blocks:
                blk_raw.setdefault(
                    released, ([info.label], info.file, line, op,
                               (key[0], esc) if esc is not None else None))
                if esc is not None:
                    esc_set.add((key[0], esc))
                else:
                    blk.setdefault(released,
                                   ([info.label], info.file, line, op))
            self.blocks[key] = blk
            self.blocks_raw[key] = blk_raw
            self.block_escapes[key] = esc_set
        changed = True
        while changed:
            changed = False
            for key in sorted(self.infos):
                info = self.infos[key]
                acq = self.acquires[key]
                for _line, _name, _held, ckey in self.callees[key]:
                    for lid, (chain, afile, aline) in \
                            self.acquires[ckey].items():
                        if lid not in acq:
                            acq[lid] = ([info.label] + chain, afile, aline)
                            changed = True
                    for summary in (self.blocks, self.blocks_raw):
                        mine = summary[key]
                        for released, witness in list(
                                summary[ckey].items()):
                            if released not in mine:
                                chain, *rest = witness
                                mine[released] = ([info.label] + chain,
                                                  *rest)
                                changed = True
                    if not self.block_escapes[ckey] \
                            <= self.block_escapes[key]:
                        self.block_escapes[key] |= \
                            self.block_escapes[ckey]
                        changed = True

    # -------------------------------------------------------------- LCK --
    def lock_edges(self) -> dict:
        """(held, acquired) -> [(site file, site line, chain labels,
        acq file, acq line), ...]: EVERY distinct site creating the
        order edge, in deterministic order -- an lck-ok at one site must
        not vouch for the same hazard spelled elsewhere."""
        edges: dict[tuple[str, str], list] = {}

        def add(h, lid, sfile, sline, chain, afile, aline):
            sites = edges.setdefault((h, lid), [])
            if not any(s[0] == sfile and s[1] == sline for s in sites):
                sites.append((sfile, sline, chain, afile, aline))

        for key in sorted(self.infos):
            info = self.infos[key]
            sites = [("acq", line, lid, held)
                     for line, lid, held in info.acquisitions if held]
            sites += [("call", line, ckey, held)
                      for line, _name, held, ckey in self.callees[key]
                      if held]
            for kind, line, payload, held in sorted(
                    sites, key=lambda s: s[1]):
                if kind == "acq":
                    for h in held:
                        add(h, payload, info.file, line, [info.label],
                            info.file, line)
                else:
                    for lid, (chain, afile, aline) in sorted(
                            self.acquires[payload].items()):
                        for h in held:
                            add(h, lid, info.file, line,
                                [info.label] + chain, afile, aline)
        return edges

    def lck_findings(self) -> tuple[list[Finding], list[Finding]]:
        edges = self.lock_edges()
        findings: list[Finding] = []
        raw: list[Finding] = []

        def emit_sites(sites, message_fn):
            # raw finding at EVERY site (any site's escape counts as
            # used), live finding at the FIRST UNESCAPED site: one
            # escaped anchor cannot vouch for the same hazard spelled
            # elsewhere, and one live finding per hazard keeps the
            # report readable
            live_done = False
            for sfile, sline, chain, afile, aline in sites:
                f = Finding(sfile, sline, "LCK",
                            message_fn(chain, afile, aline))
                raw.append(f)
                if live_done:
                    continue
                unit = self.unit_by_file.get(sfile)
                escapes = unit.escapes.get("LCK", {}) if unit else {}
                if _escape_at(escapes, sline) is None:
                    findings.append(f)
                    live_done = True

        # self-edges: re-acquisition of a non-reentrant lock (RLock is
        # exempt -- same-thread re-acquisition is its documented
        # use-case; it still participates in order cycles above)
        for (h, lid), sites in sorted(edges.items()):
            if h != lid or self.lock_kinds.get(lid) == "rlock":
                continue
            emit_sites(sites, lambda chain, afile, aline, lid=lid: (
                f"`{lid}` may be re-acquired while already held "
                f"({' -> '.join(chain)} acquires it at {afile}:{aline}); "
                "threading.Lock is non-reentrant, so this path "
                "self-deadlocks -- restructure to a *_locked helper, or "
                "escape with `# spgemm-lint: lck-ok(<reason>)` if the "
                "re-acquiring branch is provably unreachable here"))
        # cycles between distinct locks (the two-witness deadlock class);
        # pairwise detection over the edge set covers every 2-cycle, and
        # longer cycles always contain lock pairs ordered both ways
        # transitively -- report the direct pairs, which is where the fix
        # (pick one order) lands anyway.  The closure composes on one
        # representative witness per pair; emission walks every direct
        # site of the a->b direction (first unescaped wins)
        first = {pair: sites[0] for pair, sites in edges.items()}
        closure = self._transitive_closure(first)
        for (a, b) in sorted(closure):
            if a >= b or (b, a) not in closure:
                continue
            w_ba = closure[(b, a)]
            _, _, chain_ba, afile_ba, aline_ba = w_ba
            ab_sites = edges.get((a, b)) or [closure[(a, b)]]
            emit_sites(ab_sites, lambda chain, afile, aline, a=a, b=b: (
                f"lock-order cycle between `{a}` and `{b}`: "
                f"{' -> '.join(chain)} acquires `{b}` while holding "
                f"`{a}` ({afile}:{aline}), but "
                f"{' -> '.join(chain_ba)} acquires `{a}` while holding "
                f"`{b}` ({w_ba[0]}:{w_ba[1]} -> {afile_ba}:{aline_ba}) "
                "-- a potential deadlock; impose one acquisition order, "
                "or escape with `# spgemm-lint: lck-ok(<reason>)`"))
        return findings, raw

    @staticmethod
    def _transitive_closure(edges: dict) -> dict:
        """held -> acquired reachability with first witnesses: A->B and
        B->C compose to A->C so indirect inversions still close a cycle."""
        closure = dict(edges)
        changed = True
        while changed:
            changed = False
            for (a, b), w1 in list(closure.items()):
                for (b2, c), w2 in list(closure.items()):
                    if b2 != b or (a, c) in closure:
                        continue
                    # compose witnesses: anchor stays at the first hop
                    closure[(a, c)] = (w1[0], w1[1],
                                       w1[2] + ["..."] + w2[2],
                                       w2[3], w2[4])
                    changed = True
        return closure

    # -------------------------------------------------------------- BLK --
    def blk_findings(self) -> tuple[list[Finding], list[Finding]]:
        findings: list[Finding] = []
        raw: list[Finding] = []
        reported: set[tuple[str, int]] = set()

        def emit(file, line, escaped, message):
            if (file, line) in reported:
                return None
            reported.add((file, line))
            f = Finding(file, line, "BLK", message)
            raw.append(f)
            if not escaped:
                findings.append(f)
            return f

        for key in sorted(self.infos):
            info = self.infos[key]
            unit = self.unit_by_file.get(info.file)
            escapes = unit.escapes.get("BLK", {}) if unit else {}
            for line, op, held, esc, _released in info.blocks:
                if not held:
                    continue
                if esc is not None:
                    # the escape suppresses a real lock-held hazard
                    self.mods[key[0]].used_escapes.add(("BLK", esc))
                emit(info.file, line, esc is not None,
                     f"blocking `{op}` while holding {', '.join(held)}: "
                     "every other thread contending for the lock stalls "
                     "behind this call (watchdog/executor latency, drain "
                     "hangs); move the blocking work outside the critical "
                     "section, or escape with "
                     "`# spgemm-lint: blk-ok(<reason>)`")
            for line, name, held, ckey in self.callees[key]:
                if not held or not self.blocks_raw[ckey]:
                    continue

                # a witness discharges the lock its op RELEASES while
                # blocking (Condition.wait's own lock, reached through
                # a helper): pick the first witness that still leaves a
                # lock held -- the canonical cond-var pattern is not a
                # hazard, but a plain sleep behind the same call edge is
                def pick(witnesses: dict):
                    for released in sorted(
                            witnesses,
                            key=lambda r: (r is not None, r or "")):
                        effective = tuple(h for h in held if h != released)
                        if effective:
                            return witnesses[released], effective
                    return None, None

                witness, effective = pick(self.blocks[ckey])
                src_esc = None
                if witness is not None:
                    live = True
                    chain, bfile, bline, op = witness
                else:
                    live = False
                    witness, effective = pick(self.blocks_raw[ckey])
                    if witness is None:
                        continue  # every route discharges all held locks
                    chain, bfile, bline, op, src_esc = witness
                # every source escape on a blocking route reachable from
                # here is doing real work on a lock-held path: used
                for esc_mod, esc_line in self.block_escapes[ckey]:
                    self.mods[esc_mod].used_escapes.add(("BLK", esc_line))
                call_esc = _escape_at(escapes, line) is not None
                escaped = call_esc or not live
                f = emit(info.file, line, escaped,
                     f"`{name}` reaches blocking `{op}` while holding "
                     f"{', '.join(effective)}: {info.label} -> "
                     f"{' -> '.join(chain)} -> `{op}` ({bfile}:{bline}); "
                     "a lock held across a blocking call stalls every "
                     "contending thread -- hoist the call out of the "
                     "critical section, prove the op non-blocking at its "
                     "source with `# spgemm-lint: blk-ok(<reason>)`, or "
                     "escape this call site")
                if f is not None and not live and not call_esc:
                    # suppressed at the SOURCE, away from this anchor:
                    # carry the (finding, reason) pair -- reason from
                    # the escape on the WITNESSED op, so the SARIF
                    # justification argues for the blocking call the
                    # finding's own chain names
                    reason = ""
                    if src_esc is not None:
                        src_unit = self.unit_by_file.get(
                            self.mods[src_esc[0]].file)
                        if src_unit is not None:
                            reason = src_unit.escapes.get(
                                "BLK", {}).get(src_esc[1], "")
                    self.blk_suppressed.append((f, reason))
        return findings, raw

    # -------------------------------------------------------------- TSI --
    def thread_roots(self) -> dict[tuple[str, str], list[_RootSite]]:
        """Resolved thread-entry functions -> the sites that spawn them."""
        by_name = {m.module: m for m in self.cg_modules}
        roots: dict[tuple[str, str], list[_RootSite]] = {}
        for mod in self.mods.values():
            cgm = by_name[mod.module]
            for site in mod.roots:
                if site.label is not None:
                    # nested-def target: pre-resolved by the walker
                    key = (mod.module, site.label)
                    if key in self.infos:
                        roots.setdefault(key, []).append(site)
                    continue
                callee = self.graph.resolve(cgm, site.spelled, site.cls)
                if callee is None:
                    continue
                key = (callee.module, callee.label)
                if key in self.infos:
                    roots.setdefault(key, []).append(site)
        return roots

    def _root_weight(self, sites: list[_RootSite]) -> int:
        """2 when the root demonstrably runs on >= 2 threads at once --
        spawned inside a loop that does not rebind the target, or from
        two distinct sites; 1 otherwise."""
        if any(s.multi for s in sites) \
                or len({(s.file, s.line) for s in sites}) > 1:
            return 2
        return 1

    def _reachable(self, root: tuple[str, str]) -> set:
        seen = {root}
        stack = [root]
        while stack:
            key = stack.pop()
            for _line, _name, _held, ckey in self.callees.get(key, ()):
                if ckey not in seen:
                    seen.add(ckey)
                    stack.append(ckey)
        return seen

    def tsi_findings(self) -> tuple[list[Finding], list[Finding]]:
        roots = self.thread_roots()
        # a multi-instance root (loop-spawned same target, or >= 2 spawn
        # sites -- the daemon's per-connection handler) counts as two
        # threads by itself: one root is already a data race
        weight = {key: self._root_weight(sites)
                  for key, sites in roots.items()}
        roots_reaching: dict[tuple[str, str], set] = {}
        for root in roots:
            for key in self._reachable(root):
                roots_reaching.setdefault(key, set()).add(root)
        # attr key -> write records (file, line, func key, escape line)
        writes: dict[tuple, list] = {}
        for key in sorted(self.infos):
            info = self.infos[key]
            mod = self.mods[key[0]]
            for line, (scope_cls, attr, owner), esc in info.writes:
                if attr in mod.annotated.get(scope_cls, ()):
                    continue  # guarded-by-annotated: THR owns it
                writes.setdefault((owner, attr),
                                  []).append((info.file, line, key, esc))
        findings: list[Finding] = []
        raw: list[Finding] = []
        for (owner, attr), recs in sorted(writes.items()):
            recs.sort(key=lambda r: (r[0], r[1]))
            all_roots = set()
            for _file, _line, fkey, _esc in recs:
                all_roots |= roots_reaching.get(fkey, set())
            count = sum(weight[r] for r in all_roots)
            if count < 2:
                continue
            mod = self.mods[recs[0][2][0]]
            root_names = sorted(
                f"{r[1]} ({r[0]}"
                + (", multi-instance" if weight[r] > 1 else "") + ")"
                for r in all_roots)
            live = [r for r in recs if r[3] is None]
            for _file, _line, _fkey, esc in recs:
                if esc is not None:
                    mod.used_escapes.add(("TSI", esc))
            msg = (f"`{owner}.{attr}` is written from {count} "
                   f"thread roots ({'; '.join(root_names)}) without a "
                   "`# spgemm-lint: guarded-by(<lock>)` annotation: "
                   "multi-thread-written state must either declare its "
                   "lock (THR then enforces it) or argue its lock-free "
                   "protocol with `# spgemm-lint: tsi-ok(<reason>)` on "
                   "the write lines; write sites: "
                   + ", ".join(f"{r[0]}:{r[1]}" for r in recs))
            raw_f = Finding(recs[0][0], recs[0][1], "TSI", msg)
            raw.append(raw_f)
            live_roots = set()
            for _file, _line, fkey, _esc in live:
                live_roots |= roots_reaching.get(fkey, set())
            if sum(weight[r] for r in live_roots) >= 2:
                findings.append(Finding(live[0][0], live[0][1], "TSI", msg))
            else:
                # suppressed by tsi-ok escapes (possibly on non-anchor
                # write lines the anchor-based split cannot see): carry
                # the (finding, reason) pair for the SARIF suppressions
                # surface so the escape stays auditable
                for file, _line, _fkey, esc in recs:
                    if esc is None:
                        continue
                    unit = self.unit_by_file.get(file)
                    reason = unit.escapes.get("TSI", {}).get(esc, "") \
                        if unit else ""
                    self.tsi_suppressed.append((raw_f, reason))
                    break
        return findings, raw

    # -------------------------------------------------- thread inventory --
    def inventory_rows(self) -> list[dict]:
        """One row per resolved thread root: root label, spawners, locks
        it may (transitively) hold, shared attrs it may write --
        deterministic, for the generated ARCHITECTURE.md table."""
        rows = []
        for key, sites in sorted(self.thread_roots().items()):
            locks = set()
            attrs = set()
            for fkey in self._reachable(key):
                # acquires is seeded from every local acquisition before
                # the transitive merge, so it already covers them all
                locks.update(self.acquires[fkey])
                info = self.infos[fkey]
                for _line, (_scope_cls, attr, owner), _esc in info.writes:
                    attrs.add(f"{owner}.{attr}".replace("spgemm_tpu.", ""))
            spawners = sorted({f"{s.spawner} ({s.file})" for s in sites})
            rows.append({
                "root": f"{key[0]}.{key[1]}".replace("spgemm_tpu.", ""),
                "spawners": spawners,
                "locks": sorted(lk.replace("spgemm_tpu.", "")
                                for lk in locks),
                "writes": sorted(attrs),
            })
        return rows


def check(units: list[LintUnit], *, inventory: list | None = None,
          prebuilt: tuple | None = None,
          suppressed: list | None = None) -> tuple[list[Finding],
                                                   list[Finding],
                                                   set[tuple[str, str, int]]]:
    """The concurrency pass over one lint run's unit set.

    Returns (findings, raw_findings, used_escapes): findings honor
    lck-ok/blk-ok/tsi-ok escapes, raw_findings ignore them (the
    suppression audit derives usage from the difference), and
    used_escapes are (file, rule, escape line) for source-level escapes
    that suppressed taint without an anchored finding (a blk-ok on the
    blocking op itself, a tsi-ok on a non-anchor write line).

    inventory: an optional sink list the thread-inventory rows are
    appended to -- the DOC table check reuses this run's analysis
    instead of rebuilding the whole program a second time (valid only
    when the unit set IS the default scope; the caller guards that).
    prebuilt: a callgraph.build(units) result to reuse (same
    once-per-run economy for the call graph itself).
    suppressed: an optional sink for (finding, escape reason) pairs
    whose escapes sit away from the finding's anchor line (a tsi-ok on
    a non-anchor write, a blk-ok at the blocking source suppressing a
    caller's finding) -- the caller's anchor-based raw-vs-surviving
    split cannot recover those reasons."""
    analysis = _Analysis(units, prebuilt)
    findings: list[Finding] = []
    raw: list[Finding] = []
    for fn in (analysis.lck_findings, analysis.blk_findings,
               analysis.tsi_findings):
        f, r = fn()
        findings += f
        raw += r
    used: set[tuple[str, str, int]] = set()
    for mod in analysis.mods.values():
        for rule, line in mod.used_escapes:
            used.add((mod.file, rule, line))
    if inventory is not None:
        inventory.extend(analysis.inventory_rows())
    if suppressed is not None:
        suppressed.extend(analysis.blk_suppressed)
        suppressed.extend(analysis.tsi_suppressed)
    return findings, raw, used


def inventory_rows(units: list[LintUnit]) -> list[dict]:
    """Thread-inventory rows for a unit set (docrules renders the table)."""
    return _Analysis(units).inventory_rows()
