"""DOC rule: generated-vs-committed doc drift.

Three halves:

  * CLAUDE.md knob table -- the block between the `<!-- knob-table:begin -->`
    and `<!-- knob-table:end -->` markers must equal
    `knobs.knob_table_md()` exactly (regenerate with
    `python -m spgemm_tpu.analysis --write-knob-table`).
  * ARCHITECTURE.md metrics table -- the block between the
    `<!-- metrics-table:begin/end -->` markers must equal
    `obs.metrics.metrics_table_md()` (regenerate with
    `--write-metrics-table`): the scrape surface is registry-generated
    exactly like the knobs.
  * CLI help -- `cli.build_parser()` help text must cover every registered
    knob name.  The epilog is generated from the registry
    (`knobs.cli_epilog`), so this check fails only if someone hardcodes or
    drops the epilog; the check inspects the BUILT parser, not the source,
    so any spelling of coverage passes.
"""

from __future__ import annotations

from spgemm_tpu.analysis.core import Finding, rel_file
from spgemm_tpu.utils import knobs

KNOB_TABLE_BEGIN = "<!-- knob-table:begin -->"
KNOB_TABLE_END = "<!-- knob-table:end -->"

METRICS_TABLE_BEGIN = "<!-- metrics-table:begin -->"
METRICS_TABLE_END = "<!-- metrics-table:end -->"

THREAD_TABLE_BEGIN = "<!-- thread-inventory:begin -->"
THREAD_TABLE_END = "<!-- thread-inventory:end -->"

PROTOCOL_TABLE_BEGIN = "<!-- protocol-table:begin -->"
PROTOCOL_TABLE_END = "<!-- protocol-table:end -->"

EVENT_TABLE_BEGIN = "<!-- event-table:begin -->"
EVENT_TABLE_END = "<!-- event-table:end -->"


def thread_inventory_md(rows: list | None = None) -> str:
    """The generated thread-inventory table: one row per thread root the
    LCK/BLK/TSI pass resolves over the DEFAULT lint scope (always the
    default scope, independent of what a particular run linted, so the
    committed table has exactly one truth) -- root function, spawner,
    locks it may transitively hold, shared attrs it writes.

    rows: precomputed inventory rows from a lint run whose unit set WAS
    the default scope (lint_run passes them through so the default
    `make lint` builds the whole-program analysis once, not twice);
    None = build the analysis here."""
    from spgemm_tpu.analysis import core, lockrules  # noqa: PLC0415

    if rows is None:
        units = [core.LintUnit(f) for path in core.default_paths()
                 for f in core._walk_py(path)]
        rows = lockrules.inventory_rows(units)
    lines = ["| thread root | spawned by | locks it may hold "
             "| shared state it writes |",
             "|---|---|---|---|"]
    for row in rows:
        def cell(items):
            return ", ".join(f"`{i}`" for i in items) if items else "—"
        lines.append(f"| `{row['root']}` | {cell(row['spawners'])} "
                     f"| {cell(row['locks'])} | {cell(row['writes'])} |")
    return "\n".join(lines)


def render_thread_block() -> str:
    """The full marked block, ready to paste into ARCHITECTURE.md."""
    return (f"{THREAD_TABLE_BEGIN}\n{thread_inventory_md()}\n"
            f"{THREAD_TABLE_END}")


def check_thread_inventory(path: str,
                           rows: list | None = None) -> list[Finding]:
    """Diff the committed thread-inventory table against the one the
    concurrency pass generates from the default scope (the same
    keep-it-generated contract as the knob and metrics tables;
    regenerate with `--write-thread-inventory`)."""
    if rows is None:
        # generating the table means a full default-scope analysis:
        # don't pay it just to learn the file is unreadable or has no
        # markers -- those findings compare nothing
        try:
            with open(path, encoding="utf-8") as f:
                text = f.read()
        except OSError:
            text = None
        if text is None or THREAD_TABLE_BEGIN not in text \
                or THREAD_TABLE_END not in text:
            return _check_marked_block(path, THREAD_TABLE_BEGIN,
                                       THREAD_TABLE_END, "",
                                       "thread inventory",
                                       "--write-thread-inventory")
    return _check_marked_block(path, THREAD_TABLE_BEGIN, THREAD_TABLE_END,
                               thread_inventory_md(rows),
                               "thread inventory",
                               "--write-thread-inventory")


def render_knob_block() -> str:
    """The full marked block, ready to paste into CLAUDE.md."""
    return (f"{KNOB_TABLE_BEGIN}\n{knobs.knob_table_md()}\n"
            f"{KNOB_TABLE_END}")


def render_metrics_block() -> str:
    """The full marked block, ready to paste into ARCHITECTURE.md."""
    from spgemm_tpu.obs import metrics  # noqa: PLC0415

    return (f"{METRICS_TABLE_BEGIN}\n{metrics.metrics_table_md()}\n"
            f"{METRICS_TABLE_END}")


def render_protocol_block() -> str:
    """The full marked block, ready to paste into ARCHITECTURE.md."""
    from spgemm_tpu.serve import protocol  # noqa: PLC0415

    return (f"{PROTOCOL_TABLE_BEGIN}\n{protocol.protocol_table_md()}\n"
            f"{PROTOCOL_TABLE_END}")


def render_event_block() -> str:
    """The full marked block, ready to paste into ARCHITECTURE.md."""
    from spgemm_tpu.obs import events  # noqa: PLC0415

    return (f"{EVENT_TABLE_BEGIN}\n{events.event_table_md()}\n"
            f"{EVENT_TABLE_END}")


def _check_marked_block(path: str, begin_marker: str, end_marker: str,
                        generated: str, what: str,
                        regen_flag: str) -> list[Finding]:
    """Shared marker-block diff for the generated doc tables."""
    file = rel_file(path)
    try:
        with open(path, encoding="utf-8") as f:
            text = f.read()
    except OSError:
        return [Finding(file, 1, "DOC", f"{what} check: cannot read "
                        f"{file} (expected the generated {what} "
                        f"between {begin_marker} / {end_marker})")]
    begin = text.find(begin_marker)
    end = text.find(end_marker)
    if begin < 0 or end < 0 or end < begin:
        return [Finding(file, 1, "DOC",
                        f"{what} markers missing: {file} must carry the "
                        f"generated {what} between {begin_marker} "
                        f"and {end_marker} (run `python -m "
                        f"spgemm_tpu.analysis {regen_flag}`)")]
    committed = text[begin + len(begin_marker):end].strip()
    if committed != generated.strip():
        line = text[:begin].count("\n") + 1
        return [Finding(file, line, "DOC",
                        f"{what} drifted from its registry: regenerate "
                        f"with `python -m spgemm_tpu.analysis "
                        f"{regen_flag}`")]
    return []


def check_claude_md(path: str) -> list[Finding]:
    """Diff the committed knob table against the registry-generated one."""
    return _check_marked_block(path, KNOB_TABLE_BEGIN, KNOB_TABLE_END,
                               knobs.knob_table_md(), "knob table",
                               "--write-knob-table")


def check_architecture_md(path: str) -> list[Finding]:
    """Diff the committed metrics table against the obs/metrics.py
    registry (the same keep-it-generated contract as the knob table)."""
    from spgemm_tpu.obs import metrics  # noqa: PLC0415

    return _check_marked_block(path, METRICS_TABLE_BEGIN, METRICS_TABLE_END,
                               metrics.metrics_table_md(), "metrics table",
                               "--write-metrics-table")


def check_protocol_table(path: str) -> list[Finding]:
    """Diff the committed wire-protocol table against the
    serve/protocol.py registry (ops, fields, min versions, error codes)."""
    from spgemm_tpu.serve import protocol  # noqa: PLC0415

    return _check_marked_block(path, PROTOCOL_TABLE_BEGIN,
                               PROTOCOL_TABLE_END,
                               protocol.protocol_table_md(),
                               "protocol table", "--write-protocol-table")


def check_event_table(path: str) -> list[Finding]:
    """Diff the committed event-kind table against the obs/events.py
    EVENT_KINDS registry."""
    from spgemm_tpu.obs import events  # noqa: PLC0415

    return _check_marked_block(path, EVENT_TABLE_BEGIN, EVENT_TABLE_END,
                               events.event_table_md(), "event table",
                               "--write-event-table")


def check_analysis_help() -> list[Finding]:
    """`python -m spgemm_tpu.analysis --help` must list every rule id.

    The epilog is generated from core.RULES (see __main__.build_parser), so
    this fails only if someone hardcodes or drops the epilog -- the same
    keep-it-wired contract check_cli_help applies to the knob epilog.  The
    BUILT parser is inspected, not the source, so any spelling of coverage
    passes."""
    from spgemm_tpu.analysis import __main__ as analysis_main  # noqa: PLC0415
    from spgemm_tpu.analysis.core import RULES  # noqa: PLC0415

    file = rel_file(analysis_main.__file__)
    try:
        help_text = analysis_main.build_parser().format_help()
    except Exception as e:  # noqa: BLE001 -- a broken parser IS the finding
        return [Finding(file, 1, "DOC",
                        f"analysis build_parser() failed: {e!r}")]
    missing = [rule for rule in RULES if rule not in help_text]
    return [Finding(file, 1, "DOC",
                    f"analysis --help does not mention rule id {rule} (the "
                    "epilog is generated from core.RULES -- keep it wired)")
            for rule in missing]


def check_cli_help() -> list[Finding]:
    """Every registered knob must appear in the CLI help text."""
    import spgemm_tpu.cli as cli  # noqa: PLC0415 -- jax-free at module level

    file = rel_file(cli.__file__)
    try:
        help_text = cli.build_parser().format_help()
    except Exception as e:  # noqa: BLE001 -- a broken parser IS the finding
        return [Finding(file, 1, "DOC",
                        f"cli.build_parser() failed: {e!r}")]
    missing = [name for name in knobs.REGISTRY if name not in help_text]
    return [Finding(file, 1, "DOC",
                    f"CLI help does not mention knob {name} (the epilog is "
                    "generated by knobs.cli_epilog() -- keep it wired)")
            for name in missing]
