"""EXC: exception contracts on failover paths.

The serving layer's correctness depends on two handler disciplines that
used to live only in comments:

  * A broad `except Exception` is load-bearing on the failover paths
    (chain_product's device-loss retry, the executor loop, the OOC
    workers): it must carry the repo's `# noqa: BLE001 -- <reason>`
    justification ON ITS LINE, where the reason is the reviewable citation
    of which failover contract licenses the broad catch.  A naked broad
    catch is a finding.
  * A bare `except:` or `except BaseException` would also swallow
    BaseException-derived CONTROL signals -- serve.queue.JobAbandoned is a
    BaseException precisely so a watchdog abort pierces the failover
    catch to the executor loop (PR 5).  Such a handler must therefore
    provably re-raise: its body must END in a `raise` statement.  A
    conditional or absent re-raise is a finding.

Escape hatch: `# spgemm-lint: exc-ok(<reason>)` on the handler's line or
the line above, for the rare handler whose swallow is itself the contract
(audited like every escape -- a stale one is a SUP finding).
"""

from __future__ import annotations

import ast

from spgemm_tpu.analysis.core import Finding, LintUnit

BLE_MARKER = "noqa: BLE001"


def _handler_names(type_node: ast.expr | None) -> set[str]:
    """Last-component names of the caught types; {"<bare>"} for a bare
    except."""
    if type_node is None:
        return {"<bare>"}
    nodes = type_node.elts if isinstance(type_node, ast.Tuple) else [type_node]
    names = set()
    for n in nodes:
        if isinstance(n, ast.Name):
            names.add(n.id)
        elif isinstance(n, ast.Attribute):
            names.add(n.attr)
    return names


def _ends_in_raise(body: list[ast.stmt]) -> bool:
    return bool(body) and isinstance(body[-1], ast.Raise)


def _ble_reason_on(comment: str) -> bool:
    """True iff the comment carries `# noqa: BLE001 -- <non-empty reason>`
    (the comment comes from core.comment_map, so a quoted marker in a
    string on the handler line never counts)."""
    pos = comment.find(BLE_MARKER)
    if pos < 0:
        return False
    rest = comment[pos + len(BLE_MARKER):].strip()
    return rest.startswith("--") and bool(rest[2:].strip())


def check_exc(unit: LintUnit, escapes: set[int]) -> list[Finding]:
    findings: list[Finding] = []
    for node in ast.walk(unit.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.lineno in escapes or node.lineno - 1 in escapes:
            continue
        names = _handler_names(node.type)
        if "<bare>" in names or "BaseException" in names:
            if not _ends_in_raise(node.body):
                spelled = "bare `except:`" if "<bare>" in names \
                    else "`except BaseException`"
                findings.append(Finding(
                    unit.file, node.lineno, "EXC",
                    f"{spelled} must provably re-raise (end the handler "
                    "with `raise`): it would otherwise swallow "
                    "BaseException-derived control signals -- "
                    "serve.queue.JobAbandoned is a BaseException precisely "
                    "so a watchdog abort pierces broad failover catches; "
                    "escape with `# spgemm-lint: exc-ok(<reason>)` only if "
                    "the swallow IS the contract"))
        elif "Exception" in names:
            # the handler CLAUSE can wrap (a tuple of caught types split
            # across lines): the justification counts on any of its lines
            clause_end = getattr(node.type, "end_lineno", None) \
                or node.lineno
            justified = any(
                _ble_reason_on(unit.comments.get(line, ""))
                for line in range(node.lineno, clause_end + 1))
            if not justified:
                findings.append(Finding(
                    unit.file, node.lineno, "EXC",
                    "broad `except Exception` without justification: add "
                    "`# noqa: BLE001 -- <reason>` on the handler line "
                    "naming the failover contract that licenses the broad "
                    "catch (or narrow the handler); escape with "
                    "exc-ok(<reason>) for non-failover code"))
    return findings
