"""Interprocedural FLD: fold-order taint through the intra-package call graph.

The per-module FLD rule (rules.check_fld) is syntactic and module-scoped:
a numeric-path module could "hide the jnp.sum in utils/" by calling a
helper in a non-numeric module.  This pass closes that hole.  Over the
whole lint run's file set it builds a jax-free call graph -- module-level
functions, class methods, and the imports that name them -- marks every
function that DIRECTLY performs an unordered reduction (rules.fld_violation
on the spelled call name, minus reductions escaped at source with
`# spgemm-lint: fld-proof(<reason>)`), propagates that taint along resolved
call edges, and flags every call site in a NUMERIC module whose callee
lives in a non-numeric module and (transitively) reaches a reduction.  The
finding lands at the call site -- where a reviewer would look -- and names
the witness chain down to the reduction's file:line.

Resolution is deliberately name-based (the same trade the per-module rules
make: the spelled form is the form): `from pkg.mod import f` / `import
pkg.mod as m; m.f(...)` / same-module `f(...)` / `self.method(...)` within
a class all resolve; attribute calls on arbitrary objects do not.  A bare
`import x` resolves by module-path suffix only when `x` is not a stdlib
module name, so `import queue` can never alias serve/queue.py.  Everything
is stdlib-only ast -- no imports are executed.
"""

from __future__ import annotations

import ast
import sys
from dataclasses import dataclass, field

from spgemm_tpu.analysis.core import Finding, LintUnit
from spgemm_tpu.analysis.rules import dotted_name, fld_violation

_STDLIB = getattr(sys, "stdlib_module_names", frozenset())


@dataclass
class _Func:
    """One function or method: its direct (unescaped) reductions and the
    spelled calls it makes."""

    module: str                # dotted module of the defining unit
    label: str                 # "f" or "Cls.method"
    file: str
    reductions: list[tuple[int, str]] = field(default_factory=list)
    calls: list[tuple[int, str, str | None]] = field(default_factory=list)
    # calls: (lineno, spelled name, enclosing class or None)


@dataclass
class _Module:
    module: str
    unit: LintUnit
    # local import name -> list of resolution candidates, each either
    # ("mod", dotted_module) or ("member", dotted_module, member_name)
    imports: dict = field(default_factory=dict)
    funcs: dict = field(default_factory=dict)       # label -> _Func
    toplevel_calls: list = field(default_factory=list)
    used_escapes: set = field(default_factory=set)  # taint-suppressing lines
    classes: set = field(default_factory=set)       # class names defined here
    # module-level singleton instances: `ENGINE = PhaseTimers()` makes
    # `ENGINE.incr(...)` resolve to PhaseTimers.incr -- the repo's
    # process-wide registries (timers.ENGINE, trace.RECORDER, events.LOG)
    # are exactly this shape, and the concurrency pass needs their lock
    # acquisitions visible through the singleton spelling
    singletons: dict = field(default_factory=dict)  # local name -> class name


def _module_name(unit: LintUnit) -> str:
    name = unit.file
    if name.endswith(".py"):
        name = name[:-3]
    name = name.replace("/", ".")
    if name.endswith(".__init__"):
        name = name[: -len(".__init__")]
    return name


def _collect(unit: LintUnit) -> _Module:
    mod = _Module(_module_name(unit), unit)
    fld_escape_lines = set(unit.escapes["FLD"])
    used_escapes: set[int] = set()

    def add_import(node: ast.AST) -> None:
        if isinstance(node, ast.Import):
            for alias in node.names:
                local = alias.asname or alias.name.split(".", 1)[0]
                target = alias.name if alias.asname else alias.name.split(
                    ".", 1)[0]
                mod.imports.setdefault(local, []).append(("mod", target))
        elif isinstance(node, ast.ImportFrom) and node.module:
            for alias in node.names:
                local = alias.asname or alias.name
                mod.imports.setdefault(local, []).extend([
                    ("member", node.module, alias.name),
                    ("mod", f"{node.module}.{alias.name}"),
                ])

    def visit(node: ast.AST, func: _Func | None, cls: str | None) -> None:
        if isinstance(node, (ast.Import, ast.ImportFrom)):
            add_import(node)
            return
        if isinstance(node, ast.ClassDef):
            mod.classes.add(node.name)
            for child in ast.iter_child_nodes(node):
                visit(child, func, node.name)
            return
        if (isinstance(node, ast.Assign) and func is None and cls is None
                and isinstance(node.value, ast.Call)):
            # module-level singleton: NAME = Cls(...) with Cls defined in
            # this module (class defs precede their instantiation in file
            # order, so one pass sees them)
            cls_name = (dotted_name(node.value.func) or "").rsplit(".", 1)[-1]
            if cls_name in mod.classes:
                for target in node.targets:
                    if isinstance(target, ast.Name):
                        mod.singletons[target.id] = cls_name
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            label = f"{cls}.{node.name}" if cls else node.name
            # a nested def folds into its enclosing function's info (it
            # runs, at the latest, when the enclosing scope wires it up)
            f = func if func is not None else _Func(mod.module, label,
                                                    unit.file)
            if func is None:
                mod.funcs[label] = f
            for child in ast.iter_child_nodes(node):
                visit(child, f, cls)
            return
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name is not None:
                if fld_violation(name) is not None:
                    if (node.lineno in fld_escape_lines
                            or node.lineno - 1 in fld_escape_lines):
                        # a source-escaped reduction: suppresses taint,
                        # and the escape is therefore USED (audit)
                        used_escapes.add(
                            node.lineno if node.lineno in fld_escape_lines
                            else node.lineno - 1)
                    elif func is not None:
                        func.reductions.append((node.lineno, name))
                if func is not None:
                    func.calls.append((node.lineno, name, cls))
                else:
                    mod.toplevel_calls.append((node.lineno, name, cls))
        for child in ast.iter_child_nodes(node):
            visit(child, func, cls)

    for child in ast.iter_child_nodes(unit.tree):
        visit(child, None, None)
    mod.used_escapes = used_escapes
    return mod


class _Graph:
    def __init__(self, modules: list[_Module]):
        self.by_name: dict[str, _Module] = {m.module: m for m in modules}
        # suffix index for bare-name module resolution (non-stdlib only)
        self.by_tail: dict[str, list[str]] = {}
        for m in modules:
            tail = m.module.rsplit(".", 1)[-1]
            self.by_tail.setdefault(tail, []).append(m.module)
        self._taint_memo: dict[tuple[str, str], tuple | None] = {}

    def _resolve_module(self, dotted: str) -> _Module | None:
        m = self.by_name.get(dotted)
        if m is not None:
            return m
        # bare, non-stdlib names may resolve by path suffix (fixtures and
        # scripts lint under paths like tests.lint_fixtures.hosthelper but
        # import each other by bare name)
        if "." not in dotted and dotted not in _STDLIB:
            cands = self.by_tail.get(dotted, ())
            if len(cands) == 1:
                return self.by_name[cands[0]]
        return None

    def _lookup(self, module: _Module, label: str) -> _Func | None:
        f = module.funcs.get(label)
        if f is not None:
            return f
        # class instantiation: a call spelled `Cls(...)` (or resolving to
        # the class name) executes Cls.__init__
        if "." not in label and label in module.classes:
            return module.funcs.get(f"{label}.__init__")
        return None

    def _lookup_singleton(self, module: _Module, inst: str,
                          method: str) -> _Func | None:
        cls_name = module.singletons.get(inst)
        if cls_name is None:
            return None
        return module.funcs.get(f"{cls_name}.{method}")

    def resolve(self, module: _Module, name: str,
                cls: str | None) -> _Func | None:
        """Spelled call name -> defining _Func, or None."""
        parts = name.split(".")
        head, rest = parts[0], parts[1:]
        if head == "self" and cls is not None and len(rest) == 1:
            return self._lookup(module, f"{cls}.{rest[0]}")
        for kind, *info in module.imports.get(head, ()):
            if kind == "member" and not rest:
                target = self._resolve_module(info[0])
                if target is not None:
                    f = self._lookup(target, info[1])
                    if f is not None:
                        return f
            elif kind == "member" and len(rest) == 1:
                # imported singleton: `from ...timers import ENGINE;
                # ENGINE.incr(...)`
                target = self._resolve_module(info[0])
                if target is not None:
                    f = self._lookup_singleton(target, info[1], rest[0])
                    if f is not None:
                        return f
            elif kind == "mod" and rest:
                target = self._resolve_module(
                    ".".join([info[0]] + rest[:-1]))
                if target is not None:
                    f = self._lookup(target, rest[-1])
                    if f is not None:
                        return f
                if len(rest) >= 2:
                    # module-attribute singleton: `import ...timers as t;
                    # t.ENGINE.incr(...)` / `obs_events.LOG.emit(...)`
                    target = self._resolve_module(
                        ".".join([info[0]] + rest[:-2]))
                    if target is not None:
                        f = self._lookup_singleton(target, rest[-2],
                                                   rest[-1])
                        if f is not None:
                            return f
        if not rest:
            # same-module function (or Class.method spelled directly)
            return self._lookup(module, head)
        if len(rest) == 1:
            # same-module singleton: `LOG.emit(...)` under `LOG = EventLog()`
            f = self._lookup_singleton(module, head, rest[0])
            if f is not None:
                return f
        # fully-dotted spelling against the module set, longest prefix
        for split in range(len(parts) - 1, 0, -1):
            target = self.by_name.get(".".join(parts[:split]))
            if target is not None:
                return self._lookup(target, ".".join(parts[split:]))
        # Class.method within the same module
        if len(parts) == 2:
            return self._lookup(module, name)
        return None

    def taint(self, func: _Func) -> tuple | None:
        """Witness that func transitively performs an unordered reduction:
        (chain labels, reduction file, line, spelled name); None if clean.

        Memoized, but cycle-safe: a clean verdict computed while the walk
        was inside a call cycle is provisional (the on-stack ancestor's
        taint was unknown at the time), so only witnesses and
        cycle-independent Nones are cached -- naively caching the
        in-progress None would finalize an ancestor as clean even when its
        only route to a reduction runs through the cycle."""
        witness, _ = self._taint(func, set())
        return witness

    def _taint(self, func: _Func, stack: set) -> tuple:
        """(witness, provisional): provisional=True means the clean verdict
        depended on an on-stack node and must not be memoized."""
        key = (func.module, func.label)
        if key in self._taint_memo:
            return self._taint_memo[key], False
        if key in stack:
            return None, True  # cycle edge: the ancestor decides
        if func.reductions:
            line, name = func.reductions[0]
            witness = ([func.label], func.file, line, name)
            self._taint_memo[key] = witness
            return witness, False
        stack.add(key)
        witness = None
        provisional = False
        module = self.by_name[func.module]
        for _lineno, name, cls in func.calls:
            callee = self.resolve(module, name, cls)
            if callee is None:
                continue
            w, p = self._taint(callee, stack)
            provisional = provisional or p
            if w is not None:
                witness = ([func.label] + w[0], w[1], w[2], w[3])
                break
        stack.discard(key)
        if witness is not None or not provisional:
            self._taint_memo[key] = witness
        return witness, witness is None and provisional


def build(units: list[LintUnit]) -> tuple[list, "_Graph"]:
    """Collect the whole-program (modules, graph) pair ONCE per lint
    run: this pass and the LCK/BLK/TSI concurrency pass (lockrules)
    both walk the same resolved call graph, and rebuilding it per pass
    doubles the dominant AST-walk cost of `make lint`."""
    modules = [_collect(u) for u in units if u.tree is not None]
    return modules, _Graph(modules)


def check(units: list[LintUnit], *,
          prebuilt: tuple | None = None) -> tuple[list[Finding],
                                                  list[Finding],
                                                  set[tuple[str, int]]]:
    """The interprocedural pass over one lint run's unit set.

    Returns (findings, raw_findings, used_source_escapes): findings honor
    call-site `fld-proof` escapes, raw_findings ignore them (the
    suppression audit derives escape usage from the difference), and
    used_source_escapes are (file, line) of escapes that suppressed a
    reduction at its source, which keeps the callee untainted -- also
    "used" for the audit.  prebuilt: a build(units) result to reuse."""
    modules, graph = prebuilt if prebuilt is not None else build(units)
    findings: list[Finding] = []
    raw: list[Finding] = []
    used: set[tuple[str, int]] = set()
    for m in modules:
        for line in m.used_escapes:
            used.add((m.unit.file, line))
    for m in modules:
        if not m.unit.numeric:
            continue
        escapes = set(m.unit.escapes["FLD"])
        calls = list(m.toplevel_calls)
        for func in m.funcs.values():
            calls.extend(func.calls)
        seen: set[tuple[int, str]] = set()
        for lineno, name, cls in sorted(calls):
            callee = graph.resolve(m, name, cls)
            if callee is None or callee.module == m.module:
                continue
            callee_unit_numeric = graph.by_name[callee.module].unit.numeric
            if callee_unit_numeric:
                continue  # the reduction is flagged (or escaped) at source
            w = graph.taint(callee)
            if w is None or (lineno, name) in seen:
                continue
            seen.add((lineno, name))
            chain, red_file, red_line, red_name = w
            f = Finding(
                m.unit.file, lineno, "FLD",
                f"`{name}` reaches an unordered reduction outside the "
                f"numeric modules: {' -> '.join(chain)} -> `{red_name}` "
                f"({red_file}:{red_line}); fold order is load-bearing on "
                "the numeric path (SURVEY.md 2.9) -- make the helper "
                "order-preserving, prove it at the source with "
                "fld-proof(<reason>), or escape this call site")
            raw.append(f)
            if lineno not in escapes and lineno - 1 not in escapes:
                findings.append(f)
    return findings, raw, used
