"""The AST rule families: FLD (ordered fold), KNB (knob registry), BKD
(import-time backend touch).  DOC lives in docrules.py (it diffs generated
docs, not syntax trees).

All three share the dotted-name helper: rules match on the *spelled*
call -- `jnp.sum`, `jax.lax.psum`, `x.sum()` -- which is what a reviewer
reads and what a future PR would actually type.  Aliased imports
(`from jax.numpy import sum as s`) can evade an AST linter; the rule set
trades that corner for zero-dependency speed, and the tier-1 self-lint
keeps the package idiom uniform enough that the spelled form is the form.
"""

from __future__ import annotations

import ast

from spgemm_tpu.analysis.core import Finding

# ---------------------------------------------------------------- FLD ----
# Unordered-reduction call names.  `.sum()` as a METHOD on anything is
# flagged too: on the numeric path even a host-side numpy sum over values
# is a fold whose order must be argued, and the fld-proof escape hatch
# (reason mandatory) is exactly that argument.
# Builtin bare `sum(...)` is a left fold (ordered) and stays legal.
FLD_TERMINALS = {"psum", "psum_scatter", "segment_sum", "tree_reduce"}
FLD_REDUCE_NAMESPACES = {"functools", "ft"}

# ---------------------------------------------------------------- KNB ----
KNOB_PREFIX = "SPGEMM_TPU_"
ENVIRON_GETTERS = {"os.environ.get", "environ.get", "os.getenv", "getenv",
                   "os.environ.pop", "environ.pop",
                   "os.environ.setdefault", "environ.setdefault"}
ENVIRON_MAPS = {"os.environ", "environ"}

# ---------------------------------------------------------------- BKD ----
# Calls that initialize or query a backend.  On this environment a dead
# TPU HANGS inside backend init (utils/backend_probe docstring), so any of
# these at module-import time can wedge a bare `import spgemm_tpu.x`.
BACKEND_CALLS = {
    "jax.devices", "jax.local_devices", "jax.device_count",
    "jax.local_device_count", "jax.process_count", "jax.process_index",
    "jax.default_backend", "jax.device_put",
    "xla_bridge.get_backend", "xla_bridge.backends",
}
# Any CALL into the array namespace materializes a concrete array, which
# initializes the default backend just as surely as jax.devices() --
# `_ZERO = jnp.zeros(...)` at module scope is the most common spelling of
# the hazard.  (Attribute access like `jnp.uint32` as a dtype is fine;
# only calls are flagged.)
BACKEND_NAMESPACES = ("jnp.", "jax.numpy.")

# Functions marked with this decorator (utils/backend_probe.host_only) run
# on host planner/worker threads -- the chain plan-ahead planner, OOC
# staging helpers -- where a backend touch does not just hang: it hangs a
# thread the main loop is blocked on, with no exception to fail over on.
# Their WHOLE body is scanned for backend calls, not just import time.
HOST_ONLY_DECORATOR = "host_only"


def dotted_name(node: ast.expr) -> str | None:
    """'jax.lax.psum' for Attribute/Name chains; None for anything else
    (subscripts, calls, literals)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _str_const(node: ast.expr) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def fld_violation(name: str) -> str | None:
    """The finding message for a spelled call name that is an unordered
    reduction, or None.  Shared by the per-module FLD pass below and the
    interprocedural taint scan (analysis/callgraph.py)."""
    head, _, last = name.rpartition(".")
    root = head.split(".", 1)[0] if head else ""
    if last in FLD_TERMINALS:
        return (f"unordered reduction `{name}` on the numeric path: the "
                "wrap-then-mod fold is non-associative (SURVEY.md 2.9)")
    if last == "sum" and head:  # any `<expr>.sum(...)` method/ns call
        return (f"`{name}` is an unordered reduction: the reference "
                "fold order is load-bearing on the numeric path "
                "(SURVEY.md 2.9); use the ordered MAC/fold helpers "
                "(ops/u64.py) or escape with a fld-proof(<reason>)")
    if last == "reduce" and (root in FLD_REDUCE_NAMESPACES or not head):
        return (f"`{name}` folds in container-iteration order, not the "
                "reference's j-ascending pair order; spell the fold "
                "explicitly or escape with fld-proof(<reason>)")
    return None


def check_fld(tree: ast.AST, file: str, escapes: set[int]) -> list[Finding]:
    """FLD: unordered reductions on the numeric path.

    A call is escaped when its own line (or the line directly above it,
    for wrapped expressions) carries `# spgemm-lint: fld-proof(<reason>)`.
    """
    findings = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        name = dotted_name(node.func)
        if name is None:
            continue
        bad = fld_violation(name)
        if bad is None:
            continue
        if node.lineno in escapes or node.lineno - 1 in escapes:
            continue
        findings.append(Finding(file, node.lineno, "FLD", bad))
    return findings


def check_knb(tree: ast.AST, file: str) -> list[Finding]:
    """KNB: raw SPGEMM_TPU_* environment READS outside the registry.

    Writes (`os.environ[k] = v`, Store/Del contexts) stay legal: that is
    how A/B harnesses and tests drive knob values for code that then
    reads them through the registry."""
    findings = []
    msg = ("raw environment read of {key!r}: SPGEMM_TPU_* knobs must go "
           "through spgemm_tpu.utils.knobs (register the knob and call "
           "knobs.get)")
    for node in ast.walk(tree):
        key = None
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name in ENVIRON_GETTERS and node.args:
                key = _str_const(node.args[0])
        elif isinstance(node, ast.Subscript):
            if (isinstance(node.ctx, ast.Load)
                    and dotted_name(node.value) in ENVIRON_MAPS):
                key = _str_const(node.slice)
        if key is not None and key.startswith(KNOB_PREFIX):
            findings.append(Finding(file, node.lineno, "KNB",
                                    msg.format(key=key)))
    return findings


class _ImportTimeVisitor:
    """Collects backend-touching calls that execute at module import.

    Function/lambda BODIES are deferred (not import time), but their
    decorators and default-argument expressions evaluate at definition
    time -- at module scope that IS import time, so those are visited in
    the enclosing scope.  Class bodies execute at import and are walked.
    `if __name__ == "__main__"` blocks are skipped: they never run on a
    bare import, and a script driver touching the backend (after probing)
    is the CLI's job, not an import hazard.
    """

    def __init__(self, file: str):
        self.file = file
        self.findings: list[Finding] = []

    @staticmethod
    def _is_main_guard(node: ast.AST) -> bool:
        if not isinstance(node, ast.If):
            return False
        t = node.test
        return (isinstance(t, ast.Compare)
                and isinstance(t.left, ast.Name) and t.left.id == "__name__"
                and len(t.comparators) == 1
                and _str_const(t.comparators[0]) == "__main__")

    @staticmethod
    def _is_host_only(node: ast.AST) -> bool:
        for dec in getattr(node, "decorator_list", ()):
            target = dec.func if isinstance(dec, ast.Call) else dec
            name = dotted_name(target)
            if name is not None and (name == HOST_ONLY_DECORATOR
                                     or name.endswith("." + HOST_ONLY_DECORATOR)):
                return True
        return False

    def _scan_host_only(self, fn: ast.AST) -> None:
        """Flag every backend-touching call anywhere in a @host_only body
        (nested defs and lambdas included: they run on the same thread)."""
        for stmt in fn.body:
            for node in ast.walk(stmt):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                if name is not None and (name in BACKEND_CALLS
                                         or name.startswith(BACKEND_NAMESPACES)):
                    self.findings.append(Finding(
                        self.file, node.lineno, "BKD",
                        f"`{name}()` inside @host_only `{fn.name}`: "
                        "planner/worker-thread helpers must never touch a "
                        "backend (plans are pure numpy -- a backend hang "
                        "on a worker thread wedges the pipeline with no "
                        "exception to fail over on); resolve platform/"
                        "backend on the main thread and pass them in"))

    def visit(self, node: ast.AST) -> None:
        if self._is_main_guard(node):
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                self.visit(dec)
            for default in (node.args.defaults + node.args.kw_defaults):
                if default is not None:
                    self.visit(default)
            if self._is_host_only(node):
                self._scan_host_only(node)
            return  # body runs only when called (host_only scanned above)
        if isinstance(node, ast.Lambda):
            for default in (node.args.defaults + node.args.kw_defaults):
                if default is not None:
                    self.visit(default)
            return
        if isinstance(node, ast.Call):
            name = dotted_name(node.func)
            if name is not None and (name in BACKEND_CALLS
                                     or name.startswith(BACKEND_NAMESPACES)):
                self.findings.append(Finding(
                    self.file, node.lineno, "BKD",
                    f"`{name}()` at module import time initializes a "
                    "backend: a dead TPU hangs inside backend init (never "
                    "raises), so backends may only be touched lazily, "
                    "after utils/backend_probe has probed or pinned a "
                    "platform"))
        for child in ast.iter_child_nodes(node):
            self.visit(child)


def check_bkd(tree: ast.AST, file: str) -> list[Finding]:
    """BKD: module-import-time backend-touching calls."""
    visitor = _ImportTimeVisitor(file)
    visitor.visit(tree)
    return visitor.findings
