"""spgemm-lint driver: file walking, rule scoping, findings, suppressions.

Rule scoping is by path SUFFIX (posix-normalized), so the test fixtures
under tests/lint_fixtures/ops/... exercise exactly the production scoping
logic.  Everything here is stdlib-only (ast + os): the linter must be
runnable in CI without initializing jax -- importing a backend to lint for
backend-touching imports would be self-defeating on a host whose TPU hangs.

v2 grew the per-module AST passes into a package-level analysis: a run
over several files parses them once into LintUnits, runs the per-file
rule families, then the interprocedural fold-order pass (callgraph.py)
over the whole unit set, and finally the suppression audit -- every
escape-hatch comment is inventoried, and an escape whose underlying
finding no longer exists is itself a finding (SUP, like an unused noqa).
"""

from __future__ import annotations

import ast
import fnmatch
import io
import os
import tokenize
from dataclasses import asdict, dataclass

# FLD scope: the modules on the numeric path, where the reference's
# wrap-then-mod fold order is load-bearing (SURVEY.md section 2.9).
# Suffixes carry a leading "/" so matching is path-segment-anchored
# (a hypothetical devops/spgemm.py must not land in numeric scope).
NUMERIC_SUFFIXES = (
    "/ops/u64.py",
    "/ops/spgemm.py",
    "/ops/mxu_spgemm.py",
    "/ops/estimate.py",
    "/ops/delta.py",
    "/parallel/ring.py",
    "/parallel/rowshard.py",
)
NUMERIC_GLOBS = ("*/ops/pallas_*.py",)

# KNB exemption: the registry itself is the one blessed reader.
KNOB_REGISTRY_SUFFIX = "/utils/knobs.py"
# BKD exemption: the probe exists precisely to touch the backend safely.
BACKEND_PROBE_SUFFIX = "/utils/backend_probe.py"

# Escape-hatch directives, one marker per rule family that has one.  Every
# escape needs a non-empty reason -- the reason is the reviewable citation
# -- and every escape is audited: one that suppresses nothing is a SUP
# finding (see lint_report).
FLD_ESCAPE = "spgemm-lint: fld-proof("
THR_ESCAPE = "spgemm-lint: thr-ok("
EXC_ESCAPE = "spgemm-lint: exc-ok("
ESCAPE_MARKERS = {"FLD": FLD_ESCAPE, "THR": THR_ESCAPE, "EXC": EXC_ESCAPE}

# The rule-id registry: single source for the CLI --help epilog, the JSON
# counts object, and the SARIF tool.driver.rules metadata (docrules checks
# the --help epilog covers every id, so the list cannot silently drift).
RULES = {
    "FLD": "unordered reduction on the numeric path (fold order is "
           "load-bearing; includes the interprocedural pass: a numeric-"
           "module call into a helper that transitively performs an "
           "unordered reduction); escape: fld-proof(<reason>)",
    "KNB": "raw SPGEMM_TPU_* environment read outside the central registry "
           "spgemm_tpu/utils/knobs.py",
    "BKD": "backend-touching call at module import time (or anywhere in a "
           "@host_only worker body) outside utils/backend_probe.py",
    "THR": "attribute declared `# spgemm-lint: guarded-by(<lock>)` "
           "accessed without holding the lock; escape: thr-ok(<reason>)",
    "EXC": "broad `except Exception` without a `# noqa: BLE001 -- "
           "<reason>` justification, or a bare except / "
           "`except BaseException` that does not provably re-raise "
           "(the JobAbandoned contract); escape: exc-ok(<reason>)",
    "MET": "ENGINE.phase/record/incr metric name that is not a string "
           "literal declared in the metrics registry "
           "spgemm_tpu/obs/metrics.py (no ad-hoc time-series names)",
    "FPT": "failpoints.check() name that is not a string literal "
           "declared in the failpoint registry "
           "spgemm_tpu/utils/failpoints.py, or a registry entry with no "
           "check() site anywhere in the package (stale chaos surface)",
    "DOC": "generated doc drift (CLAUDE.md knob table, ARCHITECTURE.md "
           "metrics table, CLI help knob coverage, analysis --help "
           "rule-id coverage)",
    "SUP": "stale suppression: an escape-hatch comment whose underlying "
           "finding no longer exists (delete the escape)",
    "PARSE": "file does not parse (no other rule ran on it)",
}


@dataclass(frozen=True)
class Finding:
    file: str   # repo-relative posix path (absolute if outside the repo)
    line: int   # 1-indexed
    rule: str   # family id: see RULES
    message: str

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass(frozen=True)
class Suppression:
    """One escape-hatch comment, for the --json inventory.  stale=True
    means the escape suppresses nothing (also reported as a SUP finding)."""

    file: str
    line: int
    rule: str    # the family the escape belongs to (FLD | THR | EXC)
    reason: str
    stale: bool

    def to_dict(self) -> dict:
        return asdict(self)


def repo_root() -> str:
    """The directory containing the spgemm_tpu package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _posix(path: str) -> str:
    return os.path.abspath(path).replace(os.sep, "/")


def rel_file(path: str) -> str:
    """Repo-relative posix path for findings (absolute when outside)."""
    root = _posix(repo_root())
    p = _posix(path)
    if p.startswith(root + "/"):
        return p[len(root) + 1:]
    return p


def is_numeric_module(path: str) -> bool:
    p = _posix(path)
    return (p.endswith(NUMERIC_SUFFIXES)
            or any(fnmatch.fnmatch(p, g) for g in NUMERIC_GLOBS))


def comment_map(source: str) -> dict[int, str]:
    """1-indexed line -> comment text (including the `#`).  Tokenize-based,
    so directive markers quoted in docstrings or string literals (this very
    package documents its own markers) never register as live directives.
    A file that fails to tokenize yields {} -- it will carry a PARSE
    finding and no directive-driven rule runs on it anyway."""
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return {}
    return out


def _escape_map(comments: dict[int, str], marker: str) -> dict[int, str]:
    """1-indexed line -> reason for escape-hatch directives with a
    non-empty reason.  A bare `fld-proof()` is NOT an escape: the reason
    is the reviewable proof citation."""
    out: dict[int, str] = {}
    for i, text in comments.items():
        pos = text.find(marker)
        if pos < 0:
            continue
        rest = text[pos + len(marker):]
        reason = rest.split(")", 1)[0].strip()
        if reason:
            out[i] = reason
    return out


class LintUnit:
    """One parsed file: source, AST (None on a syntax error), numeric-path
    scoping, and the per-rule escape maps.  Parsed once per run and shared
    by the per-file rules, the interprocedural pass, and the audit."""

    def __init__(self, path: str, *, numeric: bool | None = None):
        self.path = path
        self.file = rel_file(path)
        with open(path, encoding="utf-8") as f:
            self.source = f.read()
        self.parse_finding: Finding | None = None
        try:
            self.tree: ast.AST | None = ast.parse(self.source, filename=path)
        except SyntaxError as e:
            self.tree = None
            # a broken file means NO rule ran on it -- its own rule id, so
            # JSON-count consumers never blame a rule family for it
            self.parse_finding = Finding(
                self.file, e.lineno or 1, "PARSE",
                f"file does not parse: {e.msg}")
        self.numeric = is_numeric_module(path) if numeric is None else numeric
        self.comments = comment_map(self.source)
        self.escapes = {rule: _escape_map(self.comments, marker)
                        for rule, marker in ESCAPE_MARKERS.items()}


def _lint_unit(unit: LintUnit) -> tuple[list[Finding],
                                        set[tuple[str, str, int]]]:
    """The per-file rule families (FLD/KNB/BKD/THR/EXC) over one unit.

    Each escapable family runs ONCE with escapes ignored; the escape
    filter is applied here, so the same pass yields both the surviving
    findings and the raw (file, rule, line) triples the suppression audit
    needs to tell used escapes from stale ones."""
    from spgemm_tpu.analysis import (excrules, fptrules, metrules,  # noqa: PLC0415
                                     rules, thrrules)

    if unit.tree is None:
        return [unit.parse_finding], set()
    p = _posix(unit.path)
    findings: list[Finding] = []
    raw: set[tuple[str, str, int]] = set()

    def escaping(family: list[Finding], rule: str) -> list[Finding]:
        escapes = set(unit.escapes[rule])
        out = []
        for f in family:
            raw.add((f.file, rule, f.line))
            if f.line not in escapes and f.line - 1 not in escapes:
                out.append(f)
        return out

    if unit.numeric:
        findings += escaping(rules.check_fld(unit.tree, unit.file, set()),
                             "FLD")
    if not p.endswith(KNOB_REGISTRY_SUFFIX):
        findings += rules.check_knb(unit.tree, unit.file)
    if not p.endswith(BACKEND_PROBE_SUFFIX):
        findings += rules.check_bkd(unit.tree, unit.file)
    findings += escaping(thrrules.check_thr(unit, set()), "THR")
    findings += escaping(excrules.check_exc(unit, set()), "EXC")
    findings += metrules.check_met(unit.tree, unit.file)
    findings += fptrules.check_fpt(unit.tree, unit.file)
    return findings, raw


def lint_file(path: str, *, numeric: bool | None = None) -> list[Finding]:
    """Run the per-file rule families over one file.

    numeric: override the path-based FLD scoping (tests); None = derive
    from the path suffix.  The cross-file passes (interprocedural FLD,
    suppression audit) need the whole unit set -- use lint_paths."""
    return _lint_unit(LintUnit(path, numeric=numeric))[0]


def _walk_py(path: str) -> list[str]:
    if os.path.isfile(path):
        return [path]
    out = []
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        out.extend(os.path.join(dirpath, f) for f in sorted(filenames)
                   if f.endswith(".py"))
    return out


def _audit_suppressions(units: list[LintUnit],
                        raw: set[tuple[str, str, int]],
                        extra_used: set[tuple[str, int]]) -> list[Suppression]:
    """The suppression inventory.  An escape is USED when the raw run of
    its rule family (escapes ignored -- the (file, rule, line) triples the
    per-file pass already produced) has a finding on the escape's line or
    the line below (the two lines an escape can attach to), or -- for
    FLD -- when it sits on an unordered reduction whose taint it suppresses
    in the interprocedural pass (extra_used, from callgraph.check)."""
    out: list[Suppression] = []
    for u in units:
        for rule, escapes in u.escapes.items():
            for line, reason in sorted(escapes.items()):
                used = ((u.file, rule, line) in raw
                        or (u.file, rule, line + 1) in raw
                        or (rule == "FLD" and ((u.file, line) in extra_used
                                               or (u.file, line + 1)
                                               in extra_used)))
                out.append(Suppression(u.file, line, rule, reason,
                                       stale=not used))
    return out


def lint_report(paths: list[str], *, claude_md: str | None = None,
                doc: bool = True) -> tuple[list[Finding], list[Suppression]]:
    """The full v2 run over files/directories: per-file rules, the
    interprocedural fold-order pass, the suppression audit (stale escapes
    are SUP findings; the full inventory is returned for --json), and
    optionally the DOC drift checks (claude_md None = skip the table
    check; the CLI/analysis help checks ride the same flag)."""
    from spgemm_tpu.analysis import callgraph, docrules, fptrules  # noqa: PLC0415

    units = [LintUnit(f) for path in paths for f in _walk_py(path)]
    findings: list[Finding] = []
    raw: set[tuple[str, str, int]] = set()
    for u in units:
        unit_findings, unit_raw = _lint_unit(u)
        findings += unit_findings
        raw |= unit_raw
    # the FPT stale-entry direction needs the whole unit set (a registry
    # entry is live if ANY module checks it); it self-gates on the
    # registry module being in scope, so fixture runs stay quiet
    findings += fptrules.check_fpt_registry(units)
    cg_findings, cg_raw, cg_used = callgraph.check(units)
    findings += cg_findings
    # interprocedural raw findings feed the audit exactly like per-file
    # raw runs: a call-site escape is used iff a raw finding sits ON the
    # escape's line or the line below -- the audit itself checks both, so
    # only the finding's own line goes into the used set (widening it
    # here would vouch for an escape two lines above the finding, which
    # suppresses nothing)
    used = set(cg_used)
    for f in cg_raw:
        used.add((f.file, f.line))
    suppressions = _audit_suppressions(units, raw, used)
    for s in suppressions:
        if s.stale:
            findings.append(Finding(
                s.file, s.line, "SUP",
                f"stale suppression: `{ESCAPE_MARKERS[s.rule]}{s.reason})` "
                f"suppresses nothing here (no underlying {s.rule} finding "
                "on this or the next line); delete the escape comment"))
    if doc:
        if claude_md is not None:
            findings += docrules.check_claude_md(claude_md)
            # the metrics table lives in ARCHITECTURE.md beside the
            # CLAUDE.md in play.  Only a CUSTOM --claude-md with no
            # sibling ARCHITECTURE.md (fixture runs) skips the check; on
            # the repo's own doc set a missing/renamed ARCHITECTURE.md is
            # a DOC finding ("cannot read"), never a silently disabled
            # drift guard -- symmetric with the knob table.
            doc_dir = os.path.dirname(os.path.abspath(claude_md))
            arch = os.path.join(doc_dir, "ARCHITECTURE.md")
            if os.path.exists(arch) or doc_dir == _posix(repo_root()) \
                    or doc_dir == repo_root():
                findings += docrules.check_architecture_md(arch)
        findings += docrules.check_cli_help()
        findings += docrules.check_analysis_help()
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings, suppressions


def lint_paths(paths: list[str], *, claude_md: str | None = None,
               doc: bool = True) -> list[Finding]:
    """lint_report without the suppression inventory (findings only)."""
    return lint_report(paths, claude_md=claude_md, doc=doc)[0]


def default_paths() -> list[str]:
    """The default lint scope: the package plus the driver-facing scripts
    that read engine knobs (bench.py, benchmarks/, the graft entry).
    tests/ stays out -- fixtures seed violations on purpose, and tests
    legitimately poke knob values via monkeypatch."""
    root = repo_root()
    return [p for p in (os.path.join(root, "spgemm_tpu"),
                        os.path.join(root, "bench.py"),
                        os.path.join(root, "__graft_entry__.py"),
                        os.path.join(root, "benchmarks"))
            if os.path.exists(p)]


def lint_repo() -> list[Finding]:
    """Self-lint the default scope + the repo docs: the tier-1 contract is
    that this returns []."""
    return lint_paths(default_paths(),
                      claude_md=os.path.join(repo_root(), "CLAUDE.md"))
