"""spgemm-lint driver: file walking, rule scoping, findings, suppressions.

Rule scoping is by path SUFFIX (posix-normalized), so the test fixtures
under tests/lint_fixtures/ops/... exercise exactly the production scoping
logic.  Everything here is stdlib-only (ast + os): the linter must be
runnable in CI without initializing jax -- importing a backend to lint for
backend-touching imports would be self-defeating on a host whose TPU hangs.

v2 grew the per-module AST passes into a package-level analysis: a run
over several files parses them once into LintUnits, runs the per-file
rule families, then the interprocedural fold-order pass (callgraph.py)
over the whole unit set, and finally the suppression audit -- every
escape-hatch comment is inventoried, and an escape whose underlying
finding no longer exists is itself a finding (SUP, like an unused noqa).
"""

from __future__ import annotations

import ast
import fnmatch
import hashlib
import io
import json
import os
import sys
import tokenize
from dataclasses import asdict, dataclass, field

# FLD scope: the modules on the numeric path, where the reference's
# wrap-then-mod fold order is load-bearing (SURVEY.md section 2.9).
# Suffixes carry a leading "/" so matching is path-segment-anchored
# (a hypothetical devops/spgemm.py must not land in numeric scope).
NUMERIC_SUFFIXES = (
    "/ops/u64.py",
    "/ops/spgemm.py",
    "/ops/mxu_spgemm.py",
    "/ops/estimate.py",
    "/ops/delta.py",
    "/parallel/ring.py",
    "/parallel/rowshard.py",
)
NUMERIC_GLOBS = ("*/ops/pallas_*.py",)

# KNB exemption: the registry itself is the one blessed reader.
KNOB_REGISTRY_SUFFIX = "/utils/knobs.py"
# BKD exemption: the probe exists precisely to touch the backend safely.
BACKEND_PROBE_SUFFIX = "/utils/backend_probe.py"

# Escape-hatch directives, one marker per rule family that has one.  Every
# escape needs a non-empty reason -- the reason is the reviewable citation
# -- and every escape is audited: one that suppresses nothing is a SUP
# finding (see lint_report).
FLD_ESCAPE = "spgemm-lint: fld-proof("
THR_ESCAPE = "spgemm-lint: thr-ok("
EXC_ESCAPE = "spgemm-lint: exc-ok("
LCK_ESCAPE = "spgemm-lint: lck-ok("
BLK_ESCAPE = "spgemm-lint: blk-ok("
TSI_ESCAPE = "spgemm-lint: tsi-ok("
DRF_ESCAPE = "spgemm-lint: drf-ok("
ESCAPE_MARKERS = {"FLD": FLD_ESCAPE, "THR": THR_ESCAPE, "EXC": EXC_ESCAPE,
                  "LCK": LCK_ESCAPE, "BLK": BLK_ESCAPE, "TSI": TSI_ESCAPE,
                  "DRF": DRF_ESCAPE}

# The rule-id registry: single source for the CLI --help epilog, the JSON
# counts object, and the SARIF tool.driver.rules metadata (docrules checks
# the --help epilog covers every id, so the list cannot silently drift).
RULES = {
    "FLD": "unordered reduction on the numeric path (fold order is "
           "load-bearing; includes the interprocedural pass: a numeric-"
           "module call into a helper that transitively performs an "
           "unordered reduction); escape: fld-proof(<reason>)",
    "KNB": "raw SPGEMM_TPU_* environment read outside the central registry "
           "spgemm_tpu/utils/knobs.py",
    "BKD": "backend-touching call at module import time (or anywhere in a "
           "@host_only worker body) outside utils/backend_probe.py",
    "THR": "attribute declared `# spgemm-lint: guarded-by(<lock>)` "
           "accessed without holding the lock; escape: thr-ok(<reason>)",
    "LCK": "lock-order deadlock hazard: a cycle in the interprocedural "
           "lock-acquisition-order graph (two paths acquire registered "
           "locks in opposite orders), or a non-reentrant lock "
           "re-acquired while already held; escape: lck-ok(<reason>)",
    "BLK": "blocking operation (sleep, subprocess, flock/fsync, socket "
           "accept/recv/sendall, Queue.get/put, Thread.join, "
           "Event/Condition.wait, block_until_ready) reached while a "
           "registered lock is held, with the witness chain; escape: "
           "blk-ok(<reason>)",
    "TSI": "thread-shared inference: an instance attribute or module "
           "global written from >= 2 thread roots "
           "(threading.Thread targets) without a guarded-by(<lock>) "
           "annotation -- THR's opt-in hole, closed; escape: "
           "tsi-ok(<reason>)",
    "EXC": "broad `except Exception` without a `# noqa: BLE001 -- "
           "<reason>` justification, or a bare except / "
           "`except BaseException` that does not provably re-raise "
           "(the JobAbandoned contract); escape: exc-ok(<reason>)",
    "MET": "ENGINE.phase/record/incr metric name that is not a string "
           "literal declared in the metrics registry "
           "spgemm_tpu/obs/metrics.py (no ad-hoc time-series names)",
    "FPT": "failpoints.check() name that is not a string literal "
           "declared in the failpoint registry "
           "spgemm_tpu/utils/failpoints.py, or a registry entry with no "
           "check() site anywhere in the package (stale chaos surface)",
    "PRO": "wire-contract violation against the serve/protocol.py "
           "registry: an undeclared request/response field literal for "
           "the op in play, an unknown op in a message literal, an "
           "error code that is not a declared ERROR_CODES value, a "
           "hardcoded protocol version (rolling-upgrade hazard), or an "
           "incoherent registry (request/response op mismatch, a "
           "post-v1 field missing its FIELD_MIN_VERSION entry, E_* "
           "constants out of sync with ERROR_CODES)",
    "EVT": "emit()/LOG.emit() event kind that is not a string literal "
           "declared in the event registry spgemm_tpu/obs/events.py "
           "EVENT_KINDS (no ad-hoc event streams)",
    "DRF": "registry drift (the reverse audit): a declared knob never "
           "read through knobs.get(), an ENGINE phase/counter or metric "
           "family never referenced, an event kind never emitted, or a "
           "protocol field / error code never referenced anywhere in "
           "the package; escape: drf-ok(<reason>)",
    "DOC": "generated doc drift (CLAUDE.md knob table, ARCHITECTURE.md "
           "metrics + protocol + event tables, CLI help knob coverage, "
           "analysis --help rule-id coverage)",
    "SUP": "stale suppression: an escape-hatch comment whose underlying "
           "finding no longer exists (delete the escape)",
    "PARSE": "file does not parse (no other rule ran on it)",
}


@dataclass(frozen=True)
class Finding:
    file: str   # repo-relative posix path (absolute if outside the repo)
    line: int   # 1-indexed
    rule: str   # family id: see RULES
    message: str

    def to_dict(self) -> dict:
        return asdict(self)


@dataclass(frozen=True)
class Suppression:
    """One escape-hatch comment, for the --json inventory.  stale=True
    means the escape suppresses nothing (also reported as a SUP finding)."""

    file: str
    line: int
    rule: str    # escape family (FLD | THR | EXC | LCK | BLK | TSI | DRF)
    reason: str
    stale: bool

    def to_dict(self) -> dict:
        return asdict(self)


def repo_root() -> str:
    """The directory containing the spgemm_tpu package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _posix(path: str) -> str:
    return os.path.abspath(path).replace(os.sep, "/")


def rel_file(path: str) -> str:
    """Repo-relative posix path for findings (absolute when outside)."""
    root = _posix(repo_root())
    p = _posix(path)
    if p.startswith(root + "/"):
        return p[len(root) + 1:]
    return p


def is_numeric_module(path: str) -> bool:
    p = _posix(path)
    return (p.endswith(NUMERIC_SUFFIXES)
            or any(fnmatch.fnmatch(p, g) for g in NUMERIC_GLOBS))


def comment_map(source: str) -> dict[int, str]:
    """1-indexed line -> comment text (including the `#`).  Tokenize-based,
    so directive markers quoted in docstrings or string literals (this very
    package documents its own markers) never register as live directives.
    A file that fails to tokenize yields {} -- it will carry a PARSE
    finding and no directive-driven rule runs on it anyway."""
    out: dict[int, str] = {}
    try:
        for tok in tokenize.generate_tokens(io.StringIO(source).readline):
            if tok.type == tokenize.COMMENT:
                out[tok.start[0]] = tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return {}
    return out


def _escape_map(comments: dict[int, str], marker: str) -> dict[int, str]:
    """1-indexed line -> reason for escape-hatch directives with a
    non-empty reason.  A bare `fld-proof()` is NOT an escape: the reason
    is the reviewable proof citation."""
    out: dict[int, str] = {}
    for i, text in comments.items():
        pos = text.find(marker)
        if pos < 0:
            continue
        rest = text[pos + len(marker):]
        reason = rest.split(")", 1)[0].strip()
        if reason:
            out[i] = reason
    return out


class LintUnit:
    """One parsed file: source, AST (None on a syntax error), numeric-path
    scoping, and the per-rule escape maps.  Parsed once per run and shared
    by the per-file rules, the interprocedural pass, and the audit."""

    def __init__(self, path: str, *, numeric: bool | None = None):
        self.path = path
        self.file = rel_file(path)
        with open(path, encoding="utf-8") as f:
            self.source = f.read()
        self.parse_finding: Finding | None = None
        try:
            self.tree: ast.AST | None = ast.parse(self.source, filename=path)
        except SyntaxError as e:
            self.tree = None
            # a broken file means NO rule ran on it -- its own rule id, so
            # JSON-count consumers never blame a rule family for it
            self.parse_finding = Finding(
                self.file, e.lineno or 1, "PARSE",
                f"file does not parse: {e.msg}")
        self.numeric = is_numeric_module(path) if numeric is None else numeric
        self.comments = comment_map(self.source)
        self.escapes = {rule: _escape_map(self.comments, marker)
                        for rule, marker in ESCAPE_MARKERS.items()}


def escape_at(escapes: dict[int, str], line: int) -> int | None:
    """The escape line covering `line` -- the line itself or the one
    above (the two lines every spgemm-lint escape can attach to).  THE
    one spelling of the attachment rule: the per-file filter, the
    suppressed-split, and the lockrules emit paths all call this."""
    if line in escapes:
        return line
    if line - 1 in escapes:
        return line - 1
    return None


def _lint_unit(unit: LintUnit) -> tuple[list[Finding],
                                        set[tuple[str, str, int]],
                                        list[tuple[Finding, str]]]:
    """The per-file rule families (FLD/KNB/BKD/THR/EXC) over one unit.

    Each escapable family runs ONCE with escapes ignored; the escape
    filter is applied here, so the same pass yields the surviving
    findings, the raw (file, rule, line) triples the suppression audit
    needs to tell used escapes from stale ones, and the suppressed
    findings with their justifications (the SARIF suppressions surface)."""
    from spgemm_tpu.analysis import (excrules, fptrules, metrules,  # noqa: PLC0415
                                     protorules, rules, thrrules)

    if unit.tree is None:
        return [unit.parse_finding], set(), []
    p = _posix(unit.path)
    findings: list[Finding] = []
    raw: set[tuple[str, str, int]] = set()
    suppressed: list[tuple[Finding, str]] = []

    def escaping(family: list[Finding], rule: str) -> list[Finding]:
        escapes = unit.escapes[rule]
        out = []
        for f in family:
            raw.add((f.file, rule, f.line))
            esc = escape_at(escapes, f.line)
            if esc is None:
                out.append(f)
            else:
                suppressed.append((f, escapes[esc]))
        return out

    if unit.numeric:
        findings += escaping(rules.check_fld(unit.tree, unit.file, set()),
                             "FLD")
    if not p.endswith(KNOB_REGISTRY_SUFFIX):
        findings += rules.check_knb(unit.tree, unit.file)
    if not p.endswith(BACKEND_PROBE_SUFFIX):
        findings += rules.check_bkd(unit.tree, unit.file)
    findings += escaping(thrrules.check_thr(unit, set()), "THR")
    findings += escaping(excrules.check_exc(unit, set()), "EXC")
    findings += metrules.check_met(unit.tree, unit.file)
    findings += fptrules.check_fpt(unit.tree, unit.file)
    # the registry modules never self-report: protocol.py speaks no op
    # (no import of itself, so PRO self-gates) and events.py's own emit
    # machinery is the registry, not a call site
    if not p.endswith(protorules.PROTOCOL_SUFFIX):
        findings += protorules.check_pro(unit.tree, unit.file)
    if not p.endswith(protorules.EVENTS_SUFFIX):
        findings += protorules.check_evt(unit.tree, unit.file)
    return findings, raw, suppressed


def lint_file(path: str, *, numeric: bool | None = None) -> list[Finding]:
    """Run the per-file rule families over one file.

    numeric: override the path-based FLD scoping (tests); None = derive
    from the path suffix.  The cross-file passes (interprocedural FLD,
    the LCK/BLK/TSI concurrency pass, the suppression audit) need the
    whole unit set -- use lint_paths."""
    return _lint_unit(LintUnit(path, numeric=numeric))[0]


DEFAULT_CACHE_DIR = ".lint_cache"


# registry modules the CACHED per-file rules validate against: MET reads
# ENGINE_PHASES/ENGINE_COUNTERS from obs/metrics.py, FPT reads REGISTRY
# from utils/failpoints.py, PRO reads the field/op/error tables from
# serve/protocol.py, EVT reads EVENT_KINDS from obs/events.py -- a
# registry edit must invalidate every cached entry even when the call
# sites' own files are untouched, so all four are part of the
# linter-version signature (paths relative to the spgemm_tpu package
# root)
_SIGNATURE_EXTRAS = ("obs/metrics.py", "utils/failpoints.py",
                     "serve/protocol.py", "obs/events.py")


def _analysis_signature() -> str:
    """Content hash of the analysis package itself plus the registry
    modules the cached rules consult -- the linter-version half of every
    cache key, so ANY rule or registry change (not just a forgotten
    version bump) invalidates every cached entry."""
    h = hashlib.sha256()
    # results also depend on the running interpreter's ast/tokenize
    # behavior (f-string tokenization, node shapes shift across
    # minors): a CI image bump must not serve the old Python's results
    h.update(sys.version.encode())
    pkg = os.path.dirname(os.path.abspath(__file__))
    files = [(name, os.path.join(pkg, name))
             for name in sorted(os.listdir(pkg)) if name.endswith(".py")]
    files += [(rel, os.path.join(os.path.dirname(pkg), rel))
              for rel in _SIGNATURE_EXTRAS]
    for label, path in files:
        h.update(label.encode())
        with open(path, "rb") as f:
            h.update(f.read())
    return h.hexdigest()


class LintCache:
    """Content-hash cache for the per-file rule families.

    One JSON file (default `.lint_cache/cache.json` under the repo root)
    maps a unit's repo-relative path to its per-file findings, raw
    triples, and suppressed findings, keyed by (sha256 of the file
    contents, sha256 of the analysis package).  The linter is proven
    env-independent and jax-free (tests pin both), so per-file results
    are a pure function of exactly those two hashes -- a warm `make lint`
    re-runs only changed files.  The cross-file passes (interprocedural
    FLD, LCK/BLK/TSI, the FPT registry direction, the suppression audit,
    DOC) always run live: they are whole-program by definition.

    hit = entry matched; miss = no entry for the file; invalidation =
    entry present but stale (file or linter changed) and replaced.
    Writes are atomic (tmp + os.replace) and best-effort: a racing or
    read-only cache degrades to a cold run, never an error."""

    def __init__(self, directory: str | None = None):
        self.directory = directory or os.path.join(repo_root(),
                                                   DEFAULT_CACHE_DIR)
        self.path = os.path.join(self.directory, "cache.json")
        self.signature = _analysis_signature()
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self._dirty = False
        self._files: dict[str, dict] = {}
        try:
            with open(self.path, encoding="utf-8") as f:
                data = json.load(f)
            if isinstance(data, dict) and isinstance(data.get("files"),
                                                     dict):
                self._files = data["files"]
        except (OSError, ValueError):
            self._files = {}

    @staticmethod
    def content_key(source: str) -> str:
        return hashlib.sha256(source.encode("utf-8")).hexdigest()

    def get(self, file: str, sha: str):
        """Cached (findings, raw, suppressed) for a unit, or None."""
        entry = self._files.get(file)
        if entry is None:
            self.misses += 1
            return None
        try:
            if entry.get("sha") != sha \
                    or entry.get("version") != self.signature:
                self.invalidations += 1
                return None
            findings = [Finding(**f) for f in entry["findings"]]
            raw = {(r[0], r[1], r[2]) for r in entry["raw"]}
            suppressed = [(Finding(**f), reason)
                          for f, reason in entry["suppressed"]]
        except (AttributeError, KeyError, IndexError, TypeError,
                ValueError):
            # structurally malformed entry (hand edit, bad merge, torn
            # concurrent write that still parses): the cold-run
            # fallback, never a crash
            self.invalidations += 1
            return None
        self.hits += 1
        return findings, raw, suppressed

    def put(self, file: str, sha: str, findings, raw, suppressed) -> None:
        self._files[file] = {
            "sha": sha, "version": self.signature,
            "findings": [f.to_dict() for f in findings],
            "raw": sorted(list(t) for t in raw),
            "suppressed": [[f.to_dict(), reason]
                           for f, reason in suppressed],
        }
        self._dirty = True

    def prune(self, keep: set[str]) -> None:
        """Drop entries for files no longer in the linted set (renames,
        deletions) -- called on default-scope runs so cache.json cannot
        grow without bound under a long-lived checkout."""
        for file in [f for f in self._files if f not in keep]:
            del self._files[file]
            self._dirty = True

    def save(self) -> None:
        if not self._dirty:
            return
        try:
            os.makedirs(self.directory, exist_ok=True)
            tmp = self.path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as f:
                json.dump({"files": self._files}, f)
            os.replace(tmp, self.path)
        except OSError:
            pass  # read-only / racing cache dir: next run is just cold

    def stats(self) -> dict:
        return {"enabled": True, "dir": self.directory, "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations}


def _walk_py(path: str) -> list[str]:
    if os.path.isfile(path):
        return [path]
    out = []
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        out.extend(os.path.join(dirpath, f) for f in sorted(filenames)
                   if f.endswith(".py"))
    return out


def _audit_suppressions(units: list[LintUnit],
                        raw: set[tuple[str, str, int]],
                        extra_used: set[tuple[str, str, int]]
                        ) -> list[Suppression]:
    """The suppression inventory.  An escape is USED when the raw run of
    its rule family (escapes ignored -- the (file, rule, line) triples the
    per-file AND package-level passes produced) has a finding on the
    escape's line or the line below (the two lines an escape can attach
    to), or when it appears in extra_used: (file, rule, escape line) of
    SOURCE escapes that suppressed taint without an anchored raw finding
    (an fld-proof on a reduction, a blk-ok on the blocking op itself, a
    tsi-ok on a non-anchor write line)."""
    out: list[Suppression] = []
    for u in units:
        for rule, escapes in u.escapes.items():
            for line, reason in sorted(escapes.items()):
                used = ((u.file, rule, line) in raw
                        or (u.file, rule, line + 1) in raw
                        or (u.file, rule, line) in extra_used)
                out.append(Suppression(u.file, line, rule, reason,
                                       stale=not used))
    return out


@dataclass
class Report:
    """One full lint run: surviving findings, the escape inventory, the
    suppressed findings with their justifications (the SARIF
    `suppressions` surface), and the cache figures when a LintCache was
    in play."""

    findings: list[Finding] = field(default_factory=list)
    suppressions: list[Suppression] = field(default_factory=list)
    suppressed: list[tuple[Finding, str]] = field(default_factory=list)
    cache: dict | None = None


def _escaped_split(findings: list[Finding], raw: list[Finding],
                   units_by_file: dict[str, LintUnit], rule: str,
                   ) -> list[tuple[Finding, str]]:
    """The raw-minus-surviving findings of a package-level pass, paired
    with the escape reasons that suppressed them."""
    survived = set(findings)
    out = []
    for f in raw:
        if f in survived:
            continue
        unit = units_by_file.get(f.file)
        if unit is None:
            continue
        escapes = unit.escapes.get(rule, {})
        esc = escape_at(escapes, f.line)
        if esc is not None:
            out.append((f, escapes[esc]))
    return out


def lint_run(paths: list[str], *, claude_md: str | None = None,
             doc: bool = True, cache: LintCache | None = None) -> Report:
    """The full v3 run over files/directories: per-file rules (optionally
    content-hash cached), the interprocedural fold-order pass, the
    LCK/BLK/TSI concurrency pass, the suppression audit (stale escapes
    are SUP findings; the full inventory rides the report), and
    optionally the DOC drift checks (claude_md None = skip the table
    checks; the CLI/analysis help checks ride the same flag)."""
    from spgemm_tpu.analysis import (callgraph, docrules,  # noqa: PLC0415
                                     fptrules, lockrules, protorules)

    units = [LintUnit(f) for path in paths for f in _walk_py(path)]
    units_by_file = {u.file: u for u in units}
    is_default_scope = list(paths) == default_paths()
    report = Report()
    findings = report.findings
    raw: set[tuple[str, str, int]] = set()
    for u in units:
        cached = None
        if cache is not None:
            sha = cache.content_key(u.source)
            cached = cache.get(u.file, sha)
        if cached is None:
            unit_findings, unit_raw, unit_sup = _lint_unit(u)
            if cache is not None:
                cache.put(u.file, sha, unit_findings, unit_raw, unit_sup)
        else:
            unit_findings, unit_raw, unit_sup = cached
        findings += unit_findings
        raw |= unit_raw
        report.suppressed += unit_sup
    if cache is not None:
        if is_default_scope:
            cache.prune({u.file for u in units})
        cache.save()
        report.cache = cache.stats()
    # the FPT stale-entry direction needs the whole unit set (a registry
    # entry is live if ANY module checks it); it self-gates on the
    # registry module being in scope, so fixture runs stay quiet
    findings += fptrules.check_fpt_registry(units)
    # the PRO registry-coherence direction (self-gated the same way)
    findings += protorules.check_pro_registry(units)
    # DRF: the reverse (drift) audit over every registry, raw findings
    # filtered through drf-ok escapes at the registry declaration lines
    # and fed to the suppression audit like any escapable family
    drf_raw = protorules.check_drf(units)
    drf_findings = []
    for f in drf_raw:
        unit = units_by_file.get(f.file)
        escapes = unit.escapes.get("DRF", {}) if unit is not None else {}
        if escape_at(escapes, f.line) is None:
            drf_findings.append(f)
    findings += drf_findings
    report.suppressed += _escaped_split(drf_findings, drf_raw,
                                        units_by_file, "DRF")
    for f in drf_raw:
        raw.add((f.file, "DRF", f.line))
    # package-level passes: interprocedural FLD taint, then the
    # concurrency-soundness pass (lock order / blocking-under-lock /
    # thread-shared inference) over the same call graph.  Their raw
    # findings feed the audit exactly like per-file raw runs: an escape
    # is used iff a raw finding sits ON its line or the line below; their
    # source-escape sets (taint suppressed at the source, no anchored
    # finding) arrive as exact (file, rule, escape-line) triples.
    extra_used: set[tuple[str, str, int]] = set()
    prebuilt = callgraph.build(units)
    cg_findings, cg_raw, cg_used = callgraph.check(units,
                                                   prebuilt=prebuilt)
    findings += cg_findings
    report.suppressed += _escaped_split(cg_findings, cg_raw,
                                        units_by_file, "FLD")
    for f in cg_raw:
        raw.add((f.file, "FLD", f.line))
    for file, line in cg_used:
        extra_used.add((file, "FLD", line))
    # when this run's unit set IS the default scope and the DOC checks
    # will want the thread-inventory table, harvest the rows from the
    # concurrency pass's analysis instead of rebuilding the whole
    # program a second time inside docrules
    inv_rows: list | None = None
    if doc and claude_md is not None and is_default_scope:
        inv_rows = []
    lk_suppressed: list = []
    lk_findings, lk_raw, lk_used = lockrules.check(units,
                                                   inventory=inv_rows,
                                                   prebuilt=prebuilt,
                                                   suppressed=lk_suppressed)
    findings += lk_findings
    for f in lk_raw:
        raw.add((f.file, f.rule, f.line))
    for rule in ("LCK", "BLK"):
        report.suppressed += _escaped_split(
            [f for f in lk_findings if f.rule == rule],
            [f for f in lk_raw if f.rule == rule], units_by_file, rule)
    # TSI escapes can sit on non-anchor write lines the anchor-based
    # split cannot see; the pass hands the pairs over directly
    report.suppressed += lk_suppressed
    extra_used |= lk_used
    suppressions = _audit_suppressions(units, raw, extra_used)
    report.suppressions = suppressions
    for s in suppressions:
        if s.stale:
            findings.append(Finding(
                s.file, s.line, "SUP",
                f"stale suppression: `{ESCAPE_MARKERS[s.rule]}{s.reason})` "
                f"suppresses nothing here (no underlying {s.rule} finding "
                "on this or the next line); delete the escape comment"))
    if doc:
        if claude_md is not None:
            findings += docrules.check_claude_md(claude_md)
            # the metrics and thread-inventory tables live in
            # ARCHITECTURE.md beside the CLAUDE.md in play.  Only a
            # CUSTOM --claude-md with no sibling ARCHITECTURE.md (fixture
            # runs) skips the checks; on the repo's own doc set a
            # missing/renamed ARCHITECTURE.md is a DOC finding ("cannot
            # read"), never a silently disabled drift guard -- symmetric
            # with the knob table.
            doc_dir = os.path.dirname(os.path.abspath(claude_md))
            arch = os.path.join(doc_dir, "ARCHITECTURE.md")
            if os.path.exists(arch) or doc_dir == _posix(repo_root()) \
                    or doc_dir == repo_root():
                findings += docrules.check_architecture_md(arch)
                findings += docrules.check_protocol_table(arch)
                findings += docrules.check_event_table(arch)
                findings += docrules.check_thread_inventory(arch,
                                                            inv_rows)
        findings += docrules.check_cli_help()
        findings += docrules.check_analysis_help()
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return report


def lint_report(paths: list[str], *, claude_md: str | None = None,
                doc: bool = True) -> tuple[list[Finding], list[Suppression]]:
    """lint_run as the historical (findings, suppressions) pair."""
    report = lint_run(paths, claude_md=claude_md, doc=doc)
    return report.findings, report.suppressions


def lint_paths(paths: list[str], *, claude_md: str | None = None,
               doc: bool = True) -> list[Finding]:
    """lint_report without the suppression inventory (findings only)."""
    return lint_report(paths, claude_md=claude_md, doc=doc)[0]


def default_paths() -> list[str]:
    """The default lint scope: the package plus the driver-facing scripts
    that read engine knobs (bench.py, benchmarks/, the graft entry).
    tests/ stays out -- fixtures seed violations on purpose, and tests
    legitimately poke knob values via monkeypatch."""
    root = repo_root()
    return [p for p in (os.path.join(root, "spgemm_tpu"),
                        os.path.join(root, "bench.py"),
                        os.path.join(root, "__graft_entry__.py"),
                        os.path.join(root, "benchmarks"))
            if os.path.exists(p)]


def lint_repo() -> list[Finding]:
    """Self-lint the default scope + the repo docs: the tier-1 contract is
    that this returns []."""
    return lint_paths(default_paths(),
                      claude_md=os.path.join(repo_root(), "CLAUDE.md"))
