"""spgemm-lint driver: file walking, rule scoping, findings.

Rule scoping is by path SUFFIX (posix-normalized), so the test fixtures
under tests/lint_fixtures/ops/... exercise exactly the production scoping
logic.  Everything here is stdlib-only (ast + os): the linter must be
runnable in CI without initializing jax -- importing a backend to lint for
backend-touching imports would be self-defeating on a host whose TPU hangs.
"""

from __future__ import annotations

import ast
import fnmatch
import os
from dataclasses import asdict, dataclass

# FLD scope: the modules on the numeric path, where the reference's
# wrap-then-mod fold order is load-bearing (SURVEY.md section 2.9).
# Suffixes carry a leading "/" so matching is path-segment-anchored
# (a hypothetical devops/spgemm.py must not land in numeric scope).
NUMERIC_SUFFIXES = (
    "/ops/u64.py",
    "/ops/spgemm.py",
    "/ops/mxu_spgemm.py",
    "/parallel/ring.py",
    "/parallel/rowshard.py",
)
NUMERIC_GLOBS = ("*/ops/pallas_*.py",)

# KNB exemption: the registry itself is the one blessed reader.
KNOB_REGISTRY_SUFFIX = "/utils/knobs.py"
# BKD exemption: the probe exists precisely to touch the backend safely.
BACKEND_PROBE_SUFFIX = "/utils/backend_probe.py"

FLD_ESCAPE = "spgemm-lint: fld-proof("


@dataclass(frozen=True)
class Finding:
    file: str   # repo-relative posix path (absolute if outside the repo)
    line: int   # 1-indexed
    rule: str   # family id: FLD | KNB | BKD | DOC | PARSE
    message: str

    def to_dict(self) -> dict:
        return asdict(self)


def repo_root() -> str:
    """The directory containing the spgemm_tpu package."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def _posix(path: str) -> str:
    return os.path.abspath(path).replace(os.sep, "/")


def rel_file(path: str) -> str:
    """Repo-relative posix path for findings (absolute when outside)."""
    root = _posix(repo_root())
    p = _posix(path)
    if p.startswith(root + "/"):
        return p[len(root) + 1:]
    return p


def is_numeric_module(path: str) -> bool:
    p = _posix(path)
    return (p.endswith(NUMERIC_SUFFIXES)
            or any(fnmatch.fnmatch(p, g) for g in NUMERIC_GLOBS))


def _escape_lines(source: str, marker: str) -> set[int]:
    """1-indexed lines carrying an escape-hatch directive with a non-empty
    reason.  A bare `fld-proof()` is NOT an escape: the reason is the
    reviewable proof citation."""
    lines = set()
    for i, text in enumerate(source.splitlines(), start=1):
        pos = text.find(marker)
        if pos < 0:
            continue
        rest = text[pos + len(marker):]
        reason = rest.split(")", 1)[0].strip()
        if reason:
            lines.add(i)
    return lines


def lint_file(path: str, *, numeric: bool | None = None) -> list[Finding]:
    """Run the AST rule families (FLD/KNB/BKD) over one file.

    numeric: override the path-based FLD scoping (tests); None = derive
    from the path suffix."""
    from spgemm_tpu.analysis import rules  # noqa: PLC0415

    with open(path, encoding="utf-8") as f:
        source = f.read()
    try:
        tree = ast.parse(source, filename=path)
    except SyntaxError as e:
        # a broken file means NO rule ran on it -- its own rule id, so
        # JSON-count consumers never blame a rule family for a parse error
        return [Finding(rel_file(path), e.lineno or 1, "PARSE",
                        f"file does not parse: {e.msg}")]
    p = _posix(path)
    findings: list[Finding] = []
    if numeric is None:
        numeric = is_numeric_module(path)
    if numeric:
        escapes = _escape_lines(source, FLD_ESCAPE)
        findings += rules.check_fld(tree, rel_file(path), escapes)
    if not p.endswith(KNOB_REGISTRY_SUFFIX):
        findings += rules.check_knb(tree, rel_file(path))
    if not p.endswith(BACKEND_PROBE_SUFFIX):
        findings += rules.check_bkd(tree, rel_file(path))
    return findings


def _walk_py(path: str) -> list[str]:
    if os.path.isfile(path):
        return [path]
    out = []
    for dirpath, dirnames, filenames in os.walk(path):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        out.extend(os.path.join(dirpath, f) for f in sorted(filenames)
                   if f.endswith(".py"))
    return out


def lint_paths(paths: list[str], *, claude_md: str | None = None,
               doc: bool = True) -> list[Finding]:
    """Lint files/directories; optionally run the DOC drift checks against
    the given CLAUDE.md (None = skip the table check)."""
    from spgemm_tpu.analysis import docrules  # noqa: PLC0415

    findings: list[Finding] = []
    for path in paths:
        for f in _walk_py(path):
            findings += lint_file(f)
    if doc:
        if claude_md is not None:
            findings += docrules.check_claude_md(claude_md)
        findings += docrules.check_cli_help()
    findings.sort(key=lambda f: (f.file, f.line, f.rule))
    return findings


def default_paths() -> list[str]:
    """The default lint scope: the package plus the driver-facing scripts
    that read engine knobs (bench.py, benchmarks/, the graft entry).
    tests/ stays out -- fixtures seed violations on purpose, and tests
    legitimately poke knob values via monkeypatch."""
    root = repo_root()
    return [p for p in (os.path.join(root, "spgemm_tpu"),
                        os.path.join(root, "bench.py"),
                        os.path.join(root, "__graft_entry__.py"),
                        os.path.join(root, "benchmarks"))
            if os.path.exists(p)]


def lint_repo() -> list[Finding]:
    """Self-lint the default scope + the repo docs: the tier-1 contract is
    that this returns []."""
    return lint_paths(default_paths(),
                      claude_md=os.path.join(repo_root(), "CLAUDE.md"))
