"""spgemm_tpu.tune: telemetry-driven autotuner (ARCHITECTURE.md "L6
autotune lifecycle").

The control loop the rest of the engine only measures: a deterministic
trial planner enumerates the bit-identical jit-static knob space per
structure class, spgemmd times the legs on idle slices (preempted the
moment a real job arrives), winners persist into the warm store's
tuned-override tier, and a promoted vector reaches live traffic behind
the canary gate.  jax-free by design: trial execution is a
daemon-supplied callback, persistence is an injected store.
"""

from spgemm_tpu.tune.tuner import (  # noqa: F401
    TUNER,
    TrialPreempted,
    Tuner,
    enabled,
    min_win,
    run_trial_leg,
    trial_cadence_s,
    trial_vectors,
)
