"""Telemetry-driven autotuner: per-structure-class search over the
bit-identical jit-static knob space (ARCHITECTURE.md "L6 autotune
lifecycle").

Every knob the planner enumerates (`SPGEMM_TPU_ACCUM_ROUTE`,
`SPGEMM_TPU_ROUND_BATCH`, `SPGEMM_TPU_MXU_R`, `SPGEMM_TPU_RING_OVERLAP`)
is bit-identical A/B by construction -- tuning steers wall clock only,
never bits -- so the search needs no numeric acceptance test beyond the
trial-time parity spot-check (every leg's result digest must equal the
baseline leg's; a mismatch is an engine bug and parks the class).

Lifecycle per (structure class, device kind):

    idle -> trialing -> canary -> live
                \\-> settled (no vector beat SPGEMM_TPU_TUNE_MIN_WIN)
    canary failure / parity mismatch -> reverted (+ exponential backoff,
    re-trialed after the backoff expires)

Scheduling is the daemon's job: spgemmd calls `run_trial_leg` from an
executor's idle tick, at most ONE leg per tick, only while the whole
pool is idle -- preemption is structural (a real job arriving mid-leg
aborts it at the next heartbeat via TrialPreempted), and trial legs are
never counted against tenant DRR or SLO windows.

jax-free by design: trial execution is a daemon-supplied
`run_fn(folder) -> digest` callback (wall time is clocked here), and
persistence is an injected store (ops/warmstore's tune tier).  The
overlay a promoted vector activates is knobs.set_tuned -- process-global
and replace-atomic; two slices concurrently activating different
classes race on wall clock only, never on bits.
"""

from __future__ import annotations

import threading
import time

from spgemm_tpu.obs import events
from spgemm_tpu.utils import failpoints, knobs
from spgemm_tpu.utils.timers import ENGINE

# Vector candidates per searched knob (deviations from the base value
# are enumerated; the base vector itself is always leg 0).
_ROUTE_CHOICES = ("auto", "ladder", "dense")
_MXU_R_CHOICES = ("4", "8", "16")

# Revert backoff: first canary/parity failure parks the class this long;
# every subsequent failure doubles it (capped).
BACKOFF0_S = 60.0
BACKOFF_CAP_S = 3600.0

# Estimator adaptation (ROADMAP item (b)): after EST_MIN_JOBS scored
# jobs, a class whose mean rel-error stays under EST_TIGHT halves its
# row-sample budget (floored at the registry minimum), and a class whose
# mean rel-error exceeds EST_MISS raises its confidence threshold by
# EST_CONF_STEP (capped at 1.0 -- past 1 the registry doc says the
# fallback fires everywhere, which is exactly the intent for a class
# the estimator keeps misjudging).
EST_MIN_JOBS = 4
EST_TIGHT = 0.05
EST_MISS = 0.5
EST_CONF_STEP = 0.2
EST_ROWS_FLOOR = 8


class TrialPreempted(Exception):
    """Raised by the daemon's trial run_fn (from the heartbeat it plants
    between multiplies) when a real job arrived mid-leg: the leg is
    discarded and the executor returns to the queue within one
    heartbeat."""


def enabled() -> bool:
    """Master tuner switch (SPGEMM_TPU_TUNE)."""
    return bool(knobs.get("SPGEMM_TPU_TUNE"))


def trial_cadence_s() -> float:
    """Idle-trial cadence (SPGEMM_TPU_TUNE_TRIAL_S; 0 = no trials)."""
    return float(knobs.get("SPGEMM_TPU_TUNE_TRIAL_S") or 0)


def min_win() -> float:
    """Promotion threshold (SPGEMM_TPU_TUNE_MIN_WIN)."""
    return float(knobs.get("SPGEMM_TPU_TUNE_MIN_WIN") or 1.1)


def trial_vectors(device_kind: str) -> list[dict[str, str]]:
    """Deterministic trial plan for one structure class: leg 0 is the
    base vector (empty overlay -- the incumbent), then one-knob
    deviations from the base in registry-stable order.  Coordinate
    search, not the cross product: the searched knobs are near-
    independent (route and batching act on disjoint dispatch layers),
    and one-at-a-time keeps the idle-lane budget at ~7 compiles per
    class instead of 36.

    MXU_R / RING_OVERLAP deviations only enumerate off-CPU: the CPU
    'mxu' lowering is an XLA oracle and single-host CPU runs never take
    the ring, so their legs would time pure noise.
    """
    legs: list[dict[str, str]] = [{}]
    base_route = str(knobs.base_get("SPGEMM_TPU_ACCUM_ROUTE"))
    for route in _ROUTE_CHOICES:
        if route != base_route:
            legs.append({"SPGEMM_TPU_ACCUM_ROUTE": route})
    base_rb = "1" if knobs.base_get("SPGEMM_TPU_ROUND_BATCH") else "0"
    legs.append({"SPGEMM_TPU_ROUND_BATCH": "0" if base_rb == "1" else "1"})
    if "cpu" not in (device_kind or "").lower():
        base_r = str(knobs.base_get("SPGEMM_TPU_MXU_R"))
        for r in _MXU_R_CHOICES:
            if r != base_r:
                legs.append({"SPGEMM_TPU_MXU_R": r})
        base_ring = "1" if knobs.base_get("SPGEMM_TPU_RING_OVERLAP") else "0"
        legs.append(
            {"SPGEMM_TPU_RING_OVERLAP": "0" if base_ring == "1" else "1"})
    return legs


class _ClassState:
    """One structure class's tuner record.  All mutable fields are owned
    by the Tuner's lock (the class object never leaves the Tuner)."""

    def __init__(self, class_key: str, device_kind: str):
        self.class_key = class_key
        self.device_kind = device_kind
        self.state = "idle"  # idle|trialing|settled|canary|live|reverted
        self.pending: list[dict[str, str]] | None = None
        self.results: list[tuple[dict[str, str], float]] = []
        self.baseline_s: float | None = None
        self.baseline_digest = None
        self.override: dict[str, str] | None = None
        self.win: float | None = None
        self.backoff_s = 0.0
        self.retry_at = 0.0          # monotonic: no re-trial before this
        self.canary_inflight = False
        self.est_n = 0
        self.est_sum = 0.0
        self.est_override: dict[str, str] = {}

    def row(self) -> dict:
        """Status row (cli tune / spgemmd stats)."""
        return {
            "class": self.class_key,
            "device_kind": self.device_kind,
            "state": self.state,
            "knobs": dict(self.override or {}),
            "est": dict(self.est_override),
            "win": self.win,
            "backoff_s": self.backoff_s,
        }


class Tuner:
    """The autotuner state machine: class registry, trial planning,
    promotion, canary accounting, estimator adaptation, persistence.

    Thread-safe: executors feed it from their idle ticks and terminal
    paths concurrently.  Trial EXECUTION happens outside the lock (the
    leg's run_fn compiles and dispatches); only bookkeeping holds it.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._classes: dict[str, _ClassState] = {}  # spgemm-lint: guarded-by(_lock)
        self._persist = None                        # spgemm-lint: guarded-by(_lock)
        self._trials = 0                            # spgemm-lint: guarded-by(_lock)
        self._reverts = 0                           # spgemm-lint: guarded-by(_lock)

    # -------------------------------------------------- wiring --
    def persist_with(self, fn) -> None:
        """Install the override store (warmstore.save_tune-shaped:
        fn(class_key, record) -> bool).  None disables persistence."""
        with self._lock:
            self._persist = fn

    def load(self, records: dict[str, dict]) -> int:
        """Seed classes from the warm store's tune tier (daemon start).
        Returns the number of records adopted.  A record whose state was
        canary when the daemon died stays canary: the first job after
        restart re-audits it.  Reverted records keep their backoff
        (re-anchored to this process's clock from the stored horizon)."""
        now = time.monotonic()
        wall = time.time()
        n = 0
        with self._lock:
            for class_key, rec in sorted(records.items()):
                st = self._classes.get(class_key)
                if st is None:
                    st = _ClassState(class_key,
                                     str(rec.get("device_kind", "")))
                    self._classes[class_key] = st
                state = rec.get("state")
                if state in ("canary", "live"):
                    ov = {str(k): str(v)
                          for k, v in (rec.get("knobs") or {}).items()
                          if k in knobs.REGISTRY}
                    if not ov:
                        continue
                    st.state = state
                    st.override = ov
                    st.win = rec.get("win")
                elif state == "reverted":
                    st.state = "reverted"
                    st.backoff_s = float(rec.get("backoff_s") or BACKOFF0_S)
                    st.retry_at = now + max(
                        0.0, float(rec.get("not_before", 0.0)) - wall)
                else:
                    continue
                st.est_override = {
                    str(k): str(v)
                    for k, v in (rec.get("est") or {}).items()
                    if k in knobs.REGISTRY}
                n += 1
        return n

    def _persist_locked(self, st: _ClassState) -> None:
        fn = self._persist
        if fn is None:
            return
        rec = {"class_key": st.class_key, "device_kind": st.device_kind,
               "state": st.state, "knobs": dict(st.override or {}),
               "est": dict(st.est_override), "win": st.win,
               "backoff_s": st.backoff_s,
               "not_before": time.time() + max(
                   0.0, st.retry_at - time.monotonic())}
        try:
            fn(st.class_key, rec)
        except Exception:  # noqa: BLE001 -- a failing store must never take down the serving path; the override just won't survive restart
            pass

    # -------------------------------------------------- job feed --
    def note_job(self, class_key: str | None, device_kind: str) -> None:
        """Register a structure class sighting (daemon terminal path).
        First sighting creates the class in idle; trials start once the
        rep-folder book can answer for it."""
        if not class_key or not enabled():
            return
        with self._lock:
            if class_key not in self._classes:
                self._classes[class_key] = _ClassState(class_key,
                                                       device_kind)

    def overlay_for(self, class_key: str | None) -> dict[str, str]:
        """The knob overlay this class's jobs should run under: the
        promoted vector (canary/live) merged with the estimator
        adaptation; {} when nothing is tuned (or tuning is off)."""
        if not class_key or not enabled():
            return {}
        with self._lock:
            st = self._classes.get(class_key)
            if st is None:
                return {}
            ov = dict(st.est_override)
            if st.state in ("canary", "live") and st.override:
                ov.update(st.override)
            return ov

    def consume_canary(self, class_key: str | None) -> bool:
        """True exactly once per canary attempt: the caller (daemon job
        pickup) tightens the job's deadline and audits its terminal
        outcome via note_terminal."""
        if not class_key or not enabled():
            return False
        with self._lock:
            st = self._classes.get(class_key)
            if st is None or st.state != "canary" or st.canary_inflight:
                return False
            st.canary_inflight = True
            return True

    def note_terminal(self, class_key: str | None, ok: bool) -> None:
        """Terminal outcome of a job that ran under this class (daemon
        _observe_terminal).  Settles an in-flight canary: success goes
        live, failure reverts the override and backs off."""
        if not class_key:
            return
        with self._lock:
            st = self._classes.get(class_key)
            if st is None or not st.canary_inflight:
                return
            st.canary_inflight = False
            if ok:
                st.state = "live"
                self._persist_locked(st)
                events.emit("tune_canary_passed", class_key=class_key,
                            win=st.win, knobs=dict(st.override or {}))
            else:
                self._revert_locked(st, "canary-failed")

    def _revert_locked(self, st: _ClassState, reason: str) -> None:
        st.state = "reverted"
        st.override = None
        st.win = None
        st.pending = None
        st.results = []
        st.baseline_s = None
        st.baseline_digest = None
        st.backoff_s = min(BACKOFF_CAP_S,
                           (st.backoff_s * 2) if st.backoff_s else BACKOFF0_S)
        st.retry_at = time.monotonic() + st.backoff_s
        self._reverts += 1
        ENGINE.incr("tune_reverts")
        self._persist_locked(st)
        events.emit("tune_revert", class_key=st.class_key, reason=reason,
                    backoff_s=st.backoff_s)

    # ------------------------------------------- estimator loop --
    def note_est_accuracy(self, class_key: str | None,
                          mean_rel_err: float, n: int = 1) -> None:
        """Feed one job's observed estimator accuracy (mean rel-error
        over the quantities obs/profile scored for it).  ROADMAP (b):
        tight classes shrink SPGEMM_TPU_EST_SAMPLE_ROWS, misfiring
        classes raise SPGEMM_TPU_EST_CONFIDENCE -- both bounded by the
        registry's declared ranges, both riding the class overlay."""
        if not class_key or not enabled() or n <= 0:
            return
        with self._lock:
            st = self._classes.get(class_key)
            if st is None:
                return
            st.est_n += n
            st.est_sum += float(mean_rel_err) * n
            if st.est_n < EST_MIN_JOBS:
                return
            mean = st.est_sum / st.est_n
            st.est_n = 0
            st.est_sum = 0.0
            if mean < EST_TIGHT:
                kb = knobs.REGISTRY["SPGEMM_TPU_EST_SAMPLE_ROWS"]
                cur = int(st.est_override.get(
                    "SPGEMM_TPU_EST_SAMPLE_ROWS",
                    knobs.base_get("SPGEMM_TPU_EST_SAMPLE_ROWS")))
                floor = max(int(kb.minimum or 1), EST_ROWS_FLOOR)
                new = max(floor, cur // 2)
                if new != cur:
                    st.est_override["SPGEMM_TPU_EST_SAMPLE_ROWS"] = str(new)
                    self._persist_locked(st)
            elif mean > EST_MISS:
                cur = float(st.est_override.get(
                    "SPGEMM_TPU_EST_CONFIDENCE",
                    knobs.base_get("SPGEMM_TPU_EST_CONFIDENCE")))
                new = min(1.0, cur + EST_CONF_STEP)
                if new != cur:
                    st.est_override["SPGEMM_TPU_EST_CONFIDENCE"] = \
                        f"{new:g}"
                    self._persist_locked(st)

    # ---------------------------------------------- trial lane --
    def next_leg(self, folder_of) -> tuple[str, str, dict[str, str]] | None:
        """Claim the next due trial leg: (class_key, folder, vector), or
        None when no class is due.  `folder_of(class_key)` resolves the
        class's representative folder (serve/placement.rep_folder); a
        class the book cannot answer for is skipped.  Classes are
        visited in sorted order for determinism; a reverted class
        re-enters trialing once its backoff expired."""
        if not enabled():
            return None
        now = time.monotonic()
        with self._lock:
            for class_key in sorted(self._classes):
                st = self._classes[class_key]
                if st.state == "reverted" and now >= st.retry_at:
                    st.state = "idle"
                if st.state not in ("idle", "trialing"):
                    continue
                folder = folder_of(class_key)
                if folder is None:
                    continue
                if st.pending is None:
                    st.state = "trialing"
                    st.pending = trial_vectors(st.device_kind)
                    st.results = []
                if not st.pending:
                    continue
                return class_key, folder, st.pending[0]
        return None

    def record_leg(self, class_key: str, vector: dict[str, str],
                   seconds: float, digest) -> None:
        """Commit one timed leg.  The baseline leg (empty vector) pins
        the parity digest; any later leg whose digest differs parks the
        class (that would be an engine bug -- the searched knobs are
        bit-identical by construction -- so the tuner must not promote
        anything on top of it).  Exhausting the plan decides: the best
        candidate is promoted to canary iff it beat the baseline by
        SPGEMM_TPU_TUNE_MIN_WIN, else the class settles untuned."""
        with self._lock:
            st = self._classes.get(class_key)
            if st is None or st.state != "trialing" or not st.pending \
                    or st.pending[0] != vector:
                return  # stale leg (revert/reload raced it): discard
            st.pending.pop(0)
            if not vector:
                st.baseline_s = seconds
                st.baseline_digest = digest
            elif st.baseline_digest is not None \
                    and digest != st.baseline_digest:
                self._revert_locked(st, "parity-mismatch")
                return
            else:
                st.results.append((dict(vector), seconds))
            if st.pending:
                return
            st.pending = None
            self._decide_locked(st)

    def record_preempted(self, class_key: str, vector: dict[str, str],
                         reason: str) -> None:
        """A leg was aborted (real job arrived, failpoint, overlay swap
        mid-measurement): discard the measurement -- class state is
        deliberately untouched, so the same leg simply re-runs at the
        next idle window."""
        events.emit("tune_trial_preempted", class_key=class_key,
                    knobs=dict(vector), reason=reason)

    def _decide_locked(self, st: _ClassState) -> None:
        if st.baseline_s is None or not st.results:
            st.state = "settled"
            return
        best_vec, best_s = min(st.results, key=lambda r: r[1])
        win = (st.baseline_s / best_s) if best_s > 0 else 0.0
        if win >= min_win():
            st.override = best_vec
            st.win = round(win, 3)
            st.state = "canary"
            st.canary_inflight = False
            with ENGINE.phase("tune_apply"):
                self._persist_locked(st)
            events.emit("tune_apply", class_key=st.class_key,
                        knobs=best_vec, win=st.win,
                        baseline_s=round(st.baseline_s, 6),
                        best_s=round(best_s, 6))
        else:
            st.state = "settled"
            st.win = round(win, 3)

    # -------------------------------------------------- surface --
    def stats(self) -> dict:
        """Stats block (spgemmd stats op / cli tune): per-class rows
        plus the counters the scrape renders."""
        with self._lock:
            rows = [self._classes[k].row() for k in sorted(self._classes)]
            trials, reverts = self._trials, self._reverts
        states: dict[str, int] = {}
        for r in rows:
            if r["state"] in ("canary", "live", "reverted"):
                states[r["state"]] = states.get(r["state"], 0) + 1
        return {"enabled": enabled(), "classes": rows,
                "overrides": states, "trials": trials, "reverts": reverts}

    def _count_trial(self) -> None:
        with self._lock:
            self._trials += 1

    def clear(self) -> None:
        """Drop every class (tests; cli tune --clear clears the store,
        the daemon's in-memory state follows at next restart)."""
        with self._lock:
            self._classes.clear()
            self._trials = 0
            self._reverts = 0


TUNER = Tuner()


def run_trial_leg(run_fn, folder_of, tuner: Tuner = None,
                  extra: dict | None = None) -> bool:
    """Execute AT MOST ONE trial leg (the daemon's idle-tick entry
    point): claim the next due (class, folder, vector), activate the
    candidate overlay, run `run_fn(folder) -> digest` under it, clock
    the wall, restore the previous overlay, and commit the measurement.
    Returns True iff a leg ran (successfully or not).

    `extra` pins measurement-context knobs onto EVERY leg's overlay --
    baseline included -- without ever joining the persisted winner
    vector (the daemon passes {"SPGEMM_TPU_DELTA": "0"}: a repeat trial
    multiply answered from the delta store's retained result would time
    a splice, not the candidate vector).

    Preemption contract: `run_fn` raises TrialPreempted from its
    inter-multiply heartbeat when a real job arrives -- the leg is
    discarded and this returns within one heartbeat.  A leg during
    which the process-global overlay generation moved (another slice
    activated a class's vector mid-measurement) is discarded too: its
    timing measured a mixture.  The armed `tune.trial` failpoint aborts
    the leg the same revert-free way -- a chaos trial must never touch
    a real job's result, SLO window, or the admission path."""
    t = tuner if tuner is not None else TUNER
    leg = t.next_leg(folder_of)
    if leg is None:
        return False
    class_key, folder, vector = leg
    prev = knobs.tuned_overlay()
    with ENGINE.phase("tune_trial"):
        ENGINE.incr("tune_trials")
        t._count_trial()
        try:
            failpoints.check("tune.trial")
            overlay = dict(prev)
            overlay.update(extra or {})
            overlay.update(vector)
            knobs.set_tuned(overlay)
            gen0 = knobs.tuned_generation()
            t0 = time.perf_counter()
            digest = run_fn(folder)
            dt = time.perf_counter() - t0
            skewed = knobs.tuned_generation() != gen0
        except TrialPreempted:
            t.record_preempted(class_key, vector, "preempted")
            return True
        except failpoints.FailpointTriggered:
            t.record_preempted(class_key, vector, "failpoint")
            return True
        except Exception as e:  # noqa: BLE001 -- a dying trial leg must never take down the executor's idle tick; the leg is discarded and the class re-tries next window
            t.record_preempted(class_key, vector, f"error:{type(e).__name__}")
            return True
        finally:
            knobs.set_tuned(prev)
        if skewed:
            t.record_preempted(class_key, vector, "overlay-swapped")
            return True
        events.emit("tune_trial", class_key=class_key, knobs=dict(vector),
                    seconds=round(dt, 6))
        t.record_leg(class_key, vector, dt, digest)
    return True
