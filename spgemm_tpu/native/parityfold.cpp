// Native full-parity checker: the reference's exact wrap-then-mod fold
// (sparse_matrix_mult.cu:48,59-61; SURVEY.md section 2.9) over EVERY output
// key, in plain uint64 C++ -- the at-scale parity statement the sampled
// checks cannot make.  Given the symbolic join's per-key pair lists (already
// in the reference's j-ascending order) and the engine's output slab, it
// recomputes each output tile independently of the JAX/Pallas numeric phase
// and counts mismatching keys.  ~1.5e10 MACs for the webbase-1Mrow config:
// seconds-to-minutes on a host core, vs hours for the python-int oracle.
//
// The structure (keys, pair lists) is shared with the engine's planner, but
// that layer is independently cross-checked bit-identical between
// native/symbolic.cpp and ops/symbolic.py; the numeric fold here shares no
// code with the device path.
//
// Build: part of libsmmio.so (utils/native.py _build).

#include <cstdint>

extern "C" {

// Returns the number of keys whose recomputed tile differs from out_tiles.
// first_bad: key index of the first mismatch, or -1.
int64_t smm_parity_fold(const uint64_t *a_tiles, const uint64_t *b_tiles,
                        const int64_t *pair_ptr, const int32_t *pair_a,
                        const int32_t *pair_b, int64_t n_keys, int64_t k,
                        const uint64_t *out_tiles, int64_t *first_bad) {
  const uint64_t MAXV = 0xFFFFFFFFFFFFFFFFull;
  const int64_t kk = k * k;
  if (k > 128) {  // stack accumulator cap; callers fall back to the oracle
    *first_bad = -1;
    return -2;
  }
  int64_t bad = 0;
  int64_t first = -1;
#pragma omp parallel for schedule(dynamic, 16) reduction(+ : bad)
  for (int64_t key = 0; key < n_keys; ++key) {
    // per-key accumulator tile on the stack (k <= 128 in this framework;
    // VLA-free fixed cap keeps this portable)
    uint64_t acc[128 * 128];
    for (int64_t i = 0; i < kk; ++i) acc[i] = 0;
    for (int64_t p = pair_ptr[key]; p < pair_ptr[key + 1]; ++p) {
      const uint64_t *A = a_tiles + (int64_t)pair_a[p] * kk;
      const uint64_t *B = b_tiles + (int64_t)pair_b[p] * kk;
      for (int64_t ty = 0; ty < k; ++ty) {
        const uint64_t *Arow = A + ty * k;
        uint64_t *accrow = acc + ty * k;
        for (int64_t j = 0; j < k; ++j) {
          const uint64_t av = Arow[j];
          const uint64_t *Brow = B + j * k;
          // per output element (ty, tx): fold order over (pair, j) is
          // pair-major then j-ascending -- the tx loop innermost keeps
          // that order for every tx simultaneously (identical sequence
          // per element as the reference kernel's :56-62 loop)
          for (int64_t tx = 0; tx < k; ++tx) {
            uint64_t prod = av * Brow[tx];  // wraps mod 2^64
            if (prod == MAXV) prod = 0;     // :59
            uint64_t s = accrow[tx] + prod; // wraps mod 2^64 first
            if (s == MAXV) s = 0;           // :61
            accrow[tx] = s;
          }
        }
      }
    }
    const uint64_t *want = out_tiles + key * kk;
    bool ok = true;
    for (int64_t i = 0; i < kk; ++i)
      if (acc[i] != want[i]) {
        ok = false;
        break;
      }
    if (!ok) {
      ++bad;
#pragma omp critical
      if (first < 0 || key < first) first = key;
    }
  }
  *first_bad = first;
  return bad;
}

}  // extern "C"
