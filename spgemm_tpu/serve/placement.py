"""Estimator-priced job placement for the spgemmd device pool.

The placement question at admission is "which slice class should run this
job": cheap jobs onto the narrowest free slice (so the wide slices stay
free for work that can use them), webbase-class jobs onto the widest
slice, and first-contact jobs -- no estimate yet -- onto the spec's
default slice.  The price signal is the sampled structure estimator's
predicted tile-pair mass (ops/estimate.chain_mass -- the Ocean-style
sampling that already steers planning budgets), recorded into a bounded
price book the first time a job's chain is actually read:

  * admission (`route`, conn-thread, jax-free, O(stat) cheap): look the
    input folder up by its stat signature (file names + sizes + mtimes --
    the same change-detection granularity the delta path's digests refine
    later).  A book hit prices the job exactly; a miss classifies
    webbase-class inputs by raw on-disk bytes (a monotone nnz proxy that
    costs three stat calls) and sends everything else to the default
    slice.
  * execution (`note_mass`, executor thread): the runner has the chain's
    coords in hand anyway -- one sampled mini-join prices the structure
    and seeds the book, so every re-submit of the folder (the serving
    workload) routes on a real estimate.

Pricing steers placement only -- never fold order, never kernel routing
-- so a mis-priced job is merely scheduled on a narrower/wider slice than
ideal, with bits identical by construction.

jax-free by design: imported by the daemon's admission path (conn
threads) and by tests that never start a backend.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict

# class thresholds (module constants, monkeypatchable in tests and small
# enough to revisit with fleet data): a job whose predicted first-pass
# pair mass reaches LARGE_MASS_PAIRS is webbase-class (route wide); a
# first-contact folder whose matrix files reach LARGE_INPUT_BYTES is
# assumed webbase-class without an estimate (raw bytes are a monotone
# nnz proxy at reference text densities)
LARGE_MASS_PAIRS = 1e6
LARGE_INPUT_BYTES = 64 << 20

# price-book capacity: one entry per distinct (folder, content-stamp);
# LRU past this, like every other client-growable resource in the daemon
BOOK_CAP = 4096

# newest book entries gossiped in stats() for the fleet router's
# replicated price book (fleet/pricebook.py): bounded so a stats answer
# stays a small wire line even with a full book
BOOK_GOSSIP_CAP = 64

_LOCK = threading.Lock()
_BOOK: "OrderedDict[str, float]" = OrderedDict()  # spgemm-lint: guarded-by(_LOCK)
# autotune class -> representative folder (same LRU discipline)
_CLASS_BOOK: "OrderedDict[str, str]" = OrderedDict()  # spgemm-lint: guarded-by(_LOCK)
_STATS = {"book_hits": 0, "book_misses": 0,
          "routed": {}}  # spgemm-lint: guarded-by(_LOCK)


def signature(folder: str) -> str | None:
    """Stat signature of a chain input folder (size file + matrix files:
    names, byte sizes, mtimes) -- the book key.  None when the folder is
    unreadable (journal replay may race a deleted input; the job itself
    will fail with the real error)."""
    try:
        names = sorted(n for n in os.listdir(folder)
                       if n == "size" or n.startswith("matrix"))
        h = hashlib.sha256(folder.encode())
        for n in names:
            st = os.stat(os.path.join(folder, n))
            h.update(f"{n}:{st.st_size}:{st.st_mtime_ns}|".encode())
        return h.hexdigest()
    except OSError:
        return None


def input_bytes(folder: str) -> int:
    """Total on-disk bytes of the folder's matrix files (the first-contact
    webbase-class proxy); 0 when unreadable."""
    total = 0
    try:
        for n in os.listdir(folder):
            if n.startswith("matrix"):
                total += os.path.getsize(os.path.join(folder, n))
    except OSError:
        return 0
    return total


def note_mass(folder: str, mass: float) -> None:
    """Record a measured/estimated pair mass for the folder's current
    content (executor side, after the chain is read)."""
    sig = signature(folder)
    if sig is None:
        return
    with _LOCK:
        _BOOK[sig] = float(mass)
        _BOOK.move_to_end(sig)
        while len(_BOOK) > BOOK_CAP:
            _BOOK.popitem(last=False)


def lookup_mass(folder: str) -> float | None:
    """The recorded pair mass for the folder's CURRENT content, or None
    on first contact / content change (the stat signature is the key, so
    a mutated input re-prices instead of riding a stale estimate)."""
    sig = signature(folder)
    with _LOCK:
        if sig is None or sig not in _BOOK:
            _STATS["book_misses"] += 1
            return None
        _BOOK.move_to_end(sig)
        _STATS["book_hits"] += 1
        return _BOOK[sig]


def note_class(class_key: str | None, folder: str) -> None:
    """Record a representative folder for an autotune structure class
    (executor terminal path, alongside note_mass): the tuner's idle
    trial legs replay THIS folder to time candidate knob vectors on the
    class's real structure.  Newest sighting wins -- any member folder
    is representative, the class groups same-structure chains."""
    if class_key is None:
        return
    with _LOCK:
        _CLASS_BOOK[class_key] = folder
        _CLASS_BOOK.move_to_end(class_key)
        while len(_CLASS_BOOK) > BOOK_CAP:
            _CLASS_BOOK.popitem(last=False)


def rep_folder(class_key: str) -> str | None:
    """The recorded representative folder for a tune class, or None
    (class never seen, evicted, or the folder vanished -- the tuner
    skips the class; a stale path is re-checked here so a deleted input
    never reaches a trial leg)."""
    with _LOCK:
        folder = _CLASS_BOOK.get(class_key)
    if folder is not None and not os.path.isdir(folder):
        with _LOCK:
            if _CLASS_BOOK.get(class_key) == folder:
                del _CLASS_BOOK[class_key]
        return None
    return folder


def route(folder: str) -> dict:
    """The admission-time placement record for a job: `class` is
    small|large|default (narrowest slice / widest slice / the spec's
    default slice), plus the price provenance for status detail and
    stats."""
    mass = lookup_mass(folder)
    if mass is not None:
        cls = "large" if mass >= LARGE_MASS_PAIRS else "small"
        source = "estimate"
    else:
        nbytes = input_bytes(folder)
        if nbytes >= LARGE_INPUT_BYTES:
            cls, source = "large", "bytes"
            mass = float(nbytes)
        else:
            cls, source = "default", "none"
    with _LOCK:
        _STATS["routed"][cls] = _STATS["routed"].get(cls, 0) + 1
    return {"class": cls, "source": source,
            **({"mass": mass} if mass is not None else {})}


def stats() -> dict:
    """Live placement state for spgemmd stats: book size/hit rate and the
    admission routing histogram."""
    with _LOCK:
        # the gossip sample: newest (most-recently-used) signatures
        # first -- the slice of the book a federation router most wants
        # replicated (what this daemon priced lately)
        newest = list(_BOOK.items())[-BOOK_GOSSIP_CAP:]
        return {"book_entries": len(_BOOK),
                "book_hits": _STATS["book_hits"],
                "book_misses": _STATS["book_misses"],
                "routed": dict(_STATS["routed"]),
                "large_mass_pairs": LARGE_MASS_PAIRS,
                "large_input_bytes": LARGE_INPUT_BYTES,
                "book": {sig: mass for sig, mass in newest}}


def clear() -> None:
    """Drop the book and zero the stats (tests, A/B harnesses)."""
    with _LOCK:
        _BOOK.clear()
        _CLASS_BOOK.clear()
        _STATS["book_hits"] = _STATS["book_misses"] = 0
        _STATS["routed"].clear()
