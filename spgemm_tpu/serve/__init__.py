"""spgemmd (L4): a resident serving daemon that keeps the engine warm.

The reference is a run-once binary (SURVEY.md section 0: read <folder>,
compute the chain, write `matrix`, exit) and the CLI mirrors that shape --
every invocation pays cold JAX import, cold jit, a cold crossover gate and
a cold plan cache (~145x over a warm plan-cache hit at 20k keys).  The
serving layer turns those per-job costs into per-fleet costs, the JITSPMM
argument applied at process scope: one long-lived device-pool-owner
process executes every job, so compiled executables, the structure-keyed
plan cache (ops/plancache) and the crossover measurement cache persist
across jobs -- and the pool scheduler (SPGEMM_TPU_SERVE_SLICES) keeps
every chip busy: one executor per device slice, estimator-priced
placement, per-tenant fair queuing, work stealing.

Modules:
  protocol.py  -- versioned newline-delimited JSON over a unix socket
                  (v2: optional submit `tenant`).
  queue.py     -- bounded per-tenant fair queue with admission control,
                  per-tenant in-flight caps + per-job deadlines.
  placement.py -- estimator-priced job routing (price book keyed by the
                  input folder's stat signature).
  daemon.py    -- per-slice executors, placement scheduler, watchdog
                  (backend_probe-based wedge detection, per-slice
                  degrade-to-CPU), on-disk job journal.
  client.py    -- client library + the CLI `serve`/`submit`/`status`
                  subcommand handlers.
  smoke.py     -- `make serve-smoke`: end-to-end daemon proof on CPU.
"""
