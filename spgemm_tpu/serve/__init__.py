"""spgemmd (L4): a resident serving daemon that keeps the engine warm.

The reference is a run-once binary (SURVEY.md section 0: read <folder>,
compute the chain, write `matrix`, exit) and the CLI mirrors that shape --
every invocation pays cold JAX import, cold jit, a cold crossover gate and
a cold plan cache (~145x over a warm plan-cache hit at 20k keys).  The
serving layer turns those per-job costs into per-fleet costs, the JITSPMM
argument applied at process scope: one long-lived single-device-owner
process executes every job, so compiled executables, the structure-keyed
plan cache (ops/plancache) and the crossover measurement cache persist
across jobs.

Modules:
  protocol.py -- versioned newline-delimited JSON over a unix socket.
  queue.py    -- bounded FIFO with admission control + per-job deadlines.
  daemon.py   -- executor thread, watchdog (backend_probe-based wedge
                 detection, degrade-to-CPU), on-disk job journal.
  client.py   -- client library + the CLI `serve`/`submit`/`status`
                 subcommand handlers.
  smoke.py    -- `make serve-smoke`: end-to-end daemon proof on CPU.
"""
