"""spgemmd wire protocol: versioned newline-delimited JSON over a unix
domain socket, and (knob-gated) the same byte stream over TCP.

One request per line, one response line per request, connections may carry
any number of requests.  Every message is a JSON object; requests carry
`{"v": PROTOCOL_VERSION, "op": <op>, ...}` and responses carry
`{"v": ..., "ok": true, ...}` or `{"v": ..., "ok": false, "error":
{"code": <code>, "message": <text>}}`.  A malformed line is answered with
a structured `bad-request` error -- the daemon must survive garbage input
(acceptance-gated in tests/test_serve.py), so decode failures never
propagate past the connection handler.

Version negotiation is centralized in the FIELD_MIN_VERSION capability
table below: a client stamps version_for(msg) -- the lowest version
carrying its optional fields (tenant: v2, trace: v3) -- and downgrades
via strip_for_version() when an older daemon's version-mismatch answer
names its accepted versions (accepted_from_error), so rolling upgrades
work in both directions without per-field stamping at call sites.

Ops:
  submit   {folder, options?, tenant?, trace?} -> {id, state, queued,
                                       trace}
                                       (tenant: optional fair-queuing
                                       identity -- deficit-round-robin
                                       across tenants with an optional
                                       per-tenant in-flight cap,
                                       SPGEMM_TPU_SERVE_TENANT_INFLIGHT;
                                       absent = the shared "default"
                                       tenant, exactly the v1 behavior.
                                       trace: optional 128-bit hex trace
                                       context the client minted -- every
                                       span/event/journal record of the
                                       job carries it; absent/v1/v2 = the
                                       daemon mints one, returned either
                                       way)
  status   {id}                     -> {job: <snapshot>}
  wait     {id, timeout?}           -> {job: <snapshot>} (blocks until the
                                       job is terminal or timeout elapses;
                                       one wait is clamped server-side to
                                       Daemon.MAX_WAIT_SLICE_S so a waiter
                                       never pins a connection slot --
                                       client.wait() polls in slices)
  stats    {}                       -> daemon-wide counters, degraded flag,
                                       plan-cache stats, journal size/
                                       compactions, per-outcome terminal
                                       job totals
  metrics  {}                       -> {text: <Prometheus text-format
                                       0.0.4>, content_type} -- the
                                       scrapeable surface (obs/metrics.py
                                       registry; `spgemm_tpu.cli metrics`)
  trace    {}                       -> {trace_events: [...]} -- the span
                                       flight recorder as Perfetto/Chrome
                                       trace_event JSON (obs/trace.py;
                                       `spgemm_tpu.cli trace-dump`)
  profile  {}                       -> {profile: <deep-profiling report>}
                                       -- compile/cost/memory accounting +
                                       estimator/delta prediction
                                       accountability (obs/profile.py;
                                       `spgemm_tpu.cli profile`)
  events   {n?}                     -> {events: [newest n JSONL records]}
                                       -- the structured event log's ring
                                       (obs/events.py; `spgemm_tpu.cli
                                       events --tail N [--follow]`)
  slo      {}                       -> {slo: <SLO engine report>} -- the
                                       rolling per-tenant objective
                                       accounts + burn state (obs/slo.py;
                                       `spgemm_tpu.cli slo [--json]`)
  shutdown {}                       -> {stopping: true}

jax-free by design: the client must be importable (and the protocol
parsable) without initializing any backend.
"""

from __future__ import annotations

import json
import os
import re
import tempfile

from spgemm_tpu.utils import knobs

PROTOCOL_VERSION = 4

# versions the daemon still speaks: v2 added the optional submit `tenant`
# field (absent = DEFAULT_TENANT), v3 the optional submit `trace` field
# (absent = the daemon mints the trace context), v4 the fleet layer's
# RESPONSE-side fields only (`backend` on submit/status/wait answers,
# `backends` on stats -- authored by the federation router, ignored by
# older clients) -- v1..v3 requests parse unchanged, and because v4 adds
# no request field, FIELD_MIN_VERSION and client stamping are untouched:
# a v4 router/daemon serves v3 clients and a v3 daemon serves v4 clients
# without a downgrade retry
ACCEPTED_VERSIONS = (1, 2, 3, 4)

# THE declarative wire registry (one table per direction, not one ad-hoc
# literal per call site): op -> field -> the lowest protocol version
# whose daemons understand it.  The PRO lint rule
# (analysis/protorules.py) holds every literal wire-field key in
# serve/daemon.py / serve/client.py / cli.py to these tables, the DRF
# audit flags a declared field no site references, version_for()/
# strip_for_version() derive from them, and the generated
# ARCHITECTURE.md protocol table renders them -- a new field lands HERE
# first or the linter rejects the call site.

# fields any message may carry regardless of op (the envelope)
ENVELOPE_FIELDS = {"v": 1, "op": 1, "ok": 1, "error": 1}

REQUEST_FIELDS: dict[str, dict[str, int]] = {
    "submit": {"folder": 1, "options": 1, "tenant": 2, "trace": 3},
    "status": {"id": 1},
    "wait": {"id": 1, "timeout": 1},
    "stats": {},
    "metrics": {},
    "trace": {},
    "profile": {},
    "events": {"n": 1},
    "slo": {},
    "shutdown": {},
}

# response fields are never stripped (the daemon answers at its own
# version and old clients ignore unknown keys), so a min version here
# documents the introduction point rather than driving negotiation
RESPONSE_FIELDS: dict[str, dict[str, int]] = {
    "submit": {"id": 1, "state": 1, "queued": 1, "trace": 3,
               "backend": 4},
    "status": {"job": 1, "backend": 4},
    "wait": {"job": 1, "backend": 4},
    "stats": {"daemon": 1, "uptime_s": 1, "degraded": 1,
              "degrade_reason": 1, "backend_probe": 1, "queue_cap": 1,
              "job_timeout_s": 1, "jobs": 1, "jobs_terminal": 1,
              "slices": 2, "slices_degraded": 2, "tenants": 2,
              "tenant_inflight_cap": 2, "placement": 2, "journal": 1,
              "failpoints": 1, "trace": 3, "events": 3, "profile": 3,
              "slo": 3, "flight_dir": 3, "plan_cache": 1, "delta": 1,
              "warm": 1, "tune": 3, "socket": 1, "backends": 4},
    "metrics": {"content_type": 1, "text": 1},
    "trace": {"spans": 1, "trace_events": 1},
    "profile": {"profile": 1},
    "events": {"events": 1, "log": 1},
    "slo": {"slo": 1},
    "shutdown": {"stopping": 1},
}

# the one negotiation input, DERIVED from the request tables: each
# post-v1 optional field -> its carrying version.  Clients consult
# version_for() to stamp the lowest version carrying their request's
# fields (a still-v2 daemon's strict version check must keep serving an
# upgraded client that uses no v3 feature), and strip_for_version() to
# shed too-new fields when a version-mismatch answer forces a downgrade
# (the daemon then supplies the field's fallback: default tenant,
# minted trace).  The PRO registry audit holds a field name spelled in
# several ops to ONE min version, so this flattening cannot be lossy.
FIELD_MIN_VERSION: dict[str, int] = {
    f: v for fields in REQUEST_FIELDS.values()
    for f, v in fields.items() if v > 1
}


def version_for(msg: dict) -> int:
    """The lowest protocol version carrying every optional field in
    `msg` (1 when none rides) -- the one negotiation rule, replacing
    per-field version stamping at call sites."""
    return max([1, *(v for field, v in FIELD_MIN_VERSION.items()
                     if msg.get(field) is not None)])


def strip_for_version(msg: dict, version: int) -> dict:
    """`msg` without the fields a v<=`version` daemon would not
    understand (the rolling-downgrade half of the capability table)."""
    return {k: v for k, v in msg.items()
            if FIELD_MIN_VERSION.get(k, 1) <= version}


def accepted_from_error(message: str) -> tuple[int, ...]:
    """Parse the daemon's accepted versions out of its version-mismatch
    error message (`protocol version mismatch: ... (accepts v1/v2) ...`
    -- the stable wording every daemon generation has used); empty when
    the message is not a version-mismatch answer.  ANCHORED to the
    message prefix on purpose: other bad-request answers echo
    client-supplied values verbatim (a tenant/trace of literally
    `accepts v1/v2`), and a spoofed match would downgrade-and-strip a
    field the daemon explicitly rejected -- the client must hear that
    rejection, not silently retry without the field."""
    if not message.startswith("protocol version mismatch"):
        return ()
    m = re.search(r"accepts ((?:v\d+/?)+)", message)
    if not m:
        return ()
    return tuple(int(part[1:]) for part in m.group(1).split("/") if part)

# the tenant every v1 (or tenant-less v2) submit maps to
DEFAULT_TENANT = "default"

# tenant names are operator-facing label values (Prometheus series, stats
# keys): bound the charset and length at admission
TENANT_MAX_LEN = 64

OPS = tuple(REQUEST_FIELDS)

# server-side bound on one request line: a peer streaming newline-free
# bytes must exhaust THIS, not the daemon's memory (real requests are a
# few hundred bytes; 1 MiB leaves room for pathological-but-legal paths)
MAX_LINE_BYTES = 1 << 20

# the chain engine's multiply backends a submit may name -- the ONE list
# the daemon validates against and the client offers (the run-once CLI
# adds its host-only "oracle" on top; the daemon reserves that path for
# degraded mode)
CHAIN_BACKENDS = ("xla", "pallas", "mxu", "hybrid")

# the structured error-code registry: code -> doc.  The E_* constants
# below are the call-site spellings; the PRO registry audit holds the
# constants and this table to set equality, and the DRF audit flags a
# code no site raises or compares against.
ERROR_CODES: dict[str, str] = {
    "bad-request": "unparsable line, unknown op, bad version, or a "
                   "field that failed admission validation",
    "queue-full": "admission control rejection "
                  "(SPGEMM_TPU_SERVE_QUEUE_CAP jobs already queued)",
    "tenant-cap": "per-tenant in-flight cap rejection "
                  "(SPGEMM_TPU_SERVE_TENANT_INFLIGHT)",
    "too-many-connections": "concurrent-connection bound hit",
    "unknown-job": "status/wait for a job id the daemon does not know",
    "shutting-down": "submit refused while the daemon drains",
    "internal-error": "handler crash (the daemon survives it)",
    "daemon-unavailable": "client-side: no daemon reachable after the "
                          "bounded connect-retry window "
                          "(ECONNREFUSED/ENOENT through a restart "
                          "rollout, retried with capped backoff, then "
                          "THIS, structured, instead of a raw OSError)",
    "job-timeout": "job reaped past SPGEMM_TPU_SERVE_JOB_TIMEOUT "
                   "(in a failed job's error dict)",
    "executor-died": "executor thread died or wedged mid-job "
                     "(in a failed job's error dict)",
    "job-error": "the chain runner raised "
                 "(in a failed job's error dict)",
    "backend-lost": "fleet router: the backend holding the job died and "
                    "the one idempotent re-submit to a healthy peer was "
                    "not possible (already retried, or no healthy peer)",
    "no-backend": "fleet router: no healthy backend available for "
                  "placement (all dead, degraded, or still unprobed)",
}

# request-level error codes
E_BAD_REQUEST = "bad-request"
E_QUEUE_FULL = "queue-full"
E_TENANT_CAP = "tenant-cap"
E_BUSY = "too-many-connections"
E_UNKNOWN_JOB = "unknown-job"
E_SHUTTING_DOWN = "shutting-down"
E_INTERNAL = "internal-error"
# client-side code (serve/client.py mints it, never the daemon)
E_UNAVAILABLE = "daemon-unavailable"

# job-failure codes (in a failed job's error dict)
E_JOB_TIMEOUT = "job-timeout"
E_EXECUTOR_DIED = "executor-died"
E_JOB_ERROR = "job-error"

# fleet-router codes (fleet/router.py mints them, never a daemon)
E_BACKEND_LOST = "backend-lost"
E_NO_BACKEND = "no-backend"


def protocol_table_md() -> str:
    """The generated wire-contract table for ARCHITECTURE.md (the DOC
    rule diffs the committed block against this; regenerate with
    `python -m spgemm_tpu.analysis --write-protocol-table`)."""
    def cell(fields: dict[str, int]) -> str:
        if not fields:
            return "—"
        return ", ".join(f"`{name}`" + (f" (v{v}+)" if v > 1 else "")
                         for name, v in fields.items())

    lines = [f"Protocol v{PROTOCOL_VERSION} (accepts "
             f"{'/'.join(f'v{a}' for a in ACCEPTED_VERSIONS)}); every "
             f"message also carries the envelope fields "
             f"{', '.join(f'`{f}`' for f in ENVELOPE_FIELDS)}.",
             "",
             "| op | request fields | response fields |",
             "|---|---|---|"]
    for op in OPS:
        lines.append(f"| `{op}` | {cell(REQUEST_FIELDS[op])} "
                     f"| {cell(RESPONSE_FIELDS[op])} |")
    lines += ["", "| error code | meaning |", "|---|---|"]
    for code, doc in ERROR_CODES.items():
        lines.append(f"| `{code}` | {doc} |")
    return "\n".join(lines)


# tenant charset: safe as a Prometheus label value and a stats dict key
# (no quotes, no whitespace, no control characters)
_TENANT_RE = re.compile(r"^[A-Za-z0-9._:-]+$")


def valid_tenant(tenant) -> bool:
    """True iff `tenant` is an acceptable wire tenant name."""
    return (isinstance(tenant, str) and 0 < len(tenant) <= TENANT_MAX_LEN
            and _TENANT_RE.match(tenant) is not None)


# 128-bit trace context, lowercase hex (protocol v3 submit field): the
# client mints it, every span/event/journal record of the job carries
# it, and `cli trace-dump --merge` stitches per-process dumps on it
TRACE_HEX_LEN = 32
_TRACE_RE = re.compile(r"^[0-9a-f]{32}$")


def valid_trace(trace) -> bool:
    """True iff `trace` is a well-formed wire trace context."""
    return (isinstance(trace, str)
            and _TRACE_RE.match(trace) is not None)


def mint_trace() -> str:
    """A fresh 128-bit trace context (client-side at submit; the daemon
    falls back to minting for v1/v2 submits and journal replays of
    pre-v3 records)."""
    return os.urandom(TRACE_HEX_LEN // 2).hex()


class ProtocolError(Exception):
    """A request that cannot be dispatched; carries the structured code."""

    def __init__(self, code: str, message: str):
        super().__init__(message)
        self.code = code
        self.message = message


def default_socket_path() -> str:
    """SPGEMM_TPU_SERVE_SOCKET, or <tmpdir>/spgemmd-<uid>.sock (uid-scoped
    so two users on one host never race on the same daemon socket)."""
    configured = knobs.get("SPGEMM_TPU_SERVE_SOCKET")
    if configured:
        return configured
    return os.path.join(tempfile.gettempdir(),
                        f"spgemmd-{os.getuid()}.sock")


def parse_addr(spec: str):
    """Parse one wire address spec into ("tcp", host, port) or
    ("unix", path).  `tcp:HOST:PORT` is the network front-end form
    (IPv6 hosts use their last colon as the port separator; port 0 is
    legal -- the listener binds an ephemeral port and reports it);
    `unix:PATH` or a bare path is the unix-domain form.  ValueError on
    anything else, naming the spec -- an address typo must fail loudly,
    never fall back to a default socket."""
    if not isinstance(spec, str) or not spec:
        raise ValueError(f"empty wire address spec {spec!r}")
    if spec.startswith("tcp:"):
        host, sep, port = spec[4:].rpartition(":")
        if not sep or not host:
            raise ValueError(
                f"bad tcp address {spec!r} (want tcp:HOST:PORT)")
        try:
            port_no = int(port)
        except ValueError:
            raise ValueError(
                f"bad tcp port in {spec!r} (want tcp:HOST:PORT)") from None
        if not 0 <= port_no <= 65535:
            raise ValueError(f"tcp port out of range in {spec!r}")
        return ("tcp", host.strip("[]"), port_no)
    if spec.startswith("unix:"):
        path = spec[5:]
        if not path:
            raise ValueError(f"empty unix path in {spec!r}")
        return ("unix", path)
    return ("unix", spec)


def format_addr(parsed) -> str:
    """The canonical spec string for a parse_addr() result (stable
    identity for backend labels and log lines)."""
    if parsed[0] == "tcp":
        return f"tcp:{parsed[1]}:{parsed[2]}"
    return f"unix:{parsed[1]}"


def default_addr() -> str:
    """The client's default target: SPGEMM_TPU_SERVE_ADDR when exported
    (the TCP front-end -- clients on other hosts share the export), else
    the local unix socket path."""
    configured = knobs.get("SPGEMM_TPU_SERVE_ADDR")
    if configured:
        return configured
    return default_socket_path()


def encode(msg: dict) -> bytes:
    """One wire line for msg (compact JSON + newline)."""
    return json.dumps(msg, separators=(",", ":")).encode() + b"\n"


def ok(**fields) -> dict:
    return {"v": PROTOCOL_VERSION, "ok": True, **fields}


def error(code: str, message: str, **fields) -> dict:
    return {"v": PROTOCOL_VERSION, "ok": False,
            "error": {"code": code, "message": message}, **fields}


def parse_request(line: str) -> dict:
    """Decode + validate one request line; ProtocolError on anything the
    dispatcher could not act on (the caller answers with error())."""
    try:
        msg = json.loads(line)
    except ValueError as e:
        raise ProtocolError(E_BAD_REQUEST,
                            f"request is not valid JSON: {e}") from None
    if not isinstance(msg, dict):
        raise ProtocolError(E_BAD_REQUEST,
                            "request must be a JSON object")
    v = msg.get("v")
    if v not in ACCEPTED_VERSIONS:
        raise ProtocolError(
            E_BAD_REQUEST,
            f"protocol version mismatch: daemon speaks v{PROTOCOL_VERSION} "
            f"(accepts {'/'.join(f'v{a}' for a in ACCEPTED_VERSIONS)}), "
            f"request carries v={v!r}")
    op = msg.get("op")
    if op not in OPS:
        raise ProtocolError(E_BAD_REQUEST,
                            f"unknown op {op!r} (expected one of "
                            f"{'|'.join(OPS)})")
    return msg


def read_lines(sock, bufsize: int = 65536, max_line: int | None = None):
    """Yield decoded lines from a socket until EOF.  Bytes that arrive
    after the last newline when the peer closes are NOT yielded -- a
    request is only a request once its newline lands.

    max_line bounds the pending (newline-less) buffer: past it,
    ProtocolError(bad-request) -- the daemon answers and drops the
    connection instead of growing without limit (garbage input must never
    kill the device owner, and that includes OOM-killing it).  The client
    side reads daemon-authored responses and needs no cap."""
    buf = b""
    while True:
        chunk = sock.recv(bufsize)
        if not chunk:
            return
        buf += chunk
        while b"\n" in buf:
            line, buf = buf.split(b"\n", 1)
            yield line.decode("utf-8", errors="replace")
        if max_line is not None and len(buf) > max_line:
            raise ProtocolError(
                E_BAD_REQUEST,
                f"request line exceeds {max_line} bytes without a newline")
