"""`make chaos-smoke`: failpoint-driven chaos proof for spgemmd on CPU.

A seeded randomized fault schedule (utils/failpoints.py registry, armed
via SPGEMM_TPU_FAILPOINTS) runs against a LIVE 2-slice daemon, and the
serving contract is asserted under fire:

  * every job ends bit-exact vs the host oracle OR failed with a
    structured error (a code-carrying error dict) -- never wrong bits,
    never an unexplained loss;
  * no job hangs past the watchdog window: every wait() returns a
    terminal state within a bound derived from the job deadline + wedge
    grace (+ engine margin);
  * the pool HEALS: the schedule always arms `serve.executor:1:1` (one
    slice wedges on its first pickup -> reap -> wedge declaration ->
    per-slice degrade), and SPGEMM_TPU_SERVE_RECOVER_S re-probes and
    reinstates it behind the canary gate -- per-slice stats must report
    `recoveries >= 1` before the leg ends, and the Prometheus scrape
    must carry a moving spgemm_failpoints_triggered_total series;
  * the journal survives a mid-write kill: the schedule arms
    `serve.journal:1:1` (one deliberately torn record), the harness
    additionally appends a half-written frame after shutdown, and a
    SECOND daemon on the same socket must replay clean -- bind, count
    the tear (stats journal.torn >= 1), and serve a fresh submit
    bit-exact;
  * shutdown is rollout-grade: the second daemon is stopped with
    SIGTERM and must drain + exit 0 with its socket unlinked.

Any step failing exits nonzero.  The harness process stays jax-free
(oracle + generator are pure numpy); only the daemons touch a backend.
"""

from __future__ import annotations

import os
import random
import signal
import subprocess
import sys
import tempfile
import time

# the watchdog window the no-hang assertion is derived from
JOB_TIMEOUT_S = 45.0
WEDGE_GRACE_S = 2.0
RECOVER_S = 0.5
# engine margin on top of the watchdog window: CPU jit of a cold shape
WAIT_MARGIN_S = 120.0

# probabilistic candidates the seeded schedule draws from (the wedge and
# the torn journal record are always armed -- the heal and replay
# assertions need them deterministically)
CANDIDATES = (
    ("plan.build", (0.1, 0.3)),
    ("plan.ensure_exact", (0.1, 0.3)),
    ("kernel.dispatch", (0.1, 0.3)),
    ("delta.diff", (0.3, 0.7)),
    ("warm.load", (0.3, 0.7)),
    ("serve.accept", (0.1, 0.3)),
    ("serve.readline", (0.05, 0.15)),
)


def _fail(procs, msg: str) -> int:
    print(f"chaos-smoke: FAIL: {msg}", file=sys.stderr)
    for proc in procs:
        if proc is not None and proc.poll() is None:
            proc.kill()
        if proc is not None:
            out, _ = proc.communicate(timeout=10)
            sys.stderr.write(out[-6000:] if out else "")
    return 1


def _start_daemon(sock: str, env: dict, procs: list):
    proc = subprocess.Popen(
        [sys.executable, "-m", "spgemm_tpu.cli", "serve",
         "--socket", sock, "--device", "cpu", "-v"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    procs.append(proc)
    deadline = time.time() + 120
    while not os.path.exists(sock):
        if proc.poll() is not None:
            return None, "daemon exited before binding its socket"
        if time.time() > deadline:
            return None, "daemon never bound its socket"
        time.sleep(0.1)
    return proc, None


def _transient(client, e) -> bool:
    """Retryable chaos weather, not an outcome: the daemon answered busy
    (MAX_CONNS under an injected accept stall) or the client's bounded
    connect retry gave up mid-restart -- both clear on their own.  Any
    other ServeError is a real structured result the caller must
    surface, never swallow in a retry loop."""
    from spgemm_tpu.serve import protocol  # noqa: PLC0415
    return isinstance(e, client.ServeError) and \
        e.code in (protocol.E_BUSY, protocol.E_UNAVAILABLE)


def _submit_retrying(client, folder, sock, options):
    """Submit, riding out an injected conn-handler death (the daemon
    drops the connection without answering -> ConnectionError; the
    request never reached admission, so a resend cannot double-submit)
    and transient busy/unavailable answers."""
    last = None
    for _ in range(6):
        try:
            return client.submit(folder, sock, options)
        except ConnectionError as e:
            last = e
            time.sleep(0.1)
        except client.ServeError as e:
            if not _transient(client, e):
                raise
            last = e
            time.sleep(0.1)
    raise last


def _wait_retrying(client, job_id, sock, timeout):
    deadline = time.time() + timeout
    last = None
    while time.time() < deadline:
        try:
            resp = client.wait(job_id, sock,
                               timeout=max(1.0, deadline - time.time()))
        except ConnectionError as e:  # injected conn death: reconnect
            last = e
            time.sleep(0.1)
            continue
        except client.ServeError as e:
            if not _transient(client, e):
                raise
            last = e
            time.sleep(0.1)
            continue
        if resp["job"]["state"] in ("done", "failed"):
            return resp
        break  # wait() returned a non-terminal snapshot: deadline hit
    if last is not None and time.time() >= deadline:
        raise last
    return None


def main(argv: list[str] | None = None) -> int:
    import argparse  # noqa: PLC0415

    import numpy as np  # noqa: PLC0415

    from spgemm_tpu.serve import client  # noqa: PLC0415
    from spgemm_tpu.utils import io_text  # noqa: PLC0415
    from spgemm_tpu.utils.blockcsr import BlockSparseMatrix  # noqa: PLC0415
    from spgemm_tpu.utils.gen import random_chain  # noqa: PLC0415
    from spgemm_tpu.utils.semantics import chain_oracle  # noqa: PLC0415

    p = argparse.ArgumentParser(
        prog="spgemm_tpu.serve.chaos_smoke",
        description="seeded failpoint chaos proof against a live "
                    "2-slice spgemmd")
    p.add_argument("--seed", type=int, default=20260804,
                   help="fault-schedule seed (default 20260804; the "
                        "schedule prints so a failure replays)")
    p.add_argument("--jobs", type=int, default=10,
                   help="submits in the chaos leg (default 10)")
    args = p.parse_args(argv)

    rng = random.Random(args.seed)
    tmp = tempfile.mkdtemp(prefix="spgemmd-chaos-")
    sock = os.path.join(tmp, "d.sock")
    procs: list = []

    # two small chains + oracles; repeat submits exercise plan-cache,
    # delta and warm paths under fire
    folders, wants = [], []
    for i, seed in enumerate((31, 32)):
        f = os.path.join(tmp, f"chain_{i}")
        mats = random_chain(4, 6, 4, 0.5, np.random.default_rng(seed),
                            "full")
        io_text.write_chain_dir(f, mats, 4)
        w = chain_oracle([m.to_dict() for m in mats], 4)
        wants.append(io_text.format_matrix(BlockSparseMatrix.from_dict(
            mats[0].rows, mats[-1].cols, 4, w).prune_zeros()))
        folders.append(f)

    # the seeded schedule: 3 probabilistic draws + the two deterministic
    # anchors the heal/replay assertions need
    drawn = rng.sample(CANDIDATES, 3)
    terms = [f"{name}:{rng.uniform(lo, hi):.2f}"
             for name, (lo, hi) in drawn]
    terms += ["serve.executor:1:1", "serve.journal:1:1"]
    schedule = ",".join(terms)
    print(f"chaos-smoke: seed={args.seed} schedule={schedule}")

    env = {k: v for k, v in os.environ.items()
           if not k.startswith("SPGEMM_TPU_WARM")
           and k != "SPGEMM_TPU_FAILPOINTS"}
    env.update({
        "SPGEMM_TPU_FAILPOINTS": schedule,
        "SPGEMM_TPU_SERVE_SLICES": "2",
        "SPGEMM_TPU_SERVE_JOB_TIMEOUT": f"{JOB_TIMEOUT_S:g}",
        "SPGEMM_TPU_SERVE_WEDGE_GRACE_S": f"{WEDGE_GRACE_S:g}",
        "SPGEMM_TPU_SERVE_RECOVER_S": f"{RECOVER_S:g}",
    })
    proc, err = _start_daemon(sock, env, procs)
    if err:
        return _fail(procs, err)

    # ---- chaos leg: every job bit-exact or structured, no hangs ----
    wait_bound = JOB_TIMEOUT_S + WEDGE_GRACE_S + WAIT_MARGIN_S
    done = failed = 0
    error_codes = set()
    for i in range(args.jobs):
        pick = rng.randrange(len(folders))
        out = os.path.join(tmp, f"out.{i}")
        try:
            resp = _submit_retrying(client, folders[pick], sock,
                                    {"output": out})
        except client.ServeError as e:
            return _fail(procs, f"submit {i} rejected unexpectedly: {e}")
        try:
            resp = _wait_retrying(client, resp["id"], sock, wait_bound)
        except client.ServeError as e:
            return _fail(procs, f"wait for job {i} answered a "
                                f"structured error: {e}")
        if resp is None:
            return _fail(procs, f"job {i} not terminal within "
                                f"{wait_bound:g}s: HANG past the "
                                "watchdog window")
        job = resp["job"]
        if job["state"] == "done":
            done += 1
            if open(out, "rb").read() != wants[pick]:
                return _fail(procs, f"job {i} completed with WRONG BITS "
                                    "vs the oracle")
        else:
            err_dict = job.get("error") or {}
            code = err_dict.get("code")
            if not code or not isinstance(code, str):
                return _fail(procs, f"job {i} failed WITHOUT a "
                                    f"structured error: {err_dict!r}")
            failed += 1
            error_codes.add(code)
    if done == 0:
        return _fail(procs, "no job completed at all; the schedule "
                            "starved the assertion (lower the probs)")

    # ---- heal leg: the wedged slice must recover and serve again ----
    deadline = time.time() + 60
    recoveries = 0
    while time.time() < deadline:
        try:
            st = client.stats(sock)
        except ConnectionError:  # injected conn death: reconnect
            time.sleep(0.1)
            continue
        except client.ServeError as e:
            if not _transient(client, e):
                return _fail(procs, f"stats answered a structured "
                                    f"error mid-heal: {e}")
            time.sleep(0.1)
            continue
        recoveries = sum(s.get("recoveries", 0) for s in st["slices"])
        if recoveries >= 1 and not any(s["degraded"] for s in st["slices"]):
            break
        time.sleep(0.25)
    if recoveries < 1:
        return _fail(procs, "pool never healed: serve_recoveries == 0 "
                            "after the wedge (recovery loop dead?)")
    scrape = None
    for _ in range(6):
        try:
            scrape = client.metrics(sock)
            break
        except ConnectionError:
            time.sleep(0.1)
        except client.ServeError as e:
            if not _transient(client, e):
                return _fail(procs, f"metrics answered a structured "
                                    f"error: {e}")
            time.sleep(0.1)
    if scrape is None:
        return _fail(procs, "metrics scrape never answered")
    if "spgemm_failpoints_triggered_total{" not in scrape:
        return _fail(procs, "failpoint triggers missing from the "
                            "Prometheus scrape")
    # post-heal submit: the reinstated pool serves bit-exact
    out = os.path.join(tmp, "out.heal")
    try:
        resp = _submit_retrying(client, folders[0], sock, {"output": out})
        resp = _wait_retrying(client, resp["id"], sock, wait_bound)
    except client.ServeError as e:
        return _fail(procs, f"post-heal submit answered a structured "
                            f"error: {e}")
    if resp is None or resp["job"]["state"] != "done" \
            or open(out, "rb").read() != wants[0]:
        return _fail(procs, "post-heal submit did not complete bit-exact")

    for _ in range(6):
        try:
            client.shutdown(sock)
            break
        except ConnectionError:  # injected conn death: reconnect
            time.sleep(0.1)
        except client.ServeError as e:
            if not _transient(client, e):
                return _fail(procs, f"shutdown answered a structured "
                                    f"error: {e}")
            time.sleep(0.1)
    try:
        rc = proc.wait(timeout=60)
    except subprocess.TimeoutExpired:
        return _fail(procs, "chaos daemon did not exit after shutdown")
    if rc != 0:
        return _fail(procs, f"chaos daemon exited {rc} after shutdown")

    # ---- torn-journal leg: replay clean after a mid-write kill ----
    journal = sock + ".journal"
    with open(journal, "a", encoding="utf-8") as f:
        # half a frame, no newline: byte-for-byte what SIGKILL mid-append
        # leaves (on top of the serve.journal-injected tear earlier)
        f.write('89abcdef 57 {"event":"submit","id":"job-torn","fold')
    env2 = dict(env)
    del env2["SPGEMM_TPU_FAILPOINTS"]  # replay leg runs un-injected
    proc2, err = _start_daemon(sock, env2, procs)
    if err:
        return _fail(procs, f"restart over torn journal: {err}")
    st = client.stats(sock)
    torn = st["journal"].get("torn", 0)
    if torn < 1:
        return _fail(procs, "restarted daemon did not count the torn "
                            f"journal tail (torn={torn})")
    out2 = os.path.join(tmp, "out.replay")
    try:
        resp = _submit_retrying(client, folders[1], sock,
                                {"output": out2})
        resp = _wait_retrying(client, resp["id"], sock, wait_bound)
    except client.ServeError as e:
        return _fail(procs, f"post-replay submit answered a structured "
                            f"error: {e}")
    if resp is None or resp["job"]["state"] != "done" \
            or open(out2, "rb").read() != wants[1]:
        return _fail(procs, "post-replay submit did not complete "
                            "bit-exact")

    # ---- rollout leg: SIGTERM drains and exits 0 ----
    proc2.send_signal(signal.SIGTERM)
    try:
        rc = proc2.wait(timeout=60)
    except subprocess.TimeoutExpired:
        return _fail(procs, "daemon did not exit on SIGTERM (graceful "
                            "drain hung)")
    if rc != 0:
        return _fail(procs, f"daemon exited {rc} on SIGTERM (want 0)")
    if os.path.exists(sock):
        return _fail([], "socket not unlinked after SIGTERM drain")

    print(f"chaos-smoke: OK (seed={args.seed}; {done} done bit-exact + "
          f"{failed} structured-failed of {args.jobs} chaos jobs, "
          f"codes={sorted(error_codes)}; recoveries={recoveries}; "
          f"journal torn counted={torn} and replayed clean; SIGTERM "
          "drain exited 0)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
