"""spgemmd client: library calls + the CLI `submit`/`status` handlers.

jax-free by design -- a submitting process must never pay the cold JAX
import the daemon exists to amortize (and must never touch a possibly-dead
backend; the daemon owns the device, clients own only the socket).
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import sys
import time

from spgemm_tpu.obs import events as obs_events
from spgemm_tpu.obs import trace as obs_trace
from spgemm_tpu.serve import protocol

# one server-side wait is bounded (Daemon.MAX_WAIT_SLICE_S), so wait()
# polls in slices: a connection is never pinned longer than a slice by an
# abandoned waiter, and a Ctrl-C'd client frees its slot at the next
# slice boundary instead of holding it until the job terminates
WAIT_SLICE_S = 15.0

# client-side backoff between wait slices: a job still running after a
# full server-side slice is a LONG job, so hundreds of idle waiters must
# not hammer the accept loop with immediate reconnects -- each expired
# slice doubles the pre-reconnect sleep from WAIT_BACKOFF_S up to
# WAIT_BACKOFF_MAX_S (the added completion-detection latency is bounded
# by the cap)
WAIT_BACKOFF_S = 0.05
WAIT_BACKOFF_MAX_S = 2.0

# connect retry during a daemon-restart window: ECONNREFUSED (socket file
# exists, no listener yet -- the successor daemon is binding) and ENOENT
# (socket unlinked -- the predecessor just exited) both retry with capped
# exponential backoff, bounded by a TOTAL wait; past it the caller gets a
# structured daemon-unavailable ServeError instead of a raw OSError
# mid-rollout.  retry_total_s=0 disables retrying (one attempt).
CONNECT_BACKOFF_S = 0.05
CONNECT_BACKOFF_MAX_S = 1.0
CONNECT_RETRY_TOTAL_S = 5.0


class ServeError(Exception):
    """A structured daemon-side error response; carries the wire code."""

    def __init__(self, code: str, message: str):
        super().__init__(f"[{code}] {message}")
        self.code = code
        self.message = message


def _connect(path: str, timeout: float | None,
             retry_total_s: float) -> socket.socket:
    """Connect to the daemon at `path` -- a unix socket path, `unix:PATH`,
    or `tcp:HOST:PORT` (protocol.parse_addr) -- riding out a restart
    window: connection-refused / socket-missing / connection-reset
    retries with capped exponential backoff for at most retry_total_s
    seconds, then raises a structured daemon-unavailable ServeError
    (chained on the last OS error).  The retry/backoff/error contract is
    transport-independent: a TCP front-end restart looks exactly like a
    unix-socket rollout to the caller."""
    parsed = protocol.parse_addr(path)
    deadline = time.time() + retry_total_s
    backoff = 0.0
    while True:
        if parsed[0] == "tcp":
            sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            target = (parsed[1], parsed[2])
        else:
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            target = parsed[1]
        sock.settimeout(timeout)
        try:
            sock.connect(target)
        except (ConnectionRefusedError, ConnectionResetError,
                FileNotFoundError) as e:
            sock.close()
            now = time.time()
            if now >= deadline:
                raise ServeError(
                    protocol.E_UNAVAILABLE,
                    f"no daemon reachable at {path} after "
                    f"{retry_total_s:g}s of connect retries ({e})") from e
            backoff = min(CONNECT_BACKOFF_MAX_S,
                          backoff * 2 if backoff else CONNECT_BACKOFF_S)
            time.sleep(min(backoff, max(0.0, deadline - now)))
        except BaseException:
            sock.close()
            raise
        else:
            return sock


def request(msg: dict, socket_path: str | None = None,
            timeout: float | None = None,
            retry_total_s: float | None = None) -> dict:
    """One request, one response.  A missing/refusing socket retries for
    up to retry_total_s (default CONNECT_RETRY_TOTAL_S -- the daemon-
    restart rollout window) before raising a structured
    daemon-unavailable ServeError; other OSError flavors raise as
    before.  Raises ServeError on an error response.

    Version negotiation is the capability table's, not per call site:
    the request advertises protocol.version_for(msg) -- the LOWEST
    version carrying its optional fields (tenant: v2, trace: v3) -- so
    a newer daemon serves old-shaped requests and an old daemon never
    sees a version it must reject for a feature the request does not
    use.  When an older daemon still rejects (its version-mismatch
    answer names what it accepts), the request retries ONCE at the best
    mutually-spoken version with the too-new fields stripped
    (protocol.strip_for_version; the daemon supplies the fallback:
    default tenant, minted trace) -- rolling upgrades work in both
    directions."""
    path = socket_path or protocol.default_addr()
    if retry_total_s is None:
        retry_total_s = CONNECT_RETRY_TOTAL_S
    version = protocol.version_for(msg)
    try:
        return _request_once(msg, version, path, timeout, retry_total_s)
    except ServeError as e:
        if e.code != protocol.E_BAD_REQUEST:
            raise
        accepted = protocol.accepted_from_error(e.message)
        best = max((a for a in accepted
                    if a in protocol.ACCEPTED_VERSIONS and a < version),
                   default=None)
        if best is None:
            raise
        return _request_once(protocol.strip_for_version(msg, best), best,
                             path, timeout, retry_total_s)


def _request_once(msg: dict, version: int, path: str,
                  timeout: float | None, retry_total_s: float) -> dict:
    with _connect(path, timeout, retry_total_s) as sock:
        sock.sendall(protocol.encode({"v": version, **msg}))
        for line in protocol.read_lines(sock):
            resp = json.loads(line)
            if not resp.get("ok"):
                err = resp.get("error") or {}
                raise ServeError(err.get("code", "error"),
                                 err.get("message", "unknown error"))
            return resp
    raise ConnectionError(f"daemon at {path} closed the connection "
                          "without responding")


def submit(folder: str, socket_path: str | None = None,
           options: dict | None = None, timeout: float | None = None,
           tenant: str | None = None, trace: str | None = None) -> dict:
    """Enqueue a chain job.  The client MINTS the end-to-end trace
    context here (or threads through the caller's `trace`) and emits a
    `client_submit` span under it into the local flight recorder -- the
    client-side end of the stitched trace `cli trace-dump --merge`
    assembles (dump this process's ring with obs.trace.dump_json).  The
    version stamp and any downgrade against an older daemon are the
    capability table's business (see request())."""
    # paths resolve CLIENT-side: the daemon's cwd is not the submitter's,
    # so a relative folder/output/checkpoint_dir sent verbatim would be
    # checked (and written!) against the wrong tree -- and journal replay
    # after a restart from yet another cwd would break the same way
    options = dict(options or {})
    for key in ("output", "checkpoint_dir"):
        if options.get(key):
            options[key] = os.path.abspath(options[key])
    trace = trace or protocol.mint_trace()
    msg = {"op": "submit", "folder": os.path.abspath(folder),
           "options": options, "trace": trace}
    if tenant is not None:
        msg["tenant"] = tenant
    t0 = time.perf_counter()
    with obs_trace.RECORDER.tagged(trace_id=trace):
        try:
            return request(msg, socket_path, timeout=timeout)
        finally:
            obs_trace.RECORDER.point("client_submit",
                                     time.perf_counter() - t0)


def status(job_id: str, socket_path: str | None = None) -> dict:
    return request({"op": "status", "id": job_id}, socket_path)


def wait(job_id: str, socket_path: str | None = None,
         timeout: float | None = None) -> dict:
    """Block until the job is terminal or timeout elapses (None = until
    terminal), polling in WAIT_SLICE_S server-side waits with exponential
    client-side backoff between them (WAIT_BACKOFF_S doubling to
    WAIT_BACKOFF_MAX_S): a fleet of idle waiters on long jobs costs the
    accept loop one reconnect per waiter per ~cap seconds, not a
    reconnect storm per slice."""
    deadline = None if timeout is None else time.time() + timeout
    backoff = 0.0
    while True:
        slice_s = WAIT_SLICE_S if deadline is None else \
            min(WAIT_SLICE_S, max(0.0, deadline - time.time()))
        # the socket read must outlive the daemon-side wait, not race it
        resp = request({"op": "wait", "id": job_id, "timeout": slice_s},
                       socket_path, timeout=slice_s + 5.0)
        if resp["job"]["state"] in ("done", "failed"):
            return resp
        if deadline is not None and time.time() >= deadline:
            return resp  # caller sees the non-terminal snapshot
        # still running after a whole server-side slice: back off before
        # reconnecting (never past the caller's deadline)
        backoff = min(WAIT_BACKOFF_MAX_S,
                      backoff * 2 if backoff else WAIT_BACKOFF_S)
        sleep_s = backoff if deadline is None else \
            min(backoff, max(0.0, deadline - time.time()))
        if sleep_s > 0:
            time.sleep(sleep_s)


def stats(socket_path: str | None = None) -> dict:
    return request({"op": "stats"}, socket_path)


def metrics(socket_path: str | None = None) -> str:
    """The daemon's Prometheus text-format 0.0.4 scrape body."""
    return request({"op": "metrics"}, socket_path)["text"]


def trace(socket_path: str | None = None) -> list[dict]:
    """The daemon's span flight recorder as trace_event JSON events."""
    return request({"op": "trace"}, socket_path)["trace_events"]


def profile(socket_path: str | None = None) -> dict:
    """The daemon's deep-profiling report (obs/profile.py): compile/
    cost/memory accounting + prediction accountability."""
    return request({"op": "profile"}, socket_path)["profile"]


def events(n: int = 50, socket_path: str | None = None) -> list[dict]:
    """The newest n structured event-log records (obs/events.py)."""
    return request({"op": "events", "n": n}, socket_path)["events"]


def events_info(n: int = 50, socket_path: str | None = None) -> dict:
    """The `events` op's full answer: {events: [...], log: <sink
    stats>} -- the log block carries the on-disk JSONL path the
    --follow mode tails."""
    return request({"op": "events", "n": n}, socket_path)


def slo(socket_path: str | None = None) -> dict:
    """The daemon's SLO engine report (obs/slo.py): per-tenant rolling
    latency quantiles / error ratio / queue-wait share, per-(tenant,
    slice) burn state, declared objectives."""
    return request({"op": "slo"}, socket_path)["slo"]


def shutdown(socket_path: str | None = None) -> dict:
    return request({"op": "shutdown"}, socket_path)


# ------------------------------------------------------------- CLI glue --
def _add_addr_arg(p: argparse.ArgumentParser) -> None:
    """The ONE uniform network-address flag every daemon-facing
    subcommand carries: `tcp:HOST:PORT` dials a TCP front-end (daemon or
    fleet router), a path dials a unix socket.  Wins over --socket;
    both unset falls back to SPGEMM_TPU_SERVE_ADDR, then the default
    unix socket -- so an exported fleet address redirects every client
    on the host without per-command flags."""
    p.add_argument("--addr", default=None, metavar="ADDR",
                   help="daemon address: tcp:HOST:PORT or a unix socket "
                        "path (wins over --socket; default: "
                        "SPGEMM_TPU_SERVE_ADDR, then the unix socket)")


def _resolve_addr(args) -> str | None:
    return args.addr or args.socket


def main_submit(argv: list[str] | None = None) -> int:
    """`spgemm_tpu submit <folder>`: enqueue a chain job on the daemon."""
    p = argparse.ArgumentParser(
        prog="spgemm_tpu submit",
        description="submit a chain job to the running spgemmd daemon")
    p.add_argument("folder",
                   help="input directory containing `size` and matrix1..N")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="daemon socket (default: SPGEMM_TPU_SERVE_SOCKET "
                        "or <tmpdir>/spgemmd-<uid>.sock)")
    _add_addr_arg(p)
    p.add_argument("--output", default=None,
                   help="result path (default: <folder>/matrix)")
    p.add_argument("--backend", choices=list(protocol.CHAIN_BACKENDS),
                   default=None)
    p.add_argument("--round-size", type=int, default=None)
    p.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                   help="per-pass chain snapshots; a daemon restart resumes "
                        "this job from the newest complete pass")
    p.add_argument("--timeout", type=float, default=None, metavar="S",
                   help="per-job deadline override (default: "
                        "SPGEMM_TPU_SERVE_JOB_TIMEOUT)")
    p.add_argument("--tenant", default=None, metavar="NAME",
                   help="fair-queuing tenant identity (optional; the "
                        "daemon round-robins across tenants and may cap "
                        "per-tenant in-flight jobs, "
                        "SPGEMM_TPU_SERVE_TENANT_INFLIGHT)")
    p.add_argument("--trace", default=None, metavar="HEX32",
                   help="thread an existing 128-bit trace context "
                        "(32 lowercase hex chars) through the job "
                        "(default: the client mints one; either way it "
                        "is echoed in the response and stamps every "
                        "span/event of the job)")
    p.add_argument("--failover", action="store_true",
                   help="run the job with chain failover enabled")
    p.add_argument("--wait", action="store_true",
                   help="block until the job is terminal and print its "
                        "final status")
    args = p.parse_args(argv)
    options = {k: v for k, v in (
        ("output", args.output), ("backend", args.backend),
        ("round_size", args.round_size),
        ("checkpoint_dir", args.checkpoint_dir),
        ("timeout_s", args.timeout),
        ("failover", args.failover or None)) if v is not None}
    addr = _resolve_addr(args)
    try:
        resp = submit(args.folder, addr, options,
                      tenant=args.tenant, trace=args.trace)
        if args.wait:
            resp = wait(resp["id"], addr)
    except (ServeError, OSError) as e:
        print(f"submit failed: {e}", file=sys.stderr)
        return 1
    print(json.dumps(resp, indent=2))
    if args.wait and resp.get("job", {}).get("state") != "done":
        return 1
    return 0


def main_metrics(argv: list[str] | None = None) -> int:
    """`spgemm_tpu metrics`: scrape the running daemon's Prometheus
    surface (text-format 0.0.4 on stdout -- pipe it straight into a
    node-exporter textfile collector or curl-style probe)."""
    p = argparse.ArgumentParser(
        prog="spgemm_tpu metrics",
        description="scrape the running spgemmd daemon's metrics "
                    "(Prometheus text-format 0.0.4: engine phase seconds, "
                    "plan-cache hits/misses, queue depth, degrade state, "
                    "terminal job totals)")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="daemon socket (default: SPGEMM_TPU_SERVE_SOCKET "
                        "or <tmpdir>/spgemmd-<uid>.sock)")
    _add_addr_arg(p)
    args = p.parse_args(argv)
    try:
        sys.stdout.write(metrics(_resolve_addr(args)))
    except (ServeError, OSError) as e:
        print(f"metrics failed: {e}", file=sys.stderr)
        return 1
    return 0


def main_profile(argv: list[str] | None = None) -> int:
    """`spgemm_tpu profile [--json]`: the running daemon's deep-profiling
    report -- compile/cost/memory accounting (compile wall, XLA FLOPs/
    bytes, temp footprints per jit site), HBM watermarks, and estimator/
    delta prediction accountability."""
    p = argparse.ArgumentParser(
        prog="spgemm_tpu profile",
        description="report the running spgemmd daemon's deep-profiling "
                    "accounts: jit compile wall + cost_analysis FLOPs/"
                    "bytes + memory_analysis footprints per engine site, "
                    "device memory watermarks, estimator and delta "
                    "prediction accuracy")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="daemon socket (default: SPGEMM_TPU_SERVE_SOCKET "
                        "or <tmpdir>/spgemmd-<uid>.sock)")
    _add_addr_arg(p)
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="full machine-readable report (per-record compile "
                        "list + every aggregate account)")
    args = p.parse_args(argv)
    try:
        rep = profile(_resolve_addr(args))
    except (ServeError, OSError) as e:
        print(f"profile failed: {e}", file=sys.stderr)
        return 1
    if args.as_json:
        print(json.dumps(rep, indent=2))
        return 0
    for site, agg in rep.get("compile_sites", {}).items():
        print(f"compile {site}: x{agg['count']} "
              f"wall={agg['seconds']['sum']:.3f}s "
              f"flops={agg['flops_total']:.3g} "
              f"bytes={agg['bytes_total']:.3g} "
              f"temp_max={agg['temp_bytes_max']}")
    if not rep.get("compile_sites"):
        print("no compile records yet")
    mem = rep.get("memory", {})
    if mem.get("available"):
        print(f"hbm: in_use={mem['bytes_in_use']} "
              f"peak={mem['peak_bytes']} samples={mem['samples']}")
    else:
        print("hbm: backend reports no memory_stats (gauges omitted)")
    est = rep.get("estimator", {})
    if est.get("count"):
        errs = {q: f"{h['sum'] / h['count']:.4f}"
                for q, h in est["rel_error"].items() if h["count"]}
        print(f"estimator: x{est['count']} mean_rel_error={errs}")
    dlt = rep.get("delta", {})
    if dlt.get("count"):
        frac = dlt["dirty_fraction"]
        mean = frac["sum"] / frac["count"] if frac["count"] else 0.0
        print(f"delta: x{dlt['count']} predicted={dlt['predicted_rows']} "
              f"executed={dlt['executed_rows']} "
              f"mispredictions={dlt['mispredictions']} "
              f"mean_dirty_fraction={mean:.4f}")
    ev = rep.get("events", {})
    print(f"events: emitted={ev.get('emitted', 0)} "
          f"bytes={ev.get('bytes', 0)} path={ev.get('path')}")
    return 0


def main_events(argv: list[str] | None = None) -> int:
    """`spgemm_tpu events [--tail N] [--follow]`: the running daemon's
    newest structured event-log records, one JSON object per line;
    --follow then streams new records as they land (tailing the
    rotating on-disk JSONL next to the journal, surviving a rotation
    boundary without dropping or duplicating lines; Ctrl-C exits 0)."""
    p = argparse.ArgumentParser(
        prog="spgemm_tpu events",
        description="print the running spgemmd daemon's newest "
                    "structured event-log records (job lifecycle, "
                    "watchdog reap/degrade, est/delta fallbacks, compile "
                    "records, slo_burn transitions) as JSONL")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="daemon socket (default: SPGEMM_TPU_SERVE_SOCKET "
                        "or <tmpdir>/spgemmd-<uid>.sock)")
    _add_addr_arg(p)
    p.add_argument("--tail", type=int, default=50, metavar="N",
                   help="newest N records (default 50; bounded by the "
                        "daemon's in-process event ring -- the on-disk "
                        "<socket>.events.jsonl holds the longer history)")
    p.add_argument("--follow", "-f", action="store_true",
                   help="after the tail, keep streaming records as the "
                        "daemon appends them (polls the rotating JSONL "
                        "sink; records are deduplicated by their seq, "
                        "so a rotation boundary neither drops nor "
                        "repeats a line; Ctrl-C exits 0)")
    args = p.parse_args(argv)
    try:
        resp = events_info(args.tail, _resolve_addr(args))
    except (ServeError, OSError) as e:
        print(f"events failed: {e}", file=sys.stderr)
        return 1
    last_seq, last_ts = 0, 0.0
    for rec in resp["events"]:
        last_seq = max(last_seq, rec.get("seq", 0))
        last_ts = max(last_ts, rec.get("ts", 0.0))
        print(json.dumps(rec, separators=(",", ":")))
    if not args.follow:
        return 0
    path = (resp.get("log") or {}).get("path")
    if not path:
        print("events --follow: the daemon has no on-disk event sink "
              "to tail (SPGEMM_TPU_OBS_EVENTS=0?)", file=sys.stderr)
        return 1
    try:
        for rec in obs_events.follow_file(path, last_seq=last_seq,
                                          last_ts=last_ts):
            print(json.dumps(rec, separators=(",", ":")), flush=True)
    except KeyboardInterrupt:
        return 0
    return 0


def main_slo(argv: list[str] | None = None) -> int:
    """`spgemm_tpu slo [--json]`: the running daemon's SLO report --
    declared objectives, per-tenant rolling latency quantiles / error
    ratio / queue-wait share, and per-(tenant, slice) burn-rate state
    (a burning window names the trace context that resolves via
    `trace-dump --merge` to the newest bad job's stitched trace)."""
    p = argparse.ArgumentParser(
        prog="spgemm_tpu slo",
        description="report the running spgemmd daemon's SLO engine: "
                    "objectives, per-tenant rolling-window latency "
                    "quantiles (p50/p95/p99), error ratio, queue-wait "
                    "share, and multi-window burn-rate state")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="daemon socket (default: SPGEMM_TPU_SERVE_SOCKET "
                        "or <tmpdir>/spgemmd-<uid>.sock)")
    _add_addr_arg(p)
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="full machine-readable report")
    args = p.parse_args(argv)
    try:
        rep = slo(_resolve_addr(args))
    except (ServeError, OSError) as e:
        print(f"slo failed: {e}", file=sys.stderr)
        return 1
    if args.as_json:
        print(json.dumps(rep, indent=2))
        return 0
    obj = rep.get("objectives", {})
    if obj.get("enabled"):
        print(f"objectives: target_s={obj['target_s']:g} "
              f"error_pct={obj['error_pct']:g} "
              f"window_s={obj['window_s']:g}")
    else:
        print("objectives: none declared (accounting-only; set "
              "SPGEMM_TPU_SLO_TARGET_S to arm burn-rate evaluation)")
    for tenant, row in rep.get("tenants", {}).items():
        lat = row["latency_s"]
        print(f"tenant {tenant}: jobs={row['jobs']} "
              f"p50={lat['p50']:g}s p95={lat['p95']:g}s "
              f"p99={lat['p99']:g}s "
              f"error_ratio={row['error_ratio']:g} "
              f"queue_share={row['queue_wait_share']:g}")
    for b in rep.get("burn", []):
        if not b.get("active") and not b.get("bad"):
            continue
        state = "BURNING" if b.get("active") else "ok"
        print(f"burn {b['tenant']}/{b['slice']}: {state} "
              f"fast={b.get('fast_burn', 0):g} "
              f"slow={b.get('slow_burn', 0):g} "
              f"bad={b.get('bad', 0)}/{b.get('jobs', 0)} "
              f"trace={b.get('trace_id')}")
    print(f"tenants_evicted={rep.get('tenants_evicted', 0)} "
          f"records={rep.get('records', 0)}")
    return 0


def main_trace_dump(argv: list[str] | None = None) -> int:
    """`spgemm_tpu trace-dump [--merge DIR] [--trace ID]`: serialize the
    daemon's span flight recorder as Perfetto/Chrome trace_event JSON
    (open the file at https://ui.perfetto.dev or chrome://tracing), OR
    stitch a directory of per-process/per-rank dumps into ONE Perfetto
    file with distinct labeled process tracks and a shared wall-clock
    timeline; --trace filters either mode down to one trace context's
    events (the flame view an slo_burn event's trace_id resolves to)."""
    p = argparse.ArgumentParser(
        prog="spgemm_tpu trace-dump",
        description="dump the running spgemmd daemon's span flight "
                    "recorder as Perfetto/Chrome trace_event JSON, or "
                    "(--merge) stitch per-process dumps into one trace")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="daemon socket (default: SPGEMM_TPU_SERVE_SOCKET "
                        "or <tmpdir>/spgemmd-<uid>.sock)")
    _add_addr_arg(p)
    p.add_argument("--merge", default=None, metavar="DIR",
                   help="instead of scraping a daemon, stitch every "
                        "*.json trace dump under DIR (client ring dumps, "
                        "daemon trace-dumps, <socket>.flight/ postmortems, "
                        "per-rank dumps) into one Perfetto file: distinct "
                        "process tracks per dump, timelines aligned on "
                        "each dump's wall-clock anchor")
    p.add_argument("--trace", default=None, metavar="ID",
                   help="keep only events carrying this 128-bit trace "
                        "context (trace_id tag), plus the metadata "
                        "tracks still backing them")
    p.add_argument("--output", "-o", default=None, metavar="FILE",
                   help="write the trace_event array here "
                        "(default: stdout)")
    args = p.parse_args(argv)
    if args.merge:
        import glob  # noqa: PLC0415

        paths = sorted(glob.glob(os.path.join(args.merge, "*.json")))
        if not paths:
            print(f"trace-dump --merge: no *.json dumps under "
                  f"{args.merge}", file=sys.stderr)
            return 1
        try:
            events = obs_trace.merge_trace_files(paths,
                                                 trace_id=args.trace)
        except (OSError, ValueError) as e:
            print(f"trace-dump --merge failed: {e}", file=sys.stderr)
            return 1
    else:
        try:
            events = trace(_resolve_addr(args))
        except (ServeError, OSError) as e:
            print(f"trace-dump failed: {e}", file=sys.stderr)
            return 1
        if args.trace:
            events = obs_trace.filter_trace(events, args.trace)
    if args.output:
        with open(args.output, "w", encoding="utf-8") as f:
            json.dump(events, f, separators=(",", ":"))
        print(f"wrote {len(events)} trace events to {args.output}",
              file=sys.stderr)
    else:
        json.dump(events, sys.stdout, separators=(",", ":"))
        sys.stdout.write("\n")
    return 0


def main_status(argv: list[str] | None = None) -> int:
    """`spgemm_tpu status [job_id]`: job status, daemon stats, shutdown."""
    p = argparse.ArgumentParser(
        prog="spgemm_tpu status",
        description="query the running spgemmd daemon: one job's status "
                    "(with its per-job phases_s/plan-cache detail), or "
                    "daemon-wide stats with no job id")
    p.add_argument("job_id", nargs="?", default=None)
    p.add_argument("--socket", default=None, metavar="PATH")
    _add_addr_arg(p)
    p.add_argument("--wait", action="store_true",
                   help="block until the job is terminal")
    p.add_argument("--shutdown", action="store_true",
                   help="ask the daemon to shut down cleanly")
    args = p.parse_args(argv)
    addr = _resolve_addr(args)
    try:
        if args.shutdown:
            resp = shutdown(addr)
        elif args.job_id is None:
            resp = stats(addr)
        elif args.wait:
            resp = wait(args.job_id, addr)
        else:
            resp = status(args.job_id, addr)
    except (ServeError, OSError) as e:
        print(f"status failed: {e}", file=sys.stderr)
        return 1
    print(json.dumps(resp, indent=2))
    return 0
