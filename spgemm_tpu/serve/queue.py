"""spgemmd job queue: bounded FIFO with admission control.

Admission control is the daemon's back-pressure contract: a submit that
arrives with SPGEMM_TPU_SERVE_QUEUE_CAP jobs already queued is rejected
with a structured queue-full error instead of hanging the caller (the
reference's analog is MPI ranks deadlocking when a peer falls behind --
here overload is an answer, not a wedge).  Per-job deadlines are stored at
submit time so the watchdog can reap a job that exceeds them with a
structured job-timeout error.

jax-free by design (imported by the client-side CLI path).
"""

from __future__ import annotations

import threading
import time
from collections import deque

TERMINAL = ("done", "failed")


class QueueFull(Exception):
    """Admission-control rejection; carries the live cap for the error."""

    def __init__(self, cap: int):
        super().__init__(f"queue full: {cap} jobs already queued")
        self.cap = cap


class JobAbandoned(BaseException):
    """Raised from a job's heartbeat to abort an abandoned chain at the
    next multiply boundary (the job reached a terminal state under the
    executor's feet: watchdog reap, or a resubmit after presumed death).

    BaseException on purpose: chain_product's failover wrapper catches
    Exception -- device loss is its use case -- and must NOT mistake the
    abort for a device failure to retry on the host oracle.  The signal
    pierces it to the executor loop, which catches it by name."""

    def __init__(self, job_id: str):
        super().__init__(f"job {job_id} reached a terminal state; "
                         "abandoning its chain")
        self.job_id = job_id


class Job:
    """One submitted chain job and its full lifecycle record.

    States: queued -> running -> done | failed.  Terminal transitions are
    first-write-wins: the watchdog may reap a job (failed/job-timeout)
    while the executor is still inside the runner, and the runner's own
    completion must then NOT resurrect it.
    """

    def __init__(self, job_id: str, folder: str, output: str,
                 options: dict, timeout_s: float = 0.0):
        self.id = job_id
        self.folder = folder
        self.output = output
        self.options = options
        self.timeout_s = timeout_s  # 0 = no deadline
        self.state = "queued"                   # spgemm-lint: guarded-by(_lock)
        self.error: dict | None = None          # spgemm-lint: guarded-by(_lock)
        self.detail: dict = {}                  # spgemm-lint: guarded-by(_lock)
        self.submitted_at = time.time()
        self.started_at: float | None = None    # spgemm-lint: guarded-by(_lock)
        self.finished_at: float | None = None   # spgemm-lint: guarded-by(_lock)
        # heartbeat_at is DELIBERATELY lock-free: single writer (the
        # executor's per-multiply touch), float-ref store is atomic under
        # the GIL, and the watchdog's read tolerates staleness by design
        self.heartbeat_at: float | None = None
        # set by the daemon's executor when it picks the job up: the live
        # PhaseScope (opaque here -- the queue stays jax-free) and the
        # path the job ran on, read by the watchdog so a reaped job's
        # status still carries its per-job phases/counters detail
        self.scope = None
        self.scope_degraded = False
        # plan-cache counter baseline captured at pickup (ops/plancache.
        # baseline): per-job detail diffs against it, so a second job's
        # hit/miss figures never inherit the first's process totals
        self.cache_base = None
        self._lock = threading.Lock()
        self._terminal = threading.Event()

    def touch(self) -> None:
        """Progress heartbeat (chain_product calls this after every
        completed multiply): the watchdog's slow-vs-wedged signal."""
        self.heartbeat_at = time.time()

    def start(self) -> None:
        with self._lock:
            if self.state == "queued":
                self.state = "running"
                self.started_at = time.time()
                self.heartbeat_at = self.started_at

    def finish(self, state: str, error: dict | None = None,
               detail: dict | None = None, on_commit=None) -> bool:
        """Terminal transition; returns False (and changes nothing) if the
        job is already terminal -- first writer wins.

        on_commit (the daemon's journal append) runs INSIDE the winning
        transition, before the terminal state wakes wait()ers or becomes
        snapshot-visible: a client that saw the job finish must never race
        a daemon restart past the journal record (a restarted daemon must
        not re-run completed work)."""
        assert state in TERMINAL
        with self._lock:
            if self.state in TERMINAL:
                return False
            self.state = state
            self.error = error
            if detail:
                self.detail = detail
            self.finished_at = time.time()
            try:
                if on_commit is not None:
                    on_commit()
            finally:
                self._terminal.set()
        return True

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the job is terminal; False on timeout."""
        return self._terminal.wait(timeout)

    def overdue(self, now: float | None = None) -> bool:
        """True iff running with a deadline and past it."""
        with self._lock:
            if self.timeout_s <= 0 or self.state != "running":
                return False
            started = self.started_at or self.submitted_at
        return (now or time.time()) - started > self.timeout_s

    def snapshot(self) -> dict:
        """Wire form for status/wait responses."""
        with self._lock:
            return {
                "id": self.id,
                "folder": self.folder,
                "output": self.output,
                "options": dict(self.options),
                "state": self.state,
                "error": self.error,
                "detail": dict(self.detail),
                "timeout_s": self.timeout_s,
                "submitted_at": self.submitted_at,
                "started_at": self.started_at,
                "finished_at": self.finished_at,
                "heartbeat_at": self.heartbeat_at,
            }


class JobQueue:
    """Bounded FIFO over Job objects + the daemon's job index.

    The cap bounds jobs in the *queued* state (a running job no longer
    occupies a queue slot).  Completed jobs stay in the index so
    status/wait work after the fact, but only the RETAIN_TERMINAL most
    recent -- a resident daemon must not grow per-job state (options,
    detail, the stashed PhaseScope) for its lifetime; a status for an
    evicted id answers unknown-job.
    """

    # terminal jobs retained; past this the oldest are evicted at the
    # next admission (class attribute so tests can shrink it)
    RETAIN_TERMINAL = 512

    def __init__(self, cap: int):
        self.cap = cap
        self._fifo: deque[Job] = deque()   # spgemm-lint: guarded-by(_lock)
        self._jobs: dict[str, Job] = {}    # spgemm-lint: guarded-by(_lock)
        self._lock = threading.Lock()
        self._avail = threading.Condition(self._lock)

    def submit(self, job: Job) -> int:
        """Admit job (FIFO order); QueueFull once cap jobs are queued.
        Returns the queue depth including the new job."""
        with self._avail:
            queued = len(self._fifo)
            if queued >= self.cap:
                raise QueueFull(self.cap)
            # evict the oldest terminal jobs beyond the retention bound
            # (dict order = admission order, oldest first)
            terminal = [j.id for j in self._jobs.values()
                        if j.state in TERMINAL]
            for jid in terminal[:max(0, len(terminal)
                                     - self.RETAIN_TERMINAL)]:
                del self._jobs[jid]
            self._fifo.append(job)
            self._jobs[job.id] = job
            self._avail.notify()
            return queued + 1

    def next(self, timeout: float | None = None) -> Job | None:
        """Pop the oldest queued job; None on timeout (executor idle
        tick)."""
        with self._avail:
            if not self._fifo:
                self._avail.wait(timeout)
            if not self._fifo:
                return None
            return self._fifo.popleft()

    def get(self, job_id: str) -> Job | None:
        with self._lock:
            return self._jobs.get(job_id)

    def running(self) -> list[Job]:
        """Jobs currently in the running state (the watchdog's sweep set
        when an executor dies: a dying thread's finally may already have
        released its current-job slot)."""
        with self._lock:
            return [j for j in self._jobs.values() if j.state == "running"]

    def counts(self) -> dict[str, int]:
        """State histogram over every job ever admitted + live depth."""
        with self._lock:
            jobs = list(self._jobs.values())
            depth = len(self._fifo)
        hist = {"queued": 0, "running": 0, "done": 0, "failed": 0}
        for j in jobs:
            hist[j.state] = hist.get(j.state, 0) + 1
        hist["depth"] = depth
        return hist
