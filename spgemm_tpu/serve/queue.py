"""spgemmd job queue: bounded multi-tenant fair queue with admission
control.

Admission control is the daemon's back-pressure contract: a submit that
arrives with SPGEMM_TPU_SERVE_QUEUE_CAP jobs already queued is rejected
with a structured queue-full error instead of hanging the caller (the
reference's analog is MPI ranks deadlocking when a peer falls behind --
here overload is an answer, not a wedge).  Per-job deadlines are stored at
submit time so the watchdog can reap a job that exceeds them with a
structured job-timeout error.

Fair queuing (the device-pool scheduler's admission half): every job
carries a tenant (the optional v2 submit field; absent maps to
protocol.DEFAULT_TENANT, exactly the v1 behavior), jobs queue per tenant,
and dispatch serves tenants deficit-round-robin -- with unit job costs the
deficit counters collapse to strict rotation, so a chatty tenant's burst
never starves a quiet tenant's single job past one round.  An optional
per-tenant in-flight cap (SPGEMM_TPU_SERVE_TENANT_INFLIGHT: queued +
running jobs per tenant) rejects the chatty tenant's overflow with a
structured tenant-cap error, never a hang; the global queue cap always
applies on top.

Dispatch is placement-aware: `next(accept=...)` lets the pool's per-slice
executors decline a tenant's head job (wrong slice class for this
executor) without popping it -- the accept predicate runs under the queue
lock, so the executor that got True is the one that owns the job.

jax-free by design (imported by the client-side CLI path).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from spgemm_tpu.serve import protocol
from spgemm_tpu.utils import knobs

TERMINAL = ("done", "failed")


class QueueFull(Exception):
    """Admission-control rejection; carries the live cap for the error."""

    def __init__(self, cap: int):
        super().__init__(f"queue full: {cap} jobs already queued")
        self.cap = cap


class TenantCapExceeded(Exception):
    """Per-tenant in-flight cap rejection (structured, never a hang);
    carries the tenant and the live cap for the error message."""

    def __init__(self, tenant: str, cap: int):
        super().__init__(f"tenant {tenant!r} already has {cap} jobs in "
                         "flight")
        self.tenant = tenant
        self.cap = cap


class JobAbandoned(BaseException):
    """Raised from a job's heartbeat to abort an abandoned chain at the
    next multiply boundary (the job reached a terminal state under the
    executor's feet: watchdog reap, or a resubmit after presumed death).

    BaseException on purpose: chain_product's failover wrapper catches
    Exception -- device loss is its use case -- and must NOT mistake the
    abort for a device failure to retry on the host oracle.  The signal
    pierces it to the executor loop, which catches it by name."""

    def __init__(self, job_id: str):
        super().__init__(f"job {job_id} reached a terminal state; "
                         "abandoning its chain")
        self.job_id = job_id


class Job:
    """One submitted chain job and its full lifecycle record.

    States: queued -> running -> done | failed.  Terminal transitions are
    first-write-wins: the watchdog may reap a job (failed/job-timeout)
    while the executor is still inside the runner, and the runner's own
    completion must then NOT resurrect it.
    """

    def __init__(self, job_id: str, folder: str, output: str,
                 options: dict, timeout_s: float = 0.0,
                 tenant: str = protocol.DEFAULT_TENANT,
                 trace_id: str | None = None):
        self.id = job_id
        self.folder = folder
        self.output = output
        self.options = options
        self.tenant = tenant
        # the end-to-end trace context (protocol v3): client-minted when
        # the submit carried one, else minted here -- every span/event/
        # journal record of this job carries it, and the merge tool
        # (cli trace-dump --merge) stitches per-process dumps on it
        self.trace_id = trace_id or protocol.mint_trace()
        self.timeout_s = timeout_s  # 0 = no deadline
        self.state = "queued"                   # spgemm-lint: guarded-by(_lock)
        self.error: dict | None = None          # spgemm-lint: guarded-by(_lock)
        self.detail: dict = {}                  # spgemm-lint: guarded-by(_lock)
        self.submitted_at = time.time()
        self.started_at: float | None = None    # spgemm-lint: guarded-by(_lock)
        self.finished_at: float | None = None   # spgemm-lint: guarded-by(_lock)
        # heartbeat_at is DELIBERATELY lock-free: single writer (the
        # executor's per-multiply touch), float-ref store is atomic under
        # the GIL, and the watchdog's read tolerates staleness by design
        self.heartbeat_at: float | None = None
        # placement record (serve/placement.route, set at admission before
        # the job is queue-visible) and the pickup-time assignment (slice
        # name / device positions / whether an off-class slice stole it --
        # written once by the winning executor under the QUEUE lock or
        # right after the pop, read by snapshots that tolerate staleness
        # like heartbeat_at does)
        self.placement: dict | None = None
        self.slice: str | None = None
        self.device_ids: tuple | None = None
        self.stolen = False
        # cross-job batching group key (ops/plancache.chain_structure,
        # set at admission alongside placement): jobs sharing it walk
        # identical plan sequences and may co-batch into one fused
        # dispatch.  None (first contact / unreadable folder) never
        # groups -- the job runs solo, exactly the pre-batch path.
        self.group_key: str | None = None
        # set by the winning executor when this job rode a fused batch:
        # the shared batch id (= the head job's id), for spans/status
        self.batch_id: str | None = None
        # set by the daemon's executor when it picks the job up: the live
        # PhaseScope (opaque here -- the queue stays jax-free) and the
        # path the job ran on, read by the watchdog so a reaped job's
        # status still carries its per-job phases/counters detail
        self.scope = None
        self.scope_degraded = False
        # plan-cache counter baseline captured at pickup (ops/plancache.
        # baseline): per-job detail diffs against it, so a second job's
        # hit/miss figures never inherit the first's process totals
        self.cache_base = None
        # autotune pickup state (serve/daemon + spgemm_tpu/tune): the
        # job's resolved structure-class key (None = first contact,
        # never tuned) and the estimator-accuracy baseline captured at
        # pickup (obs/profile.est_stats) -- the terminal path diffs the
        # live account against it to score this job's estimator for the
        # class's sample/confidence adaptation
        self.tune_class: str | None = None
        self.est_base = None
        self._lock = threading.Lock()
        self._terminal = threading.Event()

    def touch(self) -> None:
        """Progress heartbeat (chain_product calls this after every
        completed multiply): the watchdog's slow-vs-wedged signal."""
        self.heartbeat_at = time.time()

    def start(self) -> None:
        with self._lock:
            if self.state == "queued":
                self.state = "running"
                self.started_at = time.time()
                self.heartbeat_at = self.started_at

    def finish(self, state: str, error: dict | None = None,
               detail: dict | None = None, on_commit=None) -> bool:
        """Terminal transition; returns False (and changes nothing) if the
        job is already terminal -- first writer wins.

        on_commit (the daemon's journal append) runs INSIDE the winning
        transition, before the terminal state wakes wait()ers or becomes
        snapshot-visible: a client that saw the job finish must never race
        a daemon restart past the journal record (a restarted daemon must
        not re-run completed work)."""
        assert state in TERMINAL
        with self._lock:
            if self.state in TERMINAL:
                return False
            self.state = state
            self.error = error
            if detail:
                self.detail = detail
            self.finished_at = time.time()
            try:
                if on_commit is not None:
                    on_commit()
            finally:
                self._terminal.set()
        return True

    def wait(self, timeout: float | None = None) -> bool:
        """Block until the job is terminal; False on timeout."""
        return self._terminal.wait(timeout)

    def overdue(self, now: float | None = None) -> bool:
        """True iff running with a deadline and past it."""
        with self._lock:
            if self.timeout_s <= 0 or self.state != "running":
                return False
            started = self.started_at or self.submitted_at
        return (now or time.time()) - started > self.timeout_s

    def snapshot(self) -> dict:
        """Wire form for status/wait responses."""
        with self._lock:
            return {
                "id": self.id,
                "folder": self.folder,
                "output": self.output,
                "options": dict(self.options),
                "tenant": self.tenant,
                "trace": self.trace_id,
                "state": self.state,
                "error": self.error,
                "detail": dict(self.detail),
                "timeout_s": self.timeout_s,
                "submitted_at": self.submitted_at,
                "started_at": self.started_at,
                "finished_at": self.finished_at,
                "heartbeat_at": self.heartbeat_at,
                "slice": self.slice,
                "stolen": self.stolen,
                "batch": self.batch_id,
                "placement": dict(self.placement) if self.placement
                else None,
            }


class JobQueue:
    """Bounded per-tenant fair queue over Job objects + the daemon's job
    index.

    The cap bounds jobs in the *queued* state across every tenant (a
    running job no longer occupies a queue slot); the optional per-tenant
    in-flight cap additionally bounds queued + running per tenant.
    Completed jobs stay in the index so status/wait work after the fact,
    but only the RETAIN_TERMINAL most recent -- a resident daemon must not
    grow per-job state (options, detail, the stashed PhaseScope) for its
    lifetime; a status for an evicted id answers unknown-job.

    Dispatch order: deficit round robin across tenants (unit job costs =
    strict tenant rotation), FIFO within a tenant.  With one tenant this
    degenerates to exactly the pre-pool FIFO.
    """

    # terminal jobs retained; past this the oldest are evicted at the
    # next admission (class attribute so tests can shrink it)
    RETAIN_TERMINAL = 512

    def __init__(self, cap: int, tenant_inflight: int | None = None):
        self.cap = cap
        # explicit constructor cap wins; None falls back to the knob,
        # re-read per submit (tests flip it mid-process like every knob)
        self._tenant_cap = tenant_inflight
        self._queues: dict[str, deque[Job]] = {}  # spgemm-lint: guarded-by(_lock)
        self._rr: list[str] = []           # spgemm-lint: guarded-by(_lock)
        self._queued = 0                   # spgemm-lint: guarded-by(_lock)
        self._inflight: dict[str, int] = {}  # spgemm-lint: guarded-by(_lock)
        self._served: dict[str, int] = {}  # spgemm-lint: guarded-by(_lock)
        # newest submit wall-clock per live tenant: the recency key the
        # daemon's scrape-label cap (top-K + `other`) sorts on; retired
        # with the tenant's other per-tenant state in release()
        self._last_seen: dict[str, float] = {}  # spgemm-lint: guarded-by(_lock)
        self._jobs: dict[str, Job] = {}    # spgemm-lint: guarded-by(_lock)
        self._lock = threading.Lock()
        self._avail = threading.Condition(self._lock)

    def tenant_cap(self) -> int | None:
        """The live per-tenant in-flight cap (None = uncapped)."""
        if self._tenant_cap is not None:
            return self._tenant_cap
        return knobs.get("SPGEMM_TPU_SERVE_TENANT_INFLIGHT")

    def submit(self, job: Job) -> int:
        """Admit job (FIFO within its tenant); QueueFull once cap jobs are
        queued, TenantCapExceeded once the tenant's in-flight cap is hit.
        Returns the queue depth including the new job."""
        cap_t = self.tenant_cap()
        with self._avail:
            if self._queued >= self.cap:
                raise QueueFull(self.cap)
            if cap_t is not None \
                    and self._inflight.get(job.tenant, 0) >= cap_t:
                raise TenantCapExceeded(job.tenant, cap_t)
            # evict the oldest terminal jobs beyond the retention bound
            # (dict order = admission order, oldest first)
            terminal = [j.id for j in self._jobs.values()
                        if j.state in TERMINAL]
            for jid in terminal[:max(0, len(terminal)
                                     - self.RETAIN_TERMINAL)]:
                del self._jobs[jid]
            if job.tenant not in self._queues:
                self._queues[job.tenant] = deque()
                if job.tenant not in self._rr:
                    self._rr.append(job.tenant)
            self._queues[job.tenant].append(job)
            self._queued += 1
            self._inflight[job.tenant] = \
                self._inflight.get(job.tenant, 0) + 1
            self._last_seen[job.tenant] = time.time()
            # release() frees an in-flight slot only for jobs that took
            # one: a job whose submit RAISED (queue-full / tenant-cap)
            # may still be finished + observed by the caller, and must
            # never decrement a slot an admitted job owns
            job._admitted = True
            self._jobs[job.id] = job
            # notify_all: with placement-aware accept predicates, the one
            # waiter notify() picks may decline the job while a compatible
            # executor keeps sleeping
            self._avail.notify_all()
            return self._queued

    def _pop_locked(self, accept) -> Job | None:
        """One DRR pass over the tenant rotation (caller holds _lock):
        serve the first tenant whose head job the accept predicate takes,
        then rotate the served tenant (and everyone it skipped past) to
        the back of the order."""
        order = self._rr
        for idx, tenant in enumerate(order):
            q = self._queues.get(tenant)
            if not q:
                continue
            job = q[0]
            if accept is not None and not accept(job):
                continue
            q.popleft()
            self._queued -= 1
            if not q:
                del self._queues[tenant]
            self._served[tenant] = self._served.get(tenant, 0) + 1
            self._rr = order[idx + 1:] + order[:idx + 1]
            return job
        return None

    def _pop_scan_locked(self, accept) -> Job | None:
        """Batch-mate DRR pass (caller holds _lock): like _pop_locked,
        but scans PAST non-matching jobs inside each tenant's queue --
        a mate deeper in the FIFO may join the batch while the skipped
        jobs keep their positions (the reorder is bounded: at most one
        batch's worth of mates overtakes, and the skipped head is the
        very next solo pop).  Solo dispatch (next()) stays strictly
        head-of-tenant FIFO; only batch formation scans."""
        order = self._rr
        for idx, tenant in enumerate(order):
            q = self._queues.get(tenant)
            if not q:
                continue
            for pos, job in enumerate(q):
                if not accept(job):
                    continue
                del q[pos]
                self._queued -= 1
                if not q:
                    del self._queues[tenant]
                self._served[tenant] = self._served.get(tenant, 0) + 1
                self._rr = order[idx + 1:] + order[:idx + 1]
                return job
        return None

    def next(self, timeout: float | None = None, accept=None) -> Job | None:
        """Pop the next job in fair order that `accept` takes (None
        predicate takes anything); None on timeout (executor idle tick).
        The predicate runs under the queue lock -- it must be cheap and
        lock-free -- and the caller that received the job is exactly the
        one whose predicate returned True for it."""
        with self._avail:
            job = self._pop_locked(accept)
            if job is None:
                self._avail.wait(timeout)
                job = self._pop_locked(accept)
            return job

    def drain_batch(self, limit: int, window_s: float, accept) -> list[Job]:
        """Pop up to `limit` additional jobs the `accept` predicate takes
        (the executor's batch-mate filter: same group key / deadline class
        as the already-popped head), waiting up to `window_s` for more to
        arrive.  Pops go through the same DRR pass as next() -- tenant
        fairness and FIFO-within-tenant are decided BEFORE batch
        formation, so a chatty tenant cannot monopolize a batch past its
        rotation turns -- and scan past non-matching jobs within a
        tenant (a different-structure job at the head must not block the
        mates queued behind it; it stays first for the next solo pop).
        Returns the drained mates (possibly empty); the window only
        bounds WAITING -- jobs already queued drain immediately, so an
        armed window never delays a full batch."""
        mates: list[Job] = []
        deadline = time.time() + window_s
        with self._avail:
            while len(mates) < limit:
                job = self._pop_scan_locked(accept)
                if job is not None:
                    mates.append(job)
                    continue
                remaining = deadline - time.time()
                if remaining <= 0:
                    break
                self._avail.wait(remaining)
        return mates

    def release(self, job: Job) -> None:
        """Retire a terminal job from the per-tenant in-flight accounting
        (the daemon calls this exactly once per committed terminal
        transition).  Idempotent, and a no-op for a job that was never
        admitted: a double (or unearned) release must never free a slot
        an admitted job owns."""
        with self._lock:
            if not getattr(job, "_admitted", False) \
                    or getattr(job, "_released", False):
                return
            job._released = True
            n = self._inflight.get(job.tenant, 0) - 1
            if n > 0:
                self._inflight[job.tenant] = n
            else:
                self._inflight.pop(job.tenant, None)
            # retire the tenant's rotation + served records once it has
            # nothing queued and nothing in flight: per-tenant state must
            # not grow with the number of tenant names ever seen
            if job.tenant not in self._queues \
                    and job.tenant not in self._inflight:
                if job.tenant in self._rr:
                    self._rr.remove(job.tenant)
                self._served.pop(job.tenant, None)
                self._last_seen.pop(job.tenant, None)

    def get(self, job_id: str) -> Job | None:
        with self._lock:
            return self._jobs.get(job_id)

    def running(self) -> list[Job]:
        """Jobs currently in the running state (the watchdog's sweep set
        when an executor dies: a dying thread's finally may already have
        released its current-job slot)."""
        with self._lock:
            return [j for j in self._jobs.values() if j.state == "running"]

    def counts(self) -> dict[str, int]:
        """State histogram over every job ever admitted + live depth."""
        with self._lock:
            jobs = list(self._jobs.values())
            depth = self._queued
        hist = {"queued": 0, "running": 0, "done": 0, "failed": 0}
        for j in jobs:
            hist[j.state] = hist.get(j.state, 0) + 1
        hist["depth"] = depth
        return hist

    def tenants(self) -> dict[str, dict]:
        """Per-tenant fair-queue state (stats + the Prometheus
        spgemmd_tenant_queue_depth series): queued depth, in-flight count
        and jobs served this residency, for every tenant with live
        state."""
        with self._lock:
            names = set(self._queues) | set(self._inflight) \
                | set(self._served)
            return {t: {"queued": len(self._queues.get(t, ())),
                        "inflight": self._inflight.get(t, 0),
                        "served": self._served.get(t, 0),
                        "last_seen": self._last_seen.get(t, 0.0)}
                    for t in sorted(names)}
