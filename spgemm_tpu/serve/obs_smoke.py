"""`make obs-smoke`: end-to-end observability proof on the CPU backend.

Starts a real spgemmd subprocess on a temp socket, scrapes its Prometheus
`metrics` surface before and after one real chain job, and asserts the
observability contract:

  * the scrape is parseable text-format 0.0.4 with HELP/TYPE headers;
  * the per-phase engine series (`spgemm_phase_seconds_total{phase=...}`)
    and the plan-cache series MOVE across the submit -- a daemon whose
    metrics never change is a daemon you cannot operate;
  * the deep-profiling families (obs/profile.py) appear and move:
    compile accounting (`spgemm_compiles_total{site="numeric_round"}`
    with nonzero cost-model FLOPs), the span-fed phase latency
    histogram (`spgemm_phase_seconds_count{phase="plan"}`), estimator
    prediction accountability (`spgemm_est_rel_error_count` -- the
    chain is sized past the estimator's row-sample budget so the
    daemon's plans take the estimated route and are scored on landing),
    delta prediction accountability (`spgemm_delta_dirty_fraction_count`),
    and the event-log counters;
  * `spgemm_tpu.cli profile --json` reports >= 1 compile record with
    nonzero FLOPs through the real CLI (the acceptance gate);
  * `spgemm_tpu.cli events --tail` returns the submit's lifecycle
    records (job_submit/job_start/job_done carrying the job id) and the
    JSONL file landed next to the journal;
  * terminal job accounting works (`spgemmd_jobs_terminal_total{
    outcome="done"}` counts the job);
  * the `trace` op returns Perfetto/Chrome trace_event JSON whose spans
    carry the job id, and `spgemm_tpu.cli trace-dump -o F` round-trips it
    through the real CLI to a valid JSON file;
  * the SLO engine judges (obs/slo.py): the per-tenant latency quantile
    and error-ratio families render and move after the submit;
  * shutdown is clean.

Then the SLO burn + trace-stitching leg: a SECOND daemon starts with an
armed `serve.executor:1:1` failpoint (the backend-wedge signature), a
tight wedge grace, and declared objectives -- its first submit wedges,
the watchdog reaps it, `spgemm_slo_burn_active{tenant=,slice=}` must
flip to 1, an `slo_burn` event must land whose trace_id is EXACTLY the
trace context the client minted at submit, and `cli trace-dump --merge`
over the client's own ring dump + the daemon's trace-dump must stitch
one Perfetto file in which that trace id resolves to spans from BOTH
processes (client_submit on the client pid, the wedged job's spans on
the daemon pid) -- client submit to slice execution, one flame view.

Any step failing exits nonzero.  This process itself stays jax-free (the
client and the generator are pure numpy) -- only the daemon touches a
backend, which is the deployment shape being smoked.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time


def _fail(proc: subprocess.Popen | None, msg: str) -> int:
    print(f"obs-smoke: FAIL: {msg}", file=sys.stderr)
    if proc is not None and proc.poll() is None:
        proc.kill()
    if proc is not None:
        out, _ = proc.communicate(timeout=10)
        sys.stderr.write(out[-4000:] if out else "")
    return 1


def parse_prometheus(text: str) -> dict[str, float]:
    """`{name{labels}: value}` for every sample line (HELP/TYPE skipped);
    a malformed value line raises -- the smoke's format check."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        series, value = line.rsplit(" ", 1)
        out[series] = float(value)
    return out


def main() -> int:
    import numpy as np  # noqa: PLC0415

    from spgemm_tpu.serve import client  # noqa: PLC0415
    from spgemm_tpu.utils import io_text  # noqa: PLC0415
    from spgemm_tpu.utils.gen import banded_block_sparse  # noqa: PLC0415

    tmp = tempfile.mkdtemp(prefix="spgemmd-obs-smoke-")
    sock = os.path.join(tmp, "d.sock")
    folder = os.path.join(tmp, "chain_in")
    # banded, 64 tile-rows: PAST the estimator's row-sample budget
    # (SPGEMM_TPU_EST_SAMPLE_ROWS default 48), so the daemon's
    # first-contact plans take the estimated route and the accuracy
    # series gets scored when the deferred exact joins land
    n, k = 4, 4
    rng = np.random.default_rng(7)
    mats = [banded_block_sparse(64, k, 1, rng, "full") for _ in range(n)]
    io_text.write_chain_dir(folder, mats, k)

    # declared objectives arm the SLO engine's burn evaluation (the
    # accounting families render regardless); generous target -- this
    # leg's jobs must all land GOOD
    env = {**os.environ, "SPGEMM_TPU_SLO_TARGET_S": "60",
           "SPGEMM_TPU_SLO_WINDOW_S": "600"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "spgemm_tpu.cli", "serve",
         "--socket", sock, "--device", "cpu", "-v"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    try:
        deadline = time.time() + 120
        while not os.path.exists(sock):
            if proc.poll() is not None:
                return _fail(proc, "daemon exited before binding its socket")
            if time.time() > deadline:
                return _fail(proc, "daemon never bound its socket")
            time.sleep(0.1)

        text0 = client.metrics(sock)
        before = parse_prometheus(text0)
        if "spgemmd_uptime_seconds" not in before:
            return _fail(proc, "first scrape lacks the daemon gauges")

        out = os.path.join(tmp, "matrix.1")
        resp = client.submit(folder, sock, {"output": out})
        job_id = resp["id"]
        resp = client.wait(job_id, sock, timeout=300)
        if resp["job"]["state"] != "done":
            return _fail(proc, f"job ended {resp['job']['state']}: "
                               f"{resp['job']['error']}")

        text1 = client.metrics(sock)
        if "# TYPE spgemm_phase_seconds_total counter" not in text1:
            return _fail(proc, "post-job scrape lacks the TYPE header for "
                               "the phase series")
        after = parse_prometheus(text1)
        plan_series = 'spgemm_phase_seconds_total{phase="plan"}'
        if after.get(plan_series, 0.0) <= before.get(plan_series, 0.0):
            return _fail(proc, f"{plan_series} did not move across the "
                               "submit")
        cache_moved = (
            after.get("spgemm_plan_cache_misses_total", 0)
            + after.get("spgemm_plan_cache_hits_total", 0)
            > before.get("spgemm_plan_cache_misses_total", 0)
            + before.get("spgemm_plan_cache_hits_total", 0))
        if not cache_moved:
            return _fail(proc, "plan-cache series did not move across "
                               "the submit")
        if after.get('spgemmd_jobs_terminal_total{outcome="done"}') != 1.0:
            return _fail(proc, "terminal-outcome counter did not count "
                               "the done job")
        if after.get("spgemm_trace_spans_emitted_total", 0) <= 0:
            return _fail(proc, "flight recorder emitted no spans")

        # deep-profiling families (obs/profile.py): compile accounting
        # with nonzero cost, span-fed phase latency histogram, estimator
        # + delta prediction accountability, event-log counters -- all
        # must appear and move across the submit
        compiles = 'spgemm_compiles_total{site="numeric_round"}'
        if after.get(compiles, 0) <= before.get(compiles, 0):
            return _fail(proc, f"{compiles} did not move across the "
                               "submit")
        flops = 'spgemm_compile_flops_total{site="numeric_round"}'
        if after.get(flops, 0) <= 0:
            return _fail(proc, "compile cost accounting reports zero "
                               "FLOPs for the numeric round")
        phase_hist = 'spgemm_phase_seconds_count{phase="plan"}'
        if after.get(phase_hist, 0) <= before.get(phase_hist, 0):
            return _fail(proc, f"{phase_hist} did not move across the "
                               "submit (span-fed phase histogram)")
        est_count = 'spgemm_est_rel_error_count{quantity="keys"}'
        if after.get(est_count, 0) <= 0:
            return _fail(proc, "estimator accuracy series has no "
                               "observations after an estimator-routed "
                               "submit")
        if after.get("spgemm_delta_dirty_fraction_count", 0) <= 0:
            return _fail(proc, "delta prediction-accountability series "
                               "has no observations")
        ev_count = "spgemm_events_emitted_total"
        if after.get(ev_count, 0) <= before.get(ev_count, 0):
            return _fail(proc, "event-log counter did not move across "
                               "the submit")

        # SLO engine families (obs/slo.py): the rolling window judged
        # the done job -- quantiles render and move, zero error ratio
        p50 = 'spgemm_slo_latency_seconds{quantile="0.5",tenant="default"}'
        if after.get(p50, 0) <= 0:
            return _fail(proc, "SLO latency quantile series did not "
                               "appear/move after the submit")
        if after.get('spgemm_slo_error_ratio{tenant="default"}',
                     None) != 0.0:
            return _fail(proc, "SLO error ratio should be 0.0 after one "
                               "done job")

        # `cli profile --json` through the real CLI: >= 1 compile record
        # with nonzero cost (the acceptance gate)
        rc = subprocess.run(
            [sys.executable, "-m", "spgemm_tpu.cli", "profile",
             "--socket", sock, "--json"],
            capture_output=True, text=True, timeout=60)
        if rc.returncode != 0:
            return _fail(proc, f"cli profile failed: {rc.stderr[-500:]}")
        prof = json.loads(rc.stdout)
        recs = [r for r in prof.get("compiles", []) if r.get("flops", 0) > 0]
        if not recs:
            return _fail(proc, "cli profile --json reports no compile "
                               "record with nonzero cost")

        # `cli events --tail` through the real CLI: the submit's
        # lifecycle records, correlated by job id
        rc = subprocess.run(
            [sys.executable, "-m", "spgemm_tpu.cli", "events",
             "--socket", sock, "--tail", "200"],
            capture_output=True, text=True, timeout=60)
        if rc.returncode != 0:
            return _fail(proc, f"cli events failed: {rc.stderr[-500:]}")
        recs = [json.loads(line) for line in rc.stdout.splitlines() if line]
        kinds = {r["kind"] for r in recs
                 if r.get("job_id") == job_id}
        if not {"job_submit", "job_start", "job_done"} <= kinds:
            return _fail(proc, f"event log lacks the job lifecycle for "
                               f"{job_id} (saw kinds {sorted(kinds)})")
        if not os.path.exists(sock + ".events.jsonl"):
            return _fail(proc, "event-log JSONL did not land next to "
                               "the journal")

        events = client.trace(sock)
        if not events or not isinstance(events, list):
            return _fail(proc, "trace op returned no events")
        for ev in events:
            need = {"name", "ph", "pid", "tid"}
            if ev.get("ph") != "M":  # metadata events carry no timestamp
                need = need | {"ts"}
            if not (need <= set(ev)):
                return _fail(proc, f"malformed trace event: {ev}")
        tagged = [ev for ev in events
                  if ev.get("args", {}).get("job_id") == job_id]
        if not tagged:
            return _fail(proc, f"no span carries job_id={job_id}")

        dump = os.path.join(tmp, "flight.trace.json")
        rc = subprocess.run(
            [sys.executable, "-m", "spgemm_tpu.cli", "trace-dump",
             "--socket", sock, "-o", dump],
            capture_output=True, text=True, timeout=60)
        if rc.returncode != 0:
            return _fail(proc, f"cli trace-dump failed: {rc.stderr[-500:]}")
        with open(dump, encoding="utf-8") as f:
            dumped = json.load(f)
        if not isinstance(dumped, list) or not dumped:
            return _fail(proc, "cli trace-dump wrote no trace_event array")

        client.shutdown(sock)
        try:
            rcode = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            return _fail(proc, "daemon did not exit after shutdown")
        if rcode != 0:
            return _fail(proc, f"daemon exited {rcode} after shutdown")
    finally:
        if proc.poll() is None:
            proc.kill()
    rc = _slo_burn_leg(tmp, folder)
    if rc != 0:
        return rc
    print(f"obs-smoke: OK (phase+plan-cache+compile+accuracy+SLO series "
          f"moved, profile/events/slo CLIs answered, {len(events)} trace "
          f"events, {len(tagged)} tagged {job_id}, burn leg stitched, "
          f"clean shutdown)")
    return 0


def _slo_burn_leg(tmp: str, folder: str) -> int:
    """The SLO-burn + end-to-end-trace leg: an armed serve.executor
    wedge must flip spgemm_slo_burn_active, land an slo_burn event
    carrying the client-minted trace context, and that trace id must
    resolve via `cli trace-dump --merge` to ONE stitched Perfetto trace
    holding spans from both the client process and the daemon."""
    from spgemm_tpu.obs import trace as obs_trace  # noqa: PLC0415
    from spgemm_tpu.serve import client  # noqa: PLC0415

    sock = os.path.join(tmp, "d2.sock")
    env = {**os.environ,
           "SPGEMM_TPU_SLO_TARGET_S": "60",
           "SPGEMM_TPU_SLO_WINDOW_S": "600",
           "SPGEMM_TPU_SERVE_WEDGE_GRACE_S": "2",
           # the backend-wedge signature, once: the first pickup hangs
           "SPGEMM_TPU_FAILPOINTS": "serve.executor:1:1"}
    proc = subprocess.Popen(
        [sys.executable, "-m", "spgemm_tpu.cli", "serve",
         "--socket", sock, "--device", "cpu", "-v"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env)
    try:
        deadline = time.time() + 120
        while not os.path.exists(sock):
            if proc.poll() is not None:
                return _fail(proc, "burn-leg daemon exited before "
                                   "binding its socket")
            if time.time() > deadline:
                return _fail(proc, "burn-leg daemon never bound its "
                                   "socket")
            time.sleep(0.1)

        out = os.path.join(tmp, "matrix.wedge")
        resp = client.submit(folder, sock,
                             {"output": out, "timeout_s": 1.0})
        job_id, trace_id = resp["id"], resp.get("trace")
        if not isinstance(trace_id, str) or len(trace_id) != 32:
            return _fail(proc, f"submit returned no 128-bit trace "
                               f"context (got {trace_id!r})")
        resp = client.wait(job_id, sock, timeout=120)
        if resp["job"]["state"] != "failed" \
                or resp["job"]["error"]["code"] != "job-timeout":
            return _fail(proc, f"wedged job should have been reaped "
                               f"job-timeout, got {resp['job']}")

        # the reap fed the SLO window: the burn gauge must flip to 1
        burning = None
        deadline = time.time() + 60
        while time.time() < deadline and burning is None:
            scraped = parse_prometheus(client.metrics(sock))
            for series, value in scraped.items():
                if series.startswith("spgemm_slo_burn_active{") \
                        and value == 1.0:
                    burning = series
            if burning is None:
                time.sleep(0.2)
        if burning is None:
            return _fail(proc, "spgemm_slo_burn_active never flipped "
                               "after the wedge reap")
        if 'tenant="default"' not in burning:
            return _fail(proc, f"burn gauge carries the wrong tenant: "
                               f"{burning}")

        # the slo op + the slo_burn event both resolve to the SUBMIT's
        # client-minted trace context -- the alert-to-trace join
        rep = client.slo(sock)
        active = [b for b in rep["burn"] if b["active"]]
        if not active or active[0].get("trace_id") != trace_id:
            return _fail(proc, f"slo report's burning window does not "
                               f"carry the submit's trace context "
                               f"(want {trace_id}, got {active})")
        recs = client.events(200, sock)
        burn_evs = [r for r in recs if r.get("kind") == "slo_burn"]
        if not burn_evs:
            return _fail(proc, "no slo_burn event landed")
        if burn_evs[-1].get("trace_id") != trace_id:
            return _fail(proc, f"slo_burn event trace_id "
                               f"{burn_evs[-1].get('trace_id')} != "
                               f"submit trace {trace_id}")

        # stitch client + daemon into one flame view keyed on the trace
        stitch = os.path.join(tmp, "stitch")
        obs_trace.dump_json(os.path.join(stitch, "client.trace.json"),
                            process_name="obs-smoke-client")
        rc = subprocess.run(
            [sys.executable, "-m", "spgemm_tpu.cli", "trace-dump",
             "--socket", sock, "-o",
             os.path.join(stitch, "daemon.trace.json")],
            capture_output=True, text=True, timeout=60)
        if rc.returncode != 0:
            return _fail(proc, f"burn-leg trace-dump failed: "
                               f"{rc.stderr[-500:]}")
        merged_path = os.path.join(tmp, "merged.trace.json")
        rc = subprocess.run(
            [sys.executable, "-m", "spgemm_tpu.cli", "trace-dump",
             "--merge", stitch, "--trace", trace_id, "-o", merged_path],
            capture_output=True, text=True, timeout=60)
        if rc.returncode != 0:
            return _fail(proc, f"cli trace-dump --merge failed: "
                               f"{rc.stderr[-500:]}")
        with open(merged_path, encoding="utf-8") as f:
            merged = json.load(f)
        spans = [ev for ev in merged if ev.get("ph") != "M"]
        if not spans:
            return _fail(proc, "merged trace holds no spans for the "
                               "burn trace id")
        pids = {ev["pid"] for ev in spans}
        names = {ev["name"] for ev in spans}
        if len(pids) < 2:
            return _fail(proc, f"merge did not stitch client AND daemon "
                               f"tracks (pids {pids}, names {names})")
        if "client_submit" not in names:
            return _fail(proc, "merged trace lacks the client_submit "
                               "span")
        if not any((ev.get("args") or {}).get("job_id") == job_id
                   for ev in spans):
            return _fail(proc, f"merged trace lacks the wedged job's "
                               f"daemon-side spans ({job_id}; saw "
                               f"{sorted(names)})")

        client.shutdown(sock)
        try:
            rcode = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            return _fail(proc, "burn-leg daemon did not exit after "
                               "shutdown")
        if rcode != 0:
            return _fail(proc, f"burn-leg daemon exited {rcode} after "
                               f"shutdown")
    finally:
        if proc.poll() is None:
            proc.kill()
    print(f"obs-smoke: burn leg OK ({burning} -> slo_burn trace "
          f"{trace_id} stitched across {len(pids)} processes)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
