"""`make obs-smoke`: end-to-end observability proof on the CPU backend.

Starts a real spgemmd subprocess on a temp socket, scrapes its Prometheus
`metrics` surface before and after one real chain job, and asserts the
observability contract:

  * the scrape is parseable text-format 0.0.4 with HELP/TYPE headers;
  * the per-phase engine series (`spgemm_phase_seconds_total{phase=...}`)
    and the plan-cache series MOVE across the submit -- a daemon whose
    metrics never change is a daemon you cannot operate;
  * the deep-profiling families (obs/profile.py) appear and move:
    compile accounting (`spgemm_compiles_total{site="numeric_round"}`
    with nonzero cost-model FLOPs), the span-fed phase latency
    histogram (`spgemm_phase_seconds_count{phase="plan"}`), estimator
    prediction accountability (`spgemm_est_rel_error_count` -- the
    chain is sized past the estimator's row-sample budget so the
    daemon's plans take the estimated route and are scored on landing),
    delta prediction accountability (`spgemm_delta_dirty_fraction_count`),
    and the event-log counters;
  * `spgemm_tpu.cli profile --json` reports >= 1 compile record with
    nonzero FLOPs through the real CLI (the acceptance gate);
  * `spgemm_tpu.cli events --tail` returns the submit's lifecycle
    records (job_submit/job_start/job_done carrying the job id) and the
    JSONL file landed next to the journal;
  * terminal job accounting works (`spgemmd_jobs_terminal_total{
    outcome="done"}` counts the job);
  * the `trace` op returns Perfetto/Chrome trace_event JSON whose spans
    carry the job id, and `spgemm_tpu.cli trace-dump -o F` round-trips it
    through the real CLI to a valid JSON file;
  * shutdown is clean.

Any step failing exits nonzero.  This process itself stays jax-free (the
client and the generator are pure numpy) -- only the daemon touches a
backend, which is the deployment shape being smoked.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile
import time


def _fail(proc: subprocess.Popen | None, msg: str) -> int:
    print(f"obs-smoke: FAIL: {msg}", file=sys.stderr)
    if proc is not None and proc.poll() is None:
        proc.kill()
    if proc is not None:
        out, _ = proc.communicate(timeout=10)
        sys.stderr.write(out[-4000:] if out else "")
    return 1


def parse_prometheus(text: str) -> dict[str, float]:
    """`{name{labels}: value}` for every sample line (HELP/TYPE skipped);
    a malformed value line raises -- the smoke's format check."""
    out: dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        series, value = line.rsplit(" ", 1)
        out[series] = float(value)
    return out


def main() -> int:
    import numpy as np  # noqa: PLC0415

    from spgemm_tpu.serve import client  # noqa: PLC0415
    from spgemm_tpu.utils import io_text  # noqa: PLC0415
    from spgemm_tpu.utils.gen import banded_block_sparse  # noqa: PLC0415

    tmp = tempfile.mkdtemp(prefix="spgemmd-obs-smoke-")
    sock = os.path.join(tmp, "d.sock")
    folder = os.path.join(tmp, "chain_in")
    # banded, 64 tile-rows: PAST the estimator's row-sample budget
    # (SPGEMM_TPU_EST_SAMPLE_ROWS default 48), so the daemon's
    # first-contact plans take the estimated route and the accuracy
    # series gets scored when the deferred exact joins land
    n, k = 4, 4
    rng = np.random.default_rng(7)
    mats = [banded_block_sparse(64, k, 1, rng, "full") for _ in range(n)]
    io_text.write_chain_dir(folder, mats, k)

    proc = subprocess.Popen(
        [sys.executable, "-m", "spgemm_tpu.cli", "serve",
         "--socket", sock, "--device", "cpu", "-v"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 120
        while not os.path.exists(sock):
            if proc.poll() is not None:
                return _fail(proc, "daemon exited before binding its socket")
            if time.time() > deadline:
                return _fail(proc, "daemon never bound its socket")
            time.sleep(0.1)

        text0 = client.metrics(sock)
        before = parse_prometheus(text0)
        if "spgemmd_uptime_seconds" not in before:
            return _fail(proc, "first scrape lacks the daemon gauges")

        out = os.path.join(tmp, "matrix.1")
        resp = client.submit(folder, sock, {"output": out})
        job_id = resp["id"]
        resp = client.wait(job_id, sock, timeout=300)
        if resp["job"]["state"] != "done":
            return _fail(proc, f"job ended {resp['job']['state']}: "
                               f"{resp['job']['error']}")

        text1 = client.metrics(sock)
        if "# TYPE spgemm_phase_seconds_total counter" not in text1:
            return _fail(proc, "post-job scrape lacks the TYPE header for "
                               "the phase series")
        after = parse_prometheus(text1)
        plan_series = 'spgemm_phase_seconds_total{phase="plan"}'
        if after.get(plan_series, 0.0) <= before.get(plan_series, 0.0):
            return _fail(proc, f"{plan_series} did not move across the "
                               "submit")
        cache_moved = (
            after.get("spgemm_plan_cache_misses_total", 0)
            + after.get("spgemm_plan_cache_hits_total", 0)
            > before.get("spgemm_plan_cache_misses_total", 0)
            + before.get("spgemm_plan_cache_hits_total", 0))
        if not cache_moved:
            return _fail(proc, "plan-cache series did not move across "
                               "the submit")
        if after.get('spgemmd_jobs_terminal_total{outcome="done"}') != 1.0:
            return _fail(proc, "terminal-outcome counter did not count "
                               "the done job")
        if after.get("spgemm_trace_spans_emitted_total", 0) <= 0:
            return _fail(proc, "flight recorder emitted no spans")

        # deep-profiling families (obs/profile.py): compile accounting
        # with nonzero cost, span-fed phase latency histogram, estimator
        # + delta prediction accountability, event-log counters -- all
        # must appear and move across the submit
        compiles = 'spgemm_compiles_total{site="numeric_round"}'
        if after.get(compiles, 0) <= before.get(compiles, 0):
            return _fail(proc, f"{compiles} did not move across the "
                               "submit")
        flops = 'spgemm_compile_flops_total{site="numeric_round"}'
        if after.get(flops, 0) <= 0:
            return _fail(proc, "compile cost accounting reports zero "
                               "FLOPs for the numeric round")
        phase_hist = 'spgemm_phase_seconds_count{phase="plan"}'
        if after.get(phase_hist, 0) <= before.get(phase_hist, 0):
            return _fail(proc, f"{phase_hist} did not move across the "
                               "submit (span-fed phase histogram)")
        est_count = 'spgemm_est_rel_error_count{quantity="keys"}'
        if after.get(est_count, 0) <= 0:
            return _fail(proc, "estimator accuracy series has no "
                               "observations after an estimator-routed "
                               "submit")
        if after.get("spgemm_delta_dirty_fraction_count", 0) <= 0:
            return _fail(proc, "delta prediction-accountability series "
                               "has no observations")
        ev_count = "spgemm_events_emitted_total"
        if after.get(ev_count, 0) <= before.get(ev_count, 0):
            return _fail(proc, "event-log counter did not move across "
                               "the submit")

        # `cli profile --json` through the real CLI: >= 1 compile record
        # with nonzero cost (the acceptance gate)
        rc = subprocess.run(
            [sys.executable, "-m", "spgemm_tpu.cli", "profile",
             "--socket", sock, "--json"],
            capture_output=True, text=True, timeout=60)
        if rc.returncode != 0:
            return _fail(proc, f"cli profile failed: {rc.stderr[-500:]}")
        prof = json.loads(rc.stdout)
        recs = [r for r in prof.get("compiles", []) if r.get("flops", 0) > 0]
        if not recs:
            return _fail(proc, "cli profile --json reports no compile "
                               "record with nonzero cost")

        # `cli events --tail` through the real CLI: the submit's
        # lifecycle records, correlated by job id
        rc = subprocess.run(
            [sys.executable, "-m", "spgemm_tpu.cli", "events",
             "--socket", sock, "--tail", "200"],
            capture_output=True, text=True, timeout=60)
        if rc.returncode != 0:
            return _fail(proc, f"cli events failed: {rc.stderr[-500:]}")
        recs = [json.loads(line) for line in rc.stdout.splitlines() if line]
        kinds = {r["kind"] for r in recs
                 if r.get("job_id") == job_id}
        if not {"job_submit", "job_start", "job_done"} <= kinds:
            return _fail(proc, f"event log lacks the job lifecycle for "
                               f"{job_id} (saw kinds {sorted(kinds)})")
        if not os.path.exists(sock + ".events.jsonl"):
            return _fail(proc, "event-log JSONL did not land next to "
                               "the journal")

        events = client.trace(sock)
        if not events or not isinstance(events, list):
            return _fail(proc, "trace op returned no events")
        for ev in events:
            need = {"name", "ph", "pid", "tid"}
            if ev.get("ph") != "M":  # metadata events carry no timestamp
                need = need | {"ts"}
            if not (need <= set(ev)):
                return _fail(proc, f"malformed trace event: {ev}")
        tagged = [ev for ev in events
                  if ev.get("args", {}).get("job_id") == job_id]
        if not tagged:
            return _fail(proc, f"no span carries job_id={job_id}")

        dump = os.path.join(tmp, "flight.trace.json")
        rc = subprocess.run(
            [sys.executable, "-m", "spgemm_tpu.cli", "trace-dump",
             "--socket", sock, "-o", dump],
            capture_output=True, text=True, timeout=60)
        if rc.returncode != 0:
            return _fail(proc, f"cli trace-dump failed: {rc.stderr[-500:]}")
        with open(dump, encoding="utf-8") as f:
            dumped = json.load(f)
        if not isinstance(dumped, list) or not dumped:
            return _fail(proc, "cli trace-dump wrote no trace_event array")

        client.shutdown(sock)
        try:
            rcode = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            return _fail(proc, "daemon did not exit after shutdown")
        if rcode != 0:
            return _fail(proc, f"daemon exited {rcode} after shutdown")
    finally:
        if proc.poll() is None:
            proc.kill()
    print(f"obs-smoke: OK (phase+plan-cache+compile+accuracy series "
          f"moved, profile/events CLIs answered, {len(events)} trace "
          f"events, {len(tagged)} tagged {job_id}, clean shutdown)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
