"""spgemmd: the resident device-pool-owner daemon.

One long-lived process owns the visible devices and executes submitted
chain jobs on a POOL of executor threads -- one per device slice
(parallel/mesh.slice_pool, SPGEMM_TPU_SERVE_SLICES; the default `1` is a
single single-device executor, exactly the pre-pool daemon) -- so
everything expensive stays warm across jobs: the jit executable cache
(XLA compiles once per shape class), the structure-keyed plan cache
(ops/plancache -- a repeated input skips the symbolic planner entirely),
and the crossover measurement cache (ops/crossover).  The run-once CLI
pays all of those per invocation.

Device-pool scheduling (the estimator-priced placement half):

  * Every admitted job is priced at admission (serve/placement.route):
    a re-submitted folder routes on the estimator's recorded pair mass
    (cheap jobs -> the narrowest slice class, webbase-class -> the
    widest), a first-contact job takes the spec's default slice, and an
    idle slice STEALS the head job when every preferred slice is busy or
    degraded -- all chips stay busy while big jobs keep the wide slice.
  * Single-device slices run the resident engine committed to their
    device; multi-device slices run the bit-exact output-space-sharded
    multiply (parallel/rowshard over the slice's mesh), so slice width
    never changes bits -- only wall.
  * Per-tenant fair queuing (serve/queue.py): submits may carry a
    `tenant` (protocol v2, optional -- v1 clients map to the default
    tenant), dispatch serves tenants deficit-round-robin, and
    SPGEMM_TPU_SERVE_TENANT_INFLIGHT caps one tenant's in-flight jobs
    with a structured tenant-cap error, never a hang.

Reliability model (the part the reference cannot have):

  * The observed accelerator failure mode is a HANG, never an exception
    (utils/backend_probe) -- so a wedged executor thread cannot be joined,
    interrupted, or trusted again.  The watchdog detects it PER SLICE (a
    running job past its deadline whose slice executor has not moved on
    within the SPGEMM_TPU_SERVE_WEDGE_GRACE_S window -- sized to exceed
    one whole multiply, since the heartbeat fires per COMPLETED multiply),
    reaps the job with a structured error, ABANDONS the wedged thread
    (daemon flag keeps it from pinning exit), probes the backend from a
    subprocess (the only safe touch), and spawns a replacement executor
    for THAT slice pinned to the CPU failover path (chain.oracle_multiply
    needs no backend at all).  The degraded slice is excluded from
    placement while any healthy slice remains -- the pool keeps serving
    on the rest -- and serves host-only when the whole pool is down
    (`stats` reports per-slice degrade state; the daemon-level `degraded`
    flag means every slice is down, which with one slice is exactly the
    old behavior).  A reaped job whose executor is merely SLOW aborts its
    chain at the next multiply boundary (JobAbandoned rides the
    heartbeat).
  * A submit beyond SPGEMM_TPU_SERVE_QUEUE_CAP is rejected with a
    structured queue-full error (serve/queue.py), never queued unbounded.
  * Every admitted job is journaled next to the socket
    (<socket>.journal); a daemon restart re-queues jobs that never
    reached a terminal state, and a job submitted with a checkpoint_dir
    resumes its chain from the newest complete pass
    (utils/checkpoint.latest_pass survives a truncated newest file).
  * Warm start (ops/warmstore, SPGEMM_TPU_WARM): the plan cache and the
    delta store's retained results persist into <socket>.warm/ -- loaded
    lazily at startup, flushed after each terminal job event and at
    shutdown -- and JAX's persistent compilation cache points at the
    same dir, so a restarted daemon's first submit is a warm plan + a
    delta recompute + cached executables instead of minutes of cold
    planning and jit.  Corrupt/skewed entries and a warm dir locked by
    another live daemon are counted cold fallbacks, never failures.
    Delta retention is placement-qualified (ops/spgemm._delta_key), so
    each slice's retained results stay on that slice's devices.

Per-job observability: each job runs under an ENGINE PhaseScope
(utils/timers) on its slice's executor thread, and every span it emits
carries the slice name tag -- its status detail carries exactly its own
phases_s and counters plus the slice/steal placement record, and job 2
never inherits job 1's totals even when they ran concurrently on two
slices.
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import signal
import socket
import sys
import threading
import time
import zlib

from spgemm_tpu.obs import events as obs_events
from spgemm_tpu.obs import metrics as obs_metrics
from spgemm_tpu.obs import profile as obs_profile
from spgemm_tpu.obs import slo as obs_slo
from spgemm_tpu.obs import trace as obs_trace
from spgemm_tpu.ops import warmstore
from spgemm_tpu.parallel import mesh as mesh_mod
from spgemm_tpu import tune as tune_mod
from spgemm_tpu.serve import placement, protocol
from spgemm_tpu.serve.queue import (TERMINAL, Job, JobAbandoned, JobQueue,
                                    QueueFull, TenantCapExceeded)
from spgemm_tpu.utils import failpoints, knobs

log = logging.getLogger("spgemm_tpu.serve")

# options a submit may carry; anything else is a bad-request (catching the
# misspelled knob early beats silently ignoring it on a fleet)
SUBMIT_OPTIONS = ("backend", "round_size", "checkpoint_dir", "output",
                  "timeout_s", "failover")


# -------------------------------------------------------- journal framing --
def journal_frame(event: dict) -> str:
    """One crash-safe journal line for `event`: `CRC32 LENGTH PAYLOAD\\n`
    (crc as 8 hex digits over the utf-8 payload bytes, length in bytes).
    A record interrupted mid-write -- daemon killed, disk full -- fails
    either check on replay and is truncated at, never parsed as garbage
    and never a crash."""
    payload = json.dumps(event, separators=(",", ":"))
    data = payload.encode("utf-8")
    return f"{zlib.crc32(data):08x} {len(data)} {payload}\n"


def journal_parse_line(line: str) -> dict | None:
    """Decode one journal line; None = torn/corrupt record (the caller
    truncates there).  Accepts the CRC32+length frame and, for journals
    written before framing existed, a legacy bare-JSON line -- a restart
    across the upgrade must not re-run (or lose) the old journal."""
    if line.startswith("{"):
        try:
            ev = json.loads(line)
        except ValueError:
            return None
        return ev if isinstance(ev, dict) else None
    parts = line.split(" ", 2)
    if len(parts) != 3:
        return None
    crc_hex, length_s, payload = parts
    try:
        want_crc = int(crc_hex, 16)
        want_len = int(length_s)
    except ValueError:
        return None
    data = payload.encode("utf-8")
    if len(data) != want_len or zlib.crc32(data) != want_crc:
        return None
    try:
        ev = json.loads(payload)
    except ValueError:
        return None
    return ev if isinstance(ev, dict) else None


def run_chain_job(job: Job, degraded: bool = False) -> None:
    """Default executor runner: read the job's folder, reduce the chain,
    write the output file (reference text format).

    Placement: job.device_ids (set by the pool executor at pickup; None =
    the default device, the single-slice legacy path) selects where the
    chain runs -- one committed device for a single-device slice, the
    bit-exact output-space-sharded multiply (parallel/rowshard) over the
    slice's mesh for a wider one.  Either way the bits match the
    single-device engine: placement steers wall, never fold order.

    degraded=True forces the host-only oracle multiply -- the CPU failover
    path, which needs no accelerator and no XLA backend (a daemon whose
    device wedged must still serve).  Imports stay inside the function:
    the daemon module itself must be importable without touching jax (BKD
    contract)."""
    from spgemm_tpu import chain  # noqa: PLC0415
    from spgemm_tpu.utils import io_text  # noqa: PLC0415

    n, k = io_text.read_size(job.folder)
    mats = io_text.read_chain(job.folder, 0, n - 1, k)
    # price the structure for the placement scheduler while the coords
    # are in hand (one sampled mini-join, ops/estimate.chain_mass): the
    # NEXT submit of this folder routes on a real estimate instead of
    # the default slice.  Best-effort -- pricing must never fail a job.
    try:
        from spgemm_tpu.ops import estimate, plancache  # noqa: PLC0415
        coords = [m.coords for m in mats]
        placement.note_mass(job.folder, estimate.chain_mass(coords))
        # record the chain's structure fingerprint under the folder's
        # stat signature (ops/plancache structure book): the NEXT submit
        # of this folder carries a group key at admission, so the queue
        # can co-batch same-structure jobs without reading anything
        plancache.note_chain_structure(placement.signature(job.folder),
                                       plancache.chain_fingerprint(coords))
    except Exception as e:  # noqa: BLE001 -- pricing is routing-only, never correctness
        log.warning("placement pricing failed for %s: %r", job.folder, e)
    kwargs: dict = {}
    if not degraded:
        if job.options.get("backend") is not None:
            kwargs["backend"] = job.options["backend"]
        if job.options.get("round_size") is not None:
            kwargs["round_size"] = int(job.options["round_size"])
        if job.options.get("failover"):
            kwargs["failover"] = True
    def beat() -> None:
        # heartbeat + abandonment check: a job the watchdog finished
        # under our feet (reap, or presumed executor death) must not keep
        # computing -- abort at the next multiply boundary instead of
        # running a failed job's chain to completion (and, for a wedged
        # executor that unwedges hours later, instead of recording the
        # rest of its phases into the replacement executor's ENGINE)
        failpoints.check("serve.heartbeat")
        job.touch()
        if job.state in TERMINAL:
            raise JobAbandoned(job.id)

    multiply = chain.oracle_multiply if degraded else None
    device_ids = None if degraded else job.device_ids
    if device_ids and len(device_ids) > 1:
        # multi-device slice: bit-exact key-space sharding over the
        # slice's mesh (rowshard) -- each output tile folds whole on one
        # device, so the non-associative accumulation order is untouched
        # and the result matches the single-device engine exactly.
        # backend/round_size ride through; the sharded multiply ignores
        # kernel-backend selection (its numeric round IS the exact one).
        from spgemm_tpu.parallel.rowshard import spgemm_sharded  # noqa: PLC0415

        slice_mesh = mesh_mod.slice_mesh(
            mesh_mod.DeviceSlice(job.slice or "slice", 0,
                                 tuple(device_ids)))
        # kernel-backend selection does not apply to the sharded multiply
        # (its numeric round IS the exact one); failover is a
        # chain_product-level feature and stays -- a device lost mid-chain
        # still restarts the pass on the host oracle when requested
        kwargs.pop("backend", None)

        def multiply(a, b, **kw):  # noqa: ARG001 -- chain passes plan kwargs
            kw.pop("plan", None)
            return spgemm_sharded(a, b, mesh=slice_mesh, **kw)
    elif device_ids and not degraded:
        # single-device slice: commit the inputs to the slice's device --
        # jit follows committed placement, so the whole chain (and its
        # delta-retained results, placement-qualified by _delta_key)
        # lives on this slice's device
        from spgemm_tpu.ops.device import DeviceBlockMatrix  # noqa: PLC0415

        dev = mesh_mod.slice_devices(
            mesh_mod.DeviceSlice(job.slice or "slice", 0,
                                 tuple(device_ids)))[0]
        mats = [DeviceBlockMatrix.from_host(m, device=dev) for m in mats]
    result = chain.chain_product(
        mats, multiply=multiply,
        checkpoint_dir=job.options.get("checkpoint_dir"),
        heartbeat=beat, **kwargs)
    if job.state in TERMINAL:
        # reaped while we were inside the chain (an abandoned wedged
        # executor can unwedge HOURS later): a resubmit may own
        # job.output by now, and a stale result must not clobber it
        return
    io_text.write_matrix(job.output, result.prune_zeros())


def run_chain_jobs(jobs: list[Job], degraded: bool = False) -> None:
    """Cross-job batched runner (SPGEMM_TPU_SERVE_BATCH_K/_WINDOW_S):
    run J same-structure chain jobs as ONE lockstep pairwise reduction --
    each step plans once (shared plan, plancache-keyed) and executes all
    J operand pairs as one fused dispatch (ops.spgemm.execute_batched),
    so J jobs pay one launch sequence instead of J.  Bit-exact by
    construction: the stacking rides the round axis the numeric kernels
    already accept, each output row's fold order is untouched, and the
    reduction tree is chain_product's helper2 pairing unchanged.

    The executor only forms batches it already vetted (same recorded
    structure fingerprint and backend/round_size options; no checkpoint,
    failover, delta, or degraded pickup reaches here) -- but the
    admission-time structure book can be stale, so the chains are
    re-verified from the actual coords and a mismatch falls back to
    running every job solo (run_chain_job): never a wrong answer, at
    worst a wasted window."""
    if degraded or len(jobs) == 1:
        for job in jobs:
            run_chain_job(job, degraded=degraded)
        return
    import numpy as np  # noqa: PLC0415

    from spgemm_tpu.ops import spgemm as spgemm_mod  # noqa: PLC0415
    from spgemm_tpu.utils import io_text  # noqa: PLC0415

    chains = []
    for job in jobs:
        n, k = io_text.read_size(job.folder)
        mats = io_text.read_chain(job.folder, 0, n - 1, k)
        # per-job pricing + structure-book refresh, exactly the solo
        # runner's best-effort block (the batch must not starve the
        # estimator or let the book go stale)
        try:
            from spgemm_tpu.ops import estimate, plancache  # noqa: PLC0415
            coords = [m.coords for m in mats]
            placement.note_mass(job.folder, estimate.chain_mass(coords))
            plancache.note_chain_structure(
                placement.signature(job.folder),
                plancache.chain_fingerprint(coords))
        except Exception as e:  # noqa: BLE001 -- pricing is routing-only, never correctness
            log.warning("placement pricing failed for %s: %r",
                        job.folder, e)
        chains.append(mats)
    head_chain = chains[0]
    same = all(
        len(mats) == len(head_chain)
        and all(m.k == h.k and m.rows == h.rows and m.cols == h.cols
                and np.array_equal(m.coords, h.coords)
                for m, h in zip(mats, head_chain))
        for mats in chains[1:])
    if not same:
        log.warning("batch of %d jobs not structure-identical after "
                    "read (stale structure book); running solo",
                    len(jobs))
        for job in jobs:
            run_chain_job(job, degraded=False)
        return

    def beat() -> None:
        # heartbeat for every member after each fused multiply; the HEAD
        # is the watchdog's reap/wedge slot (sl.current), so a reaped
        # head aborts the WHOLE batch at the next multiply boundary --
        # the executor fails the surviving mates with a structured error
        # (they shared the head's deadline class)
        failpoints.check("serve.heartbeat")
        for job in jobs:
            job.touch()
        if jobs[0].state in TERMINAL:
            raise JobAbandoned(jobs[0].id)

    device_ids = jobs[0].device_ids
    if device_ids:
        # single-device slice in a pool (the batch gate excludes wide
        # slices): commit every chain to the slice's device, like the
        # solo runner
        from spgemm_tpu.ops.device import DeviceBlockMatrix  # noqa: PLC0415

        dev = mesh_mod.slice_devices(
            mesh_mod.DeviceSlice(jobs[0].slice or "slice", 0,
                                 tuple(device_ids)))[0]
        chains = [[DeviceBlockMatrix.from_host(m, device=dev)
                   for m in mats] for mats in chains]
    import jax  # noqa: PLC0415

    platform = jax.devices()[0].platform
    backend = spgemm_mod.resolve_backend(jobs[0].options.get("backend"))
    rs = jobs[0].options.get("round_size")
    round_size = int(rs) if rs is not None else None
    arrs = chains  # one partial list per job, reduced in lockstep
    while len(arrs[0]) > 1:
        nxt: list[list] = [[] for _ in jobs]
        width = len(arrs[0])
        for i in range(0, width - 1, 2):
            # the reference's :301 progress line, once per FUSED step
            print(f"multiplying {i} {i + 1}", flush=True)
            pln = spgemm_mod.plan(arrs[0][i], arrs[0][i + 1],
                                  round_size=round_size, backend=backend,
                                  platform=platform)
            outs = spgemm_mod.execute_batched(
                pln, [(arr[i], arr[i + 1]) for arr in arrs])
            for j, out in enumerate(outs):
                nxt[j].append(out)
            beat()
            for arr in arrs:
                arr[i] = arr[i + 1] = None  # free consumed partials
        if width % 2 == 1:
            for j, arr in enumerate(arrs):
                nxt[j].append(arr[-1])  # odd element carried (:315-321)
        arrs = nxt
    for job, arr in zip(jobs, arrs):
        if job.state in TERMINAL:
            continue  # reaped mid-batch: never clobber a resubmit's output
        result = arr[0].to_host() if hasattr(arr[0], "to_host") else arr[0]
        io_text.write_matrix(job.output, result.prune_zeros())


class _Slice:
    """One pool slice's serving state: the mesh slice plus its executor
    thread, reap window and degrade flag.

    thread/gen/current/reaped/reaped_at are single-writer handoff slots
    (watchdog writes, executor compares), lock-free by design -- the
    ordering argument lives on their access sites, so they stay
    deliberately un-annotated (the pre-pool daemon's _executor/_current
    discipline, one copy per slice).  degraded/degrade_reason/jobs_total/
    steals are daemon-lock-guarded like the old daemon-level flags."""

    def __init__(self, spec: "mesh_mod.DeviceSlice"):
        self.spec = spec
        self.name = spec.name
        self.device_ids = spec.device_ids
        self.default = spec.default
        # written only under the OWNING Daemon's _lock (THR checks the
        # daemon's own spelled self.* accesses; these ride the same
        # critical sections).  The accept predicate's lock-free reads of
        # degraded are deliberate: dispatch tolerates a stale value for
        # one pop -- a just-degraded slice at worst steals one more job
        # onto its replacement CPU executor, never corrupts state.
        self.degraded = False
        self.degrade_reason: str | None = None
        self.jobs_total = 0
        self.steals = 0
        # self-healing recovery state (daemon-lock-guarded like the
        # degrade flags): the next re-probe time, the live backoff, how
        # often this slice was reinstated, when, whether its next job is
        # the canary, and whether a probe subprocess is in flight
        self.recoveries = 0
        self.recovered_at: float | None = None
        self.recover_next = 0.0
        self.recover_backoff = 0.0
        self.canary = False
        self.canary_job: "Job | None" = None  # the in-flight audition
        self.probing = False
        self.thread: threading.Thread | None = None
        self.gen = 0
        self.current: Job | None = None   # job the slice's live executor holds
        self.reaped: Job | None = None    # reaped job awaiting wedge grace
        self.reaped_at = 0.0

    @property
    def width(self) -> int:
        return len(self.device_ids)


class Daemon:
    """The spgemmd server: accept loop + executor pool + watchdog +
    journal.

    runner/probe are injectable for tests: runner(job, degraded=...) does
    the actual work (default run_chain_job; the pool passes placement via
    job.device_ids), probe() is the backend liveness check used when
    degrading (default utils/backend_probe.probe_default_backend --
    subprocess + timeout, because a dead TPU hangs in-process).
    slices/n_devices: the slice spec (default the SPGEMM_TPU_SERVE_SLICES
    knob) and the visible device count for validating it -- tests inject
    n_devices so multi-slice pools build without a backend; the real CLI
    counts devices after its startup probe.
    """

    # one compaction per this many terminal journal events: the journal
    # stays O(queue cap + this) records for a resident daemon instead of
    # growing for its lifetime (class attribute so tests can shrink it)
    JOURNAL_COMPACT_EVERY = 256

    # flight dumps retained in <socket>.flight/: a resident daemon whose
    # jobs keep timing out writes one dump per reap, so like every other
    # client-growable resource (RETAIN_TERMINAL, MAX_CONNS, the journal)
    # the dir is bounded -- oldest dumps pruned past this many
    FLIGHT_RETAIN = 64

    # concurrent-connection bound: every accepted connection pins one
    # spgemmd-conn thread (+ up to protocol.MAX_LINE_BYTES of pending
    # buffer), so a connect() loop that never closes must exhaust THIS --
    # answered with a structured busy error -- not the device owner's
    # memory or thread limit.  Sized above the queue cap so every queued
    # job can have a blocked wait()er with headroom to spare.
    MAX_CONNS = 128

    # idle connections (no request line in this many seconds) are dropped:
    # recv() raises timeout -> the handler closes.  Generous on purpose --
    # a server-side `wait` blocks in job.wait, not recv, so legitimate
    # long waits never trip this; only silent open sockets do.
    CONN_IDLE_TIMEOUT_S = 600.0

    # one server-side `wait` is clamped to this many seconds (a running
    # snapshot is answered past it; client.wait polls in slices): an
    # abandoned waiter must not pin its MAX_CONNS slot until the job
    # terminates -- which, for a job with no deadline behind a wedged
    # executor, is never
    MAX_WAIT_SLICE_S = 30.0

    # graceful drain (SIGTERM/SIGINT/shutdown): stop() waits this long
    # for in-flight jobs to finish before reaping the stragglers with a
    # structured shutting-down error -- a rollout must neither hang on a
    # wedged job nor cut a nearly-done one off at the knees (class
    # attribute so tests can shrink it)
    DRAIN_GRACE_S = 10.0

    # recovery backoff ceiling: a slice whose device stays dead re-probes
    # no more often than this, however many canaries failed
    RECOVER_BACKOFF_MAX_S = 900.0

    def __init__(self, socket_path: str | None = None, *, runner=None,
                 batch_runner=None, probe=None, queue_cap: int | None = None,
                 job_timeout_s: float | None = None,
                 wedge_grace_s: float | None = None, journal: bool = True,
                 persist_compile_cache: bool = False,
                 slices: str | None = None, n_devices: int | None = None,
                 tenant_inflight: int | None = None,
                 recover_s: float | None = None,
                 device_kind: str | None = None,
                 addr: str | None = None):
        self.socket_path = socket_path or protocol.default_socket_path()
        # optional TCP front-end (the fleet layer's network half): the
        # same protocol bytes on an AF_INET listener beside the unix
        # socket.  Unset = unix-only, byte-identical to the pre-fleet
        # daemon.  Parsed HERE so a malformed spec fails construction,
        # not the accept path.
        self._addr_spec = addr if addr is not None \
            else knobs.get("SPGEMM_TPU_SERVE_ADDR")
        if self._addr_spec:
            parsed = protocol.parse_addr(self._addr_spec)
            if parsed[0] != "tcp":
                raise ValueError(
                    f"SPGEMM_TPU_SERVE_ADDR must be tcp:HOST:PORT (the "
                    f"unix socket always listens), got {self._addr_spec!r}")
            self._tcp_bind = (parsed[1], parsed[2])
        else:
            self._tcp_bind = None
        # the REAL bound port (resolves a tcp:...:0 ephemeral bind);
        # written once in start() before the accept threads spawn
        self.tcp_port: int | None = None
        self.journal_path = self.socket_path + ".journal"
        # postmortem flight dumps (watchdog reap / wedge / degrade) land
        # here, next to the journal: <socket>.flight/<job>.trace.json
        self.flight_dir = self.socket_path + ".flight"
        # structured event log (obs/events.py): JSONL next to the journal,
        # rotated at SPGEMM_TPU_OBS_EVENTS_MAX_KB
        self.events_path = self.socket_path + ".events.jsonl"
        # warm-start store (ops/warmstore): persisted plans + delta
        # entries next to the journal, so a restarted daemon is hot in
        # seconds (SPGEMM_TPU_WARM_DIR overrides the journal-adjacent
        # default; SPGEMM_TPU_WARM=0 disables persistence entirely)
        self.warm_dir = self.socket_path + ".warm"
        self._runner = runner or run_chain_job
        # the cross-job batched runner (SPGEMM_TPU_SERVE_BATCH_*):
        # batch_runner(jobs, degraded=...) runs >= 2 vetted same-structure
        # jobs as one lockstep fused-dispatch reduction; injectable like
        # runner so tests can observe batch formation without jax
        self._batch_runner = batch_runner or run_chain_jobs
        self._probe = probe
        self._cap = queue_cap if queue_cap is not None \
            else knobs.get("SPGEMM_TPU_SERVE_QUEUE_CAP")
        self._job_timeout_s = job_timeout_s if job_timeout_s is not None \
            else knobs.get("SPGEMM_TPU_SERVE_JOB_TIMEOUT")
        # the slow-vs-wedged window must cover one whole multiply: the
        # heartbeat fires per COMPLETED multiply, so a shorter grace would
        # declare a healthy executor wedged mid-multiply and permanently
        # degrade the slice to the CPU oracle path
        self._wedge_grace_s = wedge_grace_s if wedge_grace_s is not None \
            else knobs.get("SPGEMM_TPU_SERVE_WEDGE_GRACE_S")
        # self-healing cadence: 0 = never re-probe (a degraded slice
        # stays on the CPU failover path until restart, the pre-recovery
        # behavior and the whole-feature A/B)
        self._recover_s = recover_s if recover_s is not None \
            else knobs.get("SPGEMM_TPU_SERVE_RECOVER_S")
        self._journal_enabled = journal
        # main() sets this for the real CLI daemon: jax.config's
        # compilation-cache dir is PROCESS-GLOBAL state, so an in-process
        # test daemon must never redirect the host process's compiles
        # into its (soon-deleted) tmp dir
        self._persist_compile_cache = persist_compile_cache
        self._journal_terminal_events = 0  # spgemm-lint: guarded-by(_lock)
        self._journal_compactions = 0      # spgemm-lint: guarded-by(_lock)
        self._journal_torn = 0             # spgemm-lint: guarded-by(_lock)
        # daemon-lifetime terminal outcomes (stats + the Prometheus
        # spgemmd_jobs_terminal_total series): the queue index evicts old
        # terminal jobs, so a scraper needs these to tell a healthy idle
        # daemon from one that just degraded and recovered
        self._terminal_totals = {"done": 0, "error": 0, "timeout": 0,
                                 "abandoned": 0,
                                 "drained": 0}  # spgemm-lint: guarded-by(_lock)
        self._job_wall = {
            "buckets": {le: 0 for le in obs_metrics.JOB_WALL_BUCKETS},
            "sum": 0.0, "count": 0}        # spgemm-lint: guarded-by(_lock)
        # jobs per armed-window executor pickup (the
        # spgemm_serve_batch_size histogram): size 1 = a batchable head
        # found no mates inside SPGEMM_TPU_SERVE_BATCH_WINDOW_S, >= 2 =
        # one fused dispatch served the whole batch.  Never sampled while
        # the window is 0, so the pre-batch scrape is byte-identical.
        self._batch_size = {
            "buckets": {le: 0 for le in obs_metrics.BATCH_SIZE_BUCKETS},
            "sum": 0.0, "count": 0}        # spgemm-lint: guarded-by(_lock)
        # flight dumps in THIS daemon's write order: retention must prune
        # oldest-first even on filesystems whose mtime granularity ties a
        # reap burst (mtime orders only pre-restart leftovers)
        self._flight_order: list[str] = []  # spgemm-lint: guarded-by(_lock)
        self.queue = JobQueue(self._cap, tenant_inflight=tenant_inflight)
        # the slice pool: built at construction (jax-free -- positions,
        # not live devices) so an unstarted daemon still answers stats.
        # The spec comes from the knob unless injected; n_devices
        # validates it when known (the CLI passes the post-probe count,
        # tests inject, 'auto' requires it).
        self._slice_spec = slices if slices is not None \
            else knobs.get("SPGEMM_TPU_SERVE_SLICES")
        self._n_devices = n_devices
        self.slices: list[_Slice] = [
            _Slice(s) for s in mesh_mod.slice_pool(self._slice_spec,
                                                   n_devices)]
        # daemon-level degrade state: True only when EVERY slice is on
        # the CPU failover path (with one slice this is exactly the old
        # single-executor flag).  Written by the watchdog/degrade path,
        # read by the executors and every stats request.
        self.degraded = False                    # spgemm-lint: guarded-by(_lock)
        self.degrade_reason: str | None = None   # spgemm-lint: guarded-by(_lock)
        # autotuner (spgemm_tpu/tune): the tune-class device kind (main()
        # passes the probed platform; a jax.devices() call HERE would
        # hang on a dead TPU and break the module's jax-free contract),
        # and the pool-wide last-trial-leg claim stamp -- one leg per
        # SPGEMM_TPU_TUNE_TRIAL_S across every executor's idle tick
        self._tune_device_kind = device_kind or "cpu"
        self._tune_last_trial = 0.0              # spgemm-lint: guarded-by(_lock)
        self._probe_outcome: str | None = None   # spgemm-lint: guarded-by(_lock)
        self._started_at = time.time()
        self._next_id = 1                        # spgemm-lint: guarded-by(_lock)
        self._stop = threading.Event()
        self._lock = threading.Lock()  # ids, journal file, degrade state
        self._listener: socket.socket | None = None
        self._tcp_listener: socket.socket | None = None
        self._conn_count = 0               # spgemm-lint: guarded-by(_lock)
        self._threads: list[threading.Thread] = []

    # ------------------------------------------------------------ journal --
    def _journal_append(self, event: dict) -> None:
        if not self._journal_enabled:
            return
        with self._lock:
            line = journal_frame(event)
            if failpoints.check("serve.journal"):
                # injected mid-write kill: half the frame, no newline --
                # exactly what a crashed daemon leaves, and exactly what
                # replay must truncate at (counted) instead of crashing
                line = line[:max(1, len(line) // 2)]
            with open(self.journal_path, "a", encoding="utf-8") as f:
                f.write(line)
            if event.get("event") in ("done", "failed"):
                # runtime compaction: a resident daemon serving a fleet
                # for weeks must not grow the journal (or the next
                # restart's replay) without bound
                self._journal_terminal_events += 1
                if self._journal_terminal_events >= \
                        self.JOURNAL_COMPACT_EVERY:
                    self._journal_compact_locked()

    def _journal_live_records(self) -> tuple[list[dict], int]:
        """(submit records with no matching terminal event in file order,
        torn-record count).  Every record is CRC32+length framed
        (journal_frame; legacy bare-JSON lines still parse): the first
        record that fails its frame check is a torn tail -- a mid-write
        kill, a partial disk -- and reading TRUNCATES there (everything
        after a torn record is unattributable; at-least-once replay of a
        job whose terminal event fell past the tear is the restart
        contract the journal already has), counted, never a crash."""
        submitted: dict[str, dict] = {}
        torn = 0
        with open(self.journal_path, encoding="utf-8") as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                ev = journal_parse_line(line)
                if ev is None:
                    torn += 1
                    break  # truncate at the first bad record
                if ev.get("event") == "submit":
                    submitted[ev["id"]] = ev
                elif ev.get("event") in ("done", "failed"):
                    submitted.pop(ev.get("id"), None)
        return list(submitted.values()), torn

    def _journal_compact_locked(self) -> None:
        """Rewrite the journal to only its live submit records (caller
        holds self._lock).  A torn tail is dropped by the rewrite -- the
        on-disk truncation that makes the in-memory truncation of
        _journal_live_records durable -- and counted."""
        live, torn = self._journal_live_records()
        with open(self.journal_path, "w", encoding="utf-8") as f:
            for ev in live:
                f.write(journal_frame(ev))
        self._journal_terminal_events = 0
        self._journal_compactions += 1
        if torn:
            self._journal_torn += torn
            obs_events.emit("journal_torn", records=torn,
                            path=self.journal_path)
            log.warning("journal: dropped %d torn record(s) at the tail "
                        "of %s (mid-write kill; replay truncated there)",
                        torn, self.journal_path)

    def _journal_replay(self) -> None:
        """Re-queue journaled jobs that never reached a terminal state,
        then compact the journal to exactly those (a restarted daemon must
        not re-run completed work, and the file must not grow forever)."""
        if not self._journal_enabled or not os.path.exists(self.journal_path):
            return
        live, _ = self._journal_live_records()  # compaction counts the tear
        with self._lock:
            self._journal_compact_locked()
        for ev in live:
            try:
                job = Job(ev["id"], ev["folder"], ev["output"],
                          ev.get("options", {}),
                          timeout_s=ev.get("timeout_s", 0.0),
                          tenant=ev.get("tenant", protocol.DEFAULT_TENANT),
                          # pre-v3 journal records carry no trace
                          # context: the Job mints a fresh one
                          trace_id=ev.get("trace"))
            except (KeyError, TypeError) as e:
                log.warning("journal: skipping malformed record %r (%r)",
                            ev, e)
                continue
            # re-price at replay: the folder may have changed (or gone)
            # since the original admission routed it -- the batching
            # group key re-resolves the same way (the structure book is
            # in-process state a restart emptied, so replayed jobs
            # usually run solo until an executor re-records the folder)
            job.placement = placement.route(job.folder)
            from spgemm_tpu.ops import plancache  # noqa: PLC0415
            job.group_key = plancache.chain_structure(
                placement.signature(job.folder))
            try:
                self.queue.submit(job)
                log.info("journal: re-queued unfinished job %s (%s)",
                         job.id, job.folder)
            except (QueueFull, TenantCapExceeded) as e:
                code = protocol.E_TENANT_CAP \
                    if isinstance(e, TenantCapExceeded) \
                    else protocol.E_QUEUE_FULL
                if job.finish("failed", error={
                        "code": code,
                        "message": f"{e} while re-queueing from journal"},
                        on_commit=lambda j=job: self._journal_append(
                            {"event": "failed", "id": j.id})):
                    self._observe_terminal(job, "error")
            num = int(ev["id"].rsplit("-", 1)[-1]) \
                if ev["id"].rsplit("-", 1)[-1].isdigit() else 0
            # replay runs at start(), before any serving thread exists,
            # but the id counter is _lock-guarded state -- hold the lock
            # anyway (THR) rather than argue the happens-before each time
            with self._lock:
                self._next_id = max(self._next_id, num + 1)

    # ---------------------------------------------------------- lifecycle --
    def start(self) -> None:
        """Bind the socket and start the accept/executor-pool/watchdog
        threads.  Raises RuntimeError if a live daemon already owns the
        socket (the single-pool-owner contract); a stale socket file is
        unlinked."""
        if os.path.exists(self.socket_path):
            peer = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                peer.settimeout(1.0)
                peer.connect(self.socket_path)
            except OSError:
                os.unlink(self.socket_path)  # stale: no listener behind it
            else:
                peer.close()
                raise RuntimeError(
                    f"a daemon is already serving on {self.socket_path}")
        obs_events.LOG.configure(self.events_path)
        obs_events.emit("daemon_start", socket=self.socket_path,
                        slices=[s.name for s in self.slices])
        # warm start: bind the journal-adjacent store (lock contention or
        # SPGEMM_TPU_WARM=0 leaves it cold -- configure() events both),
        # and point JAX's persistent compilation cache at its xla/ subdir
        # so re-jit of executables an earlier daemon compiled on the same
        # jit-static knob vector is a disk hit.  Loading stays LAZY: the
        # first fingerprint match deserializes its entry, startup only
        # counts files -- binding never blocks on a full deserialize.
        if warmstore.configure(self.warm_dir) \
                and self._persist_compile_cache:
            warmstore.configure_compilation_cache()
        # autotuner: wire the warm store's tune tier as the override
        # persistence (promotions/reverts flush immediately -- unlike
        # plans, a tune record mutates) and adopt every persisted
        # override up front, so a restarted daemon serves its first
        # same-class job already tuned (canary records re-audit: the
        # first post-restart job runs the tightened-deadline gate again)
        if tune_mod.enabled():
            tune_mod.TUNER.persist_with(warmstore.save_tune)
            adopted = tune_mod.TUNER.load(warmstore.load_tunes())
            if adopted:
                log.info("tuner: adopted %d persisted override record(s)",
                         adopted)
        self._journal_replay()
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.socket_path)
        self._listener.listen(16)
        # accept() must poll: close() from another thread does not wake a
        # blocked accept on Linux, and shutdown semantics vary -- the
        # accept loop re-checks the stop flag every tick instead
        self._listener.settimeout(0.2)
        if self._tcp_bind is not None:
            # the TCP front-end: same protocol bytes, same accept loop,
            # same conn cap/idle timeout -- only the address family
            # differs.  Bind failures (port taken, bad host) propagate:
            # an exported SPGEMM_TPU_SERVE_ADDR must never degrade to a
            # silently unix-only daemon.
            self._tcp_listener = socket.socket(socket.AF_INET,
                                               socket.SOCK_STREAM)
            self._tcp_listener.setsockopt(socket.SOL_SOCKET,
                                          socket.SO_REUSEADDR, 1)
            self._tcp_listener.bind(self._tcp_bind)
            self._tcp_listener.listen(16)
            self._tcp_listener.settimeout(0.2)
            self.tcp_port = self._tcp_listener.getsockname()[1]
        for sl in self.slices:
            self._spawn_executor(sl)
        accept_loops = [(self._listener, "spgemmd-accept")]
        if self._tcp_listener is not None:
            accept_loops.append((self._tcp_listener, "spgemmd-accept-tcp"))
        for listener, name in accept_loops:
            t = threading.Thread(target=self._accept_loop,
                                 args=(listener,), name=name, daemon=True)
            t.start()
            self._threads.append(t)
        t = threading.Thread(target=self._watchdog_loop,
                             name="spgemmd-watchdog", daemon=True)
        t.start()
        self._threads.append(t)
        log.info("spgemmd serving on %s%s (%d slice(s): %s; queue cap %d, "
                 "job timeout %s)",
                 self.socket_path,
                 (f" + tcp:{self._tcp_bind[0]}:{self.tcp_port}"
                  if self.tcp_port is not None else ""),
                 len(self.slices),
                 ",".join(f"{s.name}{'*' if s.default else ''}"
                          for s in self.slices),
                 self._cap, self._job_timeout_s or "none")

    def serve_forever(self) -> None:
        self.start()
        try:
            while not self._stop.wait(0.5):
                pass
        finally:
            self.stop()

    def stop(self) -> None:
        """Graceful drain + teardown (the protocol `shutdown` op, the
        SIGTERM/SIGINT handlers, and serve_forever's finally all land
        here): admission stops the instant the flag is set (_op_submit
        answers shutting-down), in-flight jobs get DRAIN_GRACE_S to
        finish, stragglers are reaped with a structured shutting-down
        error (first-write-wins: a job that finishes during the reap
        stays done), then warm store + event log flush, the flock
        releases, and the socket unlinks -- a rollout's SIGTERM exits 0
        with nothing half-written.  Queued-but-unstarted jobs keep their
        live journal records: the successor daemon re-runs them (the
        at-least-once restart contract)."""
        self._stop.set()
        for listener in (self._listener, self._tcp_listener):
            if listener is not None:
                try:
                    listener.close()
                except OSError:
                    pass
        deadline = time.time() + self.DRAIN_GRACE_S
        while time.time() < deadline and self.queue.running():
            time.sleep(0.05)
        leftovers = self.queue.running()
        if leftovers:
            obs_events.emit("daemon_drain_reap",
                            jobs=[j.id for j in leftovers])
        for job in leftovers:
            if job.finish("failed", error={
                    "code": protocol.E_SHUTTING_DOWN,
                    "message": f"daemon shut down before the job finished "
                               f"(drained {self.DRAIN_GRACE_S:g}s); "
                               "resubmit to the successor daemon"},
                    detail=self._reap_detail(job),
                    on_commit=lambda j=job: self._journal_append(
                        {"event": "failed", "id": j.id})):
                # a drain reap is a ROUTINE rollout outcome, not the
                # executor-death signal: its own outcome label, so
                # alerts keyed on "abandoned" stay meaningful
                self._observe_terminal(job, "drained")
        for t in self._threads:
            t.join(timeout=5.0)
        for sl in self.slices:
            ex = sl.thread
            if ex is not None:
                ex.join(timeout=5.0)  # wedged executor: daemon flag covers it
        # final warm flush + lock release: whatever the terminal-event
        # flushes missed (an estimator plan whose join landed late, the
        # newest delta versions) persists before the process dies, and
        # the dir's flock frees for the successor daemon
        warmstore.flush()
        warmstore.release()
        # drain the async event-log writer so a clean shutdown leaves the
        # JSONL complete (best-effort, like the sink itself)
        obs_events.LOG.flush(timeout=2.0)
        try:
            os.unlink(self.socket_path)
        except OSError:
            pass

    # ---------------------------------------------------------- placement --
    def degrade_at_start(self, reason: str) -> None:
        """Mark the whole pool degraded before serving begins (the CLI's
        startup-probe-failed path): every slice runs the CPU failover
        executor from its first job.  No serving thread exists yet, but
        degrade state is _lock-guarded (THR) -- hold the lock rather than
        argue the happens-before."""
        with self._lock:
            for sl in self.slices:
                sl.degraded = True
                sl.degrade_reason = reason
            self.degraded = True
            self.degrade_reason = reason

    def _preferred_names(self, job: Job) -> set[str]:
        """The slice names the job's placement class targets, restricted
        to healthy slices: small -> the narrowest healthy width class,
        large -> the widest, default/unknown -> the spec's default slices.
        Empty when no healthy slice exists (the accept predicate then
        lets degraded slices serve host-only)."""
        healthy = [s for s in self.slices if not s.degraded]
        if not healthy:
            return set()
        cls = (job.placement or {}).get("class", "default")
        if cls == "large":
            pick = max(s.width for s in healthy)
            return {s.name for s in healthy if s.width == pick}
        if cls == "small":
            pick = min(s.width for s in healthy)
            return {s.name for s in healthy if s.width == pick}
        defaults = {s.name for s in healthy if s.default}
        if defaults:
            return defaults
        pick = min(s.width for s in healthy)
        return {s.name for s in healthy if s.width == pick}

    def _devices_held(self, sl: _Slice) -> bool:
        """True when another slice holding a job shares a device with sl
        (overlapping specs, e.g. `auto`'s full-mesh slice): two slices
        sharing a device are mutually exclusive at dispatch."""
        ids = set(sl.device_ids)
        for other in self.slices:
            if other is not sl and other.current is not None \
                    and ids & set(other.device_ids):
                return True
        return False

    def _accepts(self, sl: _Slice, job: Job) -> bool:
        """Placement predicate for slice sl's executor (runs under the
        QUEUE lock -- cheap, lock-free reads of slice handoff slots whose
        staleness dispatch tolerates): take the job when this slice is in
        its preferred class, or STEAL it when every preferred slice is
        busy, degraded or device-blocked -- an idle chip beats a faithful
        queue position.  A degraded slice serves only when the whole pool
        is degraded (the single-slice daemon's keep-serving contract).

        Returning True CLAIMS the slice (sl.current = job) while the
        queue lock is still held: the pop that follows is atomic with the
        claim, so an overlapping slice (auto's full mesh) probing
        _devices_held can never dispatch onto a device this job is about
        to own -- the claim, not the executor's later bookkeeping, is the
        mutual-exclusion point.  The executor clears a claim it ends up
        not running (terminal-in-FIFO skip) and re-asserts it at pickup."""
        cur = sl.current
        if cur is not None and cur.state not in TERMINAL:
            # another executor generation holds a LIVE claim on this
            # slice: the recovery reinstatement retires an actively
            # polling degraded executor, and for one poll cycle both
            # generations dispatch for the slice -- serialized here
            # (claims all run under the queue lock) so the straggler's
            # job stays sl.current until terminal (deadline reaping and
            # wedge attribution keep working) and two jobs can never run
            # on one slice's devices at once.  A TERMINAL leftover claim
            # is a wedged executor's abandoned slot: the degraded
            # replacement must overwrite it or the slice never serves
            # again (the hung thread can't clear it).
            return False
        if sl.degraded:
            if any(not s.degraded for s in self.slices):
                return False
            job.stolen = False
            sl.current = job
            return True
        if self._devices_held(sl):
            return False
        preferred = self._preferred_names(job)
        if not preferred or sl.name in preferred:
            job.stolen = False
            sl.current = job
            return True
        for other in self.slices:
            if other.name in preferred and other.current is None \
                    and not self._devices_held(other):
                return False  # a preferred slice is free: leave it the job
        job.stolen = True
        sl.current = job
        return True

    # ----------------------------------------------------------- executor --
    def _spawn_executor(self, sl: _Slice,
                        degraded: bool | None = None) -> None:
        if degraded is not None:
            with self._lock:
                sl.degraded = degraded
                self.degraded = all(s.degraded for s in self.slices)
                if not self.degraded:
                    # reason set iff flag set (the alerting contract):
                    # a recovery that un-degrades the pool clears it
                    self.degrade_reason = None
        sl.gen += 1
        gen = sl.gen
        sl.thread = threading.Thread(
            target=self._executor_loop, args=(sl, gen),
            name=f"spgemmd-executor-{sl.name}-{gen}", daemon=True)
        sl.thread.start()

    def _executor_loop(self, sl: _Slice, gen: int) -> None:
        from spgemm_tpu.ops import plancache  # noqa: PLC0415
        from spgemm_tpu.utils.timers import ENGINE  # noqa: PLC0415

        while not self._stop.is_set() and gen == sl.gen:
            # the gen re-check inside accept retires this executor even
            # while it is blocked in next(): a recovery reinstatement
            # bumps sl.gen mid-poll, and without the re-check the retired
            # generation could still claim one more job before the loop
            # top notices (the live-claim refusal in _accepts closes the
            # residual read-then-bump window)
            job = self.queue.next(
                timeout=0.2,
                accept=lambda j: gen == sl.gen and self._accepts(sl, j))
            if job is None:
                # the autotune trial lane: an idle tick (no job claimed)
                # may run AT MOST one timed trial leg, and only while
                # the whole pool is idle -- a real job always wins the
                # next tick because run_trial_leg's heartbeat preempts
                # the leg the moment the queue goes nonempty
                self._maybe_tune(sl, gen)
                continue
            if job.state != "queued":  # reaped while still in the FIFO
                if sl.current is job:
                    sl.current = None  # release the dispatch claim
                continue
            # pickup-time placement: recorded BEFORE start() so the
            # watchdog's executor-death sweep can attribute the job to
            # this slice from its first instant.  A lone single-device
            # slice keeps the legacy default placement (the exact
            # pre-pool daemon, the SPGEMM_TPU_SERVE_SLICES=1 A/B); any
            # multi-slice pool -- and a lone WIDE slice (--slices 1x4
            # must shard, never silently shrink to one device) -- pins
            # the slice's devices
            job.slice = sl.name
            job.device_ids = sl.device_ids \
                if len(self.slices) > 1 or sl.width > 1 else None
            # autotune activation: resolve the job's structure class
            # (admission group key x device kind) and swap the process
            # overlay to ITS promoted vector -- replace-atomic, so a
            # class with no override restores the base vector.  The
            # estimator-accuracy baseline rides the job for the
            # terminal-side adaptation diff.  All no-ops (overlay stays
            # {} = {}) under SPGEMM_TPU_TUNE=0 or for untuned classes:
            # the phase never accumulates, the scrape stays identical.
            job.tune_class = plancache.tune_class_key(
                job.group_key, self._tune_device_kind)
            overlay = tune_mod.TUNER.overlay_for(job.tune_class)
            if overlay != knobs.tuned_overlay():
                with ENGINE.phase("tune_apply"):
                    knobs.set_tuned(overlay)
            tcanary = tune_mod.TUNER.consume_canary(job.tune_class)
            job.est_base = obs_profile.est_stats()
            with self._lock:
                degraded = sl.degraded
                canary = sl.canary and not degraded
                if canary:
                    # the gate is CONSUMED by this one pickup ("first
                    # job" means first job): the next pickup can land
                    # before the watchdog's settle tick observes this
                    # one's outcome, and must not be tightened too.
                    # canary_job keeps failure attribution -- a wedge
                    # during the audition still doubles the backoff in
                    # _degrade_slice
                    sl.canary = False
                    sl.canary_job = job
                sl.jobs_total += 1
                if job.stolen:
                    sl.steals += 1
            if canary:
                # the canary gate: the first job after a recovery
                # reinstatement runs under a TIGHTENED deadline -- if the
                # device is still wedged, the watchdog reaps fast and the
                # re-degrade (which doubles the recovery backoff) costs
                # one cheap job, not a full deadline.  Half the job's own
                # deadline when it has one; else the wedge grace window
                # (sized to one whole multiply) bounds the probe work.
                tight = job.timeout_s / 2 if job.timeout_s > 0 \
                    else self._wedge_grace_s
                if tight > 0:
                    job.timeout_s = tight
                obs_events.emit("slice_canary", slice=sl.name,
                                job_id=job.id, timeout_s=job.timeout_s)
            if tcanary:
                # the TUNED-OVERRIDE canary (PR 13's recovery-canary
                # gate, reused for rollout): the first job under a
                # freshly promoted knob vector runs a tightened deadline
                # -- if the vector somehow misbehaves at scale, the reap
                # costs one cheap job and note_terminal reverts + backs
                # off.  Same tightening arithmetic as the slice canary.
                tight = job.timeout_s / 2 if job.timeout_s > 0 \
                    else self._wedge_grace_s
                if tight > 0:
                    job.timeout_s = tight
            # cross-job batching (SPGEMM_TPU_SERVE_BATCH_K/_WINDOW_S):
            # a batchable head drains same-structure mates and the whole
            # group runs as one fused pickup.  Degraded and canary
            # pickups never batch (the failover path has no fused
            # runner; an audition -- slice recovery OR tuned-override
            # rollout -- must risk exactly one job).
            mates = [] if degraded or canary or tcanary \
                else self._drain_batch_mates(sl, job)
            if mates:
                self._run_batch_members(sl, job, mates)
                continue
            job.start()
            # the backend-wedge signature, injected: the executor hangs
            # right where a dead device would hang it -- after pickup,
            # before any result exists
            failpoints.check("serve.executor")
            if job.stolen:
                ENGINE.incr("serve_steals")
            scope = ENGINE.scope()
            # stashed on the job BEFORE it becomes sl.current: the watchdog
            # reads it to attach per-job detail when reaping, and must
            # never see a current job without its scope (the plan-cache
            # baseline rides along for the same reason: per-job cache
            # figures diff against pickup, like the PhaseScope does)
            job.scope, job.scope_degraded = scope, degraded
            job.cache_base = plancache.baseline()
            sl.current = job
            try:
                # every span this job's work emits (executor thread + the
                # plan-ahead / OOC workers it spawns, which adopt the
                # attribution) carries the job id, the END-TO-END trace
                # context (client-minted at submit, protocol v3 -- not
                # the job id: the id is this daemon's namespace, the
                # trace crosses processes) AND the slice name; queue
                # wait is the first per-job phase so a scraper sees
                # admission latency
                with obs_trace.RECORDER.tagged(job_id=job.id,
                                               trace_id=job.trace_id,
                                               slice=sl.name):
                    obs_events.emit("job_start", degraded=degraded,
                                    folder=job.folder, slice=sl.name,
                                    tenant=job.tenant, stolen=job.stolen)
                    # open this job's HBM watermark window (keyed by job
                    # id: a wedged predecessor's late samples land in
                    # ITS window, never this job's)
                    obs_profile.memory_job_begin(job.id)
                    ENGINE.record("serve_queue_wait",
                                  max(0.0, (job.started_at
                                            or job.submitted_at)
                                      - job.submitted_at))
                    with ENGINE.phase("serve_execute"):
                        self._runner(job, degraded=degraded)
            except JobAbandoned:
                # the watchdog already finished this job (reap / presumed
                # death); its chain aborted at the next multiply boundary
                # -- nothing to record, just move on to live work
                log.info("job %s abandoned mid-chain", job.id)
            except Exception as e:  # noqa: BLE001 -- a job must not kill the loop
                log.warning("job %s failed: %r", job.id, e)
                if job.finish("failed", error={
                        "code": protocol.E_JOB_ERROR, "message": repr(e)},
                        detail=self._job_detail(scope, degraded, job),
                        on_commit=lambda: self._journal_append(
                            {"event": "failed", "id": job.id})):
                    self._observe_terminal(job, "error")
                    obs_events.emit("job_failed", job_id=job.id,
                                    error=repr(e))
                # a structured job error still PROVES the executor alive
                # and responsive: the canary gate discriminates wedges,
                # not job-level failures.  Only a HEALTHY pickup settles
                # -- a straggler the degraded executor picked before the
                # reinstatement ran the CPU oracle and proves nothing
                # about the device
                if not degraded:
                    self._canary_settle(sl)
                warmstore.flush()  # terminal event: persist what the job warmed
            else:
                if job.finish("done",
                              detail=self._job_detail(scope, degraded, job),
                              on_commit=lambda: self._journal_append(
                                  {"event": "done", "id": job.id})):
                    self._observe_terminal(job, "done")
                    obs_events.emit("job_done", job_id=job.id)
                if not degraded:  # healthy pickups only, as above
                    self._canary_settle(sl)
                warmstore.flush()  # terminal event: persist what the job warmed
            finally:
                # detach the per-job collector: a wedged executor that
                # unwedges hours later closes the OLD job's scope here --
                # while it was wedged, its accumulation stayed attributed
                # to that scope, never the replacement executor's job
                scope.close()
                # an abandoned (wedged) executor can unwedge long after a
                # replacement took over: only clear the slot if it is
                # still ours, never the successor's current job
                if sl.current is job:
                    sl.current = None

    # ------------------------------------------------------------ autotune --
    def _maybe_tune(self, sl: _Slice, gen: int) -> None:
        """One idle-tick autotune hook (ARCHITECTURE.md "L6 autotune
        lifecycle"): with the trial lane armed
        (SPGEMM_TPU_TUNE_TRIAL_S > 0 and SPGEMM_TPU_TUNE on), an
        executor whose queue poll came up empty may run AT MOST one
        timed trial leg -- and only while the WHOLE pool is idle (any
        slice mid-job skews the measurement and a trial must never
        contend for the device a real job is about to want).  The
        cadence stamp is claimed under _lock so a many-slice pool still
        runs one leg per cadence window, not one per slice.  Trial legs
        are invisible to tenant DRR, admission, and the SLO windows by
        construction: they never touch the queue or Job machinery."""
        if self._stop.is_set() or gen != sl.gen:
            return
        cadence = tune_mod.trial_cadence_s()
        if cadence <= 0 or not tune_mod.enabled():
            return
        now = time.monotonic()
        with self._lock:
            if sl.degraded or sl.canary:
                return  # never trial on an untrusted / auditioning slice
            if any(s.current is not None for s in self.slices):
                return  # pool not idle: a real job is running somewhere
            if now - self._tune_last_trial < cadence:
                return
            self._tune_last_trial = now
        if self.queue.counts()["depth"] > 0:
            return  # work already waiting beats any trial
        tune_mod.run_trial_leg(self._tune_run_fn(sl, gen),
                               placement.rep_folder,
                               extra={"SPGEMM_TPU_DELTA": "0"})

    def _tune_run_fn(self, sl: _Slice, gen: int):
        """The trial leg's chain runner: read the class's representative
        folder, reduce the chain exactly as a solo job would, and return
        a content digest of the result (the tuner's parity spot-check --
        every candidate vector must reproduce the baseline leg's bits).
        The heartbeat chain_product plants between multiplies raises
        TrialPreempted the moment a real job is queued, the daemon is
        stopping, or this executor generation retired: a trial yields
        the device within one multiply boundary, the same granularity as
        the watchdog's abandonment contract.  Trials run on the
        process-default device placement -- every pool device is the
        same kind, so the wall-clock ranking transfers to any slice; the
        leg runs under SPGEMM_TPU_DELTA=0 (run_trial_leg's `extra` pin),
        so repeats are never answered from the delta store's retained
        result."""
        def run(folder: str) -> str:
            import hashlib  # noqa: PLC0415

            from spgemm_tpu import chain  # noqa: PLC0415
            from spgemm_tpu.ops import plancache  # noqa: PLC0415
            from spgemm_tpu.utils import io_text  # noqa: PLC0415

            def beat() -> None:
                if self._stop.is_set() or gen != sl.gen \
                        or self.queue.counts()["depth"] > 0:
                    raise tune_mod.TrialPreempted(folder)

            beat()  # a job may have landed between the claim and here
            n, k = io_text.read_size(folder)
            mats = io_text.read_chain(folder, 0, n - 1, k)
            result = chain.chain_product(mats, heartbeat=beat)
            h = hashlib.sha256()
            plancache.hash_update(h, result.coords)
            plancache.hash_update(h, result.tiles)
            return h.hexdigest()
        return run

    # ------------------------------------------------------------ batching --
    def _drain_batch_mates(self, sl: _Slice, head: Job) -> list[Job]:
        """Batch-formation half of cross-job batching: with the window
        armed (SPGEMM_TPU_SERVE_BATCH_WINDOW_S > 0) and a batchable head
        in hand, drain up to SPGEMM_TPU_SERVE_BATCH_K - 1 queued mates
        sharing the head's structure group key and option class.  The
        drain rides the queue's own DRR pass, so tenant fairness and
        per-tenant caps are decided BEFORE batch formation; the window
        only opens after a head was already popped, so an idle pool
        never waits.  Window 0 returns [] without touching anything --
        exactly the pre-batch executor (the whole-feature A/B)."""
        window_s = knobs.get("SPGEMM_TPU_SERVE_BATCH_WINDOW_S")
        if window_s <= 0:
            return []
        batch_k = knobs.get("SPGEMM_TPU_SERVE_BATCH_K")
        # jobs that cannot co-batch run solo: no recorded structure
        # (first contact), wide slice (the rowshard multiply has no
        # fused path), delta-eligible submits (retention would splice
        # across jobs), checkpoint/failover (per-job chain state)
        if batch_k <= 1 or sl.width > 1 or head.group_key is None \
                or knobs.get("SPGEMM_TPU_DELTA") \
                or head.options.get("checkpoint_dir") \
                or head.options.get("failover"):
            return []

        def match(j: Job) -> bool:
            # runs under the QUEUE lock via drain_batch's DRR pass:
            # cheap attribute reads only.  Same structure, same deadline
            # class, same kernel-affecting options -- mates must walk
            # the head's exact plan sequence.
            return (j.group_key == head.group_key
                    and j.timeout_s == head.timeout_s
                    and not j.options.get("checkpoint_dir")
                    and not j.options.get("failover")
                    and j.options.get("backend")
                    == head.options.get("backend")
                    and j.options.get("round_size")
                    == head.options.get("round_size"))

        mates = self.queue.drain_batch(batch_k - 1, window_s, match)
        # the batch-size histogram samples every ARMED batchable pickup
        # (size 1 = no mates arrived inside the window): the denominator
        # an operator needs to judge the window length
        with self._lock:
            hist = self._batch_size
            size = 1 + len(mates)
            hist["sum"] += size
            hist["count"] += 1
            for le in hist["buckets"]:
                if size <= le:
                    hist["buckets"][le] += 1
        return mates

    def _run_batch_members(self, sl: _Slice, head: Job,
                           mates: list[Job]) -> None:
        """Execution half of cross-job batching: the head + its drained
        mates run as ONE fused pickup (the batch runner's lockstep
        reduction).  Every member keeps its OWN PhaseScope (all opened on
        this executor thread, so the fused phases land in each member's
        scope -- the truth: they all rode the launches), its own
        journal/SLO/event records and its own end-to-end trace context;
        spans carry the shared batch_id (= the head's job id) next to the
        head's tags.  Only the head is sl.current -- the watchdog's
        reap/wedge slot -- so a head reap aborts the whole batch at the
        next multiply boundary and the surviving mates get a structured
        error."""
        from spgemm_tpu.ops import plancache  # noqa: PLC0415
        from spgemm_tpu.utils.timers import ENGINE  # noqa: PLC0415

        # a mate reaped while still in the FIFO was already finished and
        # observed by the watchdog: dropping it here is the batch-shaped
        # terminal-in-FIFO skip
        jobs = [head] + [m for m in mates if m.state == "queued"]
        batch_id = head.id
        fused = len(jobs) > 1
        for m in jobs[1:]:
            m.slice = sl.name
            m.device_ids = head.device_ids
        with self._lock:
            sl.jobs_total += len(jobs) - 1  # head counted at pickup
        if fused:
            ENGINE.incr("serve_batches")
            ENGINE.incr("serve_batched_jobs", len(jobs))
            for j in jobs:
                j.batch_id = batch_id
        for job in jobs:
            job.start()
        failpoints.check("serve.executor")
        if head.stolen:
            ENGINE.incr("serve_steals")
        scopes = [ENGINE.scope() for _ in jobs]
        cache_base = plancache.baseline()
        for job, scope in zip(jobs, scopes):
            job.scope, job.scope_degraded = scope, False
            job.cache_base = cache_base
        sl.current = head
        tags = {"job_id": head.id, "trace_id": head.trace_id,
                "slice": sl.name}
        if fused:
            tags["batch_id"] = batch_id
        try:
            with obs_trace.RECORDER.tagged(**tags):
                for job, scope in zip(jobs, scopes):
                    obs_events.emit(
                        "job_start", degraded=False, folder=job.folder,
                        slice=sl.name, tenant=job.tenant,
                        stolen=job.stolen, job_id=job.id,
                        trace_id=job.trace_id,
                        **({"batch_id": batch_id} if fused else {}))
                    # per-member queue wait into exactly that member's
                    # scope (PhaseScope.record -- the ambient
                    # ENGINE.record would fan out to every open scope)
                    scope.record("serve_queue_wait",
                                 max(0.0, (job.started_at
                                           or job.submitted_at)
                                     - job.submitted_at))
                # the HBM watermark window keys by the span job tag, and
                # the ambient tag is the head's id: one shared window
                obs_profile.memory_job_begin(head.id)
                with ENGINE.phase("serve_execute"):
                    if fused:
                        self._batch_runner(jobs, degraded=False)
                    else:
                        self._runner(head, degraded=False)
        except JobAbandoned:
            # the watchdog reaped the HEAD (the batch's sl.current slot)
            # and the runner aborted at a multiply boundary: the head's
            # terminal record is already committed; surviving mates get
            # a structured error naming the shared fate
            log.info("job %s abandoned mid-chain (batch of %d)",
                     head.id, len(jobs))
            for job, scope in zip(jobs[1:], scopes[1:]):
                if job.finish("failed", error={
                        "code": protocol.E_JOB_ERROR,
                        "message": f"co-batched with job {head.id}, "
                                   "which was reaped mid-chain; "
                                   "resubmit"},
                        detail=self._job_detail(scope, False, job),
                        on_commit=lambda j=job: self._journal_append(
                            {"event": "failed", "id": j.id})):
                    self._observe_terminal(job, "error")
                    obs_events.emit("job_failed", job_id=job.id,
                                    trace_id=job.trace_id,
                                    batch_id=batch_id,
                                    error="co-batched head reaped")
            warmstore.flush()
        except Exception as e:  # noqa: BLE001 -- a job must not kill the loop
            log.warning("batch %s failed: %r", batch_id, e)
            for job, scope in zip(jobs, scopes):
                if job.finish("failed", error={
                        "code": protocol.E_JOB_ERROR,
                        "message": repr(e)},
                        detail=self._job_detail(scope, False, job),
                        on_commit=lambda j=job: self._journal_append(
                            {"event": "failed", "id": j.id})):
                    self._observe_terminal(job, "error")
                    obs_events.emit("job_failed", job_id=job.id,
                                    trace_id=job.trace_id, error=repr(e))
            self._canary_settle(sl)
            warmstore.flush()
        else:
            for job, scope in zip(jobs, scopes):
                if job.finish("done",
                              detail=self._job_detail(scope, False, job),
                              on_commit=lambda j=job: self._journal_append(
                                  {"event": "done", "id": j.id})):
                    self._observe_terminal(job, "done")
                    obs_events.emit("job_done", job_id=job.id,
                                    trace_id=job.trace_id,
                                    **({"batch_id": batch_id}
                                       if fused else {}))
            self._canary_settle(sl)
            warmstore.flush()
        finally:
            for scope in scopes:
                scope.close()
            if sl.current is head:
                sl.current = None

    @staticmethod
    def _job_detail(scope, degraded: bool, job: Job | None = None) -> dict:
        """The per-job status detail: the same phases_s + engine counters
        bench.py emits, scoped to this job alone (PhaseScope diff).
        The job's plan-cache block diffs the counter baseline captured at
        pickup -- so the detail reports THIS job's hit/miss/eviction
        deltas, not process-lifetime totals."""
        from spgemm_tpu.ops import plancache  # noqa: PLC0415
        job_id = job.id if job is not None else None
        cache_base = job.cache_base if job is not None else None
        try:
            cache_scoped = plancache.stats(since=cache_base)
        except ValueError as e:
            cache_scoped = {"error": str(e)}
        counters = scope.counter_snapshot()
        # per-job HBM high-water mark (obs/profile window keyed by job
        # id); None on backends without memory_stats -> key omitted,
        # never a zero that reads as "no memory used"
        hbm_peak = obs_profile.memory_job_peak(job_id)
        return {"phases_s": scope.snapshot(), "degraded": degraded,
                "plan_cache": cache_scoped,
                **({"hbm_peak_bytes": hbm_peak}
                   if hbm_peak is not None else {}),
                **({"slice": job.slice, "stolen": job.stolen,
                    "tenant": job.tenant}
                   if job is not None else {}),
                "plan_cache_hits": counters.get("plan_cache_hits", 0),
                "plan_cache_misses": counters.get("plan_cache_misses", 0),
                # the delta-recompute ratio (ops/delta): output tile-rows
                # this job actually re-folded vs carried over from the
                # retained previous results -- a second submit of a
                # mostly-unchanged input reports delta_rows << total_rows
                "delta_rows": counters.get("delta_rows_recomputed", 0),
                "total_rows": counters.get("delta_rows_total", 0),
                **{k: v for k, v in counters.items()
                   if k not in ("plan_cache_hits", "plan_cache_misses",
                                "delta_rows_recomputed",
                                "delta_rows_total")}}

    def _reap_detail(self, job: Job) -> dict | None:
        """Best-effort per-job detail for a watchdog-reaped job, from the
        executor's live PhaseScope (thread-safe: timers are lock-guarded).
        The one job an operator most needs to diagnose -- it hit its
        deadline -- must not lose its phases_s/counters to the reap."""
        scope = job.scope
        if scope is None:
            return None
        return self._job_detail(scope, job.scope_degraded, job)

    # ------------------------------------------------------ observability --
    def _observe_terminal(self, job: Job, outcome: str) -> None:
        """Bookkeeping for a terminal transition THIS daemon committed
        (call only when Job.finish returned True): daemon-lifetime outcome
        totals + the job-wall histogram behind `stats` and the Prometheus
        surface, the fair queue's per-tenant in-flight release, and one
        record into the SLO engine's rolling (tenant, slice) window."""
        self.queue.release(job)
        snap = job.snapshot()
        started = snap["started_at"] or snap["submitted_at"]
        wall = max(0.0, (snap["finished_at"] or time.time()) - started)
        with self._lock:
            self._terminal_totals[outcome] = \
                self._terminal_totals.get(outcome, 0) + 1
            hist = self._job_wall
            hist["sum"] += wall
            hist["count"] += 1
            for le in hist["buckets"]:
                if wall <= le:
                    hist["buckets"][le] += 1
        # the SLO record (outside _lock: the engine has its own lock and
        # daemon/engine locks must never nest): queue wait = admission to
        # pickup (the whole wall for a job reaped before it ever started)
        queue_wait = max(0.0, (snap["started_at"]
                               or snap["finished_at"]
                               or snap["submitted_at"])
                         - snap["submitted_at"])
        obs_slo.SLO.observe(tenant=job.tenant,
                            slice_name=job.slice or "unplaced",
                            wall_s=wall, queue_wait_s=queue_wait,
                            error=outcome != "done",
                            trace_id=job.trace_id)
        # autotune terminal feed (outside _lock, like the SLO record --
        # the tuner has its own lock and daemon/engine locks never
        # nest): register the class sighting + its representative
        # folder for the idle trial lane, settle an in-flight override
        # canary on this job's outcome, and score the estimator's
        # accuracy over the job (the pickup baseline diffs against the
        # live obs/profile account) for the class's sample/confidence
        # adaptation.  job.tune_class is None for first-contact and
        # replayed jobs -- every call below no-ops then.
        tune_ck = getattr(job, "tune_class", None)
        if tune_ck is not None:
            tune_mod.TUNER.note_job(tune_ck, self._tune_device_kind)
            placement.note_class(tune_ck, job.folder)
            tune_mod.TUNER.note_terminal(tune_ck, outcome == "done")
            base = job.est_base
            if base is not None:
                cur = obs_profile.est_stats()
                errs = []
                for qty, hist in cur["rel_error"].items():
                    prev = (base.get("rel_error") or {}).get(
                        qty, {"sum": 0.0, "count": 0})
                    dn = hist["count"] - prev["count"]
                    if dn > 0:
                        errs.append((hist["sum"] - prev["sum"]) / dn)
                if errs:
                    tune_mod.TUNER.note_est_accuracy(
                        tune_ck, sum(errs) / len(errs))

    def _flight_dump(self, name: str) -> str | None:
        """Snapshot the span flight recorder next to the journal
        (<socket>.flight/<name>.trace.json, Perfetto trace_event JSON) --
        the postmortem evidence for a reap/wedge/degrade.  Best-effort:
        diagnostics must never take down the device owner."""
        path = os.path.join(self.flight_dir, f"{name}.trace.json")
        try:
            obs_trace.dump_json(path)
        except OSError as e:
            log.warning("flight dump %s failed: %r", path, e)
            return None
        # retention: drop the oldest dumps past FLIGHT_RETAIN so a
        # perpetually-reaping daemon cannot exhaust the disk the device
        # owner lives on.  Ordering is this process's write order (mtime
        # ties within one reap burst on a coarse-mtime filesystem must
        # never evict the dump just written); leftovers from a previous
        # daemon run order by mtime, ahead of anything written in this
        # one.  A prune failure (a cleanup cron racing listdir/unlink) is
        # its own warning -- the dump above LANDED, and an incident
        # responder must not be told the evidence is missing.
        try:
            with self._lock:
                if path in self._flight_order:
                    self._flight_order.remove(path)  # re-dump: now newest
                self._flight_order.append(path)
                ours = list(self._flight_order)
            on_disk = {os.path.join(self.flight_dir, f)
                       for f in os.listdir(self.flight_dir)
                       if f.endswith(".trace.json")}
            ordered = sorted(on_disk - set(ours), key=os.path.getmtime) \
                + [p for p in ours if p in on_disk]
            for stale in ordered[:max(0, len(ordered)
                                      - self.FLIGHT_RETAIN)]:
                os.unlink(stale)
                with self._lock:
                    if stale in self._flight_order:
                        self._flight_order.remove(stale)
        except OSError as e:
            log.warning("flight-dump retention prune failed (dump %s "
                        "still on disk): %r", path, e)
        log.info("flight recorder dumped to %s", path)
        return path

    # ----------------------------------------------------------- watchdog --
    def _watchdog_loop(self) -> None:
        """Reap overdue jobs; detect executor death and wedging -- per
        slice.

        Death (the thread is gone -- runner raised a BaseException, or a
        test killed it) and wedging (a reaped job's executor still has not
        moved on after the grace window -- the backend-hang signature) both
        degrade THAT SLICE to the CPU failover path: its device cannot be
        trusted, but the rest of the pool keeps serving, and the degraded
        slice still serves host-only once every slice is down."""
        while not self._stop.wait(0.05):
            for sl in self.slices:
                self._watch_slice(sl)
                self._maybe_recover(sl)

    def _watch_slice(self, sl: _Slice) -> None:
        job = sl.current
        ex = sl.thread
        if ex is not None and not ex.is_alive():
            # sweep every running job this slice owns, not just
            # sl.current: a dying thread's finally may have cleared the
            # slot already
            reason = f"executor thread for slice {sl.name} died"
            for orphan in self.queue.running():
                if orphan.slice != sl.name:
                    continue
                if orphan.finish("failed", error={
                        "code": protocol.E_EXECUTOR_DIED,
                        "message": "executor thread died mid-job"},
                        detail=self._reap_detail(orphan),
                        on_commit=lambda o=orphan: self._journal_append(
                            {"event": "failed", "id": o.id})):
                    reason += f" during job {orphan.id}"
                    self._observe_terminal(orphan, "abandoned")
                    self._flight_dump(orphan.id)
            self._degrade_slice(sl, reason)
            return
        if job is not None and sl.reaped is not job and job.overdue():
            # finish() is first-write-wins: a job that completed a
            # beat before the deadline check stays done (no spurious
            # failed journal event) and is never treated as a wedge
            if job.finish("failed", error={
                    "code": protocol.E_JOB_TIMEOUT,
                    "message": f"job exceeded its {job.timeout_s:g}s "
                               "deadline and was reaped"},
                    detail=self._reap_detail(job),
                    on_commit=lambda: self._journal_append(
                        {"event": "failed", "id": job.id})):
                sl.reaped, sl.reaped_at = job, time.time()
                # the reap's postmortem evidence: a counter on the
                # Prometheus surface, an instant marker in the span
                # timeline, and the flight dump an operator opens
                # first
                from spgemm_tpu.utils.timers import ENGINE  # noqa: PLC0415
                ENGINE.incr("serve_reaps")
                obs_trace.RECORDER.instant("serve_reap",
                                           job_id=job.id,
                                           trace_id=job.trace_id,
                                           slice=sl.name)
                obs_events.emit("watchdog_reap", job_id=job.id,
                                trace_id=job.trace_id,
                                timeout_s=job.timeout_s, slice=sl.name)
                self._observe_terminal(job, "timeout")
                self._flight_dump(job.id)
        reaped = sl.reaped
        if reaped is not None and sl.current is reaped:
            hb = reaped.heartbeat_at or 0.0
            if hb > sl.reaped_at:
                # the job heartbeats (chain_product calls touch after
                # every multiply): the executor is slow but PROGRESSING
                # inside a reaped job, not wedged in a hung backend
                # call -- restart the grace window at the newest beat
                sl.reaped_at = hb
            elif time.time() - sl.reaped_at > self._wedge_grace_s:
                sl.reaped = None
                self._flight_dump(f"{reaped.id}.wedged")
                obs_events.emit("watchdog_wedge", job_id=reaped.id,
                                trace_id=reaped.trace_id,
                                grace_s=self._wedge_grace_s,
                                slice=sl.name)
                self._degrade_slice(sl, f"executor wedged on reaped job "
                                        f"{reaped.id}")
        elif reaped is not None and sl.current is not reaped:
            sl.reaped = None  # executor moved on: slow, not wedged
            # a CANARY job reaped but outlived by its executor settles
            # the gate: moving on proves the device executes (the wedge
            # signature is the opposite), so the tightened deadline must
            # not outlive the audition -- without this, a deadline-less
            # deployment would reap every long job on a healthy
            # recovered slice forever ("first job" means first job)
            self._canary_settle(sl)

    def _degrade_slice(self, sl: _Slice, reason: str) -> None:
        """Abandon the slice's executor, record why, probe the backend (a
        subprocess -- the only safe touch of a possibly-dead device) and
        spawn a replacement executor for the slice pinned to the host-only
        oracle.  The slice is excluded from placement while any healthy
        slice remains; the daemon-level degraded flag trips only when the
        whole pool is down."""
        if self._stop.is_set():
            return
        with self._lock:
            any_before = any(s.degraded for s in self.slices)
            already = sl.degraded
            sl.degraded = True
            sl.degrade_reason = reason
            # recovery bookkeeping: a FAILED CANARY (re-degrade while the
            # reinstatement's first job was still gating) doubles the
            # backoff -- the device lied to the probe once, make it wait
            # longer before the next audition; a fresh degrade starts the
            # cadence at the knob's base
            if sl.canary or sl.canary_job is not None:
                # armed-but-unconsumed gate and in-flight audition alike:
                # the device lied to the probe, whatever failed here
                sl.canary = False
                sl.canary_job = None
                self._bump_backoff_locked(sl)
            elif not already:
                sl.recover_backoff = self._recover_s
                sl.recover_next = time.time() + sl.recover_backoff
            # already-degraded re-degrade (e.g. the CPU-failover executor
            # itself died): keep the accumulated exponential backoff --
            # resetting it would resume probing a known-dead device at
            # the base cadence
            self.degraded = all(s.degraded for s in self.slices)
            if self.degraded:
                # the daemon-level reason is set if-and-only-if the
                # daemon-level flag is (the pre-pool alerting contract):
                # a healthy pool with one bad slice reports the reason
                # per-slice, never as a whole-daemon degrade
                self.degrade_reason = reason
        # service first, diagnostics second: the replacement host-only
        # executor needs nothing from the probe, and the probe subprocess
        # can block for the full SPGEMM_TPU_PROBE_TIMEOUT (default 150 s)
        # against a dead device -- queued jobs must not wait on it, and
        # neither may the watchdog (it still has reaping to do), so the
        # probe runs on its own thread and only feeds stats
        self._spawn_executor(sl, degraded=True)
        if already:
            return
        log.warning("slice %s degrading to CPU failover path: %s",
                    sl.name, reason)
        from spgemm_tpu.utils.timers import ENGINE  # noqa: PLC0415
        ENGINE.incr("serve_degrades")
        obs_trace.RECORDER.instant("serve_degrade", job_id=None,
                                   slice=sl.name)
        obs_events.emit("daemon_degrade", reason=reason, slice=sl.name)
        # the single-slice daemon keeps its historical dump name; pool
        # slices get one postmortem each
        self._flight_dump("degrade" if len(self.slices) == 1
                          else f"degrade.{sl.name}")
        if any_before:
            return  # one probe per healthy->degraded transition is enough
        probe = self._probe
        if probe is None:
            from spgemm_tpu.utils.backend_probe import (  # noqa: PLC0415
                probe_default_backend)
            probe = probe_default_backend

        def _run_probe() -> None:
            try:
                outcome = probe()
            except Exception as e:  # noqa: BLE001 -- diagnostics must not raise
                outcome = f"probe-error: {e!r}"
            with self._lock:
                self._probe_outcome = outcome
            log.warning("backend probe after degrade: %s", outcome)

        threading.Thread(target=_run_probe, name="spgemmd-probe",
                         daemon=True).start()

    # ----------------------------------------------------------- recovery --
    def _bump_backoff_locked(self, sl: _Slice) -> None:
        """Double a degraded slice's recovery backoff and re-arm its
        timer (caller holds _lock) -- the ONE backoff policy, shared by
        the failed-canary re-degrade and the dead-probe outcome so the
        two paths can never drift onto divergent curves."""
        sl.recover_backoff = min(
            max(sl.recover_backoff, self._recover_s) * 2,
            self.RECOVER_BACKOFF_MAX_S)
        sl.recover_next = time.time() + sl.recover_backoff

    def _maybe_recover(self, sl: _Slice) -> None:
        """Watchdog tick half of self-healing: when the recovery knob is
        on and a degraded slice's backoff window has elapsed, launch one
        re-probe off-thread (the probe subprocess can block for the full
        SPGEMM_TPU_PROBE_TIMEOUT against a dead device -- the watchdog
        still has reaping to do)."""
        if self._recover_s <= 0 or self._stop.is_set():
            return
        with self._lock:
            if not sl.degraded or sl.probing \
                    or time.time() < sl.recover_next:
                return
            sl.probing = True
        threading.Thread(target=self._recover_probe, args=(sl,),
                         name=f"spgemmd-recover-{sl.name}",
                         daemon=True).start()

    def _recover_probe(self, sl: _Slice) -> None:
        """One recovery attempt for a degraded slice: probe the backend
        from a subprocess; a live outcome ('ok'/'cpu') reinstates the
        slice into placement behind the canary gate (its next job runs a
        tightened deadline; a canary failure re-degrades and doubles the
        backoff in _degrade_slice), a dead outcome doubles the backoff
        and re-arms the timer."""
        probe = self._probe
        if probe is None:
            from spgemm_tpu.utils.backend_probe import (  # noqa: PLC0415
                probe_default_backend)
            probe = probe_default_backend
        try:
            outcome = probe()
        except Exception as e:  # noqa: BLE001 -- a crashing probe is a dead device, never a dead watchdog
            outcome = f"probe-error: {e!r}"
        live = outcome in ("ok", "cpu")
        with self._lock:
            sl.probing = False
            self._probe_outcome = outcome
            if self._stop.is_set() or not sl.degraded:
                return  # raced a shutdown or a concurrent reinstatement
            if not live:
                self._bump_backoff_locked(sl)
            else:
                sl.canary = True
                sl.canary_job = None
                sl.recoveries += 1
                sl.recovered_at = time.time()
                sl.degrade_reason = None
                # keep the doubled backoff until the canary PASSES: a
                # device that probes live but wedges the canary must not
                # re-audition at the base cadence forever
                sl.recover_next = time.time() + max(sl.recover_backoff,
                                                    self._recover_s)
                # the reinstatement is ATOMIC with the bookkeeping above:
                # flipping degraded / spawning after releasing the lock
                # would let a concurrent _degrade_slice (the degraded
                # executor dying in the window) clear the canary and then
                # be stomped by our healthy spawn -- the slice would
                # rejoin placement unaudited with a stale degrade_reason.
                # _spawn_executor takes no lock when degraded is None (the
                # flag recompute is done right here).
                sl.degraded = False
                self.degraded = all(s.degraded for s in self.slices)
                if not self.degraded:
                    self.degrade_reason = None
                # spgemm-lint: lck-ok(_spawn_executor's `with self._lock:` branch is gated on degraded is not None, and this call passes degraded=None -- the re-acquiring path is unreachable; the atomicity argument above is why the call must stay under the lock)
                self._spawn_executor(sl)
        if not live:
            obs_events.emit("slice_recover_probe", slice=sl.name,
                            outcome=outcome, live=False)
            log.info("slice %s recovery probe: %s (still degraded; next "
                     "attempt in %.1fs)", sl.name, outcome,
                     sl.recover_backoff)
            return
        from spgemm_tpu.utils.timers import ENGINE  # noqa: PLC0415
        ENGINE.incr("serve_recoveries")
        obs_trace.RECORDER.instant("serve_recover", job_id=None,
                                   slice=sl.name)
        obs_events.emit("slice_recovered", slice=sl.name, outcome=outcome)
        log.warning("slice %s reinstated after live probe (%s); first "
                    "job runs the canary gate", sl.name, outcome)

    def _canary_settle(self, sl: _Slice) -> None:
        """An executor-committed terminal outcome on a canary slice
        settles the canary: the executor is alive and responsive, so the
        slice graduates to full trust and the backoff resets (wedge-path
        failures never reach here -- they re-degrade via _degrade_slice,
        which doubles the backoff instead)."""
        with self._lock:
            if sl.degraded or (not sl.canary and sl.canary_job is None):
                return
            sl.canary = False
            sl.canary_job = None
            sl.recover_backoff = 0.0
        obs_events.emit("slice_canary_passed", slice=sl.name)

    # ----------------------------------------------------------- protocol --
    def _accept_loop(self, listener: socket.socket) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed during shutdown
            # injected admission latency: clients' connect/wait backoff
            # must ride out a stalling accept loop
            failpoints.check("serve.accept")
            with self._lock:
                admit = self._conn_count < self.MAX_CONNS
                if admit:
                    self._conn_count += 1
            if not admit:
                try:
                    conn.sendall(protocol.encode(protocol.error(
                        protocol.E_BUSY,
                        f"too many concurrent connections "
                        f"({self.MAX_CONNS}); retry shortly")))
                except OSError:
                    pass
                conn.close()
                continue
            conn.settimeout(self.CONN_IDLE_TIMEOUT_S)
            t = threading.Thread(target=self._handle_conn, args=(conn,),
                                 name="spgemmd-conn", daemon=True)
            t.start()

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            for line in protocol.read_lines(
                    conn, max_line=protocol.MAX_LINE_BYTES):
                # injected handler death mid-request: the finally below
                # must still close the socket and free the conn slot
                failpoints.check("serve.readline")
                if not line.strip():
                    continue
                try:
                    msg = protocol.parse_request(line)
                except protocol.ProtocolError as e:
                    resp = protocol.error(e.code, e.message)
                else:
                    try:
                        resp = self._dispatch(msg)
                    except Exception as e:  # noqa: BLE001 -- daemon must survive
                        log.warning("request handler failed: %r", e)
                        resp = protocol.error(protocol.E_INTERNAL, repr(e))
                conn.sendall(protocol.encode(resp))
        except protocol.ProtocolError as e:
            # oversized line: answer once, then drop the connection (the
            # pending buffer cannot be resynchronized to a line boundary)
            try:
                conn.sendall(protocol.encode(protocol.error(e.code,
                                                            e.message)))
            except OSError:
                pass
        except OSError:
            pass  # peer went away mid-conversation (or idled out)
        finally:
            conn.close()
            with self._lock:
                self._conn_count -= 1

    def _dispatch(self, msg: dict) -> dict:
        op = msg["op"]
        if op == "submit":
            return self._op_submit(msg)
        if op == "status":
            return self._op_status(msg)
        if op == "wait":
            return self._op_wait(msg)
        if op == "stats":
            return self._op_stats()
        if op == "metrics":
            return self._op_metrics()
        if op == "trace":
            return self._op_trace()
        if op == "profile":
            return self._op_profile()
        if op == "events":
            return self._op_events(msg)
        if op == "slo":
            return self._op_slo()
        return self._op_shutdown()

    def _op_submit(self, msg: dict) -> dict:
        if self._stop.is_set():
            return protocol.error(protocol.E_SHUTTING_DOWN,
                                  "daemon is shutting down")
        folder = msg.get("folder")
        if not isinstance(folder, str) or not folder:
            return protocol.error(protocol.E_BAD_REQUEST,
                                  "submit requires a non-empty `folder`")
        options = msg.get("options") or {}
        if not isinstance(options, dict):
            return protocol.error(protocol.E_BAD_REQUEST,
                                  "`options` must be a JSON object")
        unknown = sorted(set(options) - set(SUBMIT_OPTIONS))
        if unknown:
            return protocol.error(
                protocol.E_BAD_REQUEST,
                f"unknown submit option(s) {', '.join(unknown)} (known: "
                f"{', '.join(SUBMIT_OPTIONS)})")
        # the optional fair-queuing identity (protocol v2); absent maps
        # to the shared default tenant, exactly the v1 behavior.  The
        # name becomes a Prometheus label value and a stats key, so the
        # charset/length are validated at admission like option values.
        tenant = msg.get("tenant", protocol.DEFAULT_TENANT)
        if not protocol.valid_tenant(tenant):
            return protocol.error(
                protocol.E_BAD_REQUEST,
                f"tenant must be 1-{protocol.TENANT_MAX_LEN} chars of "
                f"[A-Za-z0-9._:-], got {tenant!r}")
        # the optional end-to-end trace context (protocol v3): present
        # but malformed is a bad-request (a client that tried to thread
        # a trace must hear it failed, not silently get a re-mint);
        # absent (v1/v2 clients) = the Job mints one
        trace_ctx = msg.get("trace")
        if trace_ctx is not None and not protocol.valid_trace(trace_ctx):
            return protocol.error(
                protocol.E_BAD_REQUEST,
                f"trace must be {protocol.TRACE_HEX_LEN} lowercase hex "
                f"chars (a 128-bit trace context), got {trace_ctx!r}")
        # option VALUES are validated at admission like option names: a
        # bad round_size/backend must answer bad-request here, not fail
        # the job later with an opaque job-error from inside the runner
        rs = options.get("round_size")
        if rs is not None:
            try:
                rs_ok = int(rs) >= 1
            except (TypeError, ValueError):
                rs_ok = False
            if not rs_ok:
                return protocol.error(
                    protocol.E_BAD_REQUEST,
                    f"round_size must be an integer >= 1, got {rs!r}")
        backend = options.get("backend")
        if backend is not None and backend not in protocol.CHAIN_BACKENDS:
            return protocol.error(
                protocol.E_BAD_REQUEST,
                f"unknown backend {backend!r} (known: "
                f"{', '.join(protocol.CHAIN_BACKENDS)})")
        if not os.path.isfile(os.path.join(folder, "size")):
            return protocol.error(
                protocol.E_BAD_REQUEST,
                f"{folder!r} is not a chain input directory (no `size` "
                "file)")
        output = options.get("output") or os.path.join(folder, "matrix")
        # an explicit 0 means "no deadline" (the knob's own semantics), so
        # only an ABSENT option falls back to the daemon default
        ts = options.get("timeout_s")
        try:
            timeout_s = float(self._job_timeout_s if ts is None else ts)
        except (TypeError, ValueError):
            return protocol.error(protocol.E_BAD_REQUEST,
                                  f"timeout_s must be a number, got {ts!r}")
        if timeout_s < 0:
            # a negative deadline would silently mean "no deadline"
            # (overdue() treats <= 0 as none) -- reject it like any other
            # bad option value instead of un-deadlining the job
            return protocol.error(
                protocol.E_BAD_REQUEST,
                f"timeout_s must be >= 0 (0 = no deadline), got {ts!r}")
        with self._lock:
            job_id = f"job-{self._next_id}"
            self._next_id += 1
        job = Job(job_id, folder, output, options, timeout_s=timeout_s,
                  tenant=tenant, trace_id=trace_ctx)
        # estimator-priced placement, decided at admission (cheap: a
        # price-book stat lookup, never a file parse) and carried on the
        # job for the slice executors' accept predicates
        job.placement = placement.route(folder)
        # cross-job batching group key, decided at admission like the
        # placement class (cheap: a stat signature + structure-book
        # lookup, never a file parse): jobs sharing it walk identical
        # plan sequences and may co-batch into one fused dispatch.  None
        # (first contact / changed folder) runs solo, the pre-batch path.
        from spgemm_tpu.ops import plancache  # noqa: PLC0415
        job.group_key = plancache.chain_structure(
            placement.signature(folder))
        # journal BEFORE enqueueing: the executor can pop and terminally
        # finish a job the instant it is queued, and its done/failed
        # journal event (committed inside Job.finish) must never precede
        # the submit record -- replay would resurrect finished work.
        # Journal-then-reject leaves at worst a submit record a matching
        # failed event cancels; journal-then-crash re-runs an admitted
        # job, which is the at-least-once contract restarts already have.
        self._journal_append({"event": "submit", "id": job.id,
                              "folder": folder, "output": output,
                              "options": options, "timeout_s": timeout_s,
                              "tenant": tenant, "trace": job.trace_id})
        try:
            depth = self.queue.submit(job)
        except QueueFull as e:
            self._journal_append({"event": "failed", "id": job.id})
            return protocol.error(
                protocol.E_QUEUE_FULL,
                f"queue full ({e.cap} jobs queued); retry later or raise "
                "SPGEMM_TPU_SERVE_QUEUE_CAP", id=None)
        except TenantCapExceeded as e:
            self._journal_append({"event": "failed", "id": job.id})
            return protocol.error(
                protocol.E_TENANT_CAP,
                f"tenant {e.tenant!r} already has {e.cap} jobs in flight; "
                "wait for one to finish or raise "
                "SPGEMM_TPU_SERVE_TENANT_INFLIGHT", id=None)
        obs_events.emit("job_submit", job_id=job.id, folder=folder,
                        queued=depth, tenant=tenant,
                        trace_id=job.trace_id,
                        placement=job.placement)
        return protocol.ok(id=job.id, state=job.state, queued=depth,
                           trace=job.trace_id)

    def _op_status(self, msg: dict) -> dict:
        return self._job_answer(msg.get("id"))

    def _op_wait(self, msg: dict) -> dict:
        # split from _op_status so the PRO wire-contract rule can
        # attribute the `timeout` field to the `wait` op's table
        return self._job_answer(msg.get("id"), wait=True,
                                timeout=msg.get("timeout"))

    def _job_answer(self, job_id, wait: bool = False,
                    timeout=None) -> dict:
        job = self.queue.get(job_id) if isinstance(job_id, str) else None
        if job is None:
            return protocol.error(protocol.E_UNKNOWN_JOB,
                                  f"no such job: {job_id!r}")
        if wait:
            try:
                timeout = self.MAX_WAIT_SLICE_S if timeout is None \
                    else min(float(timeout), self.MAX_WAIT_SLICE_S)
            except (TypeError, ValueError):
                return protocol.error(
                    protocol.E_BAD_REQUEST,
                    f"timeout must be a number, got {timeout!r}")
            job.wait(timeout)
        return protocol.ok(job=job.snapshot())

    def _journal_stats(self) -> dict:
        """Journal health for stats/metrics: on-disk size + compactions
        (a scraper watching bytes vs compactions sees runaway growth)."""
        try:
            size = os.path.getsize(self.journal_path)
        except OSError:
            size = 0
        with self._lock:
            compactions = self._journal_compactions
            torn = self._journal_torn
        return {"path": self.journal_path, "enabled": self._journal_enabled,
                "bytes": size, "compactions": compactions, "torn": torn}

    def _slice_rows(self) -> list[dict]:
        """Per-slice serving state for stats (and, flattened, the
        Prometheus per-slice series): the pool health signal."""
        with self._lock:
            rows = []
            for sl in self.slices:
                cur = sl.current
                rows.append({
                    "name": sl.name,
                    "devices": list(sl.device_ids),
                    "width": sl.width,
                    "default": sl.default,
                    "degraded": sl.degraded,
                    "degrade_reason": sl.degrade_reason,
                    "busy": cur is not None,
                    "current": cur.id if cur is not None else None,
                    "jobs_total": sl.jobs_total,
                    "steals": sl.steals,
                    # self-healing state: reinstatements so far, when the
                    # newest one landed, whether the canary audition is
                    # still pending (gate armed or its job in flight),
                    # and the live re-probe backoff
                    "recoveries": sl.recoveries,
                    "recovered_at": sl.recovered_at,
                    "canary": sl.canary or sl.canary_job is not None,
                    "recover_backoff_s": sl.recover_backoff,
                })
        return rows

    def _op_stats(self) -> dict:
        from spgemm_tpu.ops import delta, plancache  # noqa: PLC0415

        try:
            cache = plancache.stats()
        except ValueError as e:
            cache = {"error": str(e)}
        try:
            delta_stats = delta.stats()
        except ValueError as e:
            delta_stats = {"error": str(e)}
        try:
            warm_stats = warmstore.stats()
        except ValueError as e:
            warm_stats = {"error": str(e)}
        # the chaos surface: which failpoints are live under the current
        # spec (armed() re-parses, so a malformed spec surfaces as a
        # structured error row here instead of crashing the stats op)
        try:
            armed = failpoints.armed()
        except ValueError as e:
            armed = {"error": str(e)}
        slices = self._slice_rows()
        with self._lock:
            degraded = self.degraded
            degrade_reason = self.degrade_reason
            probe_outcome = self._probe_outcome
            terminal = dict(self._terminal_totals)
        return protocol.ok(
            daemon="spgemmd",
            uptime_s=round(time.time() - self._started_at, 3),
            degraded=degraded,
            degrade_reason=degrade_reason,
            backend_probe=probe_outcome,
            queue_cap=self._cap,
            job_timeout_s=self._job_timeout_s,
            jobs=self.queue.counts(),
            # daemon-lifetime terminal outcomes: the queue's counts()
            # histogram is bounded by RETAIN_TERMINAL eviction, so only
            # these totals distinguish "healthy and idle" from "just
            # recovered after reaping half the fleet's jobs"
            jobs_terminal=terminal,
            # the device pool: per-slice health (one wedged slice shows
            # degraded HERE while the daemon-level flag stays False and
            # the rest keep serving), the fair queue's per-tenant state,
            # and the placement price book
            slices=slices,
            slices_degraded=sum(1 for s in slices if s["degraded"]),
            tenants=self.queue.tenants(),
            tenant_inflight_cap=self.queue.tenant_cap(),
            placement=placement.stats(),
            journal=self._journal_stats(),
            failpoints={"armed": armed,
                        "triggered": failpoints.triggered()},
            trace=obs_trace.RECORDER.stats(),
            events=obs_events.LOG.stats(),
            profile=obs_profile.summary(),
            slo=obs_slo.SLO.report(),
            flight_dir=self.flight_dir,
            plan_cache=cache,
            delta=delta_stats,
            warm=warm_stats,
            tune=tune_mod.TUNER.stats(),
            socket=self.socket_path,
        )

    def _op_metrics(self) -> dict:
        """The scrapeable surface: Prometheus text-format 0.0.4 rendered
        from the obs/metrics.py registry -- engine phase/counter series,
        plan-cache and flight-recorder state, the daemon's serving gauges,
        and the pool's per-slice/per-tenant series (spgemm_slice_busy,
        spgemm_slice_jobs_total{slice=...}, spgemmd_tenant_queue_depth)."""
        samples = obs_metrics.collect_engine()
        with self._lock:
            degraded = self.degraded
            terminal = dict(self._terminal_totals)
            conns = self._conn_count
            wall = {"buckets": dict(self._job_wall["buckets"]),
                    "sum": self._job_wall["sum"],
                    "count": self._job_wall["count"]}
            batch_hist = {"buckets": dict(self._batch_size["buckets"]),
                          "sum": self._batch_size["sum"],
                          "count": self._batch_size["count"]}
        counts = self.queue.counts()
        depth = counts.pop("depth")
        journal = self._journal_stats()
        samples += [
            ("spgemmd_uptime_seconds", {},
             round(time.time() - self._started_at, 3)),
            ("spgemmd_degraded", {}, int(degraded)),
            ("spgemmd_queue_depth", {}, depth),
            ("spgemmd_connections", {}, conns),
            ("spgemmd_journal_bytes", {}, journal["bytes"]),
            ("spgemmd_journal_compactions_total", {},
             journal["compactions"]),
            ("spgemmd_journal_torn_total", {}, journal["torn"]),
            ("spgemmd_job_wall_seconds", {}, wall),
        ]
        # the batch-size family only renders once the armed window has
        # sampled (count > 0): a window-0 daemon's scrape stays
        # byte-identical to the pre-batch surface
        if batch_hist["count"] > 0:
            samples.append(("spgemm_serve_batch_size", {}, batch_hist))
        samples += [("spgemmd_jobs", {"state": state}, n)
                    for state, n in sorted(counts.items())]
        samples += [("spgemmd_jobs_terminal_total", {"outcome": outcome}, n)
                    for outcome, n in sorted(terminal.items())]
        for row in self._slice_rows():
            labels = {"slice": row["name"]}
            samples += [
                ("spgemm_slice_busy", labels, int(row["busy"])),
                ("spgemm_slice_degraded", labels, int(row["degraded"])),
                ("spgemm_slice_jobs_total", labels, row["jobs_total"]),
                ("spgemm_slice_steals_total", labels, row["steals"]),
                ("spgemm_slice_recoveries_total", labels,
                 row["recoveries"]),
            ]
        # per-tenant series are cardinality-bounded at the scrape: the
        # top TENANT_RETAIN tenants by recency keep their own label, the
        # rest aggregate into one `other` row -- a tenant-id-per-request
        # client cannot grow the scrape without bound (the SLO families
        # apply the same cap inside the engine)
        tenant_rows = sorted(self.queue.tenants().items(),
                             key=lambda kv: kv[1].get("last_seen", 0.0),
                             reverse=True)
        depths: dict[str, int] = {}
        for i, (tenant, row) in enumerate(tenant_rows):
            label = tenant if i < obs_slo.TENANT_RETAIN else "other"
            depths[label] = depths.get(label, 0) + row["queued"]
        samples += [("spgemmd_tenant_queue_depth", {"tenant": tenant}, n)
                    for tenant, n in sorted(depths.items())]
        samples += obs_slo.SLO.samples()
        # autotune families render only once the tuner holds class state
        # (first sighting needs a job under a recorded structure WITH
        # tuning on), so a SPGEMM_TPU_TUNE=0 daemon's scrape -- and a
        # tuned-but-never-contacted one's -- stays byte-identical to the
        # pre-tuner surface
        tstats = tune_mod.TUNER.stats()
        if tstats["classes"]:
            samples += [("spgemm_tune_overrides", {"state": state}, n)
                        for state, n in sorted(tstats["overrides"].items())]
            samples += [("spgemm_tune_win_ratio",
                         {"class": row["class"]}, row["win"])
                        for row in tstats["classes"]
                        if row["state"] in ("canary", "live")
                        and row["win"] is not None]
        return protocol.ok(
            content_type="text/plain; version=0.0.4; charset=utf-8",
            text=obs_metrics.render(samples))

    def _op_trace(self) -> dict:
        """The span flight recorder as Perfetto/Chrome trace_event JSON
        (the same serialization the postmortem auto-dump writes)."""
        events = obs_trace.to_trace_events()
        return protocol.ok(spans=len(events), trace_events=events)

    def _op_profile(self) -> dict:
        """The deep-profiling report (obs/profile.py): compile/cost/
        memory accounting + estimator/delta prediction accountability.
        jax-free scrape-side, like metrics."""
        return protocol.ok(profile=obs_profile.report())

    def _op_events(self, msg: dict) -> dict:
        """The newest N structured event-log records (obs/events.py
        ring; the on-disk JSONL next to the journal holds the longer
        history)."""
        n = msg.get("n", 50)
        try:
            n = int(n)
        except (TypeError, ValueError):
            return protocol.error(protocol.E_BAD_REQUEST,
                                  f"n must be an integer, got {n!r}")
        return protocol.ok(events=obs_events.LOG.tail(n),
                           log=obs_events.LOG.stats())

    def _op_slo(self) -> dict:
        """The SLO engine's rolling objective report (obs/slo.py):
        per-tenant latency quantiles / error ratio / queue-wait share,
        per-(tenant, slice) burn state, declared objectives."""
        return protocol.ok(slo=obs_slo.SLO.report())

    def _op_shutdown(self) -> dict:
        self._stop.set()
        # the serve_forever loop (or the owner's stop()) tears down; the
        # response still goes out on this connection before it closes
        return protocol.ok(stopping=True)


def main(argv: list[str] | None = None) -> int:
    """`spgemm_tpu serve`: run the daemon in the foreground."""
    p = argparse.ArgumentParser(
        prog="spgemm_tpu serve",
        description="spgemmd: resident chain-serving daemon (one process "
                    "owns the device pool; jobs reuse its warm jit/plan/"
                    "crossover caches across per-slice executors)")
    p.add_argument("--socket", default=None, metavar="PATH",
                   help="unix socket path (default: SPGEMM_TPU_SERVE_SOCKET "
                        "or <tmpdir>/spgemmd-<uid>.sock)")
    p.add_argument("--addr", default=None, metavar="ADDR",
                   help="TCP front-end address, tcp:HOST:PORT (default: "
                        "SPGEMM_TPU_SERVE_ADDR; unset = unix-socket only)")
    p.add_argument("--device", default=None, metavar="PLATFORM",
                   help="pin a JAX platform before serving (e.g. cpu); "
                        "without it the default backend is probed first and "
                        "a dead accelerator starts the daemon degraded on "
                        "CPU instead of hanging")
    p.add_argument("--slices", default=None, metavar="SPEC",
                   help="device-pool slice spec override "
                        "(SPGEMM_TPU_SERVE_SLICES; e.g. '1x4+4', 'auto'; "
                        "default '1' = the single-executor daemon)")
    p.add_argument("--queue-cap", type=int, default=None,
                   help="override SPGEMM_TPU_SERVE_QUEUE_CAP for this "
                        "daemon")
    p.add_argument("--no-journal", action="store_true",
                   help="disable the on-disk job journal (jobs are lost on "
                        "restart)")
    p.add_argument("--verbose", "-v", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING,
        format="%(name)s %(message)s")
    degraded_at_start = False
    if args.device:
        from spgemm_tpu.utils.backend_probe import pin  # noqa: PLC0415
        pin(args.device)
    else:
        from spgemm_tpu.utils.backend_probe import failover_to_cpu  # noqa: PLC0415
        degraded_at_start = failover_to_cpu("spgemmd")
    # the slice pool needs the visible device count to validate its spec
    # ('auto' requires it); the probe/pin above already made this touch
    # safe, and a degraded-at-start daemon serves host-only anyway
    try:
        import jax  # noqa: PLC0415
        devices = jax.devices()
        n_devices = len(devices)
        # the autotune class key's device half: a vector tuned on this
        # pool must never be adopted by a pool of a different device
        # kind (main() resolves it here, post-probe, because the Daemon
        # itself is jax-free -- a jax.devices() call there would hang on
        # a dead TPU)
        device_kind = devices[0].platform if devices else "cpu"
    except Exception as e:  # noqa: BLE001 -- a dead backend must not kill the failover daemon
        log.warning("device count unavailable (%r); pool runs host-only",
                    e)
        n_devices = 1
        device_kind = "cpu"
        degraded_at_start = True
    try:
        daemon = Daemon(args.socket, queue_cap=args.queue_cap,
                        journal=not args.no_journal,
                        persist_compile_cache=True,
                        slices=args.slices, n_devices=n_devices,
                        device_kind=device_kind, addr=args.addr)
    except (mesh_mod.SliceSpecError, ValueError) as e:
        print(f"spgemmd: {e}", file=sys.stderr)
        return 1
    if degraded_at_start:
        # the device was dead before we ever owned it: CPU failover path
        # from the first job on every slice, reported in stats like a
        # mid-flight degrade
        daemon.degrade_at_start("startup probe: accelerator unreachable")

    # rollout-grade shutdown: SIGTERM (and a direct SIGINT) set the stop
    # flag, serve_forever's finally runs the full graceful drain -- stop
    # admission, finish or reap in-flight jobs within DRAIN_GRACE_S,
    # flush warm store + journal, release the flock -- and main returns
    # 0, so `kill <pid>` during a fleet rollout is exactly as clean as
    # the protocol `shutdown` op
    # the handler ONLY sets the flag: emitting an event here would take
    # the (non-reentrant) event-log lock on the main thread, and a
    # second signal landing while stop() itself holds it inside an emit
    # or flush would deadlock the drain -- the one thing a SIGTERM
    # handler must never do
    def _on_signal(signum, frame):  # noqa: ARG001 -- signal handler shape
        daemon._stop.set()

    for signum in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(signum, _on_signal)
        except (ValueError, OSError):
            pass  # not the main thread / exotic platform: Ctrl-C still works
    try:
        daemon.serve_forever()
    except KeyboardInterrupt:
        daemon.stop()
    except RuntimeError as e:
        # e.g. a live daemon already owns the socket: a clean one-line
        # refusal, not a traceback (the operator's retry loop reads it)
        print(f"spgemmd: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
