"""`make serve-smoke`: end-to-end spgemmd proof on the CPU backend.

Starts a real daemon subprocess on a temp socket with `--device cpu`,
submits the SAME tiny chain twice, then a THIRD submit with a handful of
tiles mutated in one operand, and asserts the serving contract:

  * all results are byte-exact against the host-only oracle multiply
    (job 3 against the oracle of the MUTATED chain);
  * the second job's status detail reports `plan_cache_hits >= 1` -- the
    warm-across-jobs proof the daemon exists for (a run-once CLI would
    re-plan from scratch);
  * the third job's status detail reports `0 < delta_rows < total_rows`
    -- the delta-recompute proof (ops/delta): a mostly-unchanged submit
    re-folds only the output rows the dirty tiles reach;
  * stats reports a healthy (non-degraded) daemon;
  * shutdown is clean (daemon exits 0, socket unlinked);
  * RESTART LEG (the warm-start proof, ops/warmstore): a second daemon
    on the same socket + warm dir re-serves the mutated chain, and its
    first-contact job must report `warm_hits >= 1` (every plan came from
    disk, not the symbolic planner -- the on-disk tier of the plan
    cache), zero `plan_cache` scoped hits but warm-loaded plans, and a
    DELTA recompute (`delta_rows == 0 < total_rows`, zero
    `delta_full_fallbacks`) against the rehydrated retained result --
    bit-exact again, clean shutdown again;
  * CONCURRENCY LEG (the device-pool proof, SPGEMM_TPU_SERVE_SLICES): a
    THIRD daemon with a 2-slice pool takes two same-cost jobs submitted
    back-to-back, which must OVERLAP -- the second job's
    `serve_queue_wait` stays well under the first job's `serve_execute`
    wall (a single-executor daemon would serialize them), the two jobs
    land on two different slices, and both results stay bit-exact vs
    the oracle -- clean shutdown once more;
  * BATCHING LEG (the cross-job fused-dispatch proof,
    SPGEMM_TPU_SERVE_BATCH_WINDOW_S): a FOURTH daemon, single slice,
    admission window armed, takes one warmup submit (first contact runs
    solo and records the structure) then THREE same-structure submits
    back-to-back -- all three must co-batch into ONE mega-launch (a
    shared `batch` id on every snapshot, `serve_batches >= 1` on the
    scrape, the `spgemm_serve_batch_size` histogram populated), and
    every output stays bit-exact vs the oracle (stacking along the
    round axis never changes any row's fold order) -- clean shutdown.

Any step failing exits nonzero.  This process itself stays jax-free (the
oracle and the generator are pure numpy) -- only the daemon touches a
backend, which is the deployment shape being smoked.
"""

from __future__ import annotations

import os
import subprocess
import sys
import tempfile
import time


def _fail(proc: subprocess.Popen | None, msg: str) -> int:
    print(f"serve-smoke: FAIL: {msg}", file=sys.stderr)
    if proc is not None and proc.poll() is None:
        proc.kill()
    if proc is not None:
        out, _ = proc.communicate(timeout=10)
        sys.stderr.write(out[-4000:] if out else "")
    return 1


def main() -> int:
    import numpy as np  # noqa: PLC0415

    from spgemm_tpu.serve import client  # noqa: PLC0415
    from spgemm_tpu.utils import io_text  # noqa: PLC0415
    from spgemm_tpu.utils.blockcsr import BlockSparseMatrix  # noqa: PLC0415
    from spgemm_tpu.utils.gen import random_chain  # noqa: PLC0415
    from spgemm_tpu.utils.semantics import chain_oracle  # noqa: PLC0415

    tmp = tempfile.mkdtemp(prefix="spgemmd-smoke-")
    sock = os.path.join(tmp, "d.sock")
    folder = os.path.join(tmp, "chain_in")
    n, k = 4, 4
    mats = random_chain(n, 6, k, 0.5, np.random.default_rng(7), "full")
    io_text.write_chain_dir(folder, mats, k)
    want = chain_oracle([m.to_dict() for m in mats], k)
    want_bytes = io_text.format_matrix(BlockSparseMatrix.from_dict(
        mats[0].rows, mats[-1].cols, k, want).prune_zeros())

    # the restart leg asserts against the socket-adjacent warm dir, so an
    # operator-exported SPGEMM_TPU_WARM*/WARM_DIR must not redirect (or
    # disable) the daemons' persistence under the harness
    env = {k: v for k, v in os.environ.items()
           if not k.startswith("SPGEMM_TPU_WARM")}
    proc = subprocess.Popen(
        [sys.executable, "-m", "spgemm_tpu.cli", "serve",
         "--socket", sock, "--device", "cpu", "-v"],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True)
    try:
        deadline = time.time() + 120
        while not os.path.exists(sock):
            if proc.poll() is not None:
                return _fail(proc, "daemon exited before binding its socket")
            if time.time() > deadline:
                return _fail(proc, "daemon never bound its socket")
            time.sleep(0.1)

        outputs = []
        for i in (1, 2):
            out = os.path.join(tmp, f"matrix.{i}")
            resp = client.submit(folder, sock, {"output": out})
            resp = client.wait(resp["id"], sock, timeout=300)
            job = resp["job"]
            if job["state"] != "done":
                return _fail(proc, f"job {i} ended {job['state']}: "
                                   f"{job['error']}")
            outputs.append((out, job))

        for i, (out, _) in enumerate(outputs, 1):
            got = open(out, "rb").read()
            if got != want_bytes:
                return _fail(proc, f"job {i} output does not match the "
                                   "oracle bytes")
        hits = outputs[1][1]["detail"].get("plan_cache_hits", 0)
        if hits < 1:
            return _fail(proc, "second submit reported plan_cache_hits="
                               f"{hits}; the daemon's plan cache is cold "
                               "across jobs")

        # third submit: mutate a handful of tiles in ONE operand (values
        # only -- structure untouched), recompute the oracle, and prove
        # the delta path engaged: bit-exact output with only the reached
        # output rows re-folded (ops/delta)
        m0 = mats[0]
        tiles = m0.tiles.copy()
        tiles[0] = tiles[0] + np.uint64(1)  # one tile-row goes dirty
        mats[0] = BlockSparseMatrix(rows=m0.rows, cols=m0.cols, k=k,
                                    coords=m0.coords, tiles=tiles)
        io_text.write_matrix(os.path.join(folder, "matrix1"), mats[0])
        want3 = chain_oracle([m.to_dict() for m in mats], k)
        want3_bytes = io_text.format_matrix(BlockSparseMatrix.from_dict(
            mats[0].rows, mats[-1].cols, k, want3).prune_zeros())
        out3 = os.path.join(tmp, "matrix.3")
        resp = client.submit(folder, sock, {"output": out3})
        resp = client.wait(resp["id"], sock, timeout=300)
        job3 = resp["job"]
        if job3["state"] != "done":
            return _fail(proc, f"job 3 ended {job3['state']}: "
                               f"{job3['error']}")
        if open(out3, "rb").read() != want3_bytes:
            return _fail(proc, "job 3 (mutated input) output does not "
                               "match the oracle bytes")
        delta_rows = job3["detail"].get("delta_rows", 0)
        total_rows = job3["detail"].get("total_rows", 0)
        if not 0 < delta_rows < total_rows:
            return _fail(proc, "third submit did not take the delta "
                               f"path: delta_rows={delta_rows} "
                               f"total_rows={total_rows} (want "
                               "0 < delta_rows < total_rows)")

        st = client.stats(sock)
        if st.get("degraded"):
            return _fail(proc, f"daemon reports degraded: "
                               f"{st.get('degrade_reason')}")

        client.shutdown(sock)
        try:
            rc = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            return _fail(proc, "daemon did not exit after shutdown")
        if rc != 0:
            return _fail(proc, f"daemon exited {rc} after shutdown")
        if os.path.exists(sock):
            return _fail(None, "socket not unlinked on clean shutdown")

        # ---- restart leg: the warm-start proof (ops/warmstore) ----
        warm_dir = sock + ".warm"
        if not any(n.endswith(".npz") for n in os.listdir(warm_dir)):
            return _fail(None, f"first daemon left no warm entries in "
                               f"{warm_dir}")
        proc = subprocess.Popen(
            [sys.executable, "-m", "spgemm_tpu.cli", "serve",
             "--socket", sock, "--device", "cpu", "-v"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        deadline = time.time() + 120
        while not os.path.exists(sock):
            if proc.poll() is not None:
                return _fail(proc, "restarted daemon exited before "
                                   "binding its socket")
            if time.time() > deadline:
                return _fail(proc, "restarted daemon never bound its "
                                   "socket")
            time.sleep(0.1)
        out4 = os.path.join(tmp, "matrix.4")
        resp = client.submit(folder, sock, {"output": out4})
        resp = client.wait(resp["id"], sock, timeout=300)
        job4 = resp["job"]
        if job4["state"] != "done":
            return _fail(proc, f"post-restart job ended {job4['state']}: "
                               f"{job4['error']}")
        if open(out4, "rb").read() != want3_bytes:
            return _fail(proc, "post-restart output does not match the "
                               "oracle bytes")
        det = job4["detail"]
        warm_hits = det.get("warm_hits", 0)
        if warm_hits < 1:
            return _fail(proc, f"post-restart job reported warm_hits="
                               f"{warm_hits}; the warm store served "
                               "nothing (want >= 1: first contact must "
                               "be a cache hit from disk)")
        if det.get("delta_full_fallbacks", 0) != 0:
            return _fail(proc, "post-restart job took a delta full "
                               "fallback; the rehydrated retained result "
                               "was not served "
                               f"(fallbacks={det.get('delta_full_fallbacks')})")
        d4_rows = det.get("delta_rows", -1)
        t4_rows = det.get("total_rows", 0)
        if not (d4_rows == 0 and t4_rows > 0):
            return _fail(proc, "post-restart submit of the unchanged "
                               "input should be a clean delta "
                               f"(0 recomputed rows), got delta_rows="
                               f"{d4_rows} total_rows={t4_rows}")
        client.shutdown(sock)
        try:
            rc = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            return _fail(proc, "restarted daemon did not exit after "
                               "shutdown")
        if rc != 0:
            return _fail(proc, f"restarted daemon exited {rc} after "
                               "shutdown")

        # ---- concurrency leg: the device-pool proof (2 slices) ----
        # two fresh same-cost chains (cold shapes: their plan + jit are
        # the measurable part of serve_execute) on a 2-slice daemon; the
        # jobs must overlap, not serialize
        sock2 = os.path.join(tmp, "pool.sock")
        folders, wants = [], []
        for i, seed in enumerate((21, 22)):
            f = os.path.join(tmp, f"conc_{i}")
            cm = random_chain(4, 12, 8, 0.4,
                              np.random.default_rng(seed), "full")
            io_text.write_chain_dir(f, cm, 8)
            w = chain_oracle([m.to_dict() for m in cm], 8)
            wants.append(io_text.format_matrix(BlockSparseMatrix.from_dict(
                cm[0].rows, cm[-1].cols, 8, w).prune_zeros()))
            folders.append(f)
        env2 = dict(env)
        env2["SPGEMM_TPU_SERVE_SLICES"] = "2"
        proc = subprocess.Popen(
            [sys.executable, "-m", "spgemm_tpu.cli", "serve",
             "--socket", sock2, "--device", "cpu", "-v"],
            env=env2, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        deadline = time.time() + 120
        while not os.path.exists(sock2):
            if proc.poll() is not None:
                return _fail(proc, "pool daemon exited before binding "
                                   "its socket")
            if time.time() > deadline:
                return _fail(proc, "pool daemon never bound its socket")
            time.sleep(0.1)
        ids = [client.submit(f, sock2,
                             {"output": f + ".out"})["id"]
               for f in folders]  # back-to-back: overlap or serialize
        jobs = []
        for jid in ids:
            r = client.wait(jid, sock2, timeout=300)
            if r["job"]["state"] != "done":
                return _fail(proc, f"pool job {jid} ended "
                                   f"{r['job']['state']}: "
                                   f"{r['job']['error']}")
            jobs.append(r["job"])
        for i, f in enumerate(folders):
            if open(f + ".out", "rb").read() != wants[i]:
                return _fail(proc, f"pool job {i + 1} output does not "
                                   "match the oracle bytes")
        slices_used = {j["detail"].get("slice") for j in jobs}
        if len(slices_used) != 2:
            return _fail(proc, "the two pool jobs did not land on two "
                               f"slices (got {slices_used})")
        a_exec = jobs[0]["detail"]["phases_s"].get("serve_execute", 0.0)
        b_wait = jobs[1]["detail"]["phases_s"].get("serve_queue_wait",
                                                   1e9)
        # overlap: job 2 was picked up while job 1 was still executing
        # (a single-executor daemon would give b_wait >= a_exec)
        if not (a_exec > 0.05 and b_wait < 0.5 * a_exec):
            return _fail(proc, "pool jobs did not overlap: job2 "
                               f"queue_wait={b_wait:.3f}s vs job1 "
                               f"execute={a_exec:.3f}s (want "
                               "queue_wait < 0.5 * execute)")
        client.shutdown(sock2)
        try:
            rc = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            return _fail(proc, "pool daemon did not exit after shutdown")
        if rc != 0:
            return _fail(proc, f"pool daemon exited {rc} after shutdown")

        # ---- batching leg: cross-job fused dispatch (1 slice) ----
        # window armed, delta off (delta-eligible submits run solo by
        # design): one warmup submit records the structure, then three
        # back-to-back same-structure submits must fuse into ONE
        # mega-launch, every output bit-exact vs the oracle
        sock3 = os.path.join(tmp, "batch.sock")
        env3 = dict(env)
        env3["SPGEMM_TPU_SERVE_BATCH_WINDOW_S"] = "0.5"
        env3["SPGEMM_TPU_SERVE_BATCH_K"] = "8"
        env3["SPGEMM_TPU_DELTA"] = "0"
        proc = subprocess.Popen(
            [sys.executable, "-m", "spgemm_tpu.cli", "serve",
             "--socket", sock3, "--device", "cpu", "-v"],
            env=env3, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        deadline = time.time() + 120
        while not os.path.exists(sock3):
            if proc.poll() is not None:
                return _fail(proc, "batch daemon exited before binding "
                                   "its socket")
            if time.time() > deadline:
                return _fail(proc, "batch daemon never bound its socket")
            time.sleep(0.1)
        warm_out = os.path.join(tmp, "matrix.warmup")
        resp = client.submit(folder, sock3, {"output": warm_out})
        resp = client.wait(resp["id"], sock3, timeout=300)
        if resp["job"]["state"] != "done":
            return _fail(proc, "batch-leg warmup job ended "
                               f"{resp['job']['state']}: "
                               f"{resp['job']['error']}")
        bids = [client.submit(
            folder, sock3,
            {"output": os.path.join(tmp, f"matrix.b{i}")})["id"]
            for i in range(3)]  # back-to-back inside the window
        bjobs = []
        for jid in bids:
            r = client.wait(jid, sock3, timeout=300)
            if r["job"]["state"] != "done":
                return _fail(proc, f"batch job {jid} ended "
                                   f"{r['job']['state']}: "
                                   f"{r['job']['error']}")
            bjobs.append(r["job"])
        for i in range(3):
            got = open(os.path.join(tmp, f"matrix.b{i}"), "rb").read()
            if got != want3_bytes:
                return _fail(proc, f"batch job {i + 1} output does not "
                                   "match the oracle bytes")
        batch_ids = {j.get("batch") for j in bjobs}
        if None in batch_ids or len(batch_ids) != 1:
            return _fail(proc, "the three same-structure submits did not "
                               f"co-batch (batch ids {batch_ids}; want "
                               "one shared non-null id)")
        scrape = client.metrics(sock3)
        batches = 0
        for ln in scrape.splitlines():
            if (ln.startswith("spgemm_engine_events_total")
                    and 'event="serve_batches"' in ln):
                batches = int(float(ln.rsplit(" ", 1)[-1]))
        if batches < 1:
            return _fail(proc, f"scrape reports serve_batches={batches} "
                               "(want >= 1)")
        if "spgemm_serve_batch_size" not in scrape:
            return _fail(proc, "spgemm_serve_batch_size histogram missing "
                               "from the scrape after a fused batch")
        client.shutdown(sock3)
        try:
            rc = proc.wait(timeout=60)
        except subprocess.TimeoutExpired:
            return _fail(proc, "batch daemon did not exit after shutdown")
        if rc != 0:
            return _fail(proc, f"batch daemon exited {rc} after shutdown")
    finally:
        if proc.poll() is None:
            proc.kill()
    print(f"serve-smoke: OK (3 jobs bit-exact vs oracle, warm hits={hits}, "
          f"delta rows {delta_rows}/{total_rows}; restart leg: "
          f"warm_hits={warm_hits}, clean delta {d4_rows}/{t4_rows}; "
          f"pool leg: 2 jobs overlapped on {sorted(slices_used)} "
          f"(queue_wait {b_wait:.3f}s vs execute {a_exec:.3f}s), "
          f"bit-exact both; batching leg: 3 jobs fused "
          f"(serve_batches={batches}), bit-exact all; clean shutdown x4)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
