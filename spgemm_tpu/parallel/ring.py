"""Ring SpGEMM: rotate B around the mesh, O(1/n) operand memory per device.

The long-context pattern of SURVEY.md section 5.7 ("shard the long axis,
rotate/reduce partials" -- structurally ring attention's KV rotation) applied
to SpGEMM: output keys are range-sharded across the ring (each device computes
its slab of C), A's tile slab is resident, and B's tile slab is partitioned
into n chunks that rotate one hop per step via `jax.lax.ppermute` over ICI.
After n steps every device has seen all of B while only ever holding 1/n of
it -- this is what lets a `webbase-1M`-scale operand exceed single-chip HBM.

Communication/compute overlap (round 7): the step body is double-buffered --
the `ppermute` for slab t+1 is issued into a second buffer BEFORE the fold
over slab t, so XLA's async collectives can put the ICI hop behind the MAC
work instead of serializing hop-after-fold (the structure ring attention and
the distributed-SpGEMM literature -- Deveci et al. 1801.03065, Nagasaka et
al. 1804.01698 -- both use).  `SPGEMM_TPU_RING_OVERLAP=0|1` (default 1)
selects the legacy fold-then-hop body for A/B runs; the two are bit-identical
because each slab's fold order is unchanged, only the hop issue point moves.

Arithmetic: field mode (clean mod-(2^64-1), ops/u64.py) -- the rotation
schedule visits each key's pairs grouped by B-slab, not in the reference's
j-ascending order, so only an associative reduction is correct here.  Use
parallel/rowshard.py when bit-order-exact results are required (it keeps every
key's fold on one device, in order).

Contrast with the reference: its distribution never slices an operand -- every
rank holds whole matrices and ships whole partials through host memory
(sparse_matrix_mult.cu:460-556).  The ring inverts that: operands stream
device-to-device over ICI, nothing touches the host.
"""

from __future__ import annotations

import time
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spgemm_tpu.obs import profile as obs_profile
from spgemm_tpu.ops import u64
from spgemm_tpu.ops.symbolic import JoinResult, symbolic_join
from spgemm_tpu.parallel.innershard import fold_pairs_field
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils import jaxcompat, knobs
from spgemm_tpu.utils.timers import ENGINE


def overlap_enabled() -> bool:
    """SPGEMM_TPU_RING_OVERLAP=0|1 (default 1): double-buffer the rotation so
    the hop for slab t+1 is in flight while slab t folds.  Bit-identical
    either way (the fold order never changes); 0 keeps the legacy serialized
    fold-then-hop body for A/B measurement."""
    return knobs.get("SPGEMM_TPU_RING_OVERLAP")


# rank lists are UNROLLED in the fold's step body (one fold+scatter per
# rank), so their count must stay O(1): cells deeper than this many pairs
# spill their remainder into ONE dense (cell, pair) tail block folded with a
# bounded-size loop -- an adversarial key with thousands of same-slab pairs
# costs tail padding, never an unbounded XLA graph
RANK_UNROLL_MAX = 8


def plan_ring(join: JoinResult, nnzb_b: int, n_dev: int,
              mass_balance: bool | None = None):
    """Host-side schedule: key chunks per device, RANK-COMPACTED pair lists
    per (device, slab) step.

    mass_balance (None = the SPGEMM_TPU_PLAN_ESTIMATE estimator family
    knob): assign each device's contiguous key range by cumulative PAIR
    MASS -- the per-key MAC count the estimator's row_mass predicts, exact
    here since the join has landed -- instead of raw key count.  Equal
    key-count ranges under power-law skew hand one device the deep keys
    and pad every other device up to its step shapes (the residual ~1.45x
    padded-MAC skew of the rank-compacted schedule); mass-balanced bounds
    attack exactly that.  Field-mode addition is an abelian group op and
    every key still folds whole on one device, so the split point cannot
    change bits -- the knob is a pure load-balance A/B.

    Pairs land in (key, slab) cells (slab = which contiguous B chunk owns the
    pair's B tile).  A power-law structure makes almost every cell hold ONE
    pair (webbase config: 2812 of 2885 occupied cells), so a dense per-cell
    pair axis pads nearly everything: the old (cell, p_max) layout carried
    4.2x the real MAC work on that config.  Instead the schedule is sliced by
    pair RANK: list r holds the r-th pair of every cell.  Within one rank each
    cell appears at most once, so rows are unique and the fold can scatter-add
    straight into the device accumulator -- and the padded MAC count collapses
    to sum_r max_over_(dev,slab)(cells with >= r+1 pairs) ~= 1.1-1.5x real.
    (Field-mode addition is an abelian group op, so folding a cell's pairs as
    r scatter-adds instead of one pre-reduced tile is bit-identical.)

    Cells deeper than RANK_UNROLL_MAX pairs spill ranks >= RANK_UNROLL_MAX
    into the dense TAIL block (the old (cell, p_max) layout restricted to
    deep cells): rank lists bound the unrolled graph, the tail bounds the
    pathological depth.

    Returns (key_chunks, slab_bounds, ranks, tail, s_max, k_max):
      key_chunks  : list of n index arrays into join.keys (device d's keys)
      slab_bounds : (n+1,) B tile-slab boundaries (contiguous equal splits)
      ranks       : list over pair rank r < RANK_UNROLL_MAX of
                    (row_idx, pa, pb), each (n, n, C_r) int32
                    [device, slab, compacted cell]:
                    row_idx = local ACC row (padding rows point at the dummy
                    accumulator row == k_max), pa = A-slab indices (sentinel
                    -1), pb = *within-slab* B indices (sentinel == s_max,
                    the slab zero tile)
      tail        : None, or (row_idx, pa, pb) with pa/pb (n, n, C_t, P_t)
                    holding every deep cell's pairs at ranks >=
                    RANK_UNROLL_MAX (same sentinels; rows unique per step)
      s_max       : max slab size
      k_max       : max local key count == the dummy accumulator row baked
                    into row_idx (single-sourced here; the fold's
                    accumulator MUST be allocated k_max + 1 rows)
    """
    if mass_balance is None:
        mass_balance = knobs.get("SPGEMM_TPU_PLAN_ESTIMATE")
    n_keys = join.num_keys
    slab_bounds = np.array([(i * nnzb_b) // n_dev for i in range(n_dev + 1)],
                           dtype=np.int64)
    slab_sizes = np.diff(slab_bounds)
    s_max = int(slab_sizes.max()) if n_dev > 0 else 0

    # contiguous key ranges (keys are sorted by (row, col), so these are
    # row-range slabs of C): equal-count legacy split, or mass-balanced --
    # boundary d lands where the cumulative pair mass crosses d/n of the
    # total, so each device folds ~the same MAC count even when the key
    # fanout distribution is power-law
    if mass_balance and n_keys > 0:
        cum = np.concatenate(([0], np.cumsum(join.fanouts, dtype=np.int64)))
        targets = np.arange(1, n_dev, dtype=np.int64) * cum[-1] // n_dev
        interior = np.searchsorted(cum, targets, side="left").astype(np.int64)
        key_bounds = np.concatenate(
            ([0], np.maximum.accumulate(interior), [n_keys]))
    else:
        key_bounds = np.array([(d * n_keys) // n_dev
                               for d in range(n_dev + 1)], dtype=np.int64)
    key_chunks = [np.arange(key_bounds[d], key_bounds[d + 1])
                  for d in range(n_dev)]
    k_max = max(1, int(np.diff(key_bounds).max()))

    # Pairs -> (key, slab) cells via one stable sort (preserves the original
    # j-ascending order within each cell; order inside a cell is what the
    # field-mode fold contract leaves free, but keep it deterministic).
    pair_ptr = np.asarray(join.pair_ptr, dtype=np.int64)
    key_of_pair = np.repeat(np.arange(n_keys, dtype=np.int64),
                            np.diff(pair_ptr))
    # slab of each pair = which contiguous B chunk owns its B tile index
    slab_of_pair = np.searchsorted(slab_bounds, join.pair_b, side="right") - 1

    cell = key_of_pair * n_dev + slab_of_pair
    order = np.argsort(cell, kind="stable")
    cell_sorted = cell[order]
    if cell.size:
        uc, uc_first, uc_counts = np.unique(cell_sorted, return_index=True,
                                            return_counts=True)
    else:
        uc = np.zeros(0, np.int64)
        uc_first = uc_counts = np.zeros(0, np.int64)
    p_max = max(1, int(uc_counts.max())) if uc.size else 1
    # rank of each sorted pair within its cell = position - cell start
    ci_of_pair = np.repeat(np.arange(len(uc), dtype=np.int64), uc_counts)
    pos = np.arange(cell.size, dtype=np.int64) - uc_first[ci_of_pair]

    # cell -> (device, slab, local acc row)
    cell_key = uc // n_dev
    cell_slab = (uc % n_dev).astype(np.int64)
    cell_dev = np.searchsorted(key_bounds, cell_key, side="right") - 1
    cell_local = (cell_key - key_bounds[cell_dev]).astype(np.int32)
    grp = cell_dev * n_dev + cell_slab

    pair_a_sorted = np.asarray(join.pair_a)[order]
    pair_b_sorted = np.asarray(join.pair_b)[order]

    def grp_slots(ci):
        """Compact a set of cells (indices into uc) within their (device,
        slab) groups: returns (slot of each cell in its group, max group
        size)."""
        grp_c = grp[ci]
        counts = np.bincount(grp_c, minlength=n_dev * n_dev)
        c_max = max(1, int(counts.max())) if ci.size else 1
        order_c = np.argsort(grp_c, kind="stable")
        offsets = np.concatenate(([0], np.cumsum(counts)))
        slots = np.empty(len(ci), np.int64)
        slots[order_c] = (np.arange(len(ci), dtype=np.int64)
                          - offsets[grp_c[order_c]])
        return slots, c_max

    ranks = []
    for r in range(min(p_max, RANK_UNROLL_MAX)):
        sel = pos == r            # at most one pair per cell at each rank
        ci = ci_of_pair[sel]
        slots, c_r = grp_slots(ci)
        row_idx = np.full((n_dev, n_dev, c_r), k_max, dtype=np.int32)  # dummy
        pa = np.full((n_dev, n_dev, c_r), -1, dtype=np.int32)
        pb = np.full((n_dev, n_dev, c_r), s_max, dtype=np.int32)
        d_i, s_i = cell_dev[ci], cell_slab[ci]
        row_idx[d_i, s_i, slots] = cell_local[ci]
        pa[d_i, s_i, slots] = pair_a_sorted[sel]
        pb[d_i, s_i, slots] = pair_b_sorted[sel] - slab_bounds[s_i]
        ranks.append((row_idx, pa, pb))

    tail = None
    if p_max > RANK_UNROLL_MAX:
        ci_deep = np.flatnonzero(uc_counts > RANK_UNROLL_MAX)
        slots_deep, c_t = grp_slots(ci_deep)
        p_t = p_max - RANK_UNROLL_MAX
        row_idx = np.full((n_dev, n_dev, c_t), k_max, dtype=np.int32)
        pa = np.full((n_dev, n_dev, c_t, p_t), -1, dtype=np.int32)
        pb = np.full((n_dev, n_dev, c_t, p_t), s_max, dtype=np.int32)
        d_i, s_i = cell_dev[ci_deep], cell_slab[ci_deep]
        row_idx[d_i, s_i, slots_deep] = cell_local[ci_deep]
        slot_of_cell = np.full(len(uc), -1, np.int64)
        slot_of_cell[ci_deep] = slots_deep
        selp = pos >= RANK_UNROLL_MAX     # the deep cells' spilled pairs
        cip = ci_of_pair[selp]
        pa[cell_dev[cip], cell_slab[cip], slot_of_cell[cip],
           pos[selp] - RANK_UNROLL_MAX] = pair_a_sorted[selp]
        pb[cell_dev[cip], cell_slab[cip], slot_of_cell[cip],
           pos[selp] - RANK_UNROLL_MAX] = (
            pair_b_sorted[selp] - slab_bounds[cell_slab[cip]])
        tail = (row_idx, pa, pb)
    return key_chunks, slab_bounds, ranks, tail, s_max, k_max


def spgemm_ring(a: BlockSparseMatrix, b: BlockSparseMatrix, *,
                mesh: Mesh | None = None, plan=None,
                **_ignored) -> BlockSparseMatrix:
    """C = A x B with B rotating around the ring (field-mode arithmetic).

    plan: an ops/symbolic.SpgemmPlan built from the same operand pair --
    the join is reused and the ring schedule comes from the plan's memoized
    `ring_schedule` hook (pure numpy, so a planner worker thread may have
    prebuilt it while the device was busy)."""
    if a.k != b.k:
        raise ValueError(f"tile size mismatch: {a.k} vs {b.k}")
    k = a.k
    overlap = overlap_enabled()  # validate the knob before any work
    if mesh is None:
        from spgemm_tpu.parallel.mesh import default_mesh
        mesh = default_mesh(axis="ring")
    n_dev = mesh.devices.size

    if plan is not None:
        plan.check_operands(a, b)
        join = plan.ensure_exact().join  # land a deferred estimated plan
    else:
        join = symbolic_join(a.coords, b.coords)
    if join.num_keys == 0:
        return BlockSparseMatrix(rows=a.rows, cols=b.cols, k=k)

    from spgemm_tpu.ops.spgemm import pack_tiles
    # proven bounded operands ride the ~6x cheaper b32 MAC (val_bound gate,
    # same proof discipline as the exact engine's nomod route); in that mode
    # the hi planes are never built, uploaded, carried, or ring-rotated --
    # half the slab HBM and half the per-hop ICI bytes
    small = u64.operands_below_2_32(a, b)
    a_hi, a_lo = pack_tiles(a)  # replicated; sentinel zero tile at a.nnzb

    with ENGINE.phase("ring_plan"):
        key_chunks, slab_bounds, ranks, tail, s_max, k_max = \
            plan.ring_schedule(b.nnzb, n_dev) if plan is not None \
            else plan_ring(join, b.nnzb, n_dev)
    # A sentinel -> zero tile (rank lists and the deep-cell tail alike)
    ranks = [(rows, np.where(pa < 0, a.nnzb, pa), pb)
             for rows, pa, pb in ranks]
    if tail is not None:
        tail = (tail[0], np.where(tail[1] < 0, a.nnzb, tail[1]), tail[2])

    # per-device B slab buffers: (n, s_max + 1, k, k), zero tile at s_max
    bh_np, bl_np = u64.u64_to_hilo(b.tiles)
    b_slab_l = np.zeros((n_dev, s_max + 1, k, k), np.uint32)
    for s in range(n_dev):
        lo, hi = slab_bounds[s], slab_bounds[s + 1]
        b_slab_l[s, : hi - lo] = bl_np[lo:hi]
    if small:
        b_slab_h = np.zeros((n_dev, 1, 1, 1), np.uint32)  # dummy, unread
    else:
        b_slab_h = np.zeros((n_dev, s_max + 1, k, k), np.uint32)
        for s in range(n_dev):
            lo, hi = slab_bounds[s], slab_bounds[s + 1]
            b_slab_h[s, : hi - lo] = bh_np[lo:hi]

    shard0 = NamedSharding(mesh, P("ring"))
    bsh = jax.device_put(b_slab_h, shard0)
    bsl = jax.device_put(b_slab_l, shard0)
    trips = ranks + ([tail] if tail is not None else [])
    rank_args = [jax.device_put(jnp.asarray(x), shard0)
                 for trip in trips for x in trip]

    # one-hop wire probe: the measured cost of rotating the resident B slab
    # a single hop -- exactly the latency the double-buffered body hides
    # behind the fold.  Timed on its own (output discarded) because the real
    # hops overlap the MACs and are invisible to host wall-clock.  Measured
    # ONCE per (mesh, slab shape, width) per process -- later calls
    # re-record the cached figure, so every ENGINE snapshot carries the hop
    # number without paying an extra hop (or its compile) inside each timed
    # multiply.
    # SPGEMM_TPU_RING_HOP_PROBE=0 skips the probe entirely (saves its one
    # compiled shape + two hops per process per slab shape -- e.g. a
    # one-shot CLI run that never reads the phase registry)
    probe_on = knobs.get("SPGEMM_TPU_RING_HOP_PROBE")
    probe_key = (mesh, n_dev, small, bsl.shape, bsh.shape)
    hop_s = _HOP_PROBE_CACHE.get(probe_key) if probe_on else None
    if probe_on and hop_s is None:
        # first execution pays jit trace + compile, which would swamp the
        # wire time by orders of magnitude -- compile un-timed, then time a
        # second execution
        jax.block_until_ready(_ring_hop_jit(bsh, bsl, mesh=mesh, n_dev=n_dev,
                                            small=small))
        t0 = time.perf_counter()
        jax.block_until_ready(_ring_hop_jit(bsh, bsl, mesh=mesh, n_dev=n_dev,
                                            small=small))
        hop_s = time.perf_counter() - t0
        _HOP_PROBE_CACHE[probe_key] = hop_s
    if hop_s is not None:
        ENGINE.record("ring_hop", hop_s)

    fold = _make_ring_fold(mesh, n_dev, small, k_max, len(ranks),
                           tail is not None, overlap)
    with ENGINE.phase("ring_fold"):
        oh, ol = fold(a_hi, a_lo, bsh, bsl, *rank_args)
        jax.block_until_ready((oh, ol))
    ENGINE.incr("ring_steps", n_dev)
    vals = u64.hilo_to_u64(np.asarray(oh), np.asarray(ol))  # (n, K_max, k, k)

    out = np.zeros((join.num_keys, k, k), dtype=np.uint64)
    for d, chunk in enumerate(key_chunks):
        out[chunk] = vals[d, : len(chunk)]
    return BlockSparseMatrix(rows=a.rows, cols=b.cols, k=k,
                             coords=join.keys, tiles=out)


# one-hop wire measurements, keyed by (mesh, n_dev, small, slab shapes);
# first spgemm_ring call per shape pays the probe, the rest replay it
_HOP_PROBE_CACHE: dict = {}


@partial(jax.jit, static_argnames=("mesh", "n_dev", "small"))
def _ring_hop_jitted(b_slab_h, b_slab_l, *, mesh, n_dev, small):
    """One rotation hop of the resident B slab(s) -- the wire-time probe."""
    def per_device(bh, bl):
        rot_perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        bl = jax.lax.ppermute(bl, "ring", rot_perm)
        if not small:
            bh = jax.lax.ppermute(bh, "ring", rot_perm)
        return bh, bl

    return jaxcompat.shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P("ring"), P("ring")),
        out_specs=(P("ring"), P("ring")),
        check_vma=False,
    )(b_slab_h, b_slab_l)


# compile-accounted (obs/profile): the ring entrypoints' compile wall +
# cost/memory analyses land in the deep-profiling layer; plain jit
# dispatch under SPGEMM_TPU_OBS_TRACE=0, bit-identical either way
_ring_hop_jit = obs_profile.ProfiledJit("ring_hop", _ring_hop_jitted)


@partial(jax.jit, static_argnames=("mesh", "n_dev", "small", "k_max",
                                   "n_ranks", "has_tail", "overlap"))
def _ring_fold_jitted(a_hi, a_lo, b_slab_h, b_slab_l, *rank_args, mesh,
                      n_dev, small, k_max, n_ranks, has_tail, overlap):
    def per_device(a_hi, a_lo, bh, bl, *rank_args):
        # local shapes: bl (1, s_max+1, k, k); per rank r: rows (1, n_slab,
        # C_r), pa/pb (1, n_slab, C_r) -- C_r is the RANK-COMPACTED cell axis
        # (plan_ring): each step folds, per rank, only the cells that hold an
        # r-th pair and scatter-adds them into the device accumulator; row
        # k_max is the padding dummy.  has_tail appends one dense (cell,
        # pair) trip for cells deeper than RANK_UNROLL_MAX.  small mode: bh
        # is a (1,1,1,1) dummy, never rotated -- the b32 route's ICI/HBM
        # saving is structural, not DCE (it rides the carry untouched).
        d = jax.lax.axis_index("ring")
        k = a_lo.shape[-1]
        rot_perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]
        trips = [rank_args[3 * r: 3 * r + 3]
                 for r in range(n_ranks + int(has_tail))]

        def rotate(bh, bl):
            bl = jax.lax.ppermute(bl, "ring", rot_perm)
            if not small:
                bh = jax.lax.ppermute(bh, "ring", rot_perm)
            return bh, bl

        def fold_slab(acc_h, acc_l, bh, bl, s):
            for rows, pa, pb in trips:
                rows_s = rows[0, s]      # (C,) -- dynamic slab index
                pa_s = pa[0, s]          # rank lists are (C,); tail (C, P_t)
                pb_s = pb[0, s]
                if pa_s.ndim == 1:
                    pa_s, pb_s = pa_s[:, None], pb_s[:, None]
                if small:  # hi args unread by the b32 fold: lo stand-ins
                    ph, pl = fold_pairs_field(a_lo, a_lo, bl[0], bl[0],
                                              pa_s, pb_s, small=True)
                else:
                    ph, pl = fold_pairs_field(a_hi, a_lo, bh[0], bl[0],
                                              pa_s, pb_s)
                # scatter-add the compacted cells into their acc rows; rows
                # are unique within one trip (at most one r-th pair per
                # cell; one tail slot per deep cell) except the dummy row,
                # whose value is never read
                nh, nl = u64.addmod_field(acc_h[rows_s], acc_l[rows_s],
                                          ph, pl)
                acc_h = acc_h.at[rows_s].set(nh)
                acc_l = acc_l.at[rows_s].set(nl)
            return acc_h, acc_l

        def step(t, carry):
            acc_h, acc_l, bh, bl = carry
            s = (d - t) % n_dev  # slab currently resident on this device
            if overlap:
                # double buffer: issue the hop for slab t+1 FIRST -- the
                # fold below reads only the t-resident buffers, so the wire
                # transfer and the MAC work have no data dependence and XLA
                # may run them concurrently (async collective start/done)
                bh_next, bl_next = rotate(bh, bl)
                acc_h, acc_l = fold_slab(acc_h, acc_l, bh, bl, s)
                return acc_h, acc_l, bh_next, bl_next
            # legacy serialized body: fold, then hop
            acc_h, acc_l = fold_slab(acc_h, acc_l, bh, bl, s)
            bh_next, bl_next = rotate(bh, bl)
            return acc_h, acc_l, bh_next, bl_next

        zero = jnp.zeros((k_max + 1, k, k), jnp.uint32)  # + dummy row
        out = jax.lax.fori_loop(0, n_dev, step, (zero, zero, bh, bl))
        acc_h, acc_l = out[0][:k_max], out[1][:k_max]
        return acc_h[None], acc_l[None]

    return jaxcompat.shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), P()) + (P("ring"),) * (2 + 3 * (n_ranks + int(has_tail))),
        out_specs=(P("ring"), P("ring")),
        check_vma=False,
    )(a_hi, a_lo, b_slab_h, b_slab_l, *rank_args)


_ring_fold_jit = obs_profile.ProfiledJit("ring_fold", _ring_fold_jitted)


def _make_ring_fold(mesh: Mesh, n_dev: int, small: bool, k_max: int,
                    n_ranks: int, has_tail: bool, overlap: bool):
    return partial(_ring_fold_jit, mesh=mesh, n_dev=n_dev, small=small,
                   k_max=k_max, n_ranks=n_ranks, has_tail=has_tail,
                   overlap=overlap)
