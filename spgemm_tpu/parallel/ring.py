"""Ring SpGEMM: rotate B around the mesh, O(1/n) operand memory per device.

The long-context pattern of SURVEY.md section 5.7 ("shard the long axis,
rotate/reduce partials" -- structurally ring attention's KV rotation) applied
to SpGEMM: output keys are range-sharded across the ring (each device computes
its slab of C), A's tile slab is resident, and B's tile slab is partitioned
into n chunks that rotate one hop per step via `jax.lax.ppermute` over ICI.
After n steps every device has seen all of B while only ever holding 1/n of
it -- this is what lets a `webbase-1M`-scale operand exceed single-chip HBM.

Arithmetic: field mode (clean mod-(2^64-1), ops/u64.py) -- the rotation
schedule visits each key's pairs grouped by B-slab, not in the reference's
j-ascending order, so only an associative reduction is correct here.  Use
parallel/rowshard.py when bit-order-exact results are required (it keeps every
key's fold on one device, in order).

Contrast with the reference: its distribution never slices an operand -- every
rank holds whole matrices and ships whole partials through host memory
(sparse_matrix_mult.cu:460-556).  The ring inverts that: operands stream
device-to-device over ICI, nothing touches the host.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from spgemm_tpu.ops import u64
from spgemm_tpu.ops.symbolic import JoinResult, symbolic_join
from spgemm_tpu.parallel.innershard import fold_pairs_field
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
from spgemm_tpu.utils import jaxcompat


def plan_ring(join: JoinResult, nnzb_b: int, n_dev: int):
    """Host-side schedule: key chunks per device, COMPACTED pair lists per
    (device, slab) cell.

    Only (key, slab) cells that actually hold pairs occupy a row -- a
    power-law structure concentrates each key's pairs in 1-2 slabs, and the
    old dense (device, slab, local_key, pair) layout padded every key into
    every slab (round-4 measurement: 10.8x padded vs real work on the
    webbase config; rowshard's fanout-bucketed rounds pad 1.1x).  The fold
    scatter-adds each step's compacted rows into the device accumulator.

    Returns (key_chunks, slab_bounds, row_idx, pa_all, pb_all, s_max, k_max):
      key_chunks  : list of n index arrays into join.keys (device d's keys)
      slab_bounds : (n+1,) B tile-slab boundaries (contiguous equal splits)
      row_idx     : (n, n, C_max) int32 -- local ACC row of each compacted
                    cell [device, slab, cell]; padding rows point at the
                    dummy accumulator row == k_max
      pa_all      : (n, n, C_max, P_max) int32 A-slab indices (sentinel -1)
      pb_all      : (n, n, C_max, P_max) int32 *within-slab* B indices
                    (sentinel == s_max, the slab zero tile)
      s_max       : max slab size
      k_max       : max local key count == the dummy accumulator row baked
                    into row_idx (single-sourced here; the fold's
                    accumulator MUST be allocated k_max + 1 rows)
    """
    n_keys = join.num_keys
    slab_bounds = np.array([(i * nnzb_b) // n_dev for i in range(n_dev + 1)],
                           dtype=np.int64)
    slab_sizes = np.diff(slab_bounds)
    s_max = int(slab_sizes.max()) if n_dev > 0 else 0

    # contiguous key ranges (keys are sorted by (row, col), so these are
    # row-range slabs of C)
    key_bounds = np.array([(d * n_keys) // n_dev for d in range(n_dev + 1)],
                          dtype=np.int64)
    key_chunks = [np.arange(key_bounds[d], key_bounds[d + 1])
                  for d in range(n_dev)]
    k_max = max(1, int(np.diff(key_bounds).max()))

    # Pairs -> (key, slab) cells via one stable sort (preserves the original
    # j-ascending order within each cell; order inside a cell is what the
    # field-mode fold contract leaves free, but keep it deterministic).
    pair_ptr = np.asarray(join.pair_ptr, dtype=np.int64)
    key_of_pair = np.repeat(np.arange(n_keys, dtype=np.int64),
                            np.diff(pair_ptr))
    # slab of each pair = which contiguous B chunk owns its B tile index
    slab_of_pair = np.searchsorted(slab_bounds, join.pair_b, side="right") - 1

    cell = key_of_pair * n_dev + slab_of_pair
    order = np.argsort(cell, kind="stable")
    cell_sorted = cell[order]
    if cell.size:
        uc, uc_first, uc_counts = np.unique(cell_sorted, return_index=True,
                                            return_counts=True)
    else:
        uc = np.zeros(0, np.int64)
        uc_first = uc_counts = np.zeros(0, np.int64)
    p_max = max(1, int(uc_counts.max())) if uc.size else 1
    # position of each sorted pair within its cell = rank - cell start
    ci_of_pair = np.repeat(np.arange(len(uc), dtype=np.int64), uc_counts)
    pos = np.arange(cell.size, dtype=np.int64) - uc_first[ci_of_pair]

    # group compacted cells by (device, slab)
    cell_key = uc // n_dev
    cell_slab = (uc % n_dev).astype(np.int64)
    cell_dev = np.searchsorted(key_bounds, cell_key, side="right") - 1
    cell_local = (cell_key - key_bounds[cell_dev]).astype(np.int32)
    grp = cell_dev * n_dev + cell_slab
    grp_counts = np.bincount(grp, minlength=n_dev * n_dev)
    c_max = max(1, int(grp_counts.max())) if uc.size else 1
    grp_order = np.argsort(grp, kind="stable")
    grp_offsets = np.concatenate(([0], np.cumsum(grp_counts)))
    pos_in_grp = np.empty(len(uc), np.int64)
    pos_in_grp[grp_order] = (np.arange(len(uc), dtype=np.int64)
                             - grp_offsets[grp[grp_order]])

    row_idx = np.full((n_dev, n_dev, c_max), k_max, dtype=np.int32)  # dummy
    row_idx[cell_dev, cell_slab, pos_in_grp] = cell_local
    pa_all = np.full((n_dev, n_dev, c_max, p_max), -1, dtype=np.int32)
    pb_all = np.full((n_dev, n_dev, c_max, p_max), s_max, dtype=np.int32)
    pa_all[cell_dev[ci_of_pair], cell_slab[ci_of_pair],
           pos_in_grp[ci_of_pair], pos] = join.pair_a[order]
    pb_all[cell_dev[ci_of_pair], cell_slab[ci_of_pair],
           pos_in_grp[ci_of_pair], pos] = (
        join.pair_b[order] - slab_bounds[cell_slab[ci_of_pair]])
    return key_chunks, slab_bounds, row_idx, pa_all, pb_all, s_max, k_max


def spgemm_ring(a: BlockSparseMatrix, b: BlockSparseMatrix, *,
                mesh: Mesh | None = None, **_ignored) -> BlockSparseMatrix:
    """C = A x B with B rotating around the ring (field-mode arithmetic)."""
    if a.k != b.k:
        raise ValueError(f"tile size mismatch: {a.k} vs {b.k}")
    k = a.k
    if mesh is None:
        from spgemm_tpu.parallel.mesh import default_mesh
        mesh = default_mesh(axis="ring")
    n_dev = mesh.devices.size

    join = symbolic_join(a.coords, b.coords)
    if join.num_keys == 0:
        return BlockSparseMatrix(rows=a.rows, cols=b.cols, k=k)

    from spgemm_tpu.ops.spgemm import pack_tiles
    # proven bounded operands ride the ~6x cheaper b32 MAC (val_bound gate,
    # same proof discipline as the exact engine's nomod route); in that mode
    # the hi planes are never built, uploaded, carried, or ring-rotated --
    # half the slab HBM and half the per-hop ICI bytes
    small = u64.operands_below_2_32(a, b)
    a_hi, a_lo = pack_tiles(a)  # replicated; sentinel zero tile at a.nnzb

    key_chunks, slab_bounds, row_idx, pa_all, pb_all, s_max, k_max = \
        plan_ring(join, b.nnzb, n_dev)
    pa_all = np.where(pa_all < 0, a.nnzb, pa_all)  # A sentinel -> zero tile

    # per-device B slab buffers: (n, s_max + 1, k, k), zero tile at s_max
    bh_np, bl_np = u64.u64_to_hilo(b.tiles)
    b_slab_l = np.zeros((n_dev, s_max + 1, k, k), np.uint32)
    for s in range(n_dev):
        lo, hi = slab_bounds[s], slab_bounds[s + 1]
        b_slab_l[s, : hi - lo] = bl_np[lo:hi]
    if small:
        b_slab_h = np.zeros((n_dev, 1, 1, 1), np.uint32)  # dummy, unread
    else:
        b_slab_h = np.zeros((n_dev, s_max + 1, k, k), np.uint32)
        for s in range(n_dev):
            lo, hi = slab_bounds[s], slab_bounds[s + 1]
            b_slab_h[s, : hi - lo] = bh_np[lo:hi]

    fold = _make_ring_fold(mesh, n_dev, small, k_max)
    shard0 = NamedSharding(mesh, P("ring"))
    oh, ol = fold(
        a_hi, a_lo,
        jax.device_put(b_slab_h, shard0), jax.device_put(b_slab_l, shard0),
        jax.device_put(jnp.asarray(row_idx), shard0),
        jax.device_put(jnp.asarray(pa_all), shard0),
        jax.device_put(jnp.asarray(pb_all), shard0),
    )
    vals = u64.hilo_to_u64(np.asarray(oh), np.asarray(ol))  # (n, K_max, k, k)

    out = np.zeros((join.num_keys, k, k), dtype=np.uint64)
    for d, chunk in enumerate(key_chunks):
        out[chunk] = vals[d, : len(chunk)]
    return BlockSparseMatrix(rows=a.rows, cols=b.cols, k=k,
                             coords=join.keys, tiles=out)


@partial(jax.jit, static_argnames=("mesh", "n_dev", "small", "k_max"))
def _ring_fold_jit(a_hi, a_lo, b_slab_h, b_slab_l, rows, pa, pb, *, mesh,
                   n_dev, small, k_max):
    def per_device(a_hi, a_lo, bh, bl, rows, pa, pb):
        # local shapes: bl (1, s_max+1, k, k), rows (1, n_slab, C),
        # pa (1, n_slab, C, P) -- C is the COMPACTED cell axis (plan_ring):
        # each step folds only the (key, slab) cells that hold pairs and
        # scatter-adds them into the device accumulator; row k_max is the
        # padding dummy.  small mode: bh is a (1,1,1,1) dummy, never in the
        # carry, never rotated -- the b32 route's ICI/HBM saving is
        # structural, not DCE.
        d = jax.lax.axis_index("ring")
        k = a_lo.shape[-1]
        rot_perm = [(i, (i + 1) % n_dev) for i in range(n_dev)]

        def step(t, carry):
            if small:
                acc_h, acc_l, bl = carry
            else:
                acc_h, acc_l, bh, bl = carry
            s = (d - t) % n_dev  # slab currently resident on this device
            rows_s = rows[0, s]  # (C,) -- dynamic index over the slab axis
            pa_s = pa[0, s]      # (C, P)
            pb_s = pb[0, s]
            if small:  # hi args unread by the b32 fold: pass lo stand-ins
                ph, pl = fold_pairs_field(a_lo, a_lo, bl[0], bl[0],
                                          pa_s, pb_s, small=True)
            else:
                ph, pl = fold_pairs_field(a_hi, a_lo, bh[0], bl[0],
                                          pa_s, pb_s)
            # scatter-add the compacted cells into their acc rows; rows are
            # unique within a step (one cell per key per slab) except the
            # dummy row, whose value is never read
            nh, nl = u64.addmod_field(acc_h[rows_s], acc_l[rows_s], ph, pl)
            acc_h = acc_h.at[rows_s].set(nh)
            acc_l = acc_l.at[rows_s].set(nl)
            bl = jax.lax.ppermute(bl, "ring", rot_perm)  # rotate B one hop
            if small:
                return acc_h, acc_l, bl
            bh = jax.lax.ppermute(bh, "ring", rot_perm)
            return acc_h, acc_l, bh, bl

        zero = jnp.zeros((k_max + 1, k, k), jnp.uint32)  # + dummy row
        carry0 = (zero, zero, bl) if small else (zero, zero, bh, bl)
        out = jax.lax.fori_loop(0, n_dev, step, carry0)
        acc_h, acc_l = out[0][:k_max], out[1][:k_max]
        return acc_h[None], acc_l[None]

    return jaxcompat.shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), P(), P("ring"), P("ring"), P("ring"), P("ring"),
                  P("ring")),
        out_specs=(P("ring"), P("ring")),
        check_vma=False,
    )(a_hi, a_lo, b_slab_h, b_slab_l, rows, pa, pb)


def _make_ring_fold(mesh: Mesh, n_dev: int, small: bool, k_max: int):
    return partial(_ring_fold_jit, mesh=mesh, n_dev=n_dev, small=small,
                   k_max=k_max)
