"""Multi-host (DCN) chain distribution: the literal `mpirun -np P` replacement.

The reference distributes the chain over MPI ranks and funnels every partial
product through a serial rank-0 Recv loop in host memory (sparse_matrix_mult.
cu:460-556, an O(P) bottleneck).  The JAX-native multi-host story:

  * each *process* (host) owns the same chain slice arithmetic as an MPI rank
    (parallel/chainpart.partition_chain -- bit-for-bit the reference's N/P
    split) and reduces its sub-chain locally;
  * partial products are exchanged with one padded all-gather over DCN
    (jax.experimental.multihost_utils) -- O(log P) collective, not a serial
    gather, and every host then runs the identical combine tree, so the
    result is replicated and any host can write it (no rank-0 hot spot);
  * within each host, the per-multiply numeric phase can additionally shard
    over local devices (rowshard/innershard/ring).

Launch (per host):
    JAX_COORDINATOR=host0:1234 JAX_NUM_PROCESSES=P JAX_PROCESS_ID=r \
        python -m spgemm_tpu.cli <folder> --distributed

Failure contract under DCN partner loss (the reference has none: a dead MPI
rank leaves the others blocked forever in MPI_Recv, sparse_matrix_mult.cu:
508-552, SURVEY.md section 5.3).  Here, every host heartbeats the JAX
coordination service (heartbeat window: `SPGEMM_TPU_DCN_HEARTBEAT_S`, default
jax's 100 s); when a partner dies, survivors terminate within that window --
fail-fast and LOUD, never a hang and never a partial `./matrix` (the writer
only runs after the replicated combine succeeds).  Two surfacing paths,
whichever fires first: the distributed service's error poller hard-exits the
process non-zero ("Terminating process because the JAX distributed service
detected fatal errors"), or a collective raises and
`chain_product_multihost` wraps it in `PartnerLostError`.
Recovery is a rerun: the engine is a single deterministic program over input
files, so there is no distributed state to salvage -- restart is the
recovery path, and per-pass checkpoints (utils/checkpoint.py, --checkpoint-
dir) let the rerun resume from the last completed chain pass.
Exercised by tests/test_multihost.py::test_partner_loss_fails_fast with a
real killed worker process.
"""

from __future__ import annotations

import logging
import os

import numpy as np

from spgemm_tpu.chain import chain_product
from spgemm_tpu.parallel.chainpart import partition_chain
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix

log = logging.getLogger("spgemm_tpu.multihost")


class PartnerLostError(RuntimeError):
    """A DCN collective failed because a partner host died mid-run."""


def init_from_env() -> None:
    """Initialize jax.distributed from JAX_COORDINATOR/JAX_NUM_PROCESSES/
    JAX_PROCESS_ID (no-op if unset or already initialized)."""
    coord = os.environ.get("JAX_COORDINATOR")
    if not coord:
        return
    from spgemm_tpu.utils import jaxcompat

    kwargs = {}
    hb = os.environ.get("SPGEMM_TPU_DCN_HEARTBEAT_S")
    if hb:
        kwargs["heartbeat_timeout_seconds"] = int(hb)
    jaxcompat.distributed_initialize(
        coordinator_address=coord,
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]),
        **kwargs,
    )


def _allgather_partials(partial: BlockSparseMatrix | None, k: int):
    """Exchange per-process partial products (variable nnzb) via two padded
    all-gathers: metadata first, then coord/tile slabs padded to the max."""
    import jax
    from jax.experimental import multihost_utils

    p = jax.process_count()
    meta_local = np.array(
        [partial.rows, partial.cols, partial.nnzb] if partial is not None
        else [-1, -1, -1], dtype=np.int64)
    metas = np.asarray(multihost_utils.process_allgather(meta_local))  # (P, 3)
    max_nnzb = max(1, int(metas[:, 2].max()))

    coords = np.full((max_nnzb, 2), -1, dtype=np.int64)
    tiles = np.zeros((max_nnzb, k, k), dtype=np.uint64)
    if partial is not None and partial.nnzb:
        coords[: partial.nnzb] = partial.coords
        tiles[: partial.nnzb] = partial.tiles
    # uint64 is not a DCN-friendly dtype everywhere; ship as two uint32 planes
    from spgemm_tpu.ops import u64 as u64mod

    t_hi, t_lo = u64mod.u64_to_hilo(tiles)
    all_coords = np.asarray(multihost_utils.process_allgather(coords))
    all_hi = np.asarray(multihost_utils.process_allgather(t_hi))
    all_lo = np.asarray(multihost_utils.process_allgather(t_lo))

    partials = []
    for r in range(p):
        rows, cols, nnzb = (int(v) for v in metas[r])
        if rows < 0:
            continue  # idle rank (N < P degenerate branch)
        partials.append(BlockSparseMatrix(
            rows=rows, cols=cols, k=k,
            coords=all_coords[r, :nnzb],
            tiles=u64mod.hilo_to_u64(all_hi[r, :nnzb], all_lo[r, :nnzb])))
    return partials


def chain_product_multihost(matrices_for_me: list[BlockSparseMatrix] | None,
                            k: int, multiply=None, **kwargs) -> BlockSparseMatrix:
    """Reduce this process's sub-chain, exchange partials over DCN, and run
    the reference's combine tree (replicated on every host)."""
    partial = (chain_product(matrices_for_me, multiply=multiply, **kwargs)
               if matrices_for_me else None)
    try:
        from jax.errors import JaxRuntimeError as _RuntimeErr
    except ImportError:  # older jaxlib spelling
        from jaxlib.xla_extension import XlaRuntimeError as _RuntimeErr
    try:
        partials = _allgather_partials(partial, k)
    except _RuntimeErr as e:  # jaxlib surfaces partner death as XlaRuntimeError;
        # deliberately narrow -- config bugs (shape mismatch, OOM in numpy)
        # must surface as themselves, not as a bogus "rerun the job"
        raise PartnerLostError(
            "DCN partner lost during partial-product exchange "
            "(a peer host died or its heartbeat lapsed). No output was "
            "written; rerun the job -- per-pass checkpoints resume the "
            "chain (see module docstring failure contract).") from e
    log.info("gathered %d partials over DCN", len(partials))
    if len(partials) == 1:
        return partials[0]
    return chain_product(partials, multiply=multiply, **kwargs)


def run_distributed(folder: str, k: int, n: int, loader, multiply=None,
                    **kwargs) -> BlockSparseMatrix:
    """Full distributed driver: partition by process_index, load only the
    local slice, reduce, exchange, combine.  `loader(start, end)` returns the
    inclusive sub-chain."""
    import jax

    p = jax.process_count()
    r = jax.process_index()
    parts = partition_chain(n, p)
    my = parts[r] if r < len(parts) else None
    mine = loader(my[0], my[1]) if my is not None else None
    log.info("process %d/%d owns chain[%s]", r, p, my)
    return chain_product_multihost(mine, k, multiply=multiply, **kwargs)
