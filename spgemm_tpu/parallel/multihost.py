"""Multi-host (DCN) chain distribution: the literal `mpirun -np P` replacement.

The reference distributes the chain over MPI ranks and funnels every partial
product through a serial rank-0 Recv loop in host memory (sparse_matrix_mult.
cu:460-556, an O(P) bottleneck).  The JAX-native multi-host story:

  * each *process* (host) owns the same chain slice arithmetic as an MPI rank
    (parallel/chainpart.partition_chain -- bit-for-bit the reference's N/P
    split) and reduces its sub-chain locally;
  * partial products are exchanged over DCN in fixed-size CHUNKS
    (jax.experimental.multihost_utils all-gathers, O(log P) each -- not a
    serial gather): every rank's partial ships `SPGEMM_TPU_DCN_CHUNK_MB`
    (default 64 MiB) at a time, so the transient exchange buffer is bounded
    at O(P x chunk) regardless of how skewed the partials are -- the padded
    all-gather it replaces materialized O(P x max_nnzb) on every host, a
    host-RAM cliff the reference's chunked point-to-point sends
    (sparse_matrix_mult.cu:467-506) never had.  The bound is logged before
    the first collective; a chunk budget too small for even one tile raises
    immediately (never a silent mid-exchange OOM), and `=0` keeps the legacy
    padded path behind a loud warning for A/B runs.  Every host then runs
    the identical combine tree, so the result is replicated and any host can
    write it (no rank-0 hot spot);
  * within each host, the per-multiply numeric phase can additionally shard
    over local devices (rowshard/innershard/ring).

Launch (per host):
    JAX_COORDINATOR=host0:1234 JAX_NUM_PROCESSES=P JAX_PROCESS_ID=r \
        python -m spgemm_tpu.cli <folder> --distributed

Failure contract under DCN partner loss (the reference has none: a dead MPI
rank leaves the others blocked forever in MPI_Recv, sparse_matrix_mult.cu:
508-552, SURVEY.md section 5.3).  Here, every host heartbeats the JAX
coordination service (heartbeat window: `SPGEMM_TPU_DCN_HEARTBEAT_S`, default
jax's 100 s); when a partner dies, survivors terminate within that window --
fail-fast and LOUD, never a hang and never a partial `./matrix` (the writer
only runs after the replicated combine succeeds).  Two surfacing paths,
whichever fires first: the distributed service's error poller hard-exits the
process non-zero ("Terminating process because the JAX distributed service
detected fatal errors"), or a collective raises and
`chain_product_multihost` wraps it in `PartnerLostError`.
Recovery is a rerun: the engine is a single deterministic program over input
files, so there is no distributed state to salvage -- restart is the
recovery path, and per-pass checkpoints (utils/checkpoint.py, --checkpoint-
dir) let the rerun resume from the last completed chain pass.
Exercised by tests/test_multihost.py::test_partner_loss_fails_fast with a
real killed worker process.
"""

from __future__ import annotations

import logging
import os

import numpy as np

from spgemm_tpu.chain import chain_product
from spgemm_tpu.obs import trace as obs_trace
from spgemm_tpu.parallel.chainpart import partition_chain
from spgemm_tpu.utils import knobs
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix

log = logging.getLogger("spgemm_tpu.multihost")


class PartnerLostError(RuntimeError):
    """A DCN collective failed because a partner host died mid-run."""


def init_from_env() -> None:
    """Initialize jax.distributed from JAX_COORDINATOR/JAX_NUM_PROCESSES/
    JAX_PROCESS_ID (no-op if unset or already initialized)."""
    coord = os.environ.get("JAX_COORDINATOR")
    if not coord:
        return
    from spgemm_tpu.utils import jaxcompat

    kwargs = {}
    hb = knobs.get("SPGEMM_TPU_DCN_HEARTBEAT_S")
    if hb is not None:
        kwargs["heartbeat_timeout_seconds"] = hb
    jaxcompat.distributed_initialize(
        coordinator_address=coord,
        num_processes=int(os.environ["JAX_NUM_PROCESSES"]),
        process_id=int(os.environ["JAX_PROCESS_ID"]),
        **kwargs,
    )


def _dcn_chunk_mb() -> float:
    """SPGEMM_TPU_DCN_CHUNK_MB: per-rank chunk budget (MiB, float) for the
    partial-product exchange; 0 selects the legacy padded all-gather
    (guard-railed -- its peak is logged loudly because it is unbounded in
    max_nnzb).  The registry validates number-ness and >= 0, naming the
    knob on failure."""
    return knobs.get("SPGEMM_TPU_DCN_CHUNK_MB")


def _allgather_partials(partial: BlockSparseMatrix | None, k: int):
    """Exchange per-process partial products (variable nnzb) over DCN with a
    BOUNDED transient footprint: metadata all-gather first, then the
    coord+tile payload ships in fixed-size chunks of at most
    `SPGEMM_TPU_DCN_CHUNK_MB` per rank, one packed uint32 buffer per chunk
    round (coords as 2 int32 words + the hi/lo tile planes -- uint64 is not
    a DCN-friendly dtype everywhere).  Peak transient memory is
    P x chunk_tiles x tile_bytes no matter how skewed the partials are; the
    accumulated result only ever holds each rank's REAL tiles (the padded
    path also materialized every rank at max_nnzb).  The computed bound is
    logged before the first payload collective; a budget that cannot fit
    even one tile raises a ValueError naming the knob."""
    import jax
    from jax.experimental import multihost_utils

    from spgemm_tpu.ops import u64 as u64mod
    from spgemm_tpu.utils.timers import ENGINE

    p = jax.process_count()
    chunk_mb = _dcn_chunk_mb()  # validate the knob before any collective
    # the chunk budget rides in the metadata gather (as exact bytes): every
    # rank must agree on the chunk ROUND COUNT or the collectives deadlock,
    # so a per-host env skew must surface as a config error, not as a hang
    # the heartbeat later mislabels partner loss
    budget_bytes = int(chunk_mb * (1 << 20))
    meta_local = np.array(
        ([partial.rows, partial.cols, partial.nnzb] if partial is not None
         else [-1, -1, -1]) + [budget_bytes], dtype=np.int64)
    with ENGINE.phase("dcn_exchange"):
        metas = np.asarray(multihost_utils.process_allgather(meta_local))
        budgets = metas[:, 3]
        if not np.all(budgets == budget_bytes):
            raise ValueError(
                "SPGEMM_TPU_DCN_CHUNK_MB differs across hosts (budgets in "
                f"bytes, by rank: {budgets.tolist()}): every host must set "
                "the same chunk budget -- the exchange round count is "
                "derived from it")
        max_nnzb = max(1, int(metas[:, 2].max()))
        tile_words = 2 + 2 * k * k  # int32 coord pair + hi/lo u32 planes
        tile_bytes = 4 * tile_words
        if chunk_mb == 0:
            return _allgather_partials_padded(partial, k, metas, max_nnzb,
                                              tile_bytes)
        budget = chunk_mb * (1 << 20)
        if budget < tile_bytes:
            raise ValueError(
                f"SPGEMM_TPU_DCN_CHUNK_MB={chunk_mb:g} cannot fit even one "
                f"k={k} tile ({tile_bytes} B including coords): raise the "
                f"chunk budget to at least {tile_bytes / (1 << 20):.4f} MiB")
        chunk_tiles = min(max_nnzb, int(budget // tile_bytes))
        n_chunks = -(-max_nnzb // chunk_tiles)
        peak = p * chunk_tiles * tile_bytes
        # the memory guard's ledger line: logged BEFORE the first payload
        # collective so an exchange that dies mid-flight still shows what
        # it was about to allocate
        log.info(
            "dcn exchange: %d ranks, max partial %d tiles -> %d chunk "
            "rounds of <=%d tiles; peak exchange buffer %.3f MiB "
            "(bound: P x SPGEMM_TPU_DCN_CHUNK_MB = %.3f MiB)",
            p, max_nnzb, n_chunks, chunk_tiles, peak / (1 << 20),
            p * chunk_mb)
        nnzb_local = int(partial.nnzb) if partial is not None else 0
        pieces: list[list[np.ndarray]] = [[] for _ in range(p)]
        for c in range(n_chunks):
            lo = c * chunk_tiles
            width = min(chunk_tiles, max_nnzb - lo)
            buf = np.zeros((width, tile_words), dtype=np.uint32)
            n_here = min(max(nnzb_local - lo, 0), width)
            if n_here:
                sl = slice(lo, lo + n_here)
                buf[:n_here, :2] = (
                    partial.coords[sl].astype(np.int32).view(np.uint32))
                t_hi, t_lo = u64mod.u64_to_hilo(partial.tiles[sl])
                buf[:n_here, 2: 2 + k * k] = t_hi.reshape(n_here, -1)
                buf[:n_here, 2 + k * k:] = t_lo.reshape(n_here, -1)
            got = np.asarray(multihost_utils.process_allgather(buf))
            ENGINE.incr("dcn_chunks")
            for r in range(p):
                n_r = min(max(int(metas[r, 2]) - lo, 0), width)
                if n_r:  # keep only rank r's REAL tiles from this round --
                    # COPIED, so the (P, width) gather buffer dies with the
                    # round instead of being pinned by slice views until the
                    # final concatenate (which would retain O(P x max_nnzb),
                    # the exact cliff this path removes)
                    pieces[r].append(got[r, :n_r].copy())
            del got
    partials = []
    for r in range(p):
        rows, cols, nnzb = (int(v) for v in metas[r, :3])
        if rows < 0:
            continue  # idle rank (N < P degenerate branch)
        if nnzb:
            flat = np.concatenate(pieces[r], axis=0)
            coords = flat[:, :2].view(np.int32).astype(np.int64)
            tiles = u64mod.hilo_to_u64(
                flat[:, 2: 2 + k * k].reshape(nnzb, k, k),
                flat[:, 2 + k * k:].reshape(nnzb, k, k))
            partials.append(BlockSparseMatrix(rows=rows, cols=cols, k=k,
                                              coords=coords, tiles=tiles))
        else:
            partials.append(BlockSparseMatrix(rows=rows, cols=cols, k=k))
    return partials


def _allgather_partials_padded(partial, k, metas, max_nnzb, tile_bytes):
    """The legacy padded exchange (pre-round-7), kept ONLY behind
    SPGEMM_TPU_DCN_CHUNK_MB=0 for A/B runs: every rank pads to max_nnzb and
    all-gathers to all hosts -- O(P x max_nnzb) transient host RAM, the
    skewed-chain cliff the chunked path exists to remove."""
    import jax
    from jax.experimental import multihost_utils

    from spgemm_tpu.ops import u64 as u64mod

    p = jax.process_count()
    log.warning(
        "dcn exchange: LEGACY PADDED path (SPGEMM_TPU_DCN_CHUNK_MB=0): peak "
        "exchange buffer %.3f MiB = P(%d) x max_nnzb(%d) x %d B -- unbounded "
        "in the largest partial; unset the knob for the chunked bounded "
        "exchange", p * max_nnzb * tile_bytes / (1 << 20), p, max_nnzb,
        tile_bytes)
    coords = np.full((max_nnzb, 2), -1, dtype=np.int64)
    tiles = np.zeros((max_nnzb, k, k), dtype=np.uint64)
    if partial is not None and partial.nnzb:
        coords[: partial.nnzb] = partial.coords
        tiles[: partial.nnzb] = partial.tiles
    # uint64 is not a DCN-friendly dtype everywhere; ship as two uint32 planes
    t_hi, t_lo = u64mod.u64_to_hilo(tiles)
    all_coords = np.asarray(multihost_utils.process_allgather(coords))
    all_hi = np.asarray(multihost_utils.process_allgather(t_hi))
    all_lo = np.asarray(multihost_utils.process_allgather(t_lo))

    partials = []
    for r in range(p):
        rows, cols, nnzb = (int(v) for v in metas[r, :3])
        if rows < 0:
            continue  # idle rank (N < P degenerate branch)
        partials.append(BlockSparseMatrix(
            rows=rows, cols=cols, k=k,
            coords=all_coords[r, :nnzb],
            tiles=u64mod.hilo_to_u64(all_hi[r, :nnzb], all_lo[r, :nnzb])))
    return partials


def chain_product_multihost(matrices_for_me: list[BlockSparseMatrix] | None,
                            k: int, multiply=None, **kwargs) -> BlockSparseMatrix:
    """Reduce this process's sub-chain, exchange partials over DCN, and run
    the reference's combine tree (replicated on every host)."""
    partial = (chain_product(matrices_for_me, multiply=multiply, **kwargs)
               if matrices_for_me else None)
    try:
        from jax.errors import JaxRuntimeError as _RuntimeErr
    except ImportError:  # older jaxlib spelling
        from jaxlib.xla_extension import XlaRuntimeError as _RuntimeErr
    try:
        partials = _allgather_partials(partial, k)
    except _RuntimeErr as e:  # jaxlib surfaces partner death as XlaRuntimeError;
        # deliberately narrow -- config bugs (shape mismatch, OOM in numpy)
        # must surface as themselves, not as a bogus "rerun the job"
        raise PartnerLostError(
            "DCN partner lost during partial-product exchange "
            "(a peer host died or its heartbeat lapsed). No output was "
            "written; rerun the job -- per-pass checkpoints resume the "
            "chain (see module docstring failure contract).") from e
    log.info("gathered %d partials over DCN", len(partials))
    if len(partials) == 1:
        return partials[0]
    return chain_product(partials, multiply=multiply, **kwargs)


def run_distributed(folder: str, k: int, n: int, loader, multiply=None,
                    **kwargs) -> BlockSparseMatrix:
    """Full distributed driver: partition by process_index, load only the
    local slice, reduce, exchange, combine.  `loader(start, end)` returns the
    inclusive sub-chain."""
    import jax

    p = jax.process_count()
    r = jax.process_index()
    parts = partition_chain(n, p)
    my = parts[r] if r < len(parts) else None
    mine = loader(my[0], my[1]) if my is not None else None
    log.info("process %d/%d owns chain[%s]", r, p, my)
    # every span this rank emits carries its rank/world tags, so the
    # per-rank trace dumps `cli trace-dump --merge` stitches show which
    # host folded what (the slice tag's multihost analog)
    with obs_trace.RECORDER.tagged(rank=r, world=p):
        return chain_product_multihost(mine, k, multiply=multiply,
                                       **kwargs)
