"""Output-space sharded SpGEMM: shard_map over the key axis (bit-exact).

The numeric phase is embarrassingly parallel over output tiles -- each output
tile's pair list folds independently -- so sharding the key axis across the
mesh preserves the reference's per-tile accumulation order exactly
(SURVEY.md section 2.9) while scaling linearly.  Tile slabs are replicated
(they live in HBM once per chip); the pair-index arrays are sharded; the
result shards concatenate without any value arithmetic, so no collective
touches data in the non-associative domain.

This is the TPU analog of the reference's only intra-multiply parallelism
(one CUDA block per output tile, sparse_matrix_mult.cu:44-66,243-248), lifted
from "blocks on one GPU" to "tiles across a pod".  Cross-device it replaces
the MPI layer's job for a single huge SpGEMM (the north star's row-partitioned
`webbase-1M` config).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spgemm_tpu.obs import profile as obs_profile
from spgemm_tpu.ops import u64
from spgemm_tpu.ops.spgemm import numeric_round_impl, pack_tiles
from spgemm_tpu.ops.symbolic import plan_rounds, symbolic_join
from spgemm_tpu.utils import jaxcompat
from spgemm_tpu.parallel.mesh import default_mesh
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix


@partial(jax.jit, static_argnames=("mesh",))
def _numeric_round_sharded_jitted(a_hi, a_lo, b_hi, b_lo, pa, pb, *,
                                  mesh: Mesh):
    shard = jaxcompat.shard_map(
        numeric_round_impl,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P("keys"), P("keys")),
        out_specs=(P("keys"), P("keys")),
        check_vma=False,  # the fori_loop zero-init carry is unvarying by construction
    )
    return shard(a_hi, a_lo, b_hi, b_lo, pa, pb)


# compile-accounted (obs/profile), like the resident engine's jits
_numeric_round_sharded = obs_profile.ProfiledJit(
    "rowshard_round", _numeric_round_sharded_jitted)


def spgemm_sharded(a: BlockSparseMatrix, b: BlockSparseMatrix, *,
                   round_size: int | None = None, mesh: Mesh | None = None,
                   plan=None, **_ignored) -> BlockSparseMatrix:
    """C = A x B, numeric phase sharded over the visible mesh. Bit-exact.

    plan: an ops/symbolic.SpgemmPlan built from the same operand pair --
    reuses its join and the memoized `rowshard_rounds` schedule hook (pure
    numpy; prebuildable on a planner worker thread)."""
    if a.k != b.k:
        raise ValueError(f"tile size mismatch: {a.k} vs {b.k}")
    k = a.k
    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size

    if plan is not None:
        plan.check_operands(a, b)
        join = plan.ensure_exact().join  # land a deferred estimated plan
    else:
        join = symbolic_join(a.coords, b.coords)
    if join.num_keys == 0:
        return BlockSparseMatrix(rows=a.rows, cols=b.cols, k=k)

    a_hi, a_lo = pack_tiles(a)
    b_hi, b_lo = pack_tiles(b)
    rounds = plan.rowshard_rounds(round_size) if plan is not None \
        else plan_rounds(join, a_sentinel=a.nnzb, b_sentinel=b.nnzb,
                         round_size=512 if round_size is None else round_size,
                         route="ladder")  # key-axis shard needs the pair grid

    out = np.zeros((join.num_keys, k, k), dtype=np.uint64)
    for rnd in rounds:
        pa, pb = rnd.pa, rnd.pb
        # pad the key axis to a multiple of the mesh size; sentinel rows
        # compute all-zero tiles that are sliced away below
        K = pa.shape[0]
        K_pad = -(-K // n_dev) * n_dev
        if K_pad != K:
            pad = ((0, K_pad - K), (0, 0))
            pa = np.pad(pa, pad, constant_values=a.nnzb)
            pb = np.pad(pb, pad, constant_values=b.nnzb)
        oh, ol = _numeric_round_sharded(a_hi, a_lo, b_hi, b_lo,
                                        jnp.asarray(pa), jnp.asarray(pb),
                                        mesh=mesh)
        vals = u64.hilo_to_u64(np.asarray(oh), np.asarray(ol))
        out[rnd.key_index] = vals[: len(rnd.key_index)]

    return BlockSparseMatrix(rows=a.rows, cols=b.cols, k=k,
                             coords=join.keys, tiles=out)
