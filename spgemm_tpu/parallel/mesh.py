"""Mesh construction helpers + the device-pool slice table.

The serving daemon's scaling story (ARCHITECTURE.md "L4 serving") is the
inverse of the reference's: one resident process owns ALL visible devices,
and `slice_pool` carves them into named slices -- one executor per slice,
so an 8-chip host serves eight cheap jobs concurrently instead of
serializing them behind one device owner while seven chips idle.

Slice-spec grammar (`SPGEMM_TPU_SERVE_SLICES`):

  spec     := "auto" | term ("+" term)*
  term     := [COUNT "x"] WIDTH ["*"]

`COUNTxWIDTH` is COUNT slices of WIDTH devices each; a bare `COUNT` is
COUNT single-device slices; a trailing `*` marks the term's slices as the
DEFAULT placement (first-contact jobs with no estimate land there).
Examples on 8 devices: `1x4+4` = one 4-device slice (devices 0-3) plus
four single-device slices (devices 4-7); `8` = eight singles; `1` = one
single-device slice -- the exact pre-pool single-executor daemon.
`auto` = one single-device slice per visible device plus one full-mesh
slice (the full-mesh slice OVERLAPS the singles; the daemon's placement
treats any two slices sharing a device as mutually exclusive at
dispatch).  Without a `*`, the narrowest slice class is the default.

Spec parsing is jax-free on purpose (the daemon parses at startup and
tests parse with an injected device count); only `slice_devices` /
`slice_mesh` touch the backend, resolving positions into live devices.
"""

from __future__ import annotations

import re
from dataclasses import dataclass


class SliceSpecError(ValueError):
    """An unparsable/overcommitted slice spec; names the spec."""


@dataclass(frozen=True)
class DeviceSlice:
    """One named slice of the visible device list.

    device_ids are POSITIONS into jax.devices() (not platform ids), so
    the table is buildable -- and testable -- without a backend.
    """

    name: str
    index: int
    device_ids: tuple[int, ...]
    default: bool = False

    @property
    def width(self) -> int:
        return len(self.device_ids)

    def overlaps(self, other: "DeviceSlice") -> bool:
        return bool(set(self.device_ids) & set(other.device_ids))


_TERM_RE = re.compile(r"^(?:(\d+)x)?(\d+)(\*)?$")


def parse_slice_spec(spec: str,
                     n_devices: int | None = None) -> list[tuple[int, bool]]:
    """Parse a slice spec into [(width, is_default), ...] in declaration
    order.  `auto` needs n_devices; explicit specs are validated against
    n_devices only when it is known (the daemon may trust an explicit
    spec before the backend is safe to count)."""
    spec = (spec or "").strip()
    if not spec:
        raise SliceSpecError("empty slice spec (SPGEMM_TPU_SERVE_SLICES)")
    if spec == "auto":
        if n_devices is None:
            raise SliceSpecError(
                "slice spec 'auto' needs the visible device count")
        out = [(1, True)] * n_devices
        if n_devices > 1:
            out.append((n_devices, False))
        return out
    widths: list[tuple[int, bool]] = []
    for term in spec.split("+"):
        m = _TERM_RE.match(term.strip())
        if m is None:
            raise SliceSpecError(
                f"bad slice-spec term {term.strip()!r} in "
                f"SPGEMM_TPU_SERVE_SLICES={spec!r} (grammar: [COUNTx]WIDTH"
                f"[*] terms joined by '+', or 'auto')")
        count_s, width_s, star = m.groups()
        if count_s is None:
            # bare N = N single-device slices (the `1x4+4` idiom)
            count, width = int(width_s), 1
        else:
            count, width = int(count_s), int(width_s)
        if count < 1 or width < 1:
            raise SliceSpecError(
                f"slice-spec term {term.strip()!r} must have count and "
                f"width >= 1 (SPGEMM_TPU_SERVE_SLICES={spec!r})")
        widths += [(width, star is not None)] * count
    total = sum(w for w, _ in widths)
    if n_devices is not None and total > n_devices:
        raise SliceSpecError(
            f"slice spec {spec!r} needs {total} devices but only "
            f"{n_devices} are visible")
    return widths


def slice_pool(spec: str | None = None,
               n_devices: int | None = None) -> list[DeviceSlice]:
    """The slice table for a spec (default: the SPGEMM_TPU_SERVE_SLICES
    knob).  Devices are assigned to terms in declaration order; `auto`
    builds per-device singles plus one overlapping full-mesh slice.
    Exactly one slice class is default (see module doc): the spec's `*`
    term, else the narrowest width present."""
    from spgemm_tpu.utils import knobs  # noqa: PLC0415

    if spec is None:
        spec = knobs.get("SPGEMM_TPU_SERVE_SLICES")
    spec = (spec or "").strip()
    if spec == "auto":
        if n_devices is None:
            raise SliceSpecError(
                "slice spec 'auto' needs the visible device count")
        slices = [DeviceSlice(f"s{i}w1", i, (i,), default=True)
                  for i in range(n_devices)]
        if n_devices > 1:
            slices.append(DeviceSlice(f"s{n_devices}w{n_devices}",
                                      n_devices, tuple(range(n_devices))))
        return slices
    widths = parse_slice_spec(spec, n_devices)
    any_default = any(d for _, d in widths)
    min_width = min(w for w, _ in widths)
    slices: list[DeviceSlice] = []
    pos = 0
    for i, (width, is_default) in enumerate(widths):
        ids = tuple(range(pos, pos + width))
        pos += width
        default = is_default if any_default else width == min_width
        slices.append(DeviceSlice(f"s{i}w{width}", i, ids, default=default))
    return slices


def slice_devices(sl: DeviceSlice) -> list:
    """The live jax devices of a slice (positions -> devices; raises if
    the spec overcommits the actually-visible device list)."""
    import jax  # noqa: PLC0415

    devs = jax.devices()
    if sl.device_ids and max(sl.device_ids) >= len(devs):
        raise SliceSpecError(
            f"slice {sl.name} needs device position {max(sl.device_ids)} "
            f"but only {len(devs)} devices are visible")
    return [devs[i] for i in sl.device_ids]


def slice_mesh(sl: DeviceSlice, axis: str = "keys"):
    """A 1-D named mesh over a slice's devices: slice width stays
    transparent to mesh-consuming engine layers (parallel/ring,
    parallel/rowshard take a mesh, not a device count)."""
    import jax  # noqa: PLC0415

    devs = slice_devices(sl)
    return jax.make_mesh((len(devs),), (axis,), devices=devs)


def default_mesh(n_devices: int | None = None, axis: str = "keys"):
    """1-D mesh over the first n visible devices (all by default)."""
    import jax  # noqa: PLC0415

    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return jax.make_mesh((len(devs),), (axis,), devices=devs)
