"""Inner-dimension partitioned SpGEMM: partial products reduced over ICI.

The north star's "MPI -> psum over ICI" mapping (BASELINE.json): each device
owns a slice of every output tile's pair list (the contraction dimension),
folds its slice into a partial tile, and the partials are combined across the
mesh with a butterfly all-reduce built from `jax.lax.ppermute` -- the log-P
exchange the reference's report *claimed* its MPI merge had (SURVEY.md
section 0 caveat 1) but its code (an O(P) serial gather to rank 0,
sparse_matrix_mult.cu:460-556) never did.  Data never leaves HBM.

Arithmetic mode: clean mod-(2^64-1) ("field mode", ops/u64.py) -- associative,
so the cross-device reduction is order-independent and deterministic.  This is
NOT bit-identical to the reference's wrap-then-mod semantics in adversarial
cases (it IS identical whenever values stay below 2^32, e.g. every benchmark
config); use rowshard for bit-exact distributed runs.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spgemm_tpu.ops import u64
from spgemm_tpu.ops.spgemm import pack_tiles
from spgemm_tpu.utils import jaxcompat
from spgemm_tpu.ops.symbolic import plan_rounds, symbolic_join
from spgemm_tpu.parallel.mesh import default_mesh
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix


def fold_pairs_field(a_hi, a_lo, b_hi, b_lo, pa, pb, *, small: bool = False):
    """Fold (K, P) pair lists into (K, k, k) partial tiles, field semantics.

    small=True is the PROVEN bounded route (operands < 2^32, caller-gated on
    val_bound): u64.mac_field_b32 -- ~6x fewer vector ops per MAC and the hi
    operand gathers drop out entirely (u64.py docstring has the proof)."""
    K, Pn = pa.shape
    k = a_hi.shape[-1]
    al = a_lo[pa]
    bl = b_lo[pb]
    atl = jnp.transpose(al, (1, 0, 2, 3))  # (P, K, ty, j)
    btl = jnp.transpose(bl, (1, 0, 2, 3))  # (P, K, j, tx)
    if not small:
        ath = jnp.transpose(a_hi[pa], (1, 0, 2, 3))
        bth = jnp.transpose(b_hi[pb], (1, 0, 2, 3))

    def body(p, acc):
        acc_h, acc_l = acc
        pal, pbl = atl[p], btl[p]
        if small:
            for j in range(k):  # unrolled: field mode is order-free anyway
                acc_h, acc_l = u64.mac_field_b32(
                    acc_h, acc_l,
                    pal[:, :, j : j + 1], pbl[:, j : j + 1, :],
                )
            return acc_h, acc_l
        pah, pbh = ath[p], bth[p]
        for j in range(k):
            acc_h, acc_l = u64.mac_field(
                acc_h, acc_l,
                pah[:, :, j : j + 1], pal[:, :, j : j + 1],
                pbh[:, j : j + 1, :], pbl[:, j : j + 1, :],
            )
        return acc_h, acc_l

    zero = jnp.zeros((K, k, k), jnp.uint32)
    if Pn == 1:
        # rank-compacted callers (parallel/ring) fold one pair per cell per
        # pass: inline the single iteration instead of paying a one-trip
        # while loop per (step, rank)
        return body(0, (zero, zero))
    return jax.lax.fori_loop(0, Pn, body, (zero, zero))


def butterfly_allreduce_modadd(hi, lo, axis_name: str, n_dev: int):
    """All-reduce with mod-(2^64-1) addition via XOR-butterfly ppermute.

    log2(n) exchange steps over ICI; n_dev must be a power of two.  This is
    `psum` with a custom modular monoid -- associativity+commutativity of
    field mode is what licenses it."""
    step = 1
    while step < n_dev:
        perm = [(i, i ^ step) for i in range(n_dev)]
        other_hi = jax.lax.ppermute(hi, axis_name, perm)
        other_lo = jax.lax.ppermute(lo, axis_name, perm)
        hi, lo = u64.addmod_field(hi, lo, other_hi, other_lo)
        step <<= 1
    return hi, lo


def _make_sharded_fold(mesh: Mesh, small: bool = False):
    n_dev = mesh.devices.size

    def per_device(a_hi, a_lo, b_hi, b_lo, pa, pb):
        part_h, part_l = fold_pairs_field(a_hi, a_lo, b_hi, b_lo, pa, pb,
                                          small=small)
        if n_dev & (n_dev - 1) == 0 and n_dev > 1:
            return butterfly_allreduce_modadd(part_h, part_l, "inner", n_dev)
        if n_dev == 1:
            return part_h, part_l
        # non-pow2 mesh: gather partials and fold in device order
        all_h = jax.lax.all_gather(part_h, "inner")  # (n_dev, K, k, k)
        all_l = jax.lax.all_gather(part_l, "inner")

        def body(i, acc):
            return u64.addmod_field(acc[0], acc[1], all_h[i], all_l[i])

        zero = jnp.zeros_like(part_h)
        return jax.lax.fori_loop(0, n_dev, body, (zero, zero))

    return jax.jit(jaxcompat.shard_map(
        per_device,
        mesh=mesh,
        in_specs=(P(), P(), P(), P(), P(None, "inner"), P(None, "inner")),
        out_specs=(P(), P()),
        check_vma=False,  # outputs are replicated by the all-reduce
    ))


def spgemm_inner(a: BlockSparseMatrix, b: BlockSparseMatrix, *,
                 round_size: int | None = None, mesh: Mesh | None = None,
                 **_ignored) -> BlockSparseMatrix:
    """C = A x B with the contraction dimension sharded over the mesh and
    partial products all-reduced over ICI (field-mode arithmetic)."""
    if a.k != b.k:
        raise ValueError(f"tile size mismatch: {a.k} vs {b.k}")
    k = a.k
    if mesh is None:
        mesh = default_mesh(axis="inner")
    n_dev = mesh.devices.size

    join = symbolic_join(a.coords, b.coords)
    if join.num_keys == 0:
        return BlockSparseMatrix(rows=a.rows, cols=b.cols, k=k)

    a_hi, a_lo = pack_tiles(a)
    b_hi, b_lo = pack_tiles(b)
    rounds = plan_rounds(join, a_sentinel=a.nnzb, b_sentinel=b.nnzb,
                         round_size=512 if round_size is None else round_size,
                         route="ladder")  # sharded fold needs the pair grid
    # proven bounded operands ride the ~6x cheaper b32 MAC (val_bound gate,
    # same proof discipline as the exact engine's nomod route)
    fold = _make_sharded_fold(mesh, u64.operands_below_2_32(a, b))

    out = np.zeros((join.num_keys, k, k), dtype=np.uint64)
    for rnd in rounds:
        pa, pb = rnd.pa, rnd.pb
        # pad the pair axis to a multiple of the mesh size
        Pn = pa.shape[1]
        P_pad = -(-Pn // n_dev) * n_dev
        if P_pad != Pn:
            pad = ((0, 0), (0, P_pad - Pn))
            pa = np.pad(pa, pad, constant_values=a.nnzb)
            pb = np.pad(pb, pad, constant_values=b.nnzb)
        oh, ol = fold(a_hi, a_lo, b_hi, b_lo, jnp.asarray(pa), jnp.asarray(pb))
        vals = u64.hilo_to_u64(np.asarray(oh), np.asarray(ol))
        out[rnd.key_index] = vals[: len(rnd.key_index)]

    return BlockSparseMatrix(rows=a.rows, cols=b.cols, k=k,
                             coords=join.keys, tiles=out)
