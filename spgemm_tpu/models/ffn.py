"""Block-sparse Transformer FFN: the float/MXU path (BASELINE.json config 5).

The u64 parity engine (ops/spgemm.py) is VPU-bound by necessity (exact modular
arithmetic); this module is where the MXU earns its keep: a two-layer FFN
whose weight matrices are block-sparse -- dense k x k tiles at ~10% block
density -- contracted against dense activations as batched MXU matmuls over
gathered tile slabs.

Weight layouts (regular structure => static shapes, no padding waste):
  * W1 (d_model -> d_ff) is column-major block-sparse: each output
    block-column owns `rpc` nonzero block-rows -- a gather + einsum.
  * W2 (d_ff -> d_model) is row-major block-sparse: each input block-row owns
    `cpc` nonzero block-columns -- an einsum + segment-sum scatter.

Sharding (SPMD over a (dp, tp) mesh, see make_sharded_train_step):
  * batch      -> dp
  * sequence   -> tp at rest (sequence parallelism); all-gathered to enter
                  the FFN -- the standard SP pattern
  * W1         -> tp by output block-column (column parallel)
  * W2         -> tp by input block-row (row parallel, aligned with W1's
                  output sharding so no resharding of activations)
  * second matmul produces partial sums -> psum over tp (over ICI)
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from spgemm_tpu.utils import jaxcompat


@dataclass(frozen=True)
class BlockSparseFFNConfig:
    d_model: int = 4096
    d_ff: int = 16384
    k: int = 128            # tile edge (MXU-native)
    block_density: float = 0.1
    dtype: str = "bfloat16"

    @property
    def nb_model(self) -> int:  # block count along d_model
        return self.d_model // self.k

    @property
    def nb_ff(self) -> int:     # block count along d_ff
        return self.d_ff // self.k

    @property
    def rpc(self) -> int:       # nonzero block-rows per W1 block-column
        return max(1, int(round(self.nb_model * self.block_density)))

    @property
    def cpc(self) -> int:       # nonzero block-cols per W2 block-row
        return max(1, int(round(self.nb_model * self.block_density)))


def init_params(cfg: BlockSparseFFNConfig, key) -> dict:
    k1, k2, k3, k4 = jax.random.split(key, 4)
    dtype = jnp.dtype(cfg.dtype)

    def choice_rows(key_r, n_lists, n_from, m):
        return jax.vmap(
            lambda s: jax.random.choice(s, n_from, shape=(m,), replace=False)
        )(jax.random.split(key_r, n_lists)).astype(jnp.int32)

    s1 = 1.0 / np.sqrt(cfg.rpc * cfg.k)
    s2 = 1.0 / np.sqrt(cfg.block_density * cfg.nb_ff * cfg.k)
    return {
        "w1": {  # column-major: (nb_ff, rpc) block-rows + tiles
            "rows": choice_rows(k1, cfg.nb_ff, cfg.nb_model, cfg.rpc),
            "tiles": (jax.random.normal(k2, (cfg.nb_ff, cfg.rpc, cfg.k, cfg.k)) * s1).astype(dtype),
        },
        "w2": {  # row-major: (nb_ff, cpc) block-cols + tiles
            "cols": choice_rows(k3, cfg.nb_ff, cfg.nb_model, cfg.cpc),
            "tiles": (jax.random.normal(k4, (cfg.nb_ff, cfg.cpc, cfg.k, cfg.k)) * s2).astype(dtype),
        },
    }


def bsmm_gather(x_blocks, w) -> jax.Array:
    """Column-parallel block-sparse matmul: (B, nbr, k) -> (B, nbc, k).

    Gathers each output block-column's nonzero input block-rows, contracts on
    the MXU: einsum (B, nbc, rpc, k) x (nbc, rpc, k, k)."""
    gathered = x_blocks[:, w["rows"], :]            # (B, nbc, rpc, k)
    return jnp.einsum("bcrk,crkj->bcj", gathered, w["tiles"])


def bsmm_scatter(x_blocks, w, n_out_blocks: int) -> jax.Array:
    """Row-parallel block-sparse matmul: (B, nbr, k) -> (B, n_out_blocks, k).

    Each input block-row contributes to its `cpc` output block-columns;
    contributions are scatter-added with a segment sum."""
    B = x_blocks.shape[0]
    k = x_blocks.shape[-1]
    contrib = jnp.einsum("brk,rckj->brcj", x_blocks, w["tiles"])  # (B, R, C, k)
    R, C = w["cols"].shape
    flat = contrib.reshape(B, R * C, k).transpose(1, 0, 2)        # (R*C, B, k)
    segs = w["cols"].reshape(R * C)
    out = jax.ops.segment_sum(flat, segs, num_segments=n_out_blocks)
    return out.transpose(1, 0, 2)                                 # (B, nbo, k)


def ffn_forward(params, x, cfg: BlockSparseFFNConfig) -> jax.Array:
    """x: (batch, seq, d_model) -> (batch, seq, d_model)."""
    B, S, D = x.shape
    xb = x.reshape(B * S, cfg.nb_model, cfg.k)
    h = jax.nn.gelu(bsmm_gather(xb, params["w1"]))   # (B*S, nb_ff, k)
    y = bsmm_scatter(h, params["w2"], cfg.nb_model)  # (B*S, nb_model, k)
    return y.reshape(B, S, D).astype(x.dtype)


def prepare_pallas_params(params, cfg: BlockSparseFFNConfig) -> dict:
    """One-time host-side prep for the Pallas forward: convert W2 to
    column-major (ops/pallas_bsmm.w2_to_column_major)."""
    from spgemm_tpu.ops.pallas_bsmm import w2_to_column_major

    rows2, tiles2 = w2_to_column_major(
        params["w2"]["cols"], params["w2"]["tiles"], cfg.nb_model)
    return {"w1": params["w1"], "w2cm": {"rows": rows2, "tiles": tiles2}}


def ffn_forward_pallas(pparams, x, cfg: BlockSparseFFNConfig,
                       block_m: int = 128, fuse_gelu: bool = False,
                       resident: bool | None = None) -> jax.Array:
    """ffn_forward with both matmuls as Pallas MXU kernels (single chip).

    pparams: output of prepare_pallas_params.  The batch*seq axis is padded to
    a block_m multiple; weights stream through VMEM via scalar-prefetch index
    maps (no gather materialization).  fuse_gelu moves the activation into
    the first kernel's epilogue (benchmarks/ffn_sweep.py A/Bs this).
    resident keeps each x row-panel VMEM-resident across output block-cols
    (bsmm_pallas_resident -- the compute-bound layout, ROOFLINE_FFN.md
    section 3 lever 2); None auto-picks it per matmul when the panel fits."""
    from spgemm_tpu.ops.pallas_bsmm import (
        bsmm_pallas, bsmm_pallas_resident, resident_panel_fits)

    B, S, D = x.shape
    M = B * S
    M_pad = -(-M // block_m) * block_m
    xf = x.reshape(M, D)
    if M_pad != M:
        xf = jnp.concatenate(
            [xf, jnp.zeros((M_pad - M, D), x.dtype)], axis=0)

    def mm(xin, w, fused):
        use_res = resident
        if use_res is None:
            use_res = resident_panel_fits(xin.shape[1], block_m,
                                          jnp.dtype(xin.dtype).itemsize,
                                          cfg.k)
        fn = bsmm_pallas_resident if use_res else bsmm_pallas
        return fn(xin, w["rows"], w["tiles"], block_m=block_m,
                  fuse_gelu=fused)

    h = mm(xf, pparams["w1"], fuse_gelu)
    if not fuse_gelu:
        h = jax.nn.gelu(h)
    y = mm(h, pparams["w2cm"], False)
    return y[:M].reshape(B, S, D).astype(x.dtype)


def loss_fn(params, x, y, cfg: BlockSparseFFNConfig):
    pred = ffn_forward(params, x, cfg)
    return jnp.mean(jnp.square(pred.astype(jnp.float32) - y.astype(jnp.float32)))


# ---------------------------------------------------------------------------
# Sharded training step.
# ---------------------------------------------------------------------------

def make_sharded_train_step(mesh: Mesh, cfg: BlockSparseFFNConfig, lr: float = 1e-3):
    """Returns jitted train_step(params, x, y) -> (params, loss).

    Every weight array is tp-sharded on axis 0 (W1 block-cols / W2 block-rows,
    both the d_ff axis -- aligned, so h never reshards); x and y are
    dp-sharded on batch and tp-sharded on sequence (SP at rest)."""

    def per_shard_loss(tiles, idx, x, y):
        w1 = {"rows": idx["w1"], "tiles": tiles["w1"]}
        w2 = {"cols": idx["w2"], "tiles": tiles["w2"]}
        # enter FFN: all-gather the sequence shards (SP -> full activations)
        x_full = jax.lax.all_gather(x, "tp", axis=1, tiled=True)
        y_full = jax.lax.all_gather(y, "tp", axis=1, tiled=True)
        B, S, D = x_full.shape
        xb = x_full.reshape(B * S, cfg.nb_model, cfg.k)
        h = jax.nn.gelu(bsmm_gather(xb, w1))         # local d_ff block-cols
        y_part = bsmm_scatter(h, w2, cfg.nb_model)   # partial over local d_ff
        y_pred = jax.lax.psum(y_part, "tp")          # row-parallel reduce (ICI)
        pred = y_pred.reshape(B, S, D)
        sq = jnp.square(pred.astype(jnp.float32) - y_full.astype(jnp.float32))
        total = jax.lax.psum(jnp.sum(sq), "dp")      # mean over global batch
        count = jax.lax.psum(jnp.asarray(sq.size, jnp.float32), "dp")
        return total / count

    def per_shard_step(params, x, y):
        tiles = {"w1": params["w1"]["tiles"], "w2": params["w2"]["tiles"]}
        idx = {"w1": params["w1"]["rows"], "w2": params["w2"]["cols"]}
        loss, grads = jax.value_and_grad(per_shard_loss)(tiles, idx, x, y)
        # tile grads are tp-local (weight sharding); dp needs an explicit mean
        grads = jax.tree.map(lambda g: jax.lax.pmean(g, "dp"), grads)
        new_tiles = jax.tree.map(
            lambda p, g: p - lr * g.astype(jnp.float32).astype(p.dtype),
            tiles, grads)
        return ({"w1": {"rows": idx["w1"], "tiles": new_tiles["w1"]},
                 "w2": {"cols": idx["w2"], "tiles": new_tiles["w2"]}}, loss)

    pspec = {"w1": {"rows": P("tp"), "tiles": P("tp")},
             "w2": {"cols": P("tp"), "tiles": P("tp")}}
    data_spec = P("dp", "tp")  # batch dp-sharded, seq tp-sharded (SP at rest)

    step = jaxcompat.shard_map(
        per_shard_step,
        mesh=mesh,
        in_specs=(pspec, data_spec, data_spec),
        out_specs=(pspec, P()),
        check_vma=False,
    )
    return jax.jit(step)


def shard_params(params, mesh: Mesh):
    """Place params with their tp shardings (axis 0 of every weight array)."""
    from jax.sharding import NamedSharding

    spec = NamedSharding(mesh, P("tp"))
    return jax.tree.map(lambda a: jax.device_put(a, spec), params)
