"""Reference text directory format: reader + byte-identical writer (C2, C3, C16).

On-disk layout (reverse-engineered from sparse_matrix_mult.cu):

  <folder>/size      "N k"                        (:410-419, read via >> )
  <folder>/matrixI   I = 1..N (1-indexed, :338-345):
      rows cols                                   (:352-353)
      blocks                                      (:362-363)
      then per block:  r c                        (:364-366)
                       k lines of k values        (:372-380)

All reads are whitespace-insensitive (istream >>). The writer must be
byte-identical to the reference's (:595-608): "R C\n", "blocks\n", then per
tile (in sorted (r,c) order -- std::map iteration) "r c\n" and k lines of
space-separated values with NO trailing space (:601-605).

The reference parses files with one OpenMP task per file over 16 threads
(:334-341); here parsing is vectorized numpy per file plus a thread pool
across files (read_chain, below), with an optional C++ fast path (native/).
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from spgemm_tpu.utils.blockcsr import BlockSparseMatrix


def read_size(folder: str) -> tuple[int, int]:
    """Read `<folder>/size` -> (N, k).  (sparse_matrix_mult.cu:410-419)"""
    path = os.path.join(folder, "size")
    with open(path) as f:
        toks = f.read().split()
    if len(toks) < 2:
        raise ValueError(f"malformed size file: {path!r}")
    return int(toks[0]), int(toks[1])


def read_matrix(path: str, k: int) -> BlockSparseMatrix:
    """Parse one matrix file into a BlockSparseMatrix.

    Fast path: the native C++ tokenizer (utils/native.py, GIL-released).
    Fallback is token-vectorized numpy: everything after the 3-token header is
    one uint64 parse + reshape to (blocks, 2 + k*k).  Either way, no
    per-element formatted reads (the reference's `>>` loop at
    sparse_matrix_mult.cu:372-380 is what motivated its OpenMP task pool).
    """
    from spgemm_tpu.utils import native

    parsed = native.parse_matrix(path, k)
    if parsed is not None:
        rows, cols, coords, tiles = parsed
        return BlockSparseMatrix.from_blocks(rows, cols, k, coords, tiles)

    with open(path, "rb") as f:
        toks = f.read().split()
    if len(toks) < 3:
        raise ValueError(f"malformed matrix file: {path!r}")
    rows, cols, blocks = int(toks[0]), int(toks[1]), int(toks[2])
    per = 2 + k * k
    need = 3 + blocks * per
    if len(toks) < need:
        raise ValueError(
            f"matrix file {path!r}: expected {need} tokens for {blocks} blocks, got {len(toks)}")
    if blocks == 0:
        return BlockSparseMatrix(rows=rows, cols=cols, k=k)
    flat = np.array(toks[3:need], dtype=np.uint64).reshape(blocks, per)
    coords = flat[:, :2].astype(np.int64)
    tiles = flat[:, 2:].reshape(blocks, k, k)
    return BlockSparseMatrix.from_blocks(rows, cols, k, coords, tiles)


def read_chain(folder: str, start: int, end: int, k: int,
               max_workers: int | None = None) -> list[BlockSparseMatrix]:
    """Load matrix{start+1}..matrix{end+1} (0-based range, 1-indexed files,
    sparse_matrix_mult.cu:338-345) concurrently -- the reference's OpenMP
    task-per-file pattern (:334-341) as a thread pool.

    max_workers=None (the default) picks min(16, 4x host cores): parsing is
    CPU-bound (GIL-released native tokenizer), so threads far beyond cores
    only add contention -- measured 2x SLOWER at 16 threads on a 1-core
    host.  An explicit max_workers is honored as given (the reference
    hardcodes 16 OpenMP threads; outputs are identical either way).
    """
    if max_workers is None:
        max_workers = min(16, 4 * (os.cpu_count() or 1))
    indices = range(start + 1, end + 2)
    paths = [os.path.join(folder, f"matrix{i}") for i in indices]
    with ThreadPoolExecutor(max_workers=max(1, max_workers)) as pool:
        return list(pool.map(lambda p: read_matrix(p, k), paths))


def format_matrix(m: BlockSparseMatrix) -> bytes:
    """Serialize in the reference writer's exact byte format
    (sparse_matrix_mult.cu:595-608)."""
    out = [f"{m.rows} {m.cols}\n{m.nnzb}\n"]
    coords = m.coords
    # itemized str() on python ints; tolist() converts u64 exactly
    for i in range(m.nnzb):
        out.append(f"{coords[i, 0]} {coords[i, 1]}\n")
        for row in m.tiles[i].tolist():
            out.append(" ".join(map(str, row)))
            out.append("\n")
    return "".join(out).encode()


def write_matrix(path: str, m: BlockSparseMatrix) -> None:
    """Write `m` to `path` byte-identically to the reference (C16).

    NOTE: the reference prunes all-zero tiles before writing
    (sparse_matrix_mult.cu:577-592); callers do that via m.prune_zeros()."""
    from spgemm_tpu.utils import native

    if native.write_matrix(path, m.rows, m.cols, m.k, m.coords, m.tiles):
        return
    with open(path, "wb") as f:
        f.write(format_matrix(m))


def write_chain_dir(folder: str, matrices: list[BlockSparseMatrix], k: int) -> None:
    """Emit a full input directory (size + matrix1..matrixN) -- test/bench helper."""
    os.makedirs(folder, exist_ok=True)
    with open(os.path.join(folder, "size"), "w") as f:
        f.write(f"{len(matrices)} {k}\n")
    for i, m in enumerate(matrices):
        write_matrix(os.path.join(folder, f"matrix{i + 1}"), m)
