"""The reference's exact arithmetic semantics, as a host-side numpy oracle.

Parity-critical (SURVEY.md section 2.9). The reference's CUDA kernel
(sparse_matrix_mult.cu:48,59-61) computes, per contraction step, in uint64:

    p   = (a * b) mod 2^64            # hardware wraparound on the product
    p'  = p mod (2^64 - 1)            # :59
    acc = ((acc + p') mod 2^64) mod (2^64 - 1)   # :61 -- the sum can wrap FIRST

This is *not* clean arithmetic mod (2^64 - 1): when `acc + p'` >= 2^64 the
wrap-then-mod result is one less than the clean modular sum, so the reduction
is **order-dependent**.  The accumulation order fixed by the reference is:

  * output tile (i, c) contracts its matching inner block-coordinates j in
    ascending order (A's std::map iteration order, sparse_matrix_mult.cu:149-156),
  * and within each tile pair, the k-loop runs j = 0..k-1
    (sparse_matrix_mult.cu:56-62).

Every implementation in this framework (numpy oracle here, the XLA numeric
phase, and the Pallas TPU kernel) reproduces this exact sequence.

Useful simplification used throughout: for x < 2^64,
    x mod (2^64 - 1) == 0 if x == 2^64 - 1 else x
so each "mod" is an equality test against MAX, never a division.
"""

from __future__ import annotations

import numpy as np

# The reference's modulus constant (sparse_matrix_mult.cu:48).
MAX_INT = 0xFFFFFFFFFFFFFFFF  # 2^64 - 1, as a python int
MAX_U64 = np.uint64(MAX_INT)
_ZERO_U64 = np.uint64(0)


# ---------------------------------------------------------------------------
# Scalar (python int) reference -- the dead-simple cross-check implementation.
# ---------------------------------------------------------------------------

def scalar_mac(acc: int, a: int, b: int) -> int:
    """One multiply-accumulate step with the reference's exact semantics."""
    p = (a * b) & MAX_INT  # mod 2^64 (keep low 64 bits only)
    if p == MAX_INT:
        p = 0
    s = (acc + p) & MAX_INT  # the sum can also wrap at 2^64 first
    if s == MAX_INT:
        s = 0
    return s


def scalar_tile_matmul(acc, a_tile, b_tile):
    """Contract one (A-tile, B-tile) pair into acc, all python ints.

    acc, a_tile, b_tile: k x k lists/arrays of ints. Mirrors the loop nest of
    matrix_multiplyKernel (sparse_matrix_mult.cu:54-62): for each output
    element (ty, tx), fold over j = 0..k-1 in order.
    """
    k = len(a_tile)
    out = [[0] * k for _ in range(k)]
    for ty in range(k):
        for tx in range(k):
            s = int(acc[ty][tx])
            for j in range(k):
                s = scalar_mac(s, int(a_tile[ty][j]), int(b_tile[j][tx]))
            out[ty][tx] = s
    return out


# ---------------------------------------------------------------------------
# Vectorized numpy oracle (uint64; hardware wraparound is numpy's behavior).
# ---------------------------------------------------------------------------

def mulmod_np(a: np.ndarray, b: np.ndarray) -> np.ndarray:
    """(a * b) mod 2^64, then mod (2^64 - 1). uint64 arrays, broadcastable."""
    with np.errstate(over="ignore"):
        p = a * b  # uint64 wraparound == mod 2^64
    return np.where(p == MAX_U64, _ZERO_U64, p)


def addmod_np(acc: np.ndarray, p: np.ndarray) -> np.ndarray:
    """((acc + p) mod 2^64) mod (2^64 - 1). uint64 arrays, broadcastable."""
    with np.errstate(over="ignore"):
        s = acc + p
    return np.where(s == MAX_U64, _ZERO_U64, s)


def tile_pair_mac_np(acc: np.ndarray, a_tile: np.ndarray, b_tile: np.ndarray) -> np.ndarray:
    """Accumulate one tile-pair product into acc (all (k,k) uint64).

    Vectorized over the k x k output lanes; sequential over j (order matters,
    see module docstring). out[ty,tx] folds A[ty,j]*B[j,tx] for j=0..k-1.
    """
    k = a_tile.shape[0]
    for j in range(k):
        prod = mulmod_np(a_tile[:, j : j + 1], b_tile[j : j + 1, :])
        acc = addmod_np(acc, prod)
    return acc


def tile_mac_oracle(a_tiles: np.ndarray, b_tiles: np.ndarray) -> np.ndarray:
    """Fold an ordered list of (A, B) tile pairs into one output tile.

    a_tiles/b_tiles: (p, k, k) uint64, already in the engine's j-ascending
    pair order for a single output key.  This is the per-key oracle used for
    sampled parity on configs too large for the full spgemm_oracle
    (benchmarks/run.py cage12/nd24k).
    """
    k = a_tiles.shape[-1]
    acc = np.zeros((k, k), dtype=np.uint64)
    for a_t, b_t in zip(a_tiles, b_tiles):
        acc = tile_pair_mac_np(acc, a_t, b_t)
    return acc


def spgemm_oracle(a_blocks: dict, b_blocks: dict, k: int) -> dict:
    """Reference-semantics block-sparse matmul on dicts {(r,c): (k,k) uint64}.

    Reproduces helper()'s symbolic join and accumulation order
    (sparse_matrix_mult.cu:141-156): iterate A's blocks in sorted (r,c) order;
    for each A block (i, j), for each B block (j, c), accumulate the tile-pair
    product into output block (i, c).  Because A's sorted order visits j
    ascending for fixed i, each output tile's pair list is j-ascending.

    NOTE: does NOT prune all-zero output tiles -- the reference keeps them in
    intermediate chain products and only prunes at final output
    (sparse_matrix_mult.cu:577-592).
    """
    b_by_row: dict = {}
    for (br, bc) in sorted(b_blocks.keys()):
        b_by_row.setdefault(br, []).append(bc)

    out: dict = {}
    for (ar, ac) in sorted(a_blocks.keys()):
        cols = b_by_row.get(ac)
        if not cols:
            continue
        a_tile = a_blocks[(ar, ac)]
        for bc in cols:
            key = (ar, bc)
            acc = out.get(key)
            if acc is None:
                acc = np.zeros((k, k), dtype=np.uint64)
            out[key] = tile_pair_mac_np(acc, a_tile, b_blocks[(ac, bc)])
    return out


def field_spgemm_oracle(a_blocks: dict, b_blocks: dict, k: int) -> dict:
    """Clean mod-(2^64-1) block-sparse matmul oracle in python ints.

    Ground truth for the FIELD-mode paths (ops/u64.py field ops, the MXU
    limb kernel, parallel/innershard + ring): C = A x B over Z/(2^64-1),
    order-free because the clean residue arithmetic is associative.  Agrees
    with spgemm_oracle exactly when no product or partial sum crosses 2^64
    (e.g. all values < 2^32); deviates on wrap-triggering inputs -- that
    deviation IS the documented contract (parallel/innershard.py docstring).
    """
    out: dict = {}
    for (ar, ac), a_tile in a_blocks.items():
        for (br, bc), b_tile in b_blocks.items():
            if ac != br:
                continue
            acc = out.setdefault((ar, bc), [[0] * k for _ in range(k)])
            for ty in range(k):
                for tx in range(k):
                    s = acc[ty][tx]
                    for j in range(k):
                        s = (s + int(a_tile[ty][j]) * int(b_tile[j][tx])) \
                            % MAX_INT
                    acc[ty][tx] = s
    return {key: np.array(tile, dtype=np.uint64) for key, tile in out.items()}


def chain_oracle(matrices: list, k: int) -> dict:
    """Pairwise-halving chain product matching helper2 (sparse_matrix_mult.cu:287-327).

    matrices: list of block dicts. Returns the final block dict. The pairing
    order (adjacent pairs, odd element carried to the end) is semantically
    irrelevant for an associative product -- but the arithmetic here is NOT
    associative (section 2.9), so we replicate the exact reduction tree.
    """
    arr = list(matrices)
    while len(arr) > 1:
        nxt = [spgemm_oracle(arr[i], arr[i + 1], k) for i in range(0, len(arr) - 1, 2)]
        if len(arr) % 2 == 1:
            nxt.append(arr[-1])
        arr = nxt
    return arr[0]
