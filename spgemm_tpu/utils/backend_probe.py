"""Backend liveness probe + platform pinning (shared by bench.py and the CLI).

The failure mode observed on this environment's TPU tunnel is a HANG inside
backend init or the first device op -- not an exception -- so an in-process
try/except can never fail soft.  The probe runs a tiny matmul in a
SUBPROCESS with a hard timeout; the main process must not touch jax's
backends until a probe has passed (or it has pinned a known-good platform).
"""

from __future__ import annotations

import os
import subprocess
import sys

from spgemm_tpu.utils import knobs


def probe_default_backend(timeout_s: float | None = None) -> str:
    """Probe outcome: 'ok' (real accelerator computed), 'cpu' (healthy but
    CPU-only -- deterministic, not worth retrying), 'timeout' (hung), or
    'error' (init crashed).  SPGEMM_TPU_PROBE_TIMEOUT overrides the default
    150 s."""
    if timeout_s is None:
        timeout_s = knobs.get("SPGEMM_TPU_PROBE_TIMEOUT")
    code = ("import jax, jax.numpy as jnp; "
            "x = jnp.ones((64, 64), jnp.bfloat16); "
            "(x @ x).block_until_ready(); "
            "print(jax.devices()[0].platform)")
    try:
        rc = subprocess.run([sys.executable, "-c", code],
                            capture_output=True, text=True, timeout=timeout_s)
        if rc.returncode != 0:
            return "error"
        plat = rc.stdout.strip().splitlines()[-1] if rc.stdout.strip() else ""
        return "cpu" if plat in ("", "cpu") else "ok"
    except subprocess.TimeoutExpired:
        return "timeout"


def failover_to_cpu(context: str, attempts: int = 2) -> bool:
    """Probe the default backend; on persistent failure pin the CPU
    platform.  Returns True iff the failover happened.  The shared guard
    used by the CLI's --failover and the driver-contract entry() (bench.py
    keeps its own richer retry/shrink logic).

    - Already pinned to cpu: nothing to probe, returns False immediately.
    - 'error' outcomes retry (a raise can be a transient tunnel blip);
      'timeout' does not (the observed hang mode persists for hours --
      re-probing burns 150 s per attempt for nothing).
    """
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        return False
    outcome = "error"
    for _ in range(max(1, attempts)):
        outcome = probe_default_backend()
        if outcome in ("ok", "cpu"):
            return False
        if outcome == "timeout":
            break
    print(f"{context}: accelerator unreachable (probe: {outcome}); "
          "falling back to cpu", file=sys.stderr, flush=True)
    pin("cpu")
    return True


def host_only(fn):
    """Marker for host-thread-only code: fn runs on planner/worker threads
    (chain.py plan-ahead, the OOC staging worker's helpers) and must NEVER
    touch a jax backend -- a dead TPU hangs inside backend init, and a hang
    on a worker thread wedges the whole pipeline with no exception to fail
    over on.  spgemm-lint's BKD rule scans the decorated function's WHOLE
    body (not just import time) for backend-touching calls; callers that
    need platform/backend identity must resolve it on the main thread and
    pass it in as data.  Runtime no-op beyond the attribute tag."""
    fn.__spgemm_host_only__ = True
    return fn


def pin(platform: str) -> None:
    """Pin the JAX platform in-process.  The env var alone is ineffective
    here: the TPU plugin's sitecustomize imports jax at interpreter start
    and snapshots JAX_PLATFORMS, so the config must be updated before any
    backend initializes."""
    import jax

    os.environ["JAX_PLATFORMS"] = platform
    from jax._src import xla_bridge
    if not xla_bridge._backends:
        jax.config.update("jax_platforms", platform)
