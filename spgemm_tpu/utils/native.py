"""ctypes bindings for the native I/O library (spgemm_tpu/native/smmio.cpp).

Loads libsmmio.so if present, building it once with g++ if the source is newer
(no pybind11 in this image; the C ABI + ctypes is the binding layer).  All
entry points release the GIL for their full duration, so the loader thread
pool gets real parallelism -- the reference's OpenMP-task-per-file pattern
(sparse_matrix_mult.cu:334-341) without the hardcoded thread count.

Set SPGEMM_TPU_NO_NATIVE=1 to force the pure-Python path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SRC = os.path.join(_DIR, "smmio.cpp")
_SO = os.path.join(_DIR, "libsmmio.so")

_lib = None
_lock = threading.Lock()
_tried = False


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-march=native", "-shared", "-fPIC", "-o", _SO, _SRC],
            check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def get_lib():
    """The loaded library, or None if unavailable/disabled."""
    global _lib, _tried
    if os.environ.get("SPGEMM_TPU_NO_NATIVE"):
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        needs_build = (not os.path.exists(_SO)
                       or os.path.getmtime(_SO) < os.path.getmtime(_SRC))
        if needs_build and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.smm_parse_matrix.restype = ctypes.c_int
        lib.smm_parse_matrix.argtypes = [
            ctypes.c_char_p, ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
            ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
        ]
        lib.smm_free.restype = None
        lib.smm_free.argtypes = [ctypes.c_void_p]
        lib.smm_write_matrix.restype = ctypes.c_int
        lib.smm_write_matrix.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64,
            np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
            np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
        ]
        _lib = lib
        return _lib


def parse_matrix(path: str, k: int):
    """Parse via native code -> (rows, cols, coords (nnzb,2) i64, tiles (nnzb,k,k) u64).

    Returns None if the native library is unavailable; raises on parse errors.
    """
    lib = get_lib()
    if lib is None:
        return None
    header = (ctypes.c_int64 * 3)()
    coords_p = ctypes.POINTER(ctypes.c_int64)()
    tiles_p = ctypes.POINTER(ctypes.c_uint64)()
    rc = lib.smm_parse_matrix(path.encode(), k, header,
                              ctypes.byref(coords_p), ctypes.byref(tiles_p))
    if rc == -1:
        raise FileNotFoundError(f"cannot open {path!r}")
    if rc != 0:
        raise ValueError(f"malformed matrix file {path!r} (native rc={rc})")
    rows, cols, blocks = header[0], header[1], header[2]
    try:
        if blocks == 0:
            coords = np.zeros((0, 2), np.int64)
            tiles = np.zeros((0, k, k), np.uint64)
        else:
            coords = np.ctypeslib.as_array(coords_p, shape=(blocks, 2)).copy()
            tiles = np.ctypeslib.as_array(tiles_p, shape=(blocks, k, k)).copy()
    finally:
        if blocks != 0:
            lib.smm_free(coords_p)
            lib.smm_free(tiles_p)
    return int(rows), int(cols), coords, tiles


def write_matrix(path: str, rows: int, cols: int, k: int,
                 coords: np.ndarray, tiles: np.ndarray) -> bool:
    """Write via native code; returns False if the library is unavailable."""
    lib = get_lib()
    if lib is None:
        return False
    coords = np.ascontiguousarray(coords, np.int64)
    tiles = np.ascontiguousarray(tiles, np.uint64)
    rc = lib.smm_write_matrix(path.encode(), rows, cols, k, len(coords),
                              coords, tiles)
    if rc != 0:
        raise OSError(f"native writer failed for {path!r} (rc={rc})")
    return True
