"""ctypes bindings for the native I/O library (spgemm_tpu/native/smmio.cpp).

Loads libsmmio.so if present, building it once with g++ if the source is newer
(no pybind11 in this image; the C ABI + ctypes is the binding layer).  All
entry points release the GIL for their full duration, so the loader thread
pool gets real parallelism -- the reference's OpenMP-task-per-file pattern
(sparse_matrix_mult.cu:334-341) without the hardcoded thread count.

Set SPGEMM_TPU_NO_NATIVE=1 to force the pure-Python path.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

from spgemm_tpu.utils import knobs

_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SRC = os.path.join(_DIR, "smmio.cpp")
_SYM_SRC = os.path.join(_DIR, "symbolic.cpp")
_FOLD_SRC = os.path.join(_DIR, "parityfold.cpp")
_SO = os.path.join(_DIR, "libsmmio.so")

_lib = None    # spgemm-lint: guarded-by(_lock)
_lock = threading.Lock()
_tried = False  # spgemm-lint: guarded-by(_lock)


def _build() -> bool:
    try:
        subprocess.run(
            ["g++", "-O3", "-march=native", "-fopenmp", "-shared", "-fPIC",
             "-o", _SO, _SRC, _SYM_SRC, _FOLD_SRC],
            check=True, capture_output=True, timeout=120)
        return True
    except (subprocess.SubprocessError, FileNotFoundError):
        return False


def get_lib():
    """The loaded library, or None if unavailable/disabled."""
    global _lib, _tried
    if knobs.get("SPGEMM_TPU_NO_NATIVE"):
        return None
    with _lock:
        if _tried:
            return _lib
        _tried = True
        # Any failure below (missing sources, stale .so without the newer
        # symbols, load errors) must degrade to the pure-Python fallback,
        # never crash the caller -- get_lib sits on the spgemm critical path.
        try:
            needs_build = (not os.path.exists(_SO)
                           or any(os.path.getmtime(_SO) < os.path.getmtime(s)
                                  for s in (_SRC, _SYM_SRC, _FOLD_SRC)))
        except OSError:
            needs_build = not os.path.exists(_SO)
        # spgemm-lint: blk-ok(one-shot memoized build: the lock MUST cover the g++ run so a second thread can neither double-compile nor CDLL a half-written .so; cold path, bounded by the 120s subprocess timeout)
        if needs_build and not _build():
            return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        try:
            lib.smm_parse_matrix.restype = ctypes.c_int
            lib.smm_parse_matrix.argtypes = [
                ctypes.c_char_p, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_uint64)),
            ]
            lib.smm_free.restype = None
            lib.smm_free.argtypes = [ctypes.c_void_p]
            lib.smm_write_matrix.restype = ctypes.c_int
            lib.smm_write_matrix.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
                ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
            ]
            lib.smm_symbolic_join.restype = ctypes.c_int
            lib.smm_symbolic_join.argtypes = [
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                ctypes.c_int64,
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                ctypes.c_int64,
                ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_int64)),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
                ctypes.POINTER(ctypes.POINTER(ctypes.c_int32)),
                ctypes.POINTER(ctypes.c_int64),
            ]
            lib.smm_sym_free.restype = None
            lib.smm_sym_free.argtypes = [ctypes.c_void_p]
            lib.smm_parity_fold.restype = ctypes.c_int64
            lib.smm_parity_fold.argtypes = [
                np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int64, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS"),
                ctypes.c_int64, ctypes.c_int64,
                np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS"),
                ctypes.POINTER(ctypes.c_int64),
            ]
        except AttributeError:
            return None  # stale .so predating a symbol: numpy fallback
        _lib = lib
        return _lib


def parse_matrix(path: str, k: int):
    """Parse via native code -> (rows, cols, coords (nnzb,2) i64, tiles (nnzb,k,k) u64).

    Returns None if the native library is unavailable; raises on parse errors.
    """
    lib = get_lib()
    if lib is None:
        return None
    header = (ctypes.c_int64 * 3)()
    coords_p = ctypes.POINTER(ctypes.c_int64)()
    tiles_p = ctypes.POINTER(ctypes.c_uint64)()
    rc = lib.smm_parse_matrix(path.encode(), k, header,
                              ctypes.byref(coords_p), ctypes.byref(tiles_p))
    if rc == -1:
        raise FileNotFoundError(f"cannot open {path!r}")
    if rc != 0:
        raise ValueError(f"malformed matrix file {path!r} (native rc={rc})")
    rows, cols, blocks = header[0], header[1], header[2]
    try:
        if blocks == 0:
            coords = np.zeros((0, 2), np.int64)
            tiles = np.zeros((0, k, k), np.uint64)
        else:
            coords = np.ctypeslib.as_array(coords_p, shape=(blocks, 2)).copy()
            tiles = np.ctypeslib.as_array(tiles_p, shape=(blocks, k, k)).copy()
    finally:
        if blocks != 0:
            lib.smm_free(coords_p)
            lib.smm_free(tiles_p)
    return int(rows), int(cols), coords, tiles


def symbolic_join_native(a_coords: np.ndarray, b_coords: np.ndarray):
    """Native structure join (native/symbolic.cpp) -- same contract as
    ops.symbolic.symbolic_join.  Returns (keys, pair_ptr, pair_a, pair_b)
    numpy arrays, or None if the native library is unavailable."""
    lib = get_lib()
    if lib is None:
        return None
    a = np.ascontiguousarray(a_coords, np.int64)
    b = np.ascontiguousarray(b_coords, np.int64)
    keys_p = ctypes.POINTER(ctypes.c_int64)()
    ptr_p = ctypes.POINTER(ctypes.c_int64)()
    pa_p = ctypes.POINTER(ctypes.c_int32)()
    pb_p = ctypes.POINTER(ctypes.c_int32)()
    nk = ctypes.c_int64()
    total = ctypes.c_int64()
    rc = lib.smm_symbolic_join(a, len(a), b, len(b),
                               ctypes.byref(keys_p), ctypes.byref(nk),
                               ctypes.byref(ptr_p),
                               ctypes.byref(pa_p), ctypes.byref(pb_p),
                               ctypes.byref(total))
    if rc != 0:
        # Contract: any native failure (allocation, overflow guard) degrades
        # to the bit-identical numpy join rather than killing the multiply.
        import logging
        logging.getLogger("spgemm_tpu.native").warning(
            "native symbolic join failed (rc=%d); falling back to numpy", rc)
        return None
    try:
        n_keys, n_pairs = int(nk.value), int(total.value)
        if n_keys == 0:
            keys = np.zeros((0, 2), np.int64)
            pair_ptr = np.zeros(1, np.int64)
            pair_a = np.zeros(0, np.int32)
            pair_b = np.zeros(0, np.int32)
        else:
            keys = np.ctypeslib.as_array(keys_p, shape=(n_keys, 2)).copy()
            pair_ptr = np.ctypeslib.as_array(ptr_p, shape=(n_keys + 1,)).copy()
            pair_a = np.ctypeslib.as_array(pa_p, shape=(n_pairs,)).copy()
            pair_b = np.ctypeslib.as_array(pb_p, shape=(n_pairs,)).copy()
    finally:
        for p in (keys_p, ptr_p, pa_p, pb_p):
            if p:
                lib.smm_sym_free(p)
    return keys, pair_ptr, pair_a, pair_b


def parity_fold_check(a_tiles: np.ndarray, b_tiles: np.ndarray,
                      pair_ptr: np.ndarray, pair_a: np.ndarray,
                      pair_b: np.ndarray, out_tiles: np.ndarray):
    """Full-parity check of EVERY output key against the reference's
    wrap-then-mod fold, recomputed in native uint64 C++ (parityfold.cpp).

    out_tiles: the engine's (n_keys, k, k) result in join-key order.
    Returns (n_bad, first_bad_key) -- (0, -1) means bit-exact on all keys --
    or None if the native library is unavailable (callers fall back to the
    python-int oracle or sampled parity).
    """
    lib = get_lib()
    if lib is None:
        return None
    k = a_tiles.shape[-1]
    n_keys = len(pair_ptr) - 1
    if n_keys == 0:
        return 0, -1
    first_bad = ctypes.c_int64(-1)
    n_bad = lib.smm_parity_fold(
        np.ascontiguousarray(a_tiles, np.uint64),
        np.ascontiguousarray(b_tiles, np.uint64),
        np.ascontiguousarray(pair_ptr, np.int64),
        np.ascontiguousarray(pair_a, np.int32),
        np.ascontiguousarray(pair_b, np.int32),
        n_keys, k,
        np.ascontiguousarray(out_tiles, np.uint64),
        ctypes.byref(first_bad))
    if n_bad == -2:
        return None  # k beyond the native stack cap: caller falls back
    return int(n_bad), int(first_bad.value)


def write_matrix(path: str, rows: int, cols: int, k: int,
                 coords: np.ndarray, tiles: np.ndarray) -> bool:
    """Write via native code; returns False if the library is unavailable."""
    lib = get_lib()
    if lib is None:
        return False
    coords = np.ascontiguousarray(coords, np.int64)
    tiles = np.ascontiguousarray(tiles, np.uint64)
    rc = lib.smm_write_matrix(path.encode(), rows, cols, k, len(coords),
                              coords, tiles)
    if rc != 0:
        raise OSError(f"native writer failed for {path!r} (rc={rc})")
    return True
