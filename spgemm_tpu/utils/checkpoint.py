"""Checkpoint/resume for chain reductions (SURVEY.md section 5.4).

The reference has no persistence beyond the final output file -- a crash
mid-chain loses everything.  Here each reduction pass can snapshot its
surviving partial products as one .npz per pass (atomic rename), and a
restart resumes from the newest complete pass.  The npz holds exactly the
BlockSparseMatrix arrays, so a checkpoint round-trips losslessly.
"""

from __future__ import annotations

import logging
import os
import re

import numpy as np

from spgemm_tpu.utils.blockcsr import BlockSparseMatrix

log = logging.getLogger("spgemm_tpu.checkpoint")

_PASS_RE = re.compile(r"^pass_(\d+)\.npz$")


def save_pass(ckpt_dir: str, pass_idx: int, matrices: list[BlockSparseMatrix]) -> str:
    """Atomically write the partial products surviving after `pass_idx`."""
    os.makedirs(ckpt_dir, exist_ok=True)
    payload: dict = {"n": np.int64(len(matrices))}
    for i, m in enumerate(matrices):
        payload[f"m{i}_meta"] = np.array([m.rows, m.cols, m.k], np.int64)
        payload[f"m{i}_coords"] = m.coords
        payload[f"m{i}_tiles"] = m.tiles
    path = os.path.join(ckpt_dir, f"pass_{pass_idx}.npz")
    tmp = path + ".tmp.npz"
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **payload)
    os.replace(tmp, path)
    return path


def _load_pass(path: str) -> list[BlockSparseMatrix]:
    with np.load(path) as z:
        n = int(z["n"])
        mats = []
        for i in range(n):
            rows, cols, k = (int(v) for v in z[f"m{i}_meta"])
            mats.append(BlockSparseMatrix(
                rows=rows, cols=cols, k=k,
                coords=z[f"m{i}_coords"], tiles=z[f"m{i}_tiles"]))
    return mats


def latest_pass(ckpt_dir: str) -> tuple[int, list[BlockSparseMatrix]] | None:
    """Newest COMPLETE checkpoint as (pass_idx, matrices), or None.

    save_pass writes atomically (tmp + rename), but the newest file can
    still be corrupt -- a torn disk write, a copy of a half-synced
    directory, filesystem damage.  A resume must not die on it: any pass
    that fails to load falls back to the next-newest with a warning (every
    pass is a self-contained snapshot, so an older one is always a valid
    -- just earlier -- restart point).  Only when no pass loads at all
    does the caller start from scratch."""
    if not os.path.isdir(ckpt_dir):
        return None
    indices = sorted(
        (int(m.group(1)) for m in map(_PASS_RE.match, os.listdir(ckpt_dir))
         if m), reverse=True)
    for idx in indices:
        path = os.path.join(ckpt_dir, f"pass_{idx}.npz")
        try:
            return idx, _load_pass(path)
        except Exception as e:  # noqa: BLE001 -- any unreadable pass falls back
            log.warning("checkpoint %s unreadable (%r); falling back to the "
                        "next-newest pass", path, e)
    return None
