"""Central failpoint registry: named chaos-injection sites, armed by env.

The engine grew a deep failure-handling surface -- watchdog reap/wedge/
degrade, per-slice degrade, warm-store corrupt fallbacks, delta full
fallbacks, journal replay -- but a fault path that is only ever exercised
by the one hand-crafted test that motivated it rots.  This module makes
fault injection a first-class, registry-disciplined facility the same way
knobs.py does for env knobs and obs/metrics.py does for series names:

  * every injection site is DECLARED here once (name, kind, site module,
    doc), and `check("name")` at the site is the entire wiring -- the FPT
    lint rule holds call sites to string literals declared below and
    flags registry entries with no site, so the registry can never drift
    from the code;
  * arming is one env knob, `SPGEMM_TPU_FAILPOINTS` (central registry,
    utils/knobs.py): comma-joined `name[:prob][:count]` terms.  `prob`
    (default 1) fires the point on that fraction of checks -- the RNG is
    seeded from the (spec, name) pair, so a given spec replays the same
    trigger sequence; `count` (default unlimited) bounds total triggers.
    Unset, every check is one registry lookup + one env read and nothing
    else: unarmed failpoints are free, so the sites ship enabled in
    production builds.
  * every trigger is observable: a `failpoint_trigger` structured event
    (obs/events) and the `spgemm_failpoints_triggered_total{point=}`
    Prometheus family (collected by obs/metrics.collect_engine).

Kinds -- what a trigger does at the site:

  raise   raise FailpointTriggered (exercises the site's error path)
  hang    block until the point is DISARMED (env cleared/changed) or
          HANG_MAX_S elapses -- the accelerator-wedge signature the
          watchdog exists for, releasable so tests can un-wedge
  corrupt check() returns True and the SITE takes its own corruption
          path (a torn journal record, a warm entry treated as corrupt)
  delay   sleep DELAY_S, then continue -- latency injection

jax-free by design: imported by ops (numeric path), serve (daemon), and
the linter -- none may touch a backend, and the numeric-path sites must
not perturb fold order (raise/hang/delay/corrupt never change bits; a
triggered site fails loudly or slowly, never wrongly).
"""

from __future__ import annotations

import random
import threading
import time
import zlib
from dataclasses import dataclass

from spgemm_tpu.utils import knobs

# a hang releases when disarmed; this is the backstop so a forgotten armed
# spec cannot pin a thread literally forever
HANG_MAX_S = 3600.0
# one delay-kind trigger sleeps this long (deterministic, no jitter)
DELAY_S = 0.25
# a releasable hang polls the arming spec at this cadence
HANG_POLL_S = 0.05


class FailpointTriggered(RuntimeError):
    """A raise-kind failpoint fired; carries the point name so the
    surviving error path (structured job error, event log) names it."""

    def __init__(self, name: str):
        super().__init__(f"failpoint {name} triggered")
        self.point = name


@dataclass(frozen=True)
class Failpoint:
    """One registered injection site.

    kind: 'raise' | 'hang' | 'corrupt' | 'delay' (what a trigger does).
    module: the site's module (repo-relative), for docs and the FPT
    stale-entry check's error message.
    """

    name: str
    kind: str
    module: str
    doc: str


_FAILPOINTS = (
    Failpoint("plan.build", "raise", "ops/spgemm.py",
              "Symbolic plan build fails (the chain runner's error path: "
              "structured job-error, never a wedge)."),
    Failpoint("plan.ensure_exact", "raise", "ops/symbolic.py",
              "The deferred exact join fails when forced (plan-ahead "
              "worker or dispatch thread -- whoever forces it owns the "
              "error)."),
    Failpoint("kernel.dispatch", "raise", "ops/spgemm.py",
              "Numeric kernel dispatch fails mid-multiply (the "
              "chain_product failover / job-error path)."),
    Failpoint("delta.diff", "corrupt", "ops/delta.py",
              "The delta content diff reports lineage ambiguity: the "
              "site returns None and the multiply takes its counted "
              "full-fallback path, never a crash."),
    Failpoint("delta.splice", "raise", "ops/spgemm.py",
              "Delta row splice fails after the sub-plan executed (the "
              "most state was in flight; the job error path owns it)."),
    Failpoint("warm.load", "corrupt", "ops/warmstore.py",
              "A warm-store entry loads as corrupt: the site takes its "
              "counted warm_corrupt cold fallback (entry unlinked, "
              "re-derived, re-persisted)."),
    Failpoint("warm.flush", "raise", "ops/warmstore.py",
              "The warm flush raises midway; flush()'s never-raises "
              "contract must hold (logged, store left self-validating)."),
    Failpoint("serve.journal", "corrupt", "serve/daemon.py",
              "One journal append writes a TORN record (truncated frame) "
              "-- the mid-write-kill signature replay must truncate at, "
              "count, and never crash on."),
    Failpoint("serve.accept", "delay", "serve/daemon.py",
              "The accept loop stalls briefly after one accept (slow "
              "admission under load; clients' connect retry covers it)."),
    Failpoint("serve.readline", "raise", "serve/daemon.py",
              "A connection handler dies mid-request (the conn thread's "
              "finally must still close the socket and free the slot)."),
    Failpoint("serve.executor", "hang", "serve/daemon.py",
              "A slice executor hangs after job pickup, before the "
              "runner -- the backend-wedge signature: reap, wedge "
              "declaration, per-slice degrade, recovery re-probe."),
    Failpoint("serve.heartbeat", "hang", "serve/daemon.py",
              "The chain heartbeat hangs mid-chain (a backend call that "
              "never returns between multiplies): no beats reach the "
              "watchdog, the wedge grace window runs out."),
    Failpoint("tune.trial", "raise", "tune/tuner.py",
              "An autotuner trial leg dies mid-measurement: the tuner "
              "discards the leg, counts the revert-free abort, and the "
              "trial lane's failure must never touch a real job's "
              "result, SLO window, or the admission path."),
)

REGISTRY: dict[str, Failpoint] = {f.name: f for f in _FAILPOINTS}


class _Arm:
    """Live arming state for one point under the current spec: fire
    probability, remaining trigger budget (None = unlimited), and the
    (spec, name)-seeded RNG that makes a spec's trigger sequence
    replayable."""

    def __init__(self, name: str, prob: float, count: int | None,
                 spec: str):
        self.prob = prob
        self.remaining = count
        self.rng = random.Random(zlib.crc32(f"{spec}|{name}".encode()))


_LOCK = threading.Lock()
_RAW: str | None = None          # spgemm-lint: guarded-by(_LOCK)
_ARMS: dict[str, _Arm] = {}      # spgemm-lint: guarded-by(_LOCK)
_TRIGGERED: dict[str, int] = {}  # spgemm-lint: guarded-by(_LOCK)


def _parse_spec(spec: str) -> dict[str, tuple[float, int | None]]:
    """`name[:prob][:count]` terms, comma-joined -> {name: (prob, count)}.
    Every failure raises naming the knob: a chaos run whose spec silently
    armed nothing would 'pass' by never injecting anything."""
    out: dict[str, tuple[float, int | None]] = {}
    for term in spec.split(","):
        term = term.strip()
        if not term:
            continue
        parts = term.split(":")
        name = parts[0].strip()
        if name not in REGISTRY:
            raise ValueError(
                f"SPGEMM_TPU_FAILPOINTS names unknown failpoint {name!r} "
                f"(registered: {', '.join(sorted(REGISTRY))})")
        if len(parts) > 3:
            raise ValueError(
                f"SPGEMM_TPU_FAILPOINTS term {term!r} has more than "
                "name:prob:count fields")
        try:
            prob = float(parts[1]) if len(parts) > 1 and parts[1] else 1.0
            count = int(parts[2]) if len(parts) > 2 and parts[2] else None
        except ValueError:
            raise ValueError(
                f"SPGEMM_TPU_FAILPOINTS term {term!r}: prob must be a "
                "number, count an integer") from None
        if not 0.0 <= prob <= 1.0 or (count is not None and count < 1):
            raise ValueError(
                f"SPGEMM_TPU_FAILPOINTS term {term!r}: need "
                "0 <= prob <= 1 and count >= 1")
        out[name] = (prob, count)
    return out


def _arm_for(name: str) -> _Arm | None:
    """The live arm for `name` under the CURRENT knob value, re-parsing
    when the spec changed (tests and the chaos harness flip it
    mid-process like every knob).  None = not armed."""
    global _RAW
    spec = knobs.get("SPGEMM_TPU_FAILPOINTS")
    with _LOCK:
        if spec != _RAW:
            # parse BEFORE committing _RAW: a malformed spec must raise on
            # EVERY check, not just the first -- otherwise one swallowed
            # ValueError leaves the bad spec cached as "armed nothing" and
            # the chaos run passes without injecting anything
            arms: dict[str, _Arm] = {}
            if spec:
                for pname, (prob, count) in _parse_spec(spec).items():
                    arms[pname] = _Arm(pname, prob, count, spec)
            _RAW = spec
            _ARMS.clear()
            _ARMS.update(arms)
        return _ARMS.get(name)


def check(name: str) -> bool:
    """The one call an injection site makes.  Returns False on the
    overwhelmingly common unarmed path; on an armed trigger performs the
    registered kind -- raises for 'raise', blocks-until-disarmed for
    'hang', sleeps for 'delay' -- and returns True only for 'corrupt'
    (the site then takes its own corruption path).  The FPT lint rule
    holds `name` to a string literal declared in REGISTRY."""
    fp = REGISTRY[name]  # registering is the price of checking
    if not knobs.get("SPGEMM_TPU_FAILPOINTS"):
        return False  # inert: one env read, no lock, no parse
    arm = _arm_for(name)
    if arm is None:
        return False
    with _LOCK:
        if arm.remaining is not None and arm.remaining <= 0:
            return False
        if arm.prob < 1.0 and arm.rng.random() >= arm.prob:
            return False
        if arm.remaining is not None:
            arm.remaining -= 1
        _TRIGGERED[name] = _TRIGGERED.get(name, 0) + 1
    _note_trigger(fp)
    if fp.kind == "raise":
        raise FailpointTriggered(name)
    if fp.kind == "hang":
        _hang(name)
        return False
    if fp.kind == "delay":
        # spgemm-lint: blk-ok(chaos injection: the delay IS the injected fault, armed only under SPGEMM_TPU_FAILPOINTS -- blocking wherever the site sits, locks included, is the point)
        time.sleep(DELAY_S)
        return False
    return True  # corrupt


def _hang(name: str) -> None:
    """Block until the point is disarmed (spec cleared or no longer
    naming it) or HANG_MAX_S passes -- the watchdog sees a genuine wedge,
    and a test un-wedges by clearing the env."""
    deadline = time.monotonic() + HANG_MAX_S
    while time.monotonic() < deadline:
        spec = knobs.get("SPGEMM_TPU_FAILPOINTS")
        if not spec or _arm_for(name) is None:
            return
        # spgemm-lint: blk-ok(chaos injection: the hang IS the injected wedge the watchdog must detect, armed only under SPGEMM_TPU_FAILPOINTS)
        time.sleep(HANG_POLL_S)


def _note_trigger(fp: Failpoint) -> None:
    """Observability for one trigger: structured event (auto-correlated
    with the emitting thread's job/trace tags) -- the metric family is
    collected from triggered() by obs/metrics.collect_engine."""
    from spgemm_tpu.obs import events  # noqa: PLC0415
    events.emit("failpoint_trigger", point=fp.name, action=fp.kind)


def triggered() -> dict[str, int]:
    """Trigger counts per point since process start (the
    spgemm_failpoints_triggered_total sample source)."""
    with _LOCK:
        return dict(_TRIGGERED)


def armed() -> dict[str, dict]:
    """Live arming state (stats/debugging): per armed point, prob and
    the remaining trigger budget under the current spec."""
    # touch the cache so the view reflects the CURRENT env value
    _arm_for(next(iter(REGISTRY)))
    with _LOCK:
        return {name: {"kind": REGISTRY[name].kind, "prob": arm.prob,
                       "remaining": arm.remaining}
                for name, arm in _ARMS.items()}


def clear() -> None:
    """Zero the trigger counters and drop the parsed-arm cache (tests;
    the env knob itself is the caller's to clear)."""
    global _RAW
    with _LOCK:
        _RAW = None
        _ARMS.clear()
        _TRIGGERED.clear()
