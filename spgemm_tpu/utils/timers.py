"""Per-phase timing + structured logging (the reference's C17, done properly).

The reference wraps every phase in chrono spans with the prints commented out
(sparse_matrix_mult.cu:101,160-163,...) and reports only the final
"time taken X seconds" (:679).  Here phases are named context managers
accumulated in a registry, reported as structured lines, with optional
jax.profiler traces; the CLI keeps the final `time taken` line for parity.

Every phase enter/exit additionally emits a SPAN into the process-wide
flight recorder (spgemm_tpu/obs/trace.py: bounded ring, job/trace tags,
parenting, Perfetto export; `SPGEMM_TPU_OBS_TRACE=0` disables emission) --
the accumulation below is the metrics surface, the spans are the timeline.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time

from spgemm_tpu.obs import trace

log = logging.getLogger("spgemm_tpu.timers")


class PhaseTimers:
    """Accumulates wall-clock per named phase (re-entrant by name), plus
    named event counters (dispatch/launch counts -- the round-batching
    regression guard: wall time alone cannot distinguish one mega-launch
    from fifty small ones on an async backend).

    Thread discipline: accumulation is lock-guarded.  The OOC pipeline's
    workers each own distinct phase/counter names, but the chain planner
    worker shares names with the main thread across mode switches (`plan`
    and the plan-cache counters run on the worker under plan-ahead and on
    the main thread under SPGEMM_TPU_PLAN_AHEAD=0, and a failover retry
    can interleave the two within one process) -- a read-modify-write on
    a shared name must never lose an update.

    Per-job attribution: scope() opens a PhaseScope bound to the CALLING
    THREAD -- accumulation lands in a scope only when the accumulating
    thread carries it, so two concurrent scopes (a watchdog-reaped job's
    wedged executor + the replacement executor's next job) can never
    double-count each other's overlap.  Worker threads doing a job's work
    adopt its scopes via attribution()/attributed()."""

    def __init__(self):
        self.totals: dict[str, float] = {}    # spgemm-lint: guarded-by(_lock)
        self.counts: dict[str, int] = {}      # spgemm-lint: guarded-by(_lock)
        self.counters: dict[str, int] = {}    # spgemm-lint: guarded-by(_lock)
        # thread ident -> PhaseScopes that thread's accumulation feeds
        self._sinks: dict[int, list] = {}     # spgemm-lint: guarded-by(_lock)
        self._lock = threading.Lock()

    def _add_phase_locked(self, name: str, dt: float) -> None:
        self.totals[name] = self.totals.get(name, 0.0) + dt
        self.counts[name] = self.counts.get(name, 0) + 1
        for sink in self._sinks.get(threading.get_ident(), ()):
            sink._add_phase_locked(name, dt)

    def _add_counter_locked(self, name: str, n: int) -> None:
        self.counters[name] = self.counters.get(name, 0) + n
        for sink in self._sinks.get(threading.get_ident(), ()):
            sink._add_counter_locked(name, n)

    @contextlib.contextmanager
    def phase(self, name: str):
        token = trace.RECORDER.begin(name)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            trace.RECORDER.end(token)
            with self._lock:
                self._add_phase_locked(name, dt)

    def record(self, name: str, seconds: float):
        """Accumulate an externally measured duration under a phase name --
        for spans whose endpoints the caller must place itself (e.g. the ring
        layer's one-hop wire probe, timed around its own completion barrier
        rather than a `with` block)."""
        trace.RECORDER.point(name, seconds)
        with self._lock:
            self._add_phase_locked(name, seconds)

    def incr(self, name: str, n: int = 1):
        """Bump a named event counter (e.g. 'dispatches' per numeric
        launch); safe from any thread."""
        with self._lock:
            self._add_counter_locked(name, n)

    def log_report(self):
        with self._lock:
            totals, counts = dict(self.totals), dict(self.counts)
            counters = dict(self.counters)
        for name, total in totals.items():
            log.info("phase %s: %.4fs (x%d)", name, total, counts.get(name, 0))
        for name, n in counters.items():
            log.info("counter %s: %d", name, n)

    def reset(self):
        """Zero the process-wide accumulation (bench iterations).  Open
        scopes are untouched: they hold their own deltas."""
        with self._lock:
            self.totals.clear()
            self.counts.clear()
            self.counters.clear()

    def snapshot(self) -> dict[str, float]:
        """Rounded totals, for embedding in structured bench/CLI output."""
        with self._lock:
            return {name: round(t, 4) for name, t in self.totals.items()}

    def count_snapshot(self) -> dict[str, int]:
        """Per-phase entry counts, next to snapshot() (metrics surface)."""
        with self._lock:
            return dict(self.counts)

    def counter_snapshot(self) -> dict[str, int]:
        """Event counters, for embedding next to snapshot() in bench output."""
        with self._lock:
            return dict(self.counters)

    def scope(self) -> "PhaseScope":
        """A per-job collector bound to the calling thread.

        The registry accumulates process-wide (bench.py resets it between
        iterations, but a resident daemon must NOT reset -- concurrent
        readers and the `cli knobs` listing see the same registry), so a
        per-job report needs attribution: everything accumulated by the
        opening thread (and any worker that adopted the scope via
        attributed()) while the scope is open, nothing else.  Used by
        serve/daemon.py so job 2's detail never includes job 1's phases --
        even when job 1's wedged executor is still accumulating
        concurrently.  close() (or the context manager) detaches it."""
        return PhaseScope(self)

    def attribution(self):
        """Opaque token capturing the calling thread's attribution: its
        active scopes plus its flight-recorder tags.  Hand it to a worker
        thread doing this thread's work (chain plan-ahead planner, OOC
        staging/landing) and wrap the worker body in attributed(token) so
        per-job scopes and span tags follow the work, not the thread."""
        with self._lock:
            sinks = tuple(self._sinks.get(threading.get_ident(), ()))
        return (sinks, trace.RECORDER.current_tags())

    @contextlib.contextmanager
    def attributed(self, token):
        """Adopt an attribution() token on the current (worker) thread for
        the duration of the block."""
        sinks, tags = token
        ident = threading.get_ident()
        with self._lock:
            lst = self._sinks.setdefault(ident, [])
            lst.extend(sinks)
        try:
            with trace.RECORDER.tagged(**tags):
                yield
        finally:
            with self._lock:
                lst = self._sinks.get(ident)
                if lst is not None:
                    for sink in sinks:
                        if sink in lst:
                            lst.remove(sink)
                    if not lst:
                        self._sinks.pop(ident, None)


class PhaseScope:
    """Per-job accumulation collector over a PhaseTimers (see
    PhaseTimers.scope): snapshot()/counter_snapshot() return exactly what
    the attributed threads accumulated while the scope was open.

    The pre-PR-7 implementation was a baseline-and-diff over the global
    totals, which double-counted whenever two scopes were open
    concurrently (a reaped job's wedged executor unwedging while the
    replacement executor runs the next job: both diffs saw both jobs'
    accumulation).  Scopes are now explicit sinks: accumulation lands in
    a scope only from threads carrying it, so concurrent scopes are
    disjoint by construction (pinned by a threaded regression test in
    tests/test_serve.py)."""

    def __init__(self, timers: PhaseTimers):
        self._timers = timers
        self._lock = timers._lock  # one lock: scopes are timers state
        self.totals: dict[str, float] = {}   # spgemm-lint: guarded-by(_lock)
        self.counts: dict[str, int] = {}     # spgemm-lint: guarded-by(_lock)
        self.counters: dict[str, int] = {}   # spgemm-lint: guarded-by(_lock)
        ident = threading.get_ident()
        with self._lock:
            timers._sinks.setdefault(ident, []).append(self)

    def _add_phase_locked(self, name: str, dt: float) -> None:
        self.totals[name] = self.totals.get(name, 0.0) + dt
        self.counts[name] = self.counts.get(name, 0) + 1

    def _add_counter_locked(self, name: str, n: int) -> None:
        self.counters[name] = self.counters.get(name, 0) + n

    def close(self) -> None:
        """Detach from every thread; the collected deltas stay readable.
        Idempotent -- a wedged executor that unwedges hours later closes a
        scope the daemon already reported from."""
        with self._lock:
            sinks = self._timers._sinks
            for ident in list(sinks):
                lst = sinks[ident]
                while self in lst:
                    lst.remove(self)
                if not lst:
                    sinks.pop(ident, None)

    def __enter__(self) -> "PhaseScope":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def record(self, name: str, seconds: float) -> None:
        """Accumulate an externally measured duration into THIS scope (and
        the global totals) without touching any other open scope -- the
        batched-executor case: J co-batched jobs each own a PhaseScope on
        the same executor thread, and a per-job figure (each mate's own
        serve_queue_wait) must land in exactly one of them.  The ambient
        ENGINE.record would fan out to every sink on the thread."""
        trace.RECORDER.point(name, seconds)
        with self._lock:
            t = self._timers
            t.totals[name] = t.totals.get(name, 0.0) + seconds
            t.counts[name] = t.counts.get(name, 0) + 1
            self._add_phase_locked(name, seconds)

    def snapshot(self) -> dict[str, float]:
        """Per-phase seconds attributed to this scope (rounded)."""
        with self._lock:
            return {name: round(t, 4) for name, t in self.totals.items()}

    def counter_snapshot(self) -> dict[str, int]:
        """Event-counter deltas attributed to this scope."""
        with self._lock:
            return dict(self.counters)


# Global registry for the SpGEMM engine's internal phases (symbolic join /
# round planning / numeric dispatch / assembly) -- the analog of the
# reference's per-phase chrono spans inside helper() (sparse_matrix_mult.cu:
# 160-274, report.pdf Table 2).  The engine accumulates here on every
# multiply; the CLI (--profile) and bench.py reset + report it.  Phase and
# counter NAMES are declared in obs/metrics.py (ENGINE_PHASES /
# ENGINE_COUNTERS) -- the MET lint rule rejects undeclared names at call
# sites, so the Prometheus surface and the flight recorder can never grow
# ad-hoc series.
ENGINE = PhaseTimers()


@contextlib.contextmanager
def maybe_profile(trace_dir: str | None):
    """jax.profiler.trace wrapper -- the XLA-level analog of the reference's
    hand-rolled chrono spans."""
    if trace_dir:
        import jax

        with jax.profiler.trace(trace_dir):
            yield
    else:
        yield
