"""Per-phase timing + structured logging (the reference's C17, done properly).

The reference wraps every phase in chrono spans with the prints commented out
(sparse_matrix_mult.cu:101,160-163,...) and reports only the final
"time taken X seconds" (:679).  Here phases are named context managers
accumulated in a registry, reported as structured lines, with optional
jax.profiler traces; the CLI keeps the final `time taken` line for parity.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time

log = logging.getLogger("spgemm_tpu.timers")


class PhaseTimers:
    """Accumulates wall-clock per named phase (re-entrant by name), plus
    named event counters (dispatch/launch counts -- the round-batching
    regression guard: wall time alone cannot distinguish one mega-launch
    from fifty small ones on an async backend).

    Thread discipline: accumulation is lock-guarded.  The OOC pipeline's
    workers each own distinct phase/counter names, but the chain planner
    worker shares names with the main thread across mode switches (`plan`
    and the plan-cache counters run on the worker under plan-ahead and on
    the main thread under SPGEMM_TPU_PLAN_AHEAD=0, and a failover retry
    can interleave the two within one process) -- a read-modify-write on
    a shared name must never lose an update."""

    def __init__(self):
        self.totals: dict[str, float] = {}
        self.counts: dict[str, int] = {}
        self.counters: dict[str, int] = {}
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.totals[name] = self.totals.get(name, 0.0) + dt
                self.counts[name] = self.counts.get(name, 0) + 1

    def record(self, name: str, seconds: float):
        """Accumulate an externally measured duration under a phase name --
        for spans whose endpoints the caller must place itself (e.g. the ring
        layer's one-hop wire probe, timed around its own completion barrier
        rather than a `with` block)."""
        with self._lock:
            self.totals[name] = self.totals.get(name, 0.0) + seconds
            self.counts[name] = self.counts.get(name, 0) + 1

    def incr(self, name: str, n: int = 1):
        """Bump a named event counter (e.g. 'dispatches' per numeric
        launch); safe from any thread."""
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def log_report(self):
        with self._lock:
            totals, counts = dict(self.totals), dict(self.counts)
            counters = dict(self.counters)
        for name, total in totals.items():
            log.info("phase %s: %.4fs (x%d)", name, total, counts.get(name, 0))
        for name, n in counters.items():
            log.info("counter %s: %d", name, n)

    def reset(self):
        with self._lock:
            self.totals.clear()
            self.counts.clear()
            self.counters.clear()

    def snapshot(self) -> dict[str, float]:
        """Rounded totals, for embedding in structured bench/CLI output."""
        with self._lock:
            return {name: round(t, 4) for name, t in self.totals.items()}

    def counter_snapshot(self) -> dict[str, int]:
        """Event counters, for embedding next to snapshot() in bench output."""
        with self._lock:
            return dict(self.counters)


# Global registry for the SpGEMM engine's internal phases (symbolic join /
# round planning / numeric dispatch / assembly) -- the analog of the
# reference's per-phase chrono spans inside helper() (sparse_matrix_mult.cu:
# 160-274, report.pdf Table 2).  The engine accumulates here on every
# multiply; the CLI (--profile) and bench.py reset + report it.
ENGINE = PhaseTimers()


@contextlib.contextmanager
def maybe_profile(trace_dir: str | None):
    """jax.profiler.trace wrapper -- the XLA-level analog of the reference's
    hand-rolled chrono spans."""
    if trace_dir:
        import jax

        with jax.profiler.trace(trace_dir):
            yield
    else:
        yield
