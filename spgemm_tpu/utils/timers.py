"""Per-phase timing + structured logging (the reference's C17, done properly).

The reference wraps every phase in chrono spans with the prints commented out
(sparse_matrix_mult.cu:101,160-163,...) and reports only the final
"time taken X seconds" (:679).  Here phases are named context managers
accumulated in a registry, reported as structured lines, with optional
jax.profiler traces; the CLI keeps the final `time taken` line for parity.
"""

from __future__ import annotations

import contextlib
import logging
import threading
import time

log = logging.getLogger("spgemm_tpu.timers")


class PhaseTimers:
    """Accumulates wall-clock per named phase (re-entrant by name), plus
    named event counters (dispatch/launch counts -- the round-batching
    regression guard: wall time alone cannot distinguish one mega-launch
    from fifty small ones on an async backend).

    Thread discipline: accumulation is lock-guarded.  The OOC pipeline's
    workers each own distinct phase/counter names, but the chain planner
    worker shares names with the main thread across mode switches (`plan`
    and the plan-cache counters run on the worker under plan-ahead and on
    the main thread under SPGEMM_TPU_PLAN_AHEAD=0, and a failover retry
    can interleave the two within one process) -- a read-modify-write on
    a shared name must never lose an update."""

    def __init__(self):
        self.totals: dict[str, float] = {}    # spgemm-lint: guarded-by(_lock)
        self.counts: dict[str, int] = {}      # spgemm-lint: guarded-by(_lock)
        self.counters: dict[str, int] = {}    # spgemm-lint: guarded-by(_lock)
        self._lock = threading.Lock()

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                self.totals[name] = self.totals.get(name, 0.0) + dt
                self.counts[name] = self.counts.get(name, 0) + 1

    def record(self, name: str, seconds: float):
        """Accumulate an externally measured duration under a phase name --
        for spans whose endpoints the caller must place itself (e.g. the ring
        layer's one-hop wire probe, timed around its own completion barrier
        rather than a `with` block)."""
        with self._lock:
            self.totals[name] = self.totals.get(name, 0.0) + seconds
            self.counts[name] = self.counts.get(name, 0) + 1

    def incr(self, name: str, n: int = 1):
        """Bump a named event counter (e.g. 'dispatches' per numeric
        launch); safe from any thread."""
        with self._lock:
            self.counters[name] = self.counters.get(name, 0) + n

    def log_report(self):
        with self._lock:
            totals, counts = dict(self.totals), dict(self.counts)
            counters = dict(self.counters)
        for name, total in totals.items():
            log.info("phase %s: %.4fs (x%d)", name, total, counts.get(name, 0))
        for name, n in counters.items():
            log.info("counter %s: %d", name, n)

    def reset(self):
        with self._lock:
            self.totals.clear()
            self.counts.clear()
            self.counters.clear()

    def snapshot(self) -> dict[str, float]:
        """Rounded totals, for embedding in structured bench/CLI output."""
        with self._lock:
            return {name: round(t, 4) for name, t in self.totals.items()}

    def counter_snapshot(self) -> dict[str, int]:
        """Event counters, for embedding next to snapshot() in bench output."""
        with self._lock:
            return dict(self.counters)

    def scope(self) -> "PhaseScope":
        """A diff view anchored at the current accumulation state.

        The registry accumulates process-wide (bench.py resets it between
        iterations, but a resident daemon must NOT reset -- concurrent
        readers and the `cli knobs` listing see the same registry), so a
        per-job report needs a baseline-and-diff: everything accumulated
        AFTER scope() was called, nothing before.  Used by serve/daemon.py
        so job 2's status never includes job 1's phases."""
        return PhaseScope(self)


class PhaseScope:
    """Snapshot/diff view over a PhaseTimers (see PhaseTimers.scope):
    snapshot()/counter_snapshot() return only what accumulated since the
    scope was opened, with untouched names dropped."""

    def __init__(self, timers: PhaseTimers):
        self._timers = timers
        with timers._lock:
            self._totals0 = dict(timers.totals)
            self._counters0 = dict(timers.counters)

    def snapshot(self) -> dict[str, float]:
        """Per-phase seconds accumulated since the scope opened (rounded,
        zero-delta names dropped)."""
        with self._timers._lock:
            now = dict(self._timers.totals)
        out = {}
        for name, total in now.items():
            delta = total - self._totals0.get(name, 0.0)
            if delta > 0.0:
                out[name] = round(delta, 4)
        return out

    def counter_snapshot(self) -> dict[str, int]:
        """Event-counter deltas since the scope opened (zero deltas
        dropped)."""
        with self._timers._lock:
            now = dict(self._timers.counters)
        out = {}
        for name, n in now.items():
            delta = n - self._counters0.get(name, 0)
            if delta:
                out[name] = delta
        return out


# Global registry for the SpGEMM engine's internal phases (symbolic join /
# round planning / numeric dispatch / assembly) -- the analog of the
# reference's per-phase chrono spans inside helper() (sparse_matrix_mult.cu:
# 160-274, report.pdf Table 2).  The engine accumulates here on every
# multiply; the CLI (--profile) and bench.py reset + report it.
ENGINE = PhaseTimers()


@contextlib.contextmanager
def maybe_profile(trace_dir: str | None):
    """jax.profiler.trace wrapper -- the XLA-level analog of the reference's
    hand-rolled chrono spans."""
    if trace_dir:
        import jax

        with jax.profiler.trace(trace_dir):
            yield
    else:
        yield
