"""Central registry of every `SPGEMM_TPU_*` engine knob.

This module is the ONLY place in the package allowed to touch
`os.environ` for a `SPGEMM_TPU_*` name -- the KNB rule of the repo linter
(`python -m spgemm_tpu.analysis`) flags raw reads anywhere else.  The
registry single-sources, per knob: type, default, allowed values, whether
the value is a jit-static (one compiled executable per value -- the
round-batched dispatch and ring-overlap layers depend on knob values
never varying inside a traced region), the consuming module, and a doc
string.  From it are generated:

  * the typed, validated accessor `get()` used by every consuming module
    (an invalid value raises `ValueError` naming the knob -- never a
    silent default, never a bare crash deep inside a kernel);
  * the CLAUDE.md knob table (`knob_table_md`; the linter's DOC rule
    diffs the generated text against the committed block);
  * the CLI help epilog (`cli_epilog`) and the `spgemm_tpu.cli knobs`
    subcommand listing (`snapshot`).

Reads are lazy -- the environment is consulted at each `get()` call, not
at import -- so tests and A/B harnesses may monkeypatch values
mid-process exactly as before the registry existed.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass

_UNSET = "(unset)"


@dataclass(frozen=True)
class Knob:
    """One registered env knob.

    kind: 'enum' | 'int' | 'float' | 'bool01' | 'flag' | 'path' | 'str'
      - bool01: value must be the string 0 or 1; get() returns bool
      - flag:   truthy iff set to a non-empty string; get() returns bool
    default: the DEFAULT in string form, or None ("unset" -- get() then
      returns None for enum/int/float/path, False for flag; the consuming
      module owns the unset fallback, e.g. a platform-dependent policy).
    minimum: inclusive lower bound for int/float kinds.
    jit_static: the value is baked into compiled executables (one jit
      cache entry per value); flipping it mid-process recompiles, never
      retraces stale code.
    module: the consuming module (repo-relative), for docs and the CLI.
    """

    name: str
    kind: str
    doc: str
    module: str
    default: str | None = None
    choices: tuple[str, ...] | None = None
    minimum: float | None = None
    jit_static: bool = False


_KNOBS = (
    Knob("SPGEMM_TPU_VPU_ALGO", "enum",
         "Exact VPU kernel layout; vecj is interpret-mode-only (miscompiles "
         "on TPU hardware, rejected there with the knob named).",
         "ops/spgemm.py", default="colbcast", choices=("colbcast", "vecj"),
         jit_static=True),
    Knob("SPGEMM_TPU_VPU_PB", "int",
         "VPU pair-axis blocking; >1 is interpret-mode-only (rejected on "
         "TPU hardware).",
         "ops/spgemm.py", default="1", minimum=1, jit_static=True),
    Knob("SPGEMM_TPU_MXU_R", "int",
         "MXU limb-kernel pair width R (whole-engine A/B, like the VPU "
         "knobs).",
         "ops/spgemm.py", default="8", minimum=1, jit_static=True),
    Knob("SPGEMM_TPU_ACCUM_ROUTE", "enum",
         "Accumulator route for the exact fold (the whole-engine A/B): "
         "ladder = every key pads its pair axis to the 3/4-pow-2 fanout "
         "class (the pre-route engine -- bytes AND dispatch counts "
         "identical); dense = every class ships as ONE contiguous pair "
         "stream plus a segment vector, folded strictly left-to-right into "
         "a dense per-output-tile-row accumulator (index-ordered segmented "
         "fold -- the same wrap-then-mod MAC order per output row, no "
         "padded-key or padded-fanout MACs); auto = deep classes "
         "(>= DENSE_MIN_CLASS) carry both layouts and dispatch picks per "
         "(key class, fanout class, k) via the measured crossover gate "
         "(ops/crossover.dense_wins), exactly like the hybrid MXU gate.  "
         "Bit-identical on every input by construction.  The pure 'mxu' "
         "field-mode backend and the sharded strategies (ring/rowshard/"
         "out-of-core) always plan ladder.",
         "ops/symbolic.py", default="auto",
         choices=("auto", "ladder", "dense"), jit_static=True),
    Knob("SPGEMM_TPU_ROUND_BATCH", "bool01",
         "Round-batched dispatch: 1 = one mega-launch per fanout class x "
         "kernel choice + fused single-gather assembly, 0 = legacy "
         "one-launch-per-round loop; bit-identical either way.",
         "ops/spgemm.py", default="1"),
    Knob("SPGEMM_TPU_OOC_DEPTH", "int",
         "Out-of-core pipeline depth (host-side cadence, not a jit "
         "static): 1 = synchronous minimal HBM, >=2 = 3-stage pipeline "
         "with staging and landing workers.",
         "ops/spgemm.py", default="2", minimum=1),
    Knob("SPGEMM_TPU_PLAN_AHEAD", "int",
         "Chain plan-ahead depth: up to N upcoming pairs are planned by a "
         "host worker thread while the device executes the current pair; "
         "0 = legacy inline planning (bit-identical either way -- planning "
         "is deterministic and dispatch order is unchanged).",
         "chain.py", default="2", minimum=0),
    Knob("SPGEMM_TPU_PLAN_CACHE", "bool01",
         "Structure-keyed SpgemmPlan memoization: 1 = multiplies whose "
         "operand-structure fingerprint matches reuse the cached plan "
         "(repeated inputs skip the symbolic planner), 0 = plan every "
         "multiply from scratch.",
         "ops/plancache.py", default="1"),
    Knob("SPGEMM_TPU_PLAN_CACHE_CAP", "int",
         "Plan-cache LRU capacity (plans retained per process; a plan "
         "holds its padded pa/pb index arrays, ~8 bytes per tile pair).",
         "ops/plancache.py", default="32", minimum=1),
    Knob("SPGEMM_TPU_DELTA", "bool01",
         "Delta SpGEMM (row-granular incremental recompute): 1 = a "
         "multiply whose structure fingerprint was seen before diffs "
         "per-tile-row content digests (or the producer's dirty tag) "
         "against the previous submit, re-executes only the output "
         "tile-rows the changed input rows reach, and splices them into "
         "the retained previous result (device memory for up to "
         "SPGEMM_TPU_DELTA_RETAIN retained results); 0 = always full "
         "recompute "
         "-- the whole-engine A/B, bit-identical either way (untouched "
         "rows keep their exact bytes; dirty rows re-fold in full).  "
         "Ambiguity (first contact, structure change, store eviction) "
         "falls back loudly to the full path.  The run-once CLI, "
         "bench.py, and benchmarks/run.py pin it off unless exported: "
         "retention only pays where the process outlives the submit "
         "(spgemmd).",
         "ops/delta.py", default="1"),
    Knob("SPGEMM_TPU_DELTA_RETAIN", "int",
         "Delta store capacity in ENTRIES (LRU, one per multiply "
         "structure): each entry pins its previous result's device "
         "planes, so retention memory is bounded by this count TIMES the "
         "largest result -- size the cap (or set SPGEMM_TPU_DELTA=0) for "
         "the deployment's result scale; an evicted entry makes the next "
         "same-structure multiply a counted full fallback.",
         "ops/delta.py", default="16", minimum=1),
    Knob("SPGEMM_TPU_PLAN_ESTIMATE", "bool01",
         "Sampled structure estimator for first-contact plans: 1 = a "
         "bounded row sample predicts output nnz/fanout/mass, the plan "
         "returns fast with the exact symbolic join deferred off the "
         "critical path (SpgemmPlan.ensure_exact -- run by the plan-ahead "
         "worker or at execute), and the ring schedule balances key slabs "
         "by predicted MACs; 0 = always build the exact join inline.  "
         "Bit-identical either way: estimation steers budgets and "
         "routing, never fold order.",
         "ops/estimate.py", default="1"),
    Knob("SPGEMM_TPU_EST_SAMPLE_ROWS", "int",
         "Estimator row-sample budget: distinct A tile-rows sampled "
         "(evenly spaced, deterministic); structures with this many rows "
         "or fewer skip estimation -- the sample would be the population, "
         "so the exact join runs instead.",
         "ops/estimate.py", default="48", minimum=1),
    Knob("SPGEMM_TPU_EST_CONFIDENCE", "float",
         "Estimator confidence threshold: an estimate whose confidence "
         "(1 - relative standard error of the sampled per-row pair mass) "
         "falls below this takes the exact-join fallback inline "
         "(join_fallback phase, est_fallbacks counter); above 1 forces "
         "the fallback everywhere.",
         "ops/estimate.py", default="0.5", minimum=0),
    Knob("SPGEMM_TPU_WARM", "bool01",
         "Persistent warm start (ops/warmstore.py): 1 = the structure-"
         "keyed plan cache and the delta store's retained results are "
         "serialized into the warm dir (spgemmd: <socket>.warm/, or "
         "SPGEMM_TPU_WARM_DIR) and reloaded lazily on fingerprint match "
         "after a restart, and spgemmd points JAX's persistent "
         "compilation cache at the same dir -- restart-to-first-result "
         "drops from a cold plan + cold jit + full recompute to a disk "
         "hit; 0 = no persistence anywhere (the whole-engine A/B: "
         "bit-identical either way, persistence only short-circuits "
         "planning and retention, never fold order).  Any corrupt, "
         "version-skewed, or knob-vector-mismatched entry is a loudly "
         "counted cold fallback (warm_corrupt), never a crash or wrong "
         "bits.",
         "ops/warmstore.py", default="1"),
    Knob("SPGEMM_TPU_WARM_DIR", "path",
         "Warm-start store directory (unset: no persistence for run-once "
         "processes; spgemmd defaults to <socket>.warm/ next to its job "
         "journal).  Safe to share across restarts but not across LIVE "
         "processes: a flock guards the dir, and a process that cannot "
         "take it runs cold (counted) instead of corrupting a concurrent "
         "daemon's entries.",
         "ops/warmstore.py"),
    Knob("SPGEMM_TPU_WARM_MAX_MB", "int",
         "Warm store on-disk budget, MiB: after each flush the oldest "
         "plan/delta entries are pruned until the store fits (the JAX "
         "compilation-cache subdir manages its own size and is not "
         "counted).  A pruned entry just makes the next same-structure "
         "contact a counted cold fallback.",
         "ops/warmstore.py", default="256", minimum=1),
    Knob("SPGEMM_TPU_HYBRID_GATE", "enum",
         "Hybrid speed-gate policy: auto = measured per-shape crossover, "
         "proof = route on the exactness proof alone (unset: auto on TPU, "
         "proof elsewhere).",
         "ops/crossover.py", choices=("auto", "proof")),
    Knob("SPGEMM_TPU_CROSSOVER_CACHE", "path",
         "Crossover-measurement cache directory (unset: "
         "~/.cache/jax_bench).",
         "ops/crossover.py"),
    Knob("SPGEMM_TPU_RING_OVERLAP", "bool01",
         "Double-buffered ring rotation: 1 = the ppermute for slab t+1 is "
         "issued before the fold over slab t, 0 = legacy serialized "
         "fold-then-hop; bit-identical (the fold order never changes).",
         "parallel/ring.py", default="1", jit_static=True),
    Knob("SPGEMM_TPU_RING_HOP_PROBE", "bool01",
         "One-hop wire probe before the ring fold; 0 skips the probe and "
         "its compiled shape when the phase registry is not consumed.",
         "parallel/ring.py", default="1"),
    Knob("SPGEMM_TPU_DCN_CHUNK_MB", "float",
         "Multihost partial-exchange chunk budget (MiB per rank): bounds "
         "the transient DCN buffer at O(P x chunk); 0 = legacy padded "
         "all-gather behind a loud warning.",
         "parallel/multihost.py", default="64", minimum=0),
    Knob("SPGEMM_TPU_DCN_HEARTBEAT_S", "int",
         "Multihost partner-loss detection window, seconds (unset: jax's "
         "default, 100 s).",
         "parallel/multihost.py", minimum=1),
    Knob("SPGEMM_TPU_SERVE_SOCKET", "path",
         "spgemmd unix-domain socket path (unset: "
         "<tmpdir>/spgemmd-<uid>.sock); the on-disk job journal lives "
         "next to it at <socket>.journal.",
         "serve/protocol.py"),
    Knob("SPGEMM_TPU_SERVE_ADDR", "str",
         "spgemmd TCP front-end address, `tcp:HOST:PORT` (e.g. "
         "tcp:127.0.0.1:7463; port 0 binds ephemeral and the daemon "
         "logs the real port): the daemon listens HERE beside the unix "
         "socket, same newline-JSON protocol / version negotiation / "
         "line cap / conn cap / idle timeout, and clients that inherit "
         "the export dial it by default.  Unset = unix-socket only -- "
         "byte-identical to the pre-fleet daemon (the whole-feature "
         "A/B).  A malformed spec fails startup loudly (never a "
         "silently unix-only daemon).",
         "serve/protocol.py"),
    Knob("SPGEMM_TPU_ROUTER_BACKENDS", "str",
         "spgemm-router backend list: comma-joined wire addresses "
         "(`tcp:HOST:PORT` or unix socket paths) of the spgemmd "
         "instances the federation router fronts (fleet/router.py; "
         "`cli route --backends` overrides).  Each backend is polled "
         "for health/depth/slices and priced into placement; a dead or "
         "degraded backend is excluded exactly like a degraded slice.  "
         "Empty/unset with no --backends fails router startup loudly.",
         "fleet/router.py"),
    Knob("SPGEMM_TPU_ROUTER_POLL_S", "float",
         "spgemm-router backend health/price-book poll cadence, "
         "seconds: each cycle refreshes every backend's stats op "
         "(queue depth, slices, degraded flag, placement price-book "
         "sample) off the request path; a backend that fails its poll "
         "is marked down until a later poll answers.  Smaller = faster "
         "failure detection, more stats traffic.",
         "fleet/router.py", default="2", minimum=0.1),
    Knob("SPGEMM_TPU_SERVE_SLICES", "str",
         "spgemmd device-pool slice spec (parallel/mesh.slice_pool): "
         "terms [COUNTx]WIDTH[*] joined by '+', or 'auto' (one "
         "single-device slice per visible device plus one full-mesh "
         "slice).  Each slice gets its own executor thread with its own "
         "warm per-placement delta/warm state, and the placement "
         "scheduler routes jobs by the estimator's predicted mass (cheap "
         "-> narrowest free slice, webbase-class -> widest, first "
         "contact -> the '*'-marked default term, else the narrowest "
         "class) with work-stealing when a slice idles.  Example: "
         "'1x4+4' = one 4-device slice plus four singles.  The default "
         "'1' is one single-device executor -- exactly the pre-pool "
         "daemon (the whole-pool A/B).  An unparsable or overcommitted "
         "spec fails daemon startup loudly (never a silently smaller "
         "pool).",
         "serve/daemon.py", default="1"),
    Knob("SPGEMM_TPU_SERVE_TENANT_INFLIGHT", "int",
         "spgemmd per-tenant in-flight cap (queued + running jobs per "
         "tenant): a submit arriving with this many of its tenant's jobs "
         "already in flight is rejected with a structured tenant-cap "
         "error -- one chatty client cannot fill the whole admission "
         "queue.  Unset = no per-tenant cap (the pre-pool behavior); "
         "the global SPGEMM_TPU_SERVE_QUEUE_CAP always applies on top.",
         "serve/queue.py", minimum=1),
    Knob("SPGEMM_TPU_SERVE_QUEUE_CAP", "int",
         "spgemmd admission cap: a submit arriving with this many jobs "
         "already queued is rejected with a structured queue-full error "
         "(serve/queue.py) instead of hanging the caller.",
         "serve/daemon.py", default="64", minimum=1),
    Knob("SPGEMM_TPU_SERVE_BATCH_K", "int",
         "spgemmd cross-job batch width: when the batching window is "
         "armed (SPGEMM_TPU_SERVE_BATCH_WINDOW_S > 0) a slice executor "
         "picking up a job drains up to this many queued jobs sharing "
         "the head job's recorded structure fingerprint (same folder "
         "structure = same plan) and executes them as ONE fused "
         "dispatch per multiply -- operands stacked along the round "
         "axis the numeric kernels already accept, per-job results "
         "de-interleaved at assembly, every job's fold order untouched "
         "(bit-exact by construction).  Jobs that cannot co-batch "
         "(structure mismatch, different deadline class, checkpointed "
         "or delta-eligible submits) run solo.",
         "serve/daemon.py", default="8", minimum=1),
    Knob("SPGEMM_TPU_SERVE_BATCH_WINDOW_S", "float",
         "spgemmd cross-job batching window, seconds: after popping a "
         "batchable head job the executor waits up to this long for "
         "same-structure mates to arrive (DRR tenant fairness and "
         "per-tenant caps apply BEFORE batch formation, so one tenant "
         "cannot monopolize a batch).  Bounds the admission-latency "
         "cost of batching: the window only opens when a batchable head "
         "was already popped, so an idle pool never waits.  0 = no "
         "cross-job batching at all -- exactly the pre-batch executor "
         "(the whole-feature A/B).",
         "serve/daemon.py", default="0", minimum=0),
    Knob("SPGEMM_TPU_SERVE_JOB_TIMEOUT", "float",
         "spgemmd per-job deadline, seconds: a job running past it is "
         "reaped with a structured job-timeout error, and an executor "
         "still stuck on it afterwards counts as wedged (watchdog "
         "degrade-to-CPU path); 0 = no deadline.",
         "serve/daemon.py", default="0", minimum=0),
    Knob("SPGEMM_TPU_SERVE_RECOVER_S", "float",
         "spgemmd self-healing re-probe cadence, seconds: a degraded "
         "slice is re-probed (subprocess backend_probe, off-thread) this "
         "long after degrading, with exponential backoff between failed "
         "attempts; a live probe reinstates the slice into placement "
         "behind a canary gate -- the first job after reinstatement runs "
         "with a tightened deadline, and a canary failure re-degrades "
         "and doubles the backoff (serve_recoveries counter, "
         "recovered_at in per-slice stats).  0 = never re-probe (the "
         "pre-recovery behavior: a degraded slice stays on the CPU "
         "failover path until daemon restart).",
         "serve/daemon.py", default="0", minimum=0),
    Knob("SPGEMM_TPU_FAILPOINTS", "str",
         "Chaos failpoint arming spec (utils/failpoints.py registry): "
         "comma-joined `name[:prob][:count]` terms naming registered "
         "injection points (e.g. `serve.executor:1:1,warm.load:0.5`); "
         "prob defaults to 1, count to unlimited.  Each armed trigger "
         "performs the point's registered kind (raise | hang | corrupt "
         "| delay), emits a failpoint_trigger event and counts on the "
         "spgemm_failpoints_triggered_total{point=} series.  Unset = "
         "every failpoint inert (zero overhead beyond one env read per "
         "check).  An unknown name or malformed term raises naming the "
         "knob -- a chaos run must never silently arm nothing.",
         "utils/failpoints.py"),
    Knob("SPGEMM_TPU_SERVE_WEDGE_GRACE_S", "float",
         "spgemmd slow-vs-wedged discrimination window, seconds: after "
         "reaping a job the watchdog waits this long for an executor "
         "heartbeat (one fires per COMPLETED multiply) before declaring "
         "the executor wedged and degrading to the CPU failover path -- "
         "must exceed the longest single multiply expected on the "
         "deployment, or a merely-slow job degrades a healthy daemon.",
         "serve/daemon.py", default="60", minimum=0),
    Knob("SPGEMM_TPU_OBS_TRACE", "bool01",
         "Span flight recorder: 1 = every engine phase enter/exit emits a "
         "span into the bounded in-process ring (obs/trace.py), 0 = no "
         "span recording (timers still accumulate; the whole-engine A/B "
         "pair for proving the recorder's overhead).",
         "obs/trace.py", default="1"),
    Knob("SPGEMM_TPU_OBS_RING_CAP", "int",
         "Flight-recorder capacity in spans: the ring keeps the newest N "
         "spans and evicts the oldest (dropped spans are counted, never "
         "an unbounded buffer in a resident daemon).",
         "obs/trace.py", default="4096", minimum=1),
    Knob("SPGEMM_TPU_OBS_EVENTS", "bool01",
         "Structured event log (obs/events.py): 1 = engine/daemon "
         "lifecycle events (job transitions, watchdog reap/degrade, "
         "est/delta fallbacks with reasons, jit compile records) are "
         "emitted as JSONL -- into a bounded in-process ring always, and "
         "onto disk next to the spgemmd journal (<socket>.events.jsonl, "
         "rotated at SPGEMM_TPU_OBS_EVENTS_MAX_KB); 0 = no event "
         "emission anywhere.",
         "obs/events.py", default="1"),
    Knob("SPGEMM_TPU_OBS_EVENTS_MAX_KB", "int",
         "Event-log rotation threshold in KiB: when the on-disk JSONL "
         "grows past this the file rotates to <path>.1 (one rotation "
         "generation -- worst-case disk is ~2x this cap, never unbounded "
         "under a resident daemon).",
         "obs/events.py", default="256", minimum=1),
    Knob("SPGEMM_TPU_SLO_TARGET_S", "float",
         "Per-job latency objective, seconds (obs/slo.py SLO engine): a "
         "terminal job slower than this (or failed) is a BAD event "
         "against the tenant's error budget, and multi-window burn-rate "
         "evaluation runs over every rolling (tenant, slice) window -- "
         "a window whose bad fraction exceeds the SPGEMM_TPU_SLO_ERROR_"
         "PCT budget in both the fast (window/12) and slow (full "
         "window) views emits a structured slo_burn event carrying the "
         "newest bad job's trace context and flips spgemm_slo_burn_"
         "active{tenant=,slice=}.  Unset = accounting-only: latency "
         "quantile / error-ratio / queue-wait-share series still "
         "render, burn evaluation never runs.",
         "obs/slo.py", minimum=0),
    Knob("SPGEMM_TPU_SLO_ERROR_PCT", "float",
         "SLO error budget, percent of jobs the rolling window may "
         "spend as bad events (failed, or slower than SPGEMM_TPU_SLO_"
         "TARGET_S) before the window counts as burning: burn rate = "
         "bad fraction / (this/100), breach at >= 1 in both burn "
         "windows.  Only consulted while SPGEMM_TPU_SLO_TARGET_S is "
         "set (the objective on/off switch).",
         "obs/slo.py", default="1", minimum=0),
    Knob("SPGEMM_TPU_SLO_WINDOW_S", "float",
         "SLO rolling-window length, seconds: per-(tenant, slice) job "
         "records older than this age out of the quantile/error/burn "
         "accounting (the fast burn window is 1/12 of it, SRE-workbook "
         "style).  Window memory stays bounded regardless "
         "(RECORD_RETAIN records per window, TENANT_RETAIN tenants "
         "top-K by recency -- an evicted tenant's windows are dropped "
         "and counted on spgemm_slo_tenants_evicted_total).",
         "obs/slo.py", default="3600", minimum=1),
    Knob("SPGEMM_TPU_TUNE", "bool01",
         "Telemetry-driven autotuner master switch (spgemm_tpu/tune): 1 = "
         "spgemmd loads persisted tuned overrides from the warm store at "
         "start, applies each structure class's winning knob vector at "
         "job pickup behind the canary gate (first job under a fresh "
         "vector runs a tightened deadline; a canary failure reverts the "
         "override and backs off), and adapts the estimator's per-class "
         "sampling budget from observed rel-error; 0 = no overrides ever "
         "applied or loaded and no trials run -- the whole-feature A/B, "
         "byte-identical to the pre-tuner daemon.  Safe by construction: "
         "every searched knob is bit-identical A/B, so tuning can only "
         "ever change wall clock, never bits.",
         "tune/tuner.py", default="1"),
    Knob("SPGEMM_TPU_TUNE_TRIAL_S", "float",
         "Idle-slice trial cadence, seconds: a slice executor whose whole "
         "pool is idle (empty queue, no slice busy) this long after the "
         "previous trial leg runs ONE timed trial leg (one knob vector of "
         "the deterministic per-class enumeration) on the class's "
         "recorded representative folder, returning to the job poll "
         "between legs so a real job preempts within one queue "
         "heartbeat.  0 = no background trials at all (the default: "
         "persisted overrides still apply under SPGEMM_TPU_TUNE=1, but "
         "the daemon never spends idle cycles searching).",
         "serve/daemon.py", default="0", minimum=0),
    Knob("SPGEMM_TPU_TUNE_MIN_WIN", "float",
         "Minimum measured speedup (incumbent wall / candidate wall) "
         "before the autotuner promotes a trial winner to a tuned "
         "override: below this the class keeps its incumbent vector and "
         "the trial result is recorded as a loss.  Guards against "
         "promoting measurement noise into canary churn.",
         "tune/tuner.py", default="1.1", minimum=1),
    Knob("SPGEMM_TPU_PROBE_TIMEOUT", "float",
         "Backend liveness probe subprocess timeout, seconds (a dead TPU "
         "HANGS, never raises -- the probe is the only safe touch).",
         "utils/backend_probe.py", default="150", minimum=0),
    Knob("SPGEMM_TPU_NO_NATIVE", "flag",
         "Force the pure-Python I/O + symbolic-join paths (never build or "
         "load libsmmio).",
         "utils/native.py"),
    Knob("SPGEMM_TPU_FORCE_1MROW", "flag",
         "Run the webbase-1Mrow suite config off-TPU (normally TPU-gated: "
         "impractical at CPU kernel rates).",
         "benchmarks/run.py"),
    Knob("SPGEMM_TPU_BENCH_TIMEOUT", "float",
         "bench.py self-wrap kill budget, seconds: the outer supervisor "
         "SIGKILLs a hung inner bench and emits the failure JSON itself.",
         "bench.py", default="2700", minimum=0),
    Knob("SPGEMM_TPU_BENCH_INNER", "flag",
         "INTERNAL: set by bench.py's outer supervisor on the inner child "
         "it spawns; not an operator knob.",
         "bench.py"),
    Knob("SPGEMM_TPU_EVIDENCE_DIR", "path",
         "TPU evidence capture directory (unset: benchmarks/evidence); "
         "read by benchmarks/run.py and tpu_evidence.sh.",
         "benchmarks/run.py"),
    # spgemm-lint: drf-ok(shell-side knob: read by benchmarks/tpu_evidence.sh, never by Python)
    Knob("SPGEMM_TPU_EVIDENCE_STEPS", "str",
         "Comma-separated tpu_evidence.sh step list (shell-side knob; a "
         "full default list does not arm the strict gates).",
         "benchmarks/tpu_evidence.sh"),
)

REGISTRY: dict[str, Knob] = {k.name: k for k in _KNOBS}


class _TunedOverlay:
    """Process-wide tuned-override overlay (spgemm_tpu/tune).

    The autotuner activates one structure class's winning knob vector at
    job pickup by REPLACING this overlay atomically; `get()` resolves
    env > tuned > default, so an operator's exported value always beats a
    tuned one.  Every value the tuner may set is bit-identical A/B by
    construction, so a concurrent slice reading a just-swapped overlay
    can only ever change wall clock, never bits.  The generation counter
    lets a timed trial detect that another slice swapped the overlay
    under it (the measurement is then discarded, not promoted)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._values: dict[str, str] = {}  # spgemm-lint: guarded-by(_lock)
        self._gen = 0  # spgemm-lint: guarded-by(_lock)

    def replace(self, mapping: dict[str, str]) -> None:
        validated: dict[str, str] = {}
        for name, raw in mapping.items():
            kb = REGISTRY[name]  # registering is the price of tuning
            assert kb.kind != "flag", "flag knobs have no tunable value"
            _parse(kb, str(raw))  # invalid tuned value raises HERE
            validated[name] = str(raw)
        with self._lock:
            if validated != self._values:
                self._values = validated
                self._gen += 1

    def lookup(self, name: str) -> str | None:
        with self._lock:
            return self._values.get(name)

    def snapshot(self) -> dict[str, str]:
        with self._lock:
            return dict(self._values)

    def generation(self) -> int:
        with self._lock:
            return self._gen


_OVERLAY = _TunedOverlay()


def set_tuned(mapping: dict[str, str]) -> None:
    """Atomically replace the tuned-override overlay with `mapping`
    ({knob name: string value}); {} clears it.  Values are validated
    against the registry immediately (an invalid tuned value raises at
    activation, never deep inside a kernel)."""
    _OVERLAY.replace(mapping)


def clear_tuned() -> None:
    """Drop every tuned override (the pre-tuner resolution order)."""
    _OVERLAY.replace({})


def tuned_overlay() -> dict[str, str]:
    """Copy of the live tuned-override overlay ({} when none active)."""
    return _OVERLAY.snapshot()


def tuned_generation() -> int:
    """Monotonic overlay swap counter: a timed trial records it before
    and after a measurement and discards the leg when it moved (another
    slice activated a different class's vector mid-measurement)."""
    return _OVERLAY.generation()


def _parse(kb: Knob, raw: str):
    """Validate + convert one raw string for knob kb.  Every failure names
    the knob (tests pin this: an invalid value must raise immediately,
    never silently run some default)."""
    if kb.kind == "bool01":
        if raw not in ("0", "1"):
            raise ValueError(f"{kb.name} must be 0 or 1, got {raw!r}")
        return raw == "1"
    if kb.kind == "enum":
        if raw not in kb.choices:
            raise ValueError(f"{kb.name} must be one of "
                             f"{'|'.join(kb.choices)}, got {raw!r}")
        return raw
    if kb.kind == "int":
        try:
            val = int(raw)
        except ValueError:
            raise ValueError(
                f"{kb.name} must be an integer"
                + (f" >= {kb.minimum:g}" if kb.minimum is not None else "")
                + f", got {raw!r}") from None
        if kb.minimum is not None and val < kb.minimum:
            raise ValueError(
                f"{kb.name} must be an integer >= {kb.minimum:g}, "
                f"got {raw!r}")
        return val
    if kb.kind == "float":
        try:
            val = float(raw)
        except ValueError:
            raise ValueError(
                f"{kb.name} must be a number"
                + (f" >= {kb.minimum:g}" if kb.minimum is not None else "")
                + f", got {raw!r}") from None
        if kb.minimum is not None and val < kb.minimum:
            raise ValueError(
                f"{kb.name} must be a number >= {kb.minimum:g}, "
                f"got {raw!r}")
        return val
    if kb.kind in ("path", "str"):
        return raw
    raise AssertionError(f"unknown knob kind {kb.kind!r}")  # registry bug


def _resolve(name: str, use_tuned: bool):
    """Shared resolution body for get()/base_get(): env > [tuned >]
    default, typed and validated."""
    kb = REGISTRY[name]
    raw = os.environ.get(name)
    if kb.kind == "flag":
        return bool(raw)  # set-and-non-empty; flags have no default form
    if raw is not None:
        raw = raw.strip()
    if not raw:
        if use_tuned:
            tuned = _OVERLAY.lookup(name)
            if tuned is not None:
                return _parse(kb, tuned)
        raw = kb.default
        if raw is None:
            return None
    return _parse(kb, raw)


def get(name: str):
    """Typed, validated value of a registered knob.

    Resolution order: a (non-empty) env value wins, else a live tuned
    override (spgemm_tpu/tune, set via `set_tuned`), else the registered
    default; with no default, returns None (False for flag knobs).
    Invalid values raise ValueError naming the knob.  Unregistered names
    raise KeyError -- registering is the price of reading."""
    return _resolve(name, use_tuned=True)


def base_get(name: str):
    """`get()` with the tuned overlay IGNORED: env > default only.

    The warm store's tuned-override tier validates its on-disk entries
    against this base form (`base_jit_static_vector`) -- validating
    against the overlaid vector would be circular, since loading an
    override is exactly what changes the overlaid vector."""
    return _resolve(name, use_tuned=False)


def jit_static_vector() -> tuple:
    """Every jit-static knob's current (name, value) pair, in registry
    order -- THE canonical staticity vector: the plan-cache fingerprint
    (ops/spgemm), the compile records (obs/profile), and the warm-start
    store's on-disk validation (ops/warmstore) all key on this one
    definition, so the three surfaces can never drift on what "same
    compiled configuration" means.  Tuned overrides flow through (a
    tuned MXU_R compiles and fingerprints like an exported one); the
    warm store's tune tier alone keys on `base_jit_static_vector`."""
    return tuple((kb.name, str(get(kb.name)))
                 for kb in REGISTRY.values() if kb.jit_static)


def base_jit_static_vector() -> tuple:
    """`jit_static_vector` with the tuned overlay ignored (env > default
    only): the validation key for the warm store's tuned-override tier.
    An env-exported jit-static knob that changed across a restart makes
    every persisted override a counted cold fallback -- it was measured
    under a different base configuration."""
    return tuple((kb.name, str(base_get(kb.name)))
                 for kb in REGISTRY.values() if kb.jit_static)


def pin_unless_exported(name: str, value: str):
    """Write-through-environ harness pin: set registered knob `name` to
    `value` UNLESS the operator exported it (an explicit env value always
    wins).  Returns a zero-arg restore callable (a no-op when nothing was
    pinned) -- in-process callers (the run-once CLI, tests) wrap their
    work in try/finally so the pin never leaks; process-scoped harnesses
    (bench.py, benchmarks/run.py) may discard it.  THE one definition of
    the idiom: env writes are the blessed harness channel (KNB lints
    reads only), and the exported-or-not check goes through the
    registry."""
    kb = REGISTRY[name]  # registering is the price of pinning
    assert kb.kind != "flag", "flag knobs have no pinnable value form"
    if source(name) == "env":
        return lambda: None
    os.environ[name] = value

    def restore() -> None:
        try:
            del os.environ[name]
        except KeyError:
            pass
    return restore


def source(name: str) -> str:
    """'env' if the process environment supplies a (non-empty) value for
    this registered knob, 'tuned' if a live tuned override covers it,
    else 'default'."""
    kb = REGISTRY[name]
    raw = os.environ.get(name)
    if kb.kind == "flag":
        return "env" if raw else "default"
    if raw is not None and raw.strip():
        return "env"
    if _OVERLAY.lookup(name) is not None:
        return "tuned"
    return "default"


def _display(val) -> str:
    if val is None:
        return _UNSET
    if isinstance(val, bool):
        return "1" if val else "0"
    if isinstance(val, float):
        return f"{val:g}"
    return str(val)


def snapshot() -> list[dict]:
    """Current state of every knob (for `spgemm_tpu.cli knobs`): name,
    typed current value, default, source, and registry metadata.  An
    INVALID env value must not abort the listing -- auditing a
    misconfigured A/B session is this function's whole point -- so it is
    reported per-row (`error` key, value shows the raw string) while
    `get()` at the consuming call site stays strict."""
    rows = []
    for kb in _KNOBS:
        try:
            value = _display(get(kb.name))
            error = None
        except ValueError as e:
            value = f"INVALID {os.environ.get(kb.name, '')!r}"
            error = str(e)
        rows.append({
            "name": kb.name,
            "value": value,
            "default": _display(
                False if kb.kind == "flag" and kb.default is None
                else kb.default),
            "source": source(kb.name),
            "kind": kb.kind,
            "jit_static": kb.jit_static,
            "module": kb.module,
            "doc": kb.doc,
            **({"error": error} if error else {}),
        })
    return rows


def _values_col(kb: Knob) -> str:
    if kb.choices:
        return "|".join(kb.choices)
    if kb.kind == "bool01":
        return "0|1"
    if kb.kind == "flag":
        return "set/unset"
    if kb.minimum is not None:
        return f"{kb.kind} >= {kb.minimum:g}"
    return kb.kind


def knob_table_md() -> str:
    """The generated CLAUDE.md knob table.  The linter's DOC rule diffs
    this text against the committed block between the
    `<!-- knob-table:begin -->` / `<!-- knob-table:end -->` markers."""
    lines = [
        "| knob | values | default | jit-static | read in | what it does |",
        "|---|---|---|---|---|---|",
    ]
    def md(cell: str) -> str:  # literal pipes would split the table cell
        return cell.replace("|", "\\|")

    for kb in _KNOBS:
        default = _UNSET if kb.default is None else f"`{kb.default}`"
        lines.append(
            f"| `{kb.name}` | {md(_values_col(kb))} | {default} "
            f"| {'yes' if kb.jit_static else 'no'} | `{kb.module}` "
            f"| {md(kb.doc)} |")
    return "\n".join(lines)


def cli_epilog() -> str:
    """argparse epilog for the CLI: the registry's knob list, so `--help`
    can never drift from the code (the DOC rule checks coverage)."""
    lines = ["environment knobs (see `spgemm_tpu.cli knobs` for live "
             "values; central registry: spgemm_tpu/utils/knobs.py):"]
    for kb in _KNOBS:
        default = "unset" if kb.default is None else kb.default
        lines.append(f"  {kb.name}={_values_col(kb)} (default {default}): "
                     f"{kb.doc}")
    return "\n".join(lines)
