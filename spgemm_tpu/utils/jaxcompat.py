"""Version-skew shims for the pinned jax_graft toolchain.

The repo targets the newest jax API names; the baked-in toolchain may lag a
release or two.  Every cross-version symbol is resolved HERE, once, so kernel
and sharding modules never branch on jax versions themselves:

  * ``shard_map``: ``jax.shard_map`` (new) vs
    ``jax.experimental.shard_map.shard_map`` (<= 0.4.x), whose
    ``check_vma`` kwarg was then spelled ``check_rep``.
  * ``CompilerParams``: ``pallas.tpu.CompilerParams`` (new) vs the older
    ``TPUCompilerParams`` spelling -- same fields.
"""

from __future__ import annotations

import jax

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:  # <= 0.4.x spelling
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(*args, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        return _shard_map(*args, **kwargs)

def __getattr__(name):
    # CompilerParams resolves LAZILY (PEP 562): consumers of the
    # non-Pallas shims (multihost distributed init, shard_map) must not
    # crash at import time on a toolchain whose pallas.tpu is itself
    # missing or broken -- exactly the skew window this module exists for.
    if name == "CompilerParams":
        from jax.experimental.pallas import tpu as _pltpu

        return getattr(_pltpu, "CompilerParams", None) \
            or _pltpu.TPUCompilerParams
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")


def distributed_initialize(**kwargs):
    """``jax.distributed.initialize`` minus the kwargs this jax predates
    (``heartbeat_timeout_seconds`` postdates 0.4.x).  Dropping an
    unsupported kwarg falls back to the runtime's default detection window
    -- slower partner-loss detection, same correctness.

    On a CPU backend, 0.4.x additionally needs the gloo collectives
    implementation selected BEFORE backend init or every cross-process
    collective dies with "Multiprocess computations aren't implemented on
    the CPU backend" (newer jax defaults to gloo on CPU)."""
    import inspect

    try:
        if jax.config.jax_platforms == "cpu":
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
    except AttributeError:  # newer jax: option gone, gloo is the default
        pass
    params = inspect.signature(jax.distributed.initialize).parameters
    jax.distributed.initialize(
        **{k: v for k, v in kwargs.items() if k in params})
