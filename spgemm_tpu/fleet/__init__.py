"""L7 fleet layer: multi-daemon federation behind the TCP front-end.

`fleet/router.py` is the jax-free resident router (`cli route`) that
fronts N spgemmd backends over the same newline-JSON protocol the unix
socket speaks; `fleet/pricebook.py` is its replicated estimator price
book (pair-mass signatures gossiped via each backend's stats placement
block).  `fleet/fleet_smoke.py` is the end-to-end CPU proof
(`make fleet-smoke`).

jax-free by design, like serve/client.py: a router must place and proxy
without ever paying a JAX import or touching a possibly-dead backend
device -- the daemons own the devices, the router owns only sockets.
"""
