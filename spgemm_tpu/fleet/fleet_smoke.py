"""`make fleet-smoke`: end-to-end fleet proof on the CPU backend.

Starts TWO real spgemmd subprocesses, each listening on a unix socket
AND a TCP port (`--addr tcp:127.0.0.1:P` -- the network front-end), and
one spgemm-router subprocess fronting both over TCP, then asserts the
fleet contract:

  * the router's poll marks both backends healthy (stats `backends`
    block) and placement spreads a mixed-tenant burst across BOTH
    backends, every result byte-exact against the host-only oracle
    multiply, every submit answer naming its `backend`;
  * the aggregated scrape carries the router's own families
    (spgemm_router_backend_up per backend) AND every backend's own
    series re-labeled with `backend=` -- one flat fleet surface;
  * TRACE LEG: a submit's client-minted trace context passes through
    the router untouched, and `cli trace-dump --merge` over the
    client's ring dump + the router's trace + the serving backend's
    trace stitches ONE Perfetto file in which that trace id resolves
    to spans from all THREE processes (client_submit -> router_submit
    -> backend job spans);
  * KILL LEG: SIGKILL one backend under a burst of in-flight jobs --
    every job either completes bit-exact (failed over to the survivor:
    re-submitted once, idempotent by the stored submit message) or
    fails with a structured error (backend-lost), never a hang; the
    router marks the dead backend down and lands every later submit on
    the survivor;
  * shutdown is clean: SIGTERM drains the router (exit 0) and the
    surviving daemon (exit 0).

Any step failing exits nonzero.  This process itself stays jax-free
(the oracle and generator are pure numpy; the router is jax-free by
design) -- only the daemons touch a backend, which is the deployment
shape being smoked.
"""

from __future__ import annotations

import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import time


def _fail(procs, msg: str) -> int:
    print(f"fleet-smoke: FAIL: {msg}", file=sys.stderr)
    for proc in procs:
        if proc is not None and proc.poll() is None:
            proc.kill()
    for proc in procs:
        if proc is not None:
            try:
                out, _ = proc.communicate(timeout=10)
            except subprocess.TimeoutExpired:
                continue
            sys.stderr.write(out[-3000:] if out else "")
    return 1


def _free_port() -> int:
    s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _wait_tcp(port: int, proc, procs, what: str,
              deadline_s: float = 120.0) -> int | None:
    deadline = time.time() + deadline_s
    while True:
        if proc.poll() is not None:
            return _fail(procs, f"{what} exited before listening on "
                                f"port {port}")
        try:
            probe = socket.create_connection(("127.0.0.1", port),
                                             timeout=1.0)
        except OSError:
            if time.time() > deadline:
                return _fail(procs, f"{what} never listened on port "
                                    f"{port}")
            time.sleep(0.1)
            continue
        probe.close()
        return None


def main() -> int:
    import numpy as np  # noqa: PLC0415

    from spgemm_tpu.obs import trace as obs_trace  # noqa: PLC0415
    from spgemm_tpu.serve import client  # noqa: PLC0415
    from spgemm_tpu.utils import io_text  # noqa: PLC0415
    from spgemm_tpu.utils.blockcsr import BlockSparseMatrix  # noqa: PLC0415
    from spgemm_tpu.utils.gen import random_chain  # noqa: PLC0415
    from spgemm_tpu.utils.semantics import chain_oracle  # noqa: PLC0415

    tmp = tempfile.mkdtemp(prefix="spgemm-fleet-smoke-")
    k = 8
    folder = os.path.join(tmp, "chain_in")
    mats = random_chain(4, 12, k, 0.4, np.random.default_rng(11), "full")
    io_text.write_chain_dir(folder, mats, k)
    want = chain_oracle([m.to_dict() for m in mats], k)
    want_bytes = io_text.format_matrix(BlockSparseMatrix.from_dict(
        mats[0].rows, mats[-1].cols, k, want).prune_zeros())

    # the harness owns every serve/fleet knob it asserts against
    env = {key: v for key, v in os.environ.items()
           if not (key.startswith("SPGEMM_TPU_WARM")
                   or key.startswith("SPGEMM_TPU_SERVE")
                   or key.startswith("SPGEMM_TPU_ROUTER"))}
    ports = [_free_port(), _free_port()]
    router_port = _free_port()
    socks = [os.path.join(tmp, f"b{i}.sock") for i in (0, 1)]
    backend_names = [f"tcp:127.0.0.1:{p}" for p in ports]
    router_addr = f"tcp:127.0.0.1:{router_port}"

    backends = []
    procs: list[subprocess.Popen | None] = []
    for i in (0, 1):
        proc = subprocess.Popen(
            [sys.executable, "-m", "spgemm_tpu.cli", "serve",
             "--socket", socks[i], "--addr", backend_names[i],
             "--device", "cpu", "-v"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        backends.append(proc)
        procs.append(proc)
    router = None
    try:
        for i in (0, 1):
            rc = _wait_tcp(ports[i], backends[i], procs,
                           f"backend {i}")
            if rc is not None:
                return rc

        router = subprocess.Popen(
            [sys.executable, "-m", "spgemm_tpu.cli", "route",
             "--listen", router_addr,
             "--backends", ",".join(backend_names),
             "--poll-s", "0.5", "-v"],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        procs.append(router)
        rc = _wait_tcp(router_port, router, procs, "router")
        if rc is not None:
            return rc
        deadline = time.time() + 60
        while True:
            st = client.stats(router_addr)
            up = [name for name, b in (st.get("backends") or {}).items()
                  if b.get("up")]
            if len(up) == 2:
                break
            if time.time() > deadline:
                return _fail(procs, f"router never saw both backends "
                                    f"healthy (up: {up})")
            time.sleep(0.2)
        if st.get("daemon") != "spgemm-router":
            return _fail(procs, f"stats daemon={st.get('daemon')!r} "
                                "(want spgemm-router)")

        # ---- mixed-tenant burst through the front door ----
        jobs = []
        for i in range(6):
            out = os.path.join(tmp, f"matrix.{i}")
            resp = client.submit(folder, router_addr, {"output": out},
                                 tenant=f"team-{i % 3}")
            if not resp.get("backend"):
                return _fail(procs, f"submit {i} answer carries no "
                                    "`backend` field")
            jobs.append((resp["id"], resp["backend"], out))
        served = set()
        for jid, backend, out in jobs:
            r = client.wait(jid, router_addr, timeout=300)
            if r["job"]["state"] != "done":
                return _fail(procs, f"job {jid} ended "
                                    f"{r['job']['state']}: "
                                    f"{r['job'].get('error')}")
            if r["job"]["id"] != jid:
                return _fail(procs, f"wait answered job "
                                    f"{r['job']['id']} for fleet id "
                                    f"{jid}")
            if open(out, "rb").read() != want_bytes:
                return _fail(procs, f"job {jid} output does not match "
                                    "the oracle bytes")
            served.add(backend)
        if len(served) != 2:
            return _fail(procs, "the burst did not spread across both "
                                f"backends (served by {served})")

        # ---- aggregated scrape: router families + relabeled backends --
        scrape = client.metrics(router_addr)
        for name in backend_names:
            needle = f'spgemm_router_backend_up{{backend="{name}"}} 1'
            if needle not in scrape:
                return _fail(procs, f"scrape lacks {needle!r}")
        if not any(('backend="' in ln
                    and not ln.startswith("spgemm_router_"))
                   for ln in scrape.splitlines()):
            return _fail(procs, "scrape carries no backend-relabeled "
                                "passthrough series")

        # ---- trace leg: client -> router -> backend, one flame view --
        out_t = os.path.join(tmp, "matrix.trace")
        resp = client.submit(folder, router_addr, {"output": out_t},
                             tenant="tracer")
        trace_id = resp.get("trace")
        t_backend = resp["backend"]
        if not isinstance(trace_id, str) or len(trace_id) != 32:
            return _fail(procs, f"submit returned no 128-bit trace "
                                f"context through the router "
                                f"(got {trace_id!r})")
        r = client.wait(resp["id"], router_addr, timeout=300)
        if r["job"]["state"] != "done":
            return _fail(procs, f"trace-leg job ended "
                                f"{r['job']['state']}: "
                                f"{r['job'].get('error')}")
        stitch = os.path.join(tmp, "stitch")
        obs_trace.dump_json(os.path.join(stitch, "client.trace.json"),
                            process_name="fleet-smoke-client")
        for addr, fname in ((router_addr, "router.trace.json"),
                            (t_backend, "backend.trace.json")):
            rc = subprocess.run(
                [sys.executable, "-m", "spgemm_tpu.cli", "trace-dump",
                 "--addr", addr, "-o", os.path.join(stitch, fname)],
                capture_output=True, text=True, timeout=60)
            if rc.returncode != 0:
                return _fail(procs, f"trace-dump --addr {addr} failed: "
                                    f"{rc.stderr[-500:]}")
        merged_path = os.path.join(tmp, "merged.trace.json")
        rc = subprocess.run(
            [sys.executable, "-m", "spgemm_tpu.cli", "trace-dump",
             "--merge", stitch, "--trace", trace_id, "-o", merged_path],
            capture_output=True, text=True, timeout=60)
        if rc.returncode != 0:
            return _fail(procs, f"cli trace-dump --merge failed: "
                                f"{rc.stderr[-500:]}")
        with open(merged_path, encoding="utf-8") as f:
            merged = json.load(f)
        spans = [ev for ev in merged if ev.get("ph") != "M"]
        pids = {ev["pid"] for ev in spans}
        names = {ev["name"] for ev in spans}
        if len(pids) < 3:
            return _fail(procs, f"merge did not stitch client AND "
                                f"router AND backend tracks (pids "
                                f"{pids}, names {sorted(names)})")
        for span in ("client_submit", "router_submit"):
            if span not in names:
                return _fail(procs, f"merged trace lacks the {span} "
                                    f"span (saw {sorted(names)})")

        # ---- kill leg: one backend dies under load ----
        kill_jobs = []
        for i in range(6):
            out = os.path.join(tmp, f"matrix.k{i}")
            resp = client.submit(folder, router_addr, {"output": out},
                                 tenant=f"team-{i % 3}")
            kill_jobs.append((resp["id"], out))
        backends[0].kill()  # SIGKILL: no drain, jobs die with it
        completed = structured = 0
        for jid, out in kill_jobs:
            try:
                r = client.wait(jid, router_addr, timeout=300)
            except client.ServeError as e:
                if e.code not in ("backend-lost", "no-backend",
                                  "job-error", "unknown-job"):
                    return _fail(procs, f"job {jid} failed with an "
                                        f"undeclared code after the "
                                        f"kill: [{e.code}] {e.message}")
                structured += 1
                continue
            if r["job"]["state"] == "done":
                if open(out, "rb").read() != want_bytes:
                    return _fail(procs, f"post-kill job {jid} output "
                                        "does not match the oracle "
                                        "bytes")
                completed += 1
            else:
                structured += 1  # terminal failed with a structured error
        if completed + structured != len(kill_jobs):
            return _fail(procs, "some post-kill job neither completed "
                                "nor failed structured")

        # the router must have benched the dead backend and every new
        # submit must land on the survivor
        deadline = time.time() + 30
        while True:
            st = client.stats(router_addr)
            dead = (st.get("backends") or {}).get(backend_names[0], {})
            if not dead.get("up"):
                break
            if time.time() > deadline:
                return _fail(procs, "router still reports the killed "
                                    "backend up")
            time.sleep(0.2)
        out_s = os.path.join(tmp, "matrix.survivor")
        resp = client.submit(folder, router_addr, {"output": out_s})
        if resp["backend"] != backend_names[1]:
            return _fail(procs, f"post-kill submit landed on "
                                f"{resp['backend']} (want the survivor "
                                f"{backend_names[1]})")
        r = client.wait(resp["id"], router_addr, timeout=300)
        if r["job"]["state"] != "done":
            return _fail(procs, f"survivor job ended "
                                f"{r['job']['state']}: "
                                f"{r['job'].get('error')}")
        if open(out_s, "rb").read() != want_bytes:
            return _fail(procs, "survivor output does not match the "
                                "oracle bytes")
        failovers = (client.stats(router_addr).get("jobs")
                     or {}).get("failovers", 0)

        # ---- clean drain: router then the survivor ----
        router.send_signal(signal.SIGTERM)
        try:
            rc_router = router.wait(timeout=30)
        except subprocess.TimeoutExpired:
            return _fail(procs, "router did not exit after SIGTERM")
        if rc_router != 0:
            return _fail(procs, f"router exited {rc_router} after "
                                "SIGTERM")
        client.shutdown(socks[1])
        try:
            rc_b = backends[1].wait(timeout=60)
        except subprocess.TimeoutExpired:
            return _fail(procs, "surviving daemon did not exit after "
                                "shutdown")
        if rc_b != 0:
            return _fail(procs, f"surviving daemon exited {rc_b} after "
                                "shutdown")
    finally:
        for proc in procs:
            if proc is not None and proc.poll() is None:
                proc.kill()
    print(f"fleet-smoke: OK (6 mixed-tenant jobs bit-exact across "
          f"{sorted(served)}; aggregated scrape labeled per backend; "
          f"trace {trace_id} stitched across {len(pids)} processes; "
          f"kill leg: {completed} completed / {structured} structured "
          f"of {len(kill_jobs)} with {failovers} failover(s), survivor "
          f"took the rest; router + survivor drained clean)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
