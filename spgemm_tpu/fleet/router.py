"""spgemm-router: the federation front door for a fleet of spgemmd
backends (`cli route`).

One resident jax-free process speaks the spgemmd wire protocol on its
own listener (TCP or unix -- protocol.parse_addr) and fronts N backends:

  * health: a poll thread refreshes every backend's `stats` op each
    SPGEMM_TPU_ROUTER_POLL_S seconds -- queue depth, widest slice,
    degraded flag, and the gossiped placement price book
    (fleet/pricebook.py).  A backend that fails its poll (or reports
    degraded) leaves placement exactly like a degraded slice leaves the
    in-daemon pool; a later healthy poll reinstates it.
  * placement: submits are priced by the replicated price book --
    cheap jobs to the least-loaded narrow backend, webbase-class jobs
    to the widest, first contact round-robins per tenant -- the same
    estimator signal the in-daemon scheduler routes slices by, one
    level up.
  * fleet tenant fairness: per-tenant round-robin spread plus a
    fleet-level in-flight cap (SPGEMM_TPU_SERVE_TENANT_INFLIGHT x
    healthy backends) on top of each daemon's own DRR, so one chatty
    tenant cannot fill every backend's queue through the router.
  * proxying: status/wait follow the job to its backend (snapshots
    come back under the FLEET job id plus a `backend` field); metrics
    aggregates every backend's scrape under an injected backend=
    label beside the router's own families; profile/slo nest
    per-backend reports.  The client-minted trace context passes
    through UNTOUCHED, and the router's own spans carry it, so
    `trace-dump --merge` stitches client -> router -> backend.
  * failover: a job whose backend dies mid-flight is re-submitted ONCE
    to a healthy peer -- idempotent by job fingerprint (same folder
    bytes, same options, same deterministic fold order, same output
    path), counted on spgemm_router_failovers_total -- otherwise the
    caller gets a structured `backend-lost` error, never a hang.

The router holds no queue of its own: submits forward synchronously
(admission pressure is each backend's SPGEMM_TPU_SERVE_QUEUE_CAP), so a
router restart loses only the fleet-id -> backend-id map, and every
backend keeps its jobs, journal, and warm state.
"""

from __future__ import annotations

import argparse
import logging
import os
import signal
import socket
import sys
import threading
import time

from spgemm_tpu.fleet.pricebook import PriceBook
from spgemm_tpu.obs import events as obs_events
from spgemm_tpu.obs import metrics as obs_metrics
from spgemm_tpu.obs import trace as obs_trace
from spgemm_tpu.serve import client, placement, protocol
from spgemm_tpu.utils import knobs

log = logging.getLogger("spgemm-router")

# the router's default front door (the ISSUE's example port); tests and
# the smoke bind tcp:127.0.0.1:0 for an ephemeral port
DEFAULT_LISTEN = "tcp:127.0.0.1:7463"


def _label_scrape(text: str, backend: str) -> str:
    """Inject `backend="..."` into every sample line of one backend's
    Prometheus scrape body (comment lines dropped: HELP/TYPE would
    duplicate across backends; samples without metadata are legal
    text-format 0.0.4)."""
    esc = backend.replace("\\", "\\\\").replace('"', '\\"')
    out = []
    for line in text.splitlines():
        if not line.strip() or line.startswith("#"):
            continue
        try:
            series, value = line.rsplit(" ", 1)
        except ValueError:
            continue
        if series.endswith("}") and "{" in series:
            i = series.index("{")
            body = series[i + 1:-1]
            inner = f'backend="{esc}"' + ("," + body if body else "")
            series = f"{series[:i]}{{{inner}}}"
        else:
            series = f'{series}{{backend="{esc}"}}'
        out.append(f"{series} {value}")
    return "\n".join(out)


class Router:
    """The resident federation router (one instance per process)."""

    MAX_CONNS = 128          # same admission bound as the daemon
    CONN_IDLE_TIMEOUT_S = 600.0
    POLL_TIMEOUT_S = 5.0     # one backend stats poll / forward probe
    FORWARD_RETRY_S = 1.0    # brief ride-out of a backend restart

    def __init__(self, listen: str | None = None,
                 backends: list[str] | None = None,
                 poll_s: float | None = None):
        self.listen_spec = listen or DEFAULT_LISTEN
        self._listen_parsed = protocol.parse_addr(self.listen_spec)
        if backends is None:
            raw = knobs.get("SPGEMM_TPU_ROUTER_BACKENDS") or ""
            backends = [b.strip() for b in raw.split(",") if b.strip()]
        if not backends:
            raise ValueError(
                "spgemm-router needs at least one backend "
                "(--backends or SPGEMM_TPU_ROUTER_BACKENDS)")
        self._poll_s = poll_s if poll_s is not None \
            else knobs.get("SPGEMM_TPU_ROUTER_POLL_S")
        # backend table: stable name (canonical addr spec) -> live state.
        # Inner fields mutate under _lock from the poll thread (health
        # refresh) and conn threads (mark-down on forward failure).
        self._backends: dict[str, dict] = {}  # spgemm-lint: guarded-by(_lock)
        for spec in backends:
            name = protocol.format_addr(protocol.parse_addr(spec))
            if name in self._backends:
                raise ValueError(f"duplicate backend {spec!r}")
            self._backends[name] = {
                "spec": spec, "up": False, "degraded": False,
                "depth": 0, "width": 1, "jobs_total": 0,
                "last_seen": 0.0, "last_error": "unprobed"}
        self.book = PriceBook()
        # fleet job table: fleet id -> routed-job record (the original
        # submit message rides along so failover can re-submit it
        # verbatim -- the idempotent fingerprint is the message itself)
        self._jobs: dict[str, dict] = {}  # spgemm-lint: guarded-by(_lock)
        self._tenant_rr: dict[str, int] = {}  # spgemm-lint: guarded-by(_lock)
        self._failovers = 0                   # spgemm-lint: guarded-by(_lock)
        self._next_id = 1                     # spgemm-lint: guarded-by(_lock)
        self._conn_count = 0                  # spgemm-lint: guarded-by(_lock)
        self._started_at = time.time()
        self._stop = threading.Event()
        self._lock = threading.Lock()
        self._listener: socket.socket | None = None
        self.port: int | None = None
        self._threads: list[threading.Thread] = []

    # ---------------------------------------------------------- lifecycle --
    def start(self) -> None:
        """Bind the front door, run one synchronous backend poll (so the
        first submit already has health + prices), and spawn the
        accept/poll threads."""
        with self._lock:
            backend_names = sorted(self._backends)
        obs_events.emit("router_start", listen=self.listen_spec,
                        backends=backend_names, poll_s=self._poll_s)
        if self._listen_parsed[0] == "tcp":
            self._listener = socket.socket(socket.AF_INET,
                                           socket.SOCK_STREAM)
            self._listener.setsockopt(socket.SOL_SOCKET,
                                      socket.SO_REUSEADDR, 1)
            self._listener.bind((self._listen_parsed[1],
                                 self._listen_parsed[2]))
            self.port = self._listener.getsockname()[1]
        else:
            path = self._listen_parsed[1]
            if os.path.exists(path):
                peer = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                try:
                    peer.settimeout(1.0)
                    peer.connect(path)
                except OSError:
                    os.unlink(path)  # stale: no listener behind it
                else:
                    peer.close()
                    raise RuntimeError(
                        f"a router/daemon is already serving on {path}")
            self._listener = socket.socket(socket.AF_UNIX,
                                           socket.SOCK_STREAM)
            self._listener.bind(path)
        self._listener.listen(16)
        # accept() polls for the stop flag, same as the daemon's loop
        self._listener.settimeout(0.2)
        self._poll_once()
        for target, name in ((self._accept_loop, "router-accept"),
                             (self._poll_loop, "router-poll")):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)
        log.info("spgemm-router serving on %s (%d backend(s): %s; "
                 "poll %gs)",
                 self.listen_spec
                 + (f" [port {self.port}]" if self.port is not None
                    else ""),
                 len(backend_names), ",".join(backend_names),
                 self._poll_s)

    def serve_forever(self) -> None:
        self.start()
        try:
            while not self._stop.wait(0.5):
                pass
        finally:
            self.stop()

    def stop(self) -> None:
        """Drain: stop accepting, let conn threads finish their current
        request (they are synchronous proxies -- no in-flight job state
        lives here), flush the event log, unlink a unix front door."""
        self._stop.set()
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:
                pass
        for t in self._threads:
            t.join(timeout=5.0)
        obs_events.LOG.flush(timeout=2.0)
        if self._listen_parsed[0] == "unix":
            try:
                os.unlink(self._listen_parsed[1])
            except OSError:
                pass

    # --------------------------------------------------------- health poll --
    def _poll_loop(self) -> None:
        while not self._stop.wait(self._poll_s):
            self._poll_once()

    def _poll_once(self) -> None:
        """Refresh every backend's health/depth/width and merge its
        gossiped price book.  Network happens OUTSIDE the lock; only the
        state write-back takes it."""
        with self._lock:
            targets = [(name, b["spec"]) for name, b in
                       self._backends.items()]
        for name, spec in targets:
            if self._stop.is_set():
                return
            try:
                st = client.request({"op": "stats"}, spec,
                                    timeout=self.POLL_TIMEOUT_S,
                                    retry_total_s=0.0)
            except (client.ServeError, OSError) as e:
                self._mark_down(name, repr(e))
                continue
            self.book.merge(st.get("placement"))
            degraded = bool(st.get("degraded"))
            depth = (st.get("jobs") or {}).get("depth", 0)
            width = max([s.get("width", 1) for s in
                         (st.get("slices") or [])] or [1])
            with self._lock:
                b = self._backends[name]
                was_healthy = b["up"] and not b["degraded"]
                b["up"] = True
                b["degraded"] = degraded
                b["depth"] = depth
                b["width"] = width
                b["last_seen"] = time.time()
                b["last_error"] = None
                now_healthy = not degraded
            if now_healthy and not was_healthy:
                obs_events.emit("router_backend_up", backend=name)
            elif degraded and was_healthy:
                obs_events.emit("router_backend_down", backend=name,
                                reason="backend reports degraded")

    def _mark_down(self, name: str, reason: str) -> None:
        with self._lock:
            b = self._backends[name]
            was_healthy = b["up"] and not b["degraded"]
            b["up"] = False
            b["last_error"] = reason
        if was_healthy:
            obs_events.emit("router_backend_down", backend=name,
                            reason=reason)

    def _healthy(self) -> list[tuple[str, dict]]:
        """(name, state-copy) rows for every placeable backend."""
        with self._lock:
            return [(name, dict(b)) for name, b in self._backends.items()
                    if b["up"] and not b["degraded"]]

    # ----------------------------------------------------------- transport --
    def _accept_loop(self) -> None:
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed during shutdown
            with self._lock:
                admit = self._conn_count < self.MAX_CONNS
                if admit:
                    self._conn_count += 1
            if not admit:
                try:
                    conn.sendall(protocol.encode(protocol.error(
                        protocol.E_BUSY,
                        f"too many concurrent connections "
                        f"({self.MAX_CONNS}); retry shortly")))
                except OSError:
                    pass
                conn.close()
                continue
            conn.settimeout(self.CONN_IDLE_TIMEOUT_S)
            t = threading.Thread(target=self._handle_conn, args=(conn,),
                                 name="router-conn", daemon=True)
            t.start()

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            for line in protocol.read_lines(
                    conn, max_line=protocol.MAX_LINE_BYTES):
                if not line.strip():
                    continue
                try:
                    msg = protocol.parse_request(line)
                except protocol.ProtocolError as e:
                    resp = protocol.error(e.code, e.message)
                else:
                    try:
                        resp = self._dispatch(msg)
                    except protocol.ProtocolError as e:
                        resp = protocol.error(e.code, e.message)
                    except client.ServeError as e:
                        # a backend's structured refusal (queue-full,
                        # tenant-cap, unknown-job after a backend wipe)
                        # passes through verbatim -- the router adds no
                        # error surface of its own here
                        resp = protocol.error(e.code, e.message)
                    except Exception as e:  # noqa: BLE001 -- router must survive any handler crash
                        log.warning("request handler failed: %r", e)
                        resp = protocol.error(protocol.E_INTERNAL,
                                              repr(e))
                conn.sendall(protocol.encode(resp))
        except protocol.ProtocolError as e:
            # oversized line: answer once, then drop the connection
            try:
                conn.sendall(protocol.encode(protocol.error(e.code,
                                                            e.message)))
            except OSError:
                pass
        except OSError:
            pass  # peer went away mid-conversation (or idled out)
        finally:
            conn.close()
            with self._lock:
                self._conn_count -= 1

    def _dispatch(self, msg: dict) -> dict:
        op = msg["op"]
        if op == "submit":
            return self._op_submit(msg)
        if op == "status":
            return self._op_status(msg)
        if op == "wait":
            return self._op_wait(msg)
        if op == "stats":
            return self._op_stats()
        if op == "metrics":
            return self._op_metrics()
        if op == "trace":
            return self._op_trace()
        if op == "profile":
            return self._op_profile()
        if op == "events":
            return self._op_events(msg)
        if op == "slo":
            return self._op_slo()
        return self._op_shutdown()

    # ----------------------------------------------------------- placement --
    def _place(self, folder, tenant: str) -> list[tuple[str, str]]:
        """Ordered (name, spec) candidates for one submit: price-book
        hit -> heavy to the widest / cheap to the least-loaded
        narrowest; first contact -> per-tenant round-robin.  Raises
        ProtocolError(no-backend) when nothing is placeable."""
        healthy = self._healthy()
        if not healthy:
            with self._lock:
                total = len(self._backends)
            raise protocol.ProtocolError(
                protocol.E_NO_BACKEND,
                f"no healthy backend among {total} "
                "(all dead, degraded, or unprobed)")
        mass = self.book.lookup(folder) \
            if isinstance(folder, str) else None
        if mass is None:
            # first contact: spread per tenant, so one tenant's stream
            # round-robins independently of everyone else's
            healthy.sort(key=lambda row: row[0])
            with self._lock:
                cursor = self._tenant_rr.get(tenant, 0)
                self._tenant_rr[tenant] = cursor + 1
            k = cursor % len(healthy)
            ordered = healthy[k:] + healthy[:k]
        elif mass >= placement.LARGE_MASS_PAIRS:
            ordered = sorted(healthy, key=lambda row: (
                -row[1]["width"], row[1]["depth"], row[0]))
        else:
            ordered = sorted(healthy, key=lambda row: (
                row[1]["depth"], row[1]["width"], row[0]))
        return [(name, b["spec"]) for name, b in ordered]

    def _tenant_inflight(self, tenant: str) -> int:
        with self._lock:
            return sum(1 for j in self._jobs.values()
                       if j["tenant"] == tenant and not j["terminal"])

    def _forward_submit(self, fwd: dict, candidates) -> tuple[dict, str]:
        """Try each candidate backend in placement order; a dead one is
        marked down and skipped, a structured refusal propagates
        (ServeError).  Returns (backend answer, backend name)."""
        last_err = None
        for name, spec in candidates:
            try:
                answer = client.request(
                    fwd, spec, retry_total_s=self.FORWARD_RETRY_S)
            except client.ServeError as e:
                if e.code != protocol.E_UNAVAILABLE:
                    raise
                self._mark_down(name, e.message)
                last_err = e
                continue
            except OSError as e:
                self._mark_down(name, repr(e))
                last_err = e
                continue
            return answer, name
        raise protocol.ProtocolError(
            protocol.E_NO_BACKEND,
            f"every placeable backend refused the connection "
            f"(last: {last_err!r})")

    # ---------------------------------------------------------------- ops --
    def _op_submit(self, msg: dict) -> dict:
        if self._stop.is_set():
            return protocol.error(protocol.E_SHUTTING_DOWN,
                                  "router is shutting down")
        folder = msg.get("folder")
        if not isinstance(folder, str) or not folder:
            return protocol.error(protocol.E_BAD_REQUEST,
                                  "submit requires a non-empty `folder`")
        tenant = msg.get("tenant", protocol.DEFAULT_TENANT)
        if not protocol.valid_tenant(tenant):
            return protocol.error(
                protocol.E_BAD_REQUEST,
                f"tenant must be 1-{protocol.TENANT_MAX_LEN} chars of "
                f"[A-Za-z0-9._:-], got {tenant!r}")
        trace_in = msg.get("trace")
        if trace_in is not None and not protocol.valid_trace(trace_in):
            return protocol.error(
                protocol.E_BAD_REQUEST,
                f"trace must be {protocol.TRACE_HEX_LEN} lowercase hex "
                f"chars, got {trace_in!r}")
        candidates = self._place(folder, tenant)
        # fleet-level tenant fairness on top of each daemon's DRR: the
        # per-daemon in-flight cap scaled by the healthy backend count
        # bounds one tenant's total fleet footprint through the router
        per_daemon_cap = knobs.get("SPGEMM_TPU_SERVE_TENANT_INFLIGHT")
        if per_daemon_cap is not None:
            fleet_cap = per_daemon_cap * len(candidates)
            if self._tenant_inflight(tenant) >= fleet_cap:
                return protocol.error(
                    protocol.E_TENANT_CAP,
                    f"tenant {tenant!r} already has {fleet_cap} job(s) "
                    "in flight across the fleet")
        # forward the request UNTOUCHED (minus the envelope version --
        # client.request re-stamps the capability table's): the trace
        # context, tenant, and options reach the backend byte-for-byte
        fwd = {k: v for k, v in msg.items() if k != "v"}
        t0 = time.perf_counter()
        resp, name = self._forward_submit(fwd, candidates)
        with self._lock:
            fleet_id = f"r{self._next_id}"
            self._next_id += 1
            self._jobs[fleet_id] = {
                "backend": name, "backend_id": resp.get("id"),
                "msg": fwd, "tenant": tenant,
                "trace": resp.get("trace") or trace_in,
                "failovers": 0, "terminal": None}
            self._backends[name]["jobs_total"] += 1
            self._backends[name]["depth"] += 1  # optimistic; poll refreshes
            trace_id = self._jobs[fleet_id]["trace"]
        # the router's own span under the SAME trace context the client
        # minted: `trace-dump --merge` lines this up between the
        # client_submit span and the backend's job spans
        with obs_trace.RECORDER.tagged(trace_id=trace_id, tenant=tenant,
                                       backend=name):
            obs_trace.RECORDER.point("router_submit",
                                     time.perf_counter() - t0)
        resp["id"] = fleet_id
        resp["backend"] = name
        return resp

    def _job(self, msg: dict) -> dict:
        jid = msg.get("id")
        with self._lock:
            job = self._jobs.get(jid) if isinstance(jid, str) else None
            if job is None:
                raise protocol.ProtocolError(
                    protocol.E_UNKNOWN_JOB,
                    f"unknown job id {jid!r} (the router's job map is "
                    "process-local; resubmit after a router restart)")
            return dict(job, fleet_id=jid)

    def _failover(self, fleet_id: str, dead: str) -> str | None:
        """Re-submit a lost job ONCE to a healthy peer (idempotent: the
        forwarded submit message is the job's fingerprint -- same
        folder bytes, same options, same deterministic output).
        Returns the new backend name, or None when the job cannot fail
        over (already retried, or no healthy peer)."""
        with self._lock:
            job = self._jobs[fleet_id]
            if job["failovers"] >= 1 or job["terminal"]:
                return None
            fwd = dict(job["msg"])
            tenant = job["tenant"]
        self._mark_down(dead, "died mid-job")
        candidates = [(n, s) for n, s in
                      ((name, b["spec"]) for name, b in self._healthy())
                      if n != dead]
        if not candidates:
            obs_events.emit("router_failover", job=fleet_id,
                            dead=dead, outcome="backend-lost")
            return None
        try:
            answer, name = self._forward_submit(fwd, candidates)
        except (protocol.ProtocolError, client.ServeError):
            obs_events.emit("router_failover", job=fleet_id,
                            dead=dead, outcome="backend-lost")
            return None
        with self._lock:
            job = self._jobs[fleet_id]
            job["backend"] = name
            job["backend_id"] = answer.get("id")
            job["failovers"] += 1
            self._backends[name]["jobs_total"] += 1
            self._failovers += 1
            trace_id = job["trace"]
        obs_events.emit("router_failover", job=fleet_id, dead=dead,
                        to=name, outcome="resubmitted", trace=trace_id)
        log.warning("job %s failed over %s -> %s", fleet_id, dead, name)
        return name

    def _proxy_job_op(self, msg: dict, fwd: dict,
                      retried: bool = False) -> dict:
        """Forward one status/wait to the job's backend; a dead backend
        triggers the one-shot failover, then ONE retry of the op
        against the new backend."""
        job = self._job(msg)
        fwd = dict(fwd, id=job["backend_id"])
        try:
            resp = client.request(fwd, self._backend_spec(job["backend"]),
                                  timeout=self.POLL_TIMEOUT_S + 30.0,
                                  retry_total_s=self.FORWARD_RETRY_S)
        except (client.ServeError, OSError) as e:
            # a SIGKILLed backend surfaces as daemon-unavailable on
            # reconnect or a raw reset mid-stream -- both mean the
            # backend is gone and the job should fail over
            if isinstance(e, client.ServeError) \
                    and e.code != protocol.E_UNAVAILABLE or retried:
                raise
            if self._failover(job["fleet_id"], job["backend"]) is None:
                return protocol.error(
                    protocol.E_BACKEND_LOST,
                    f"backend {job['backend']} died holding job "
                    f"{job['fleet_id']} and no healthy peer could "
                    "take the re-submit")
            return self._proxy_job_op(msg, fwd, retried=True)
        snap = resp.get("job")
        if isinstance(snap, dict):
            snap["id"] = job["fleet_id"]
            if snap.get("state") in ("done", "failed"):
                with self._lock:
                    live = self._jobs.get(job["fleet_id"])
                    if live is not None and not live["terminal"]:
                        live["terminal"] = snap["state"]
        resp["backend"] = job["backend"]
        return resp

    def _backend_spec(self, name: str) -> str:
        with self._lock:
            return self._backends[name]["spec"]

    def _op_status(self, msg: dict) -> dict:
        return self._proxy_job_op(msg, {"op": "status"})

    def _op_wait(self, msg: dict) -> dict:
        fwd = {"op": "wait"}
        if msg.get("timeout") is not None:
            fwd["timeout"] = msg["timeout"]
        return self._proxy_job_op(msg, fwd)

    def _op_stats(self) -> dict:
        with self._lock:
            backends = {name: {k: b[k] for k in
                               ("up", "degraded", "depth", "width",
                                "jobs_total", "last_seen", "last_error")}
                        for name, b in self._backends.items()}
            jobs = {"routed": len(self._jobs),
                    "inflight": sum(1 for j in self._jobs.values()
                                    if not j["terminal"]),
                    "failovers": self._failovers}
            tenants = {}
            for j in self._jobs.values():
                row = tenants.setdefault(j["tenant"],
                                         {"jobs": 0, "inflight": 0})
                row["jobs"] += 1
                row["inflight"] += 0 if j["terminal"] else 1
        return protocol.ok(
            daemon="spgemm-router",
            uptime_s=round(time.time() - self._started_at, 3),
            backends=backends,
            jobs=jobs,
            tenants=tenants,
            placement=self.book.stats(),
            events=obs_events.LOG.stats(),
            trace=obs_trace.RECORDER.stats(),
        )

    def _op_metrics(self) -> dict:
        """The router's own families, then every live backend's scrape
        with a `backend=` label injected -- one aggregated fleet
        surface per scrape."""
        with self._lock:
            rows = [(name, dict(b)) for name, b in
                    self._backends.items()]
            failovers = self._failovers
        samples = []
        for name, b in rows:
            labels = {"backend": name}
            samples += [
                ("spgemm_router_backend_up", labels,
                 int(b["up"] and not b["degraded"])),
                ("spgemm_router_backend_queue_depth", labels,
                 b["depth"]),
                ("spgemm_router_jobs_total", labels, b["jobs_total"]),
            ]
        samples.append(("spgemm_router_failovers_total", {}, failovers))
        parts = [obs_metrics.render(samples)]
        for name, b in rows:
            if not b["up"]:
                continue
            try:
                resp = client.request({"op": "metrics"}, b["spec"],
                                      timeout=self.POLL_TIMEOUT_S,
                                      retry_total_s=0.0)
            except (client.ServeError, OSError) as e:
                self._mark_down(name, repr(e))
                continue
            parts.append(_label_scrape(resp.get("text") or "", name))
        return protocol.ok(
            content_type="text/plain; version=0.0.4; charset=utf-8",
            text="\n".join(p for p in parts if p) + "\n")

    def _op_trace(self) -> dict:
        events = obs_trace.to_trace_events()
        return protocol.ok(spans=len(events), trace_events=events)

    def _op_profile(self) -> dict:
        return protocol.ok(profile=self._fan_in("profile"))

    def _op_slo(self) -> dict:
        return protocol.ok(slo=self._fan_in("slo"))

    def _fan_in(self, op: str) -> dict:
        """One op fanned to every live backend; a failing backend
        contributes a structured error row instead of failing the
        aggregate."""
        with self._lock:
            rows = [(name, b["spec"]) for name, b in
                    self._backends.items() if b["up"]]
        out = {}
        for name, spec in rows:
            try:
                answer = client.request({"op": op}, spec,
                                        timeout=self.POLL_TIMEOUT_S,
                                        retry_total_s=0.0)
            except (client.ServeError, OSError) as e:
                out[name] = {"error": repr(e)}
                continue
            out[name] = answer.get(op)
        return out

    def _op_events(self, msg: dict) -> dict:
        n = msg.get("n", 50)
        try:
            n = int(n)
        except (TypeError, ValueError):
            return protocol.error(protocol.E_BAD_REQUEST,
                                  f"n must be an integer, got {n!r}")
        return protocol.ok(events=obs_events.LOG.tail(n),
                           log=obs_events.LOG.stats())

    def _op_shutdown(self) -> dict:
        self._stop.set()
        return protocol.ok(stopping=True)


def main(argv: list[str] | None = None) -> int:
    """`spgemm_tpu route`: run the federation router in the foreground."""
    p = argparse.ArgumentParser(
        prog="spgemm_tpu route",
        description="spgemm-router: jax-free federation front door for "
                    "N spgemmd backends -- health-polled estimator-"
                    "priced placement, fleet tenant fairness, scrape "
                    "aggregation, trace passthrough, one-shot failover")
    p.add_argument("--listen", default=None, metavar="ADDR",
                   help=f"front-door address: tcp:HOST:PORT or a unix "
                        f"socket path (default {DEFAULT_LISTEN}; "
                        f"tcp port 0 binds ephemeral and logs the "
                        f"real port)")
    p.add_argument("--backends", default=None, metavar="LIST",
                   help="comma-joined backend addresses (default: "
                        "SPGEMM_TPU_ROUTER_BACKENDS)")
    p.add_argument("--poll-s", type=float, default=None, metavar="S",
                   help="backend poll cadence override "
                        "(SPGEMM_TPU_ROUTER_POLL_S)")
    p.add_argument("--verbose", "-v", action="store_true")
    args = p.parse_args(argv)
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING,
        format="%(name)s %(message)s")
    backends = None
    if args.backends is not None:
        backends = [b.strip() for b in args.backends.split(",")
                    if b.strip()]
    try:
        router = Router(listen=args.listen, backends=backends,
                        poll_s=args.poll_s)
    except ValueError as e:
        print(f"spgemm-router: {e}", file=sys.stderr)
        return 1

    # same rollout contract as spgemmd: the handler ONLY sets the flag,
    # serve_forever's finally runs the drain and main returns 0
    def _on_signal(signum, frame):  # noqa: ARG001 -- signal handler shape
        router._stop.set()

    for signum in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(signum, _on_signal)
        except (ValueError, OSError):
            pass  # not the main thread: Ctrl-C still works
    try:
        router.serve_forever()
    except KeyboardInterrupt:
        router.stop()
    except RuntimeError as e:
        print(f"spgemm-router: {e}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
