"""Replicated estimator price book for fleet-level placement.

Each spgemmd prices structures it has actually read (the sampled
estimator's pair mass, serve/placement.note_mass) and gossips its newest
book entries in every stats answer (`placement.book`, bounded by
placement.BOOK_GOSSIP_CAP).  The router's poll loop merges those samples
HERE, so a submit whose folder any backend has priced routes on a real
estimate -- the same Ocean-style estimation-steers-resources signal the
in-daemon scheduler uses, one level up.

Keys are serve/placement.signature stat signatures (folder + file
names/sizes/mtimes), so the book is content-stamped exactly like the
per-daemon one: a mutated input re-prices instead of riding a stale
mass.  Pricing steers placement only, never bits.

jax-free by design (imported by the router's conn and poll threads).
"""

from __future__ import annotations

import threading
from collections import OrderedDict

from spgemm_tpu.serve import placement

# merged-book capacity, LRU past it (same scale as the per-daemon book:
# one entry per distinct (folder, content-stamp) across the fleet)
CAP = 4096


class PriceBook:
    """The router's merged (signature -> pair mass) book."""

    def __init__(self, cap: int = CAP):
        self._cap = cap
        self._lock = threading.Lock()
        self._book: "OrderedDict[str, float]" = OrderedDict()  # spgemm-lint: guarded-by(_lock)
        self._merged = 0   # spgemm-lint: guarded-by(_lock)
        self._hits = 0     # spgemm-lint: guarded-by(_lock)
        self._misses = 0   # spgemm-lint: guarded-by(_lock)

    def merge(self, placement_block) -> int:
        """Fold one backend's gossiped stats placement block in (newest
        sightings win); returns the number of entries taken.  A
        malformed block contributes nothing -- gossip is best-effort,
        placement falls back to round-robin."""
        book = (placement_block or {}).get("book") \
            if isinstance(placement_block, dict) else None
        if not isinstance(book, dict):
            return 0
        taken = 0
        with self._lock:
            for sig, mass in book.items():
                if not isinstance(sig, str) \
                        or not isinstance(mass, (int, float)):
                    continue
                self._book[sig] = float(mass)
                self._book.move_to_end(sig)
                taken += 1
            while len(self._book) > self._cap:
                self._book.popitem(last=False)
            self._merged += taken
        return taken

    def lookup(self, folder: str) -> float | None:
        """The fleet-replicated pair mass for the folder's CURRENT
        content, or None on first contact / content change / unreadable
        folder."""
        sig = placement.signature(folder)
        with self._lock:
            if sig is None or sig not in self._book:
                self._misses += 1
                return None
            self._book.move_to_end(sig)
            self._hits += 1
            return self._book[sig]

    def stats(self) -> dict:
        with self._lock:
            return {"book_entries": len(self._book),
                    "book_hits": self._hits,
                    "book_misses": self._misses,
                    "merged": self._merged}
