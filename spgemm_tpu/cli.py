"""CLI driver (L6): the `a4`-compatible entrypoint.

Reference contract (sparse_matrix_mult.cu:402-682):

    mpirun -np P ./a4 <folder>

reads `<folder>/size` (N, k) and `<folder>/matrix1..matrixN`, computes the
chain product, prunes all-zero tiles, writes `./matrix`, prints
`time taken X seconds`.

TPU-native contract (north star, BASELINE.json): same positional argument,
same files, same output, no MPI launcher --

    python -m spgemm_tpu.cli <folder> [--device tpu|cpu] [--backend xla|pallas]
                             [--output matrix] [--round-size N] [--threads 16]

The reference's hard-coded globals become flags with the same defaults
(SURVEY.md section 5.6).  Multi-chip sharding is picked up automatically from
the visible mesh (see parallel/), replacing the mpirun -np P contract.

`python -m spgemm_tpu.cli knobs [--json]` lists the central knob registry
(spgemm_tpu/utils/knobs.py) with each knob's current value, default, and
source (env vs default) -- whole-engine A/B setups are inspectable without
grepping the environment.

`serve` / `submit` / `status` drive spgemmd (spgemm_tpu/serve/): a
resident daemon owning the device whose warm jit/plan/crossover caches are
reused across jobs, vs this run-once entrypoint paying them per
invocation.  `metrics` scrapes the daemon's Prometheus text-format
surface and `trace-dump` serializes its span flight recorder as
Perfetto/Chrome trace_event JSON (spgemm_tpu/obs/) -- `trace-dump
--merge DIR [--trace ID]` stitches per-process/per-rank dumps into one
trace with labeled process tracks on a shared wall-clock timeline.
`profile` reports the daemon's deep-profiling accounts (jit compile
wall + cost/memory analyses per engine site, HBM watermarks,
estimator/delta prediction accuracy), `events` tails its structured
event log (obs/events.py JSONL: job lifecycle, watchdog transitions,
fallbacks with reasons; `--follow` streams the rotating sink live),
and `slo` reports the SLO engine (obs/slo.py: per-tenant rolling
latency quantiles, error ratio, queue-wait share, burn-rate state).
`warm --stat|--clear` inspects or empties the persistent warm-start
store (ops/warmstore: the on-disk plan/delta entries + xla compilation
cache a restarted spgemmd rehydrates from).
"""

from __future__ import annotations

import argparse
import logging
import sys
import time

from spgemm_tpu.utils import knobs as knobs_registry


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="spgemm_tpu",
        description="TPU-native block-sparse matrix chain product "
                    "(reference-compatible).  Also: `spgemm_tpu knobs` "
                    "lists the engine env-knob registry with live values.",
        # the epilog is GENERATED from the knob registry, so --help can
        # never drift from the code (the spgemm-lint DOC rule checks it)
        epilog=knobs_registry.cli_epilog(),
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    p.add_argument("folder", help="input directory containing `size` and `matrix1..N`")
    p.add_argument("--device", default=None, metavar="PLATFORM",
                   help="force a JAX platform, e.g. tpu or cpu "
                        "(default: whatever JAX selects)")
    # the device backends come from the serve-layer wire contract (ONE
    # list shared with the daemon's submit validation and the submit
    # CLI); the run-once path alone adds the host-only oracle
    from spgemm_tpu.serve.protocol import CHAIN_BACKENDS  # noqa: PLC0415
    p.add_argument("--backend",
                   choices=[*CHAIN_BACKENDS, "oracle"],
                   default=None,
                   help="numeric-phase implementation (default: pallas on "
                        "TPU, xla elsewhere; mxu = field-mode limb matmul on "
                        "the systolic array, hybrid = per-round mxu where "
                        "provably bit-exact, exact kernel elsewhere)")
    p.add_argument("--output", default="matrix",
                   help="output path (reference writes ./matrix)")
    p.add_argument("--round-size", type=int, default=None,
                   help="max output tiles per numeric launch (default: auto -- "
                        "SMEM-bounded on the Pallas backend, 512 on XLA; the "
                        "reference's small_size=500)")
    p.add_argument("--threads", type=int, default=None,
                   help="file-loader thread pool size (default: min(16, 4x "
                        "host cores); the reference hardcodes num_threads(16))")
    p.add_argument("--shard", choices=["none", "keys", "inner", "ring", "chain"],
                   default="none",
                   help="shard over the visible device mesh: 'keys' = output-"
                        "tile sharding per multiply (bit-exact), 'inner' = "
                        "contraction sharding + ICI all-reduce, 'ring' = rotate "
                        "B around the ring, O(1/n) operand memory, hop "
                        "double-buffered behind the fold "
                        "(SPGEMM_TPU_RING_OVERLAP=0 serializes it, bit-"
                        "identical) ('inner'/'ring' use clean mod-(2^64-1) "
                        "arithmetic, see parallel/), 'chain' = one chain rank "
                        "per device executing concurrently (bit-exact, the "
                        "reference's MPI data parallelism at P = n_devices)")
    p.add_argument("--stream", action="store_true",
                   help="host-resident chain partials: each multiply uploads "
                        "its two operands, computes on device, and fetches "
                        "the result back, so peak HBM is one multiply's "
                        "working set instead of the whole pass -- the knob "
                        "for chains larger than device memory (costs one "
                        "D2H+H2D round-trip per partial per pass; the keys/"
                        "inner/ring shard strategies already keep partials "
                        "host-resident, and --shard chain ignores this flag)")
    p.add_argument("--out-of-core", action="store_true",
                   help="never materialize an operand slab in HBM: partials "
                        "stay host-resident (implies --stream) and each "
                        "numeric round uploads only the tiles it references, "
                        "so peak HBM is two rounds' working sets (depth-2 "
                        "pipeline) -- multiplies bigger than device memory, "
                        "the reference's host-staging capacity model "
                        "(sparse_matrix_mult.cu:167-257)")
    p.add_argument("--checkpoint-dir", default=None, metavar="DIR",
                   help="snapshot chain partials after each reduction pass and "
                        "resume from the newest snapshot on restart")
    p.add_argument("--failover", action="store_true",
                   help="failure detection + recovery: if the device dies "
                        "mid-chain, restart the current pass on the host-only "
                        "oracle (keeps host copies of each pass -- one extra "
                        "D2H per pass)")
    p.add_argument("--ranks", type=int, default=1, metavar="P",
                   help="emulate `mpirun -np P` chain partitioning semantics "
                        "(reference sparse_matrix_mult.cu:438-456)")
    p.add_argument("--distributed", action="store_true",
                   help="multi-host mode: partition the chain across JAX "
                        "processes (set JAX_COORDINATOR/JAX_NUM_PROCESSES/"
                        "JAX_PROCESS_ID per host; replaces `mpirun -np P`). "
                        "Partial products exchange over DCN in bounded "
                        "chunks of SPGEMM_TPU_DCN_CHUNK_MB (default 64) per "
                        "rank; 0 = legacy padded all-gather")
    p.add_argument("--verbose", "-v", action="store_true")
    p.add_argument("--profile", default=None, metavar="DIR",
                   help="write a jax.profiler trace to DIR")
    return p


def run_knobs(argv: list[str]) -> int:
    """`spgemm_tpu knobs [--json]`: the registry's live state -- one line
    per knob (name, current value, source, default) so an exported A/B
    session is auditable at a glance."""
    p = argparse.ArgumentParser(prog="spgemm_tpu knobs",
                                description="list engine env knobs: "
                                "current value, default, and source")
    p.add_argument("--json", action="store_true", dest="as_json",
                   help="machine-readable: {knobs: [one object per knob], "
                        "plan_cache: live hit/miss/eviction stats, "
                        "estimator: live est_hits/est_fallbacks routing "
                        "stats, delta: live incremental-recompute stats}")
    args = p.parse_args(argv)
    rows = knobs_registry.snapshot()
    # tuned-source marking (spgemm_tpu/tune): a knob carried by any
    # persisted canary/live override -- the autotuner's warm tune tier,
    # read from disk like `cli tune --status` -- gets its class keys
    # attached to the row, so the listing shows which values a serving
    # daemon would overlay per class.  Best-effort: a missing/foreign
    # warm dir must never break the listing.
    tuned_by: dict[str, list[str]] = {}
    try:
        from spgemm_tpu.ops import warmstore as _ws  # noqa: PLC0415
        from spgemm_tpu.serve import protocol as _proto  # noqa: PLC0415
        tune_dir = (knobs_registry.get("SPGEMM_TPU_WARM_DIR")
                    or _proto.default_socket_path() + ".warm")
        for ck, rec in sorted(_ws.scan_tunes(tune_dir).items()):
            if rec.get("state") in ("canary", "live"):
                for kn in {**(rec.get("knobs") or {}),
                           **(rec.get("est") or {})}:
                    tuned_by.setdefault(str(kn), []).append(ck)
    except Exception:  # noqa: BLE001 -- the listing renders with or without a readable warm dir
        tuned_by = {}
    for r in rows:
        if r["name"] in tuned_by:
            r["tuned_classes"] = tuned_by[r["name"]]
    # live plan-cache + estimator + delta state next to the knob rows
    # (jax-free imports): the whole-engine A/B pairs
    # (SPGEMM_TPU_PLAN_AHEAD=0|2, SPGEMM_TPU_PLAN_ESTIMATE=0|1,
    # SPGEMM_TPU_DELTA=0|1) and the routing health (estimated vs
    # exact-fallback plans, delta-served vs full-fallback multiplies) are
    # inspectable together without a bench run or a metrics scrape
    from spgemm_tpu.ops import delta, estimate, plancache  # noqa: PLC0415

    try:
        cache = plancache.stats()
    except ValueError as e:
        # an INVALID cache-knob value must not abort the listing (the
        # per-knob rows above already carry the error); report it in place
        cache = {"hits": 0, "misses": 0, "evictions": 0, "entries": 0,
                 "capacity": "?", "enabled": "?", "error": str(e)}
    try:
        est = estimate.stats()
    except ValueError as e:
        est = {"hits": 0, "fallbacks": 0, "enabled": "?",
               "sample_rows": "?", "confidence_threshold": "?",
               "error": str(e)}
    try:
        dlt = delta.stats()
    except ValueError as e:
        dlt = {"hits": 0, "full_fallbacks": 0, "evictions": 0,
               "rows_recomputed": 0, "rows_total": 0, "entries": 0,
               "capacity": "?", "enabled": "?", "error": str(e)}
    from spgemm_tpu.ops import warmstore  # noqa: PLC0415

    try:
        warm = warmstore.stats()
    except ValueError as e:
        warm = {"plans": 0, "deltas": 0, "bytes": 0, "plan_hits": 0,
                "plan_misses": 0, "delta_hits": 0, "delta_misses": 0,
                "corrupt": 0, "dir": None, "enabled": "?",
                "error": str(e)}
    # deep-profiling digest (obs/profile, jax-free): compile count/wall +
    # prediction-accuracy means ride next to the routing stats, so an
    # estimator drifting off its predictions is visible in the same
    # listing that shows the knobs steering it.  Same degrade-to-error-
    # row contract as the cache/estimator/delta blocks above: an invalid
    # obs knob must not abort the listing
    from spgemm_tpu.obs import profile as obs_profile  # noqa: PLC0415
    if args.as_json:
        import json  # noqa: PLC0415

        try:
            prof_report = obs_profile.report()
        except ValueError as e:
            prof_report = {"error": str(e)}
        print(json.dumps({"knobs": rows, "plan_cache": cache,
                          "estimator": est, "delta": dlt, "warm": warm,
                          "profile": prof_report}, indent=2))
        return 0
    try:
        prof = obs_profile.summary()
    except ValueError as e:
        prof = {"compiles": 0, "compile_s": 0, "est_mean_rel_error": {},
                "delta_mean_dirty_fraction": None, "hbm_peak_bytes": None,
                "error": str(e)}
    name_w = max(len(r["name"]) for r in rows)
    val_w = max(len(r["value"]) for r in rows)
    try:
        for r in rows:
            static = " [jit-static]" if r["jit_static"] else ""
            tuned = (f" [tuned: {len(r['tuned_classes'])} class(es)]"
                     if r.get("tuned_classes") else "")
            print(f"{r['name']:<{name_w}}  {r['value']:>{val_w}}  "
                  f"({r['source']}, default {r['default']}){static}{tuned}")
            if r.get("error"):
                print(f"{'':<{name_w}}  !! {r['error']}")
            print(f"{'':<{name_w}}  {r['doc']}  [{r['module']}]")
        enabled = cache["enabled"]
        print(f"plan cache: hits={cache['hits']} misses={cache['misses']} "
              f"evictions={cache.get('evictions', 0)} "
              f"entries={cache['entries']}/{cache['capacity']} "
              f"enabled={enabled if enabled == '?' else int(enabled)}"
              "  [ops/plancache.py]")
        if cache.get("error"):
            print(f"  !! {cache['error']}")
        e_on = est["enabled"]
        print(f"estimator:  est_hits={est['hits']} "
              f"est_fallbacks={est['fallbacks']} "
              f"enabled={e_on if e_on == '?' else int(e_on)} "
              f"sample_rows={est['sample_rows']} "
              f"confidence>={est['confidence_threshold']}"
              "  [ops/estimate.py]")
        if est.get("error"):
            print(f"  !! {est['error']}")
        d_on = dlt["enabled"]
        print(f"delta:      hits={dlt['hits']} "
              f"full_fallbacks={dlt['full_fallbacks']} "
              f"rows={dlt['rows_recomputed']}/{dlt['rows_total']} "
              f"entries={dlt['entries']}/{dlt['capacity']} "
              f"enabled={d_on if d_on == '?' else int(d_on)}"
              "  [ops/delta.py]")
        if dlt.get("error"):
            print(f"  !! {dlt['error']}")
        w_on = warm["enabled"]
        print(f"warm:       plans={warm['plans']} deltas={warm['deltas']} "
              f"bytes={warm['bytes']} "
              f"hits={warm['plan_hits'] + warm['delta_hits']} "
              f"misses={warm['plan_misses'] + warm['delta_misses']} "
              f"corrupt={warm['corrupt']} "
              f"dir={warm['dir'] or '(unbound)'} "
              f"enabled={w_on if w_on == '?' else int(w_on)}"
              "  [ops/warmstore.py]")
        if warm.get("error"):
            print(f"  !! {warm['error']}")
        print(f"profile:    compiles={prof['compiles']} "
              f"({prof['compile_s']}s) "
              f"est_err={prof['est_mean_rel_error'] or None} "
              f"delta_dirty_frac={prof['delta_mean_dirty_fraction']} "
              f"hbm_peak={prof['hbm_peak_bytes']}"
              "  [obs/profile.py]")
        if prof.get("error"):
            print(f"  !! {prof['error']}")
    except BrokenPipeError:
        # `spgemm_tpu knobs | head` closing the pipe is not an error for a
        # listing; swap in devnull so the interpreter's exit flush of
        # stdout cannot raise again
        import os  # noqa: PLC0415

        os.dup2(os.open(os.devnull, os.O_WRONLY), sys.stdout.fileno())
    return 0


def run_warm(argv: list[str]) -> int:
    """`spgemm_tpu warm [--stat|--clear|--clone SRC_DIR] [--dir PATH]
    [--json]`: inspect, empty, or seed the persistent warm-start store
    (ops/warmstore) -- the on-disk plan/delta entries a restarted
    spgemmd rehydrates from.  The dir resolves like the daemon's:
    --dir, else SPGEMM_TPU_WARM_DIR, else the default socket's
    journal-adjacent <socket>.warm/."""
    p = argparse.ArgumentParser(
        prog="spgemm_tpu warm",
        description="inspect (--stat, default), empty (--clear), or "
                    "seed from a peer (--clone) the persistent "
                    "warm-start store")
    g = p.add_mutually_exclusive_group()
    g.add_argument("--stat", action="store_true",
                   help="entry counts, bytes, budget, and whether a live "
                        "process holds the dir (the default action)")
    g.add_argument("--clear", action="store_true",
                   help="delete every warm entry and the xla compilation-"
                        "cache subdir; refuses while a live process holds "
                        "the dir's lock")
    g.add_argument("--clone", default=None, metavar="SRC_DIR",
                   help="copy a peer's warm entries into the dir (fleet "
                        "seeding: a new backend skips the fleet's known "
                        "first contacts) -- envelope-checked entry by "
                        "entry, schema skew is a counted skip, existing "
                        "local entries are kept; refuses while a live "
                        "process holds the destination's lock")
    p.add_argument("--dir", default=None, metavar="PATH",
                   help="warm dir (default: SPGEMM_TPU_WARM_DIR, else "
                        "<default socket>.warm)")
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)
    from spgemm_tpu.ops import warmstore  # noqa: PLC0415
    from spgemm_tpu.serve import protocol  # noqa: PLC0415
    target = (args.dir or knobs_registry.get("SPGEMM_TPU_WARM_DIR")
              or protocol.default_socket_path() + ".warm")
    if args.clear:
        try:
            removed = warmstore.clear(target)
        except RuntimeError as e:
            print(f"warm: {e}", file=sys.stderr)
            return 1
        print(f"warm: cleared {removed} entries from {target}")
        return 0
    if args.clone:
        try:
            result = warmstore.clone(args.clone, target)
        except RuntimeError as e:
            print(f"warm: {e}", file=sys.stderr)
            return 1
        if args.as_json:
            import json  # noqa: PLC0415

            print(json.dumps(result, indent=2))
        else:
            print(f"warm: cloned {result['copied']} entries "
                  f"{args.clone} -> {target} "
                  f"({result['skipped']} skipped"
                  + (f": {result['skip_reasons']}"
                     if result["skip_reasons"] else "") + ")")
        return 0
    info = warmstore.scan(target)
    if args.as_json:
        import json  # noqa: PLC0415

        print(json.dumps(info, indent=2))
        return 0
    state = "missing" if not info["exists"] else \
        "in use by a live process" if info["locked"] else "idle"
    print(f"warm store {target}: {state}")
    print(f"  plans={info['plans']} deltas={info['deltas']} "
          f"bytes={info['bytes']} budget={info['budget_bytes']}")
    return 0


def run_tune(argv: list[str]) -> int:
    """`spgemm_tpu tune [--status|--clear] [--dir PATH] [--json]`: the
    autotuner's persisted override table (ops/warmstore tune tier,
    spgemm_tpu/tune) -- one row per structure class: rollout state,
    tuned knob vector, measured win, estimator adaptation -- plus the
    `dense-v1:` ladder-vs-dense crossover captures trial legs persisted
    into the shared measurement cache (ops/crossover).  Reads the warm
    dir from DISK (no daemon round-trip, works against a stopped
    daemon), resolving like `warm`: --dir, else SPGEMM_TPU_WARM_DIR,
    else <default socket>.warm."""
    p = argparse.ArgumentParser(
        prog="spgemm_tpu tune",
        description="inspect (--status, default) or empty (--clear) the "
                    "autotuner's persisted per-class knob overrides")
    g = p.add_mutually_exclusive_group()
    g.add_argument("--status", action="store_true",
                   help="override table: class, rollout state, knob "
                        "vector, measured win, estimator adaptation "
                        "(the default action)")
    g.add_argument("--clear", action="store_true",
                   help="delete the tune tier's entries (warm plans and "
                        "deltas stay); refuses while a live process "
                        "holds the dir's lock")
    p.add_argument("--dir", default=None, metavar="PATH",
                   help="warm dir (default: SPGEMM_TPU_WARM_DIR, else "
                        "<default socket>.warm)")
    p.add_argument("--json", action="store_true", dest="as_json")
    args = p.parse_args(argv)
    from spgemm_tpu.ops import crossover, warmstore  # noqa: PLC0415
    from spgemm_tpu.serve import protocol  # noqa: PLC0415
    target = (args.dir or knobs_registry.get("SPGEMM_TPU_WARM_DIR")
              or protocol.default_socket_path() + ".warm")
    if args.clear:
        try:
            removed = warmstore.clear_tunes(target)
        except RuntimeError as e:
            print(f"tune: {e}", file=sys.stderr)
            return 1
        print(f"tune: cleared {removed} override record(s) from {target}")
        return 0
    records = warmstore.scan_tunes(target)
    dense = crossover.entries("dense-v1:")
    if args.as_json:
        import json  # noqa: PLC0415

        print(json.dumps({"dir": target, "overrides": records,
                          "crossover_dense": dense}, indent=2))
        return 0
    print(f"tune store {target}: {len(records)} class record(s)")
    for ck, rec in sorted(records.items()):
        vec = " ".join(f"{k}={v}" for k, v in
                       sorted((rec.get("knobs") or {}).items())) or "-"
        est = " ".join(f"{k}={v}" for k, v in
                       sorted((rec.get("est") or {}).items()))
        win = rec.get("win")
        line = (f"  {ck}  [{rec.get('state', '?')}]  "
                f"win={win if win is not None else '-'}  {vec}")
        if est:
            line += f"  est: {est}"
        print(line)
    if dense:
        print(f"crossover dense-v1 captures: {len(dense)}")
        for key, hit in sorted(dense.items()):
            ladder_s, dense_s = hit.get("ladder_s"), hit.get("dense_s")
            verdict = "dense" if (dense_s is not None
                                  and ladder_s is not None
                                  and dense_s < ladder_s) else "ladder"
            print(f"  {key}  ladder={ladder_s}s dense={dense_s}s "
                  f"-> {verdict}")
    return 0


def _subcommands() -> dict:
    """Name -> handler for the non-folder subcommands.  Each handler
    imports its own machinery only when invoked: `knobs` must never pay
    for (or break on) the serve package, and a plain chain run loads
    neither."""
    def serve(argv: list[str]) -> int:
        from spgemm_tpu.serve import daemon  # noqa: PLC0415
        return daemon.main(argv)

    def submit(argv: list[str]) -> int:
        from spgemm_tpu.serve import client  # noqa: PLC0415
        return client.main_submit(argv)

    def status(argv: list[str]) -> int:
        from spgemm_tpu.serve import client  # noqa: PLC0415
        return client.main_status(argv)

    def metrics(argv: list[str]) -> int:
        from spgemm_tpu.serve import client  # noqa: PLC0415
        return client.main_metrics(argv)

    def trace_dump(argv: list[str]) -> int:
        from spgemm_tpu.serve import client  # noqa: PLC0415
        return client.main_trace_dump(argv)

    def profile(argv: list[str]) -> int:
        from spgemm_tpu.serve import client  # noqa: PLC0415
        return client.main_profile(argv)

    def events(argv: list[str]) -> int:
        from spgemm_tpu.serve import client  # noqa: PLC0415
        return client.main_events(argv)

    def slo(argv: list[str]) -> int:
        from spgemm_tpu.serve import client  # noqa: PLC0415
        return client.main_slo(argv)

    def route(argv: list[str]) -> int:
        from spgemm_tpu.fleet import router  # noqa: PLC0415
        return router.main(argv)

    return {"knobs": run_knobs, "serve": serve,
            "submit": submit, "status": status,
            "metrics": metrics, "trace-dump": trace_dump,
            "profile": profile, "events": events, "slo": slo,
            "warm": run_warm, "tune": run_tune, "route": route}


def run(argv: list[str] | None = None) -> int:
    import os  # noqa: PLC0415 -- only for the subcommand/folder disambiguation

    if argv is None:
        argv = sys.argv[1:]
    # `knobs`/`serve`/`submit`/`status`/`metrics`/`trace-dump` are
    # subcommands UNLESS an INPUT directory of that name exists (the
    # reference contract requires a `size` file) -- a pre-existing
    # `./knobs` matrix folder keeps its old meaning, while an unrelated
    # scratch dir does not swallow the subcommand
    if (argv and argv[0] in ("knobs", "serve", "submit", "status",
                             "metrics", "trace-dump", "profile", "events",
                             "slo", "warm", "tune", "route")
            and not os.path.exists(os.path.join(argv[0], "size"))):
        return _subcommands()[argv[0]](argv[1:])
    parser = build_parser()
    args = parser.parse_args(argv)
    # delta retention (ops/delta) pays off only when the process outlives
    # the submit (spgemmd keeps it warm across jobs); a run-once
    # invocation would pay the per-multiply digest + result-retention
    # cost for a store it throws away at exit -- pin it off unless the
    # operator exported the knob explicitly, restore-scoped so
    # in-process callers (tests) never leak the pin
    restore = knobs_registry.pin_unless_exported("SPGEMM_TPU_DELTA", "0")
    try:
        return _run_chain(args)
    finally:
        restore()


def _run_chain(args) -> int:
    """The reference-contract chain run (see run()); split out so the
    delta-knob pin above can wrap it in one try/finally."""
    if (args.stream or args.out_of_core) and args.shard in ("keys", "inner", "ring"):
        print(f"--shard {args.shard} already keeps chain partials host-"
              "resident; --out-of-core per-round staging does not apply to "
              "the sharded multiplies", file=sys.stderr, flush=True)
    if args.device:
        # env var + in-process config update: the TPU plugin's sitecustomize
        # imports jax at interpreter start and snapshots JAX_PLATFORMS, so
        # the env var alone is too late (utils/backend_probe.pin docs)
        from spgemm_tpu.utils.backend_probe import pin
        pin(args.device)
    elif args.failover:
        # Maximum-survivability mode: the observed accelerator failure mode
        # is a HANG at backend init (utils/backend_probe), which no
        # in-process handler can escape -- probe in a subprocess first and
        # start on CPU if the accelerator is dead.  (stderr only: stdout
        # keeps reference parity -- `multiplying` / `time taken` lines.)
        from spgemm_tpu.utils.backend_probe import failover_to_cpu
        failover_to_cpu("--failover")
    logging.basicConfig(
        level=logging.INFO if args.verbose else logging.WARNING,
        format="%(name)s %(message)s",
    )

    t_start = time.perf_counter()

    # imports after JAX_PLATFORMS is pinned
    from spgemm_tpu.chain import chain_product
    from spgemm_tpu.utils import io_text
    from spgemm_tpu.utils.timers import PhaseTimers, maybe_profile

    if (args.stream or args.out_of_core) and (args.distributed
                                              or args.backend == "oracle"):
        print("--stream/--out-of-core ignored: the oracle backend is "
              "host-only and the distributed path manages residency per "
              "process", file=sys.stderr, flush=True)

    if args.distributed:
        from spgemm_tpu.parallel import multihost

        multihost.init_from_env()
        import jax

        n, k = io_text.read_size(args.folder)
        result = multihost.run_distributed(
            args.folder, k, n,
            loader=lambda s, e: io_text.read_chain(
                args.folder, s, e, k, max_workers=args.threads),
            round_size=args.round_size)
        if jax.process_index() == 0:
            io_text.write_matrix(args.output, result.prune_zeros())
        print(f"time taken {time.perf_counter() - t_start} seconds")
        return 0

    timers = PhaseTimers()
    with maybe_profile(args.profile):
        with timers.phase("load"):
            n, k = io_text.read_size(args.folder)
            matrices = io_text.read_chain(args.folder, 0, n - 1, k,
                                          max_workers=args.threads)

        with timers.phase("chain"):
            if args.backend == "oracle":
                from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
                from spgemm_tpu.utils.semantics import chain_oracle
                blocks = chain_oracle([m.to_dict() for m in matrices], k)
                result = BlockSparseMatrix.from_dict(
                    matrices[0].rows, matrices[-1].cols, k, blocks)
            elif args.shard == "chain":
                if args.stream or args.out_of_core:
                    print("--stream/--out-of-core ignored with --shard chain "
                          "(per-rank partials are device-resident by design)",
                          file=sys.stderr, flush=True)
                from spgemm_tpu.parallel.chainpart import chain_product_on_devices
                kwargs = {"round_size": args.round_size,
                          "backend": args.backend}
                if args.checkpoint_dir:
                    kwargs["checkpoint_dir"] = args.checkpoint_dir
                if args.failover:
                    kwargs["failover"] = True
                if args.ranks > 1:
                    kwargs["num_parts"] = args.ranks  # parity needs exact P
                result = chain_product_on_devices(matrices, **kwargs)
            else:
                multiply, kwargs = None, {"round_size": args.round_size}
                if args.shard == "keys":
                    from spgemm_tpu.parallel.rowshard import spgemm_sharded as multiply
                elif args.shard == "inner":
                    from spgemm_tpu.parallel.innershard import spgemm_inner as multiply
                elif args.shard == "ring":
                    from spgemm_tpu.parallel.ring import spgemm_ring as multiply
                    kwargs.pop("round_size")
                else:
                    kwargs["backend"] = args.backend
                    if args.out_of_core:
                        # host-resident partials AND per-round tile staging:
                        # peak HBM is one round's sub-slabs, so multiplies
                        # need not fit in device memory at all
                        from spgemm_tpu.ops.spgemm import spgemm_outofcore as multiply
                    elif args.stream:
                        # host-resident partials: spgemm (host-to-host) bounds
                        # peak HBM to one multiply's operands + result
                        from spgemm_tpu.ops.spgemm import spgemm as multiply
                if args.checkpoint_dir:
                    kwargs["checkpoint_dir"] = args.checkpoint_dir
                if args.failover:
                    kwargs["failover"] = True
                if args.ranks > 1:
                    from spgemm_tpu.parallel.chainpart import chain_product_partitioned
                    result = chain_product_partitioned(
                        matrices, args.ranks, multiply=multiply, **kwargs)
                else:
                    result = chain_product(matrices, multiply=multiply, **kwargs)

        with timers.phase("prune+write"):
            io_text.write_matrix(args.output, result.prune_zeros())

    timers.log_report()
    from spgemm_tpu.utils.timers import ENGINE
    ENGINE.log_report()  # per-multiply engine phases (symbolic/plan/dispatch/assembly)
    # byte-parity with the reference's only surviving print (sparse_matrix_mult.cu:679)
    print(f"time taken {time.perf_counter() - t_start} seconds")
    return 0


def main() -> None:
    sys.exit(run())


if __name__ == "__main__":
    main()
