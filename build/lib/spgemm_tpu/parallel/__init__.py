"""Distribution layer (L5): device meshes + XLA collectives replace MPI.

The reference's distribution story (SURVEY.md C12-C14) is: range-partition the
matrix chain over MPI ranks, blocking Send/Recv every partial product to rank
0 through host memory, then rank 0 multiplies the partials alone.  The
TPU-native inversion:

  * rowshard  -- shard one SpGEMM's *output tile space* across the mesh with
    shard_map (bit-exact: each output tile is computed whole on one device,
    so the non-associative accumulation order is untouched).
  * innershard -- partition the contraction (inner) dimension and psum partial
    products over ICI (the north-star's "MPI -> psum" mapping; mathematically
    mod-(2^64-1) but NOT bit-order-exact, see module docstring).
  * chainpart -- the reference's chain partition + combine, device-placed
    (exact helper2 parity per sub-chain and for the combine tree).

Everything here runs identically on a real pod and on the
`--xla_force_host_platform_device_count=8` CPU mesh used by tests.
"""
