"""Chain partition + combine: the reference's MPI distribution, re-done (C12, C14).

The reference range-partitions the chain over P ranks (sparse_matrix_mult.cu:
438-456): rank r owns [r*q, (r+1)*q - 1] with q = N/P (integer), the last rank
takes the remainder, and if q == 0 rank 0 does everything alone (:612-666).
Each rank reduces its sub-chain with helper2, partials are gathered to rank 0
(:460-556) and rank 0 runs helper2 over the P partials (:557-571).

Here the partition arithmetic is replicated exactly -- including the q == 0
degenerate branch -- because with non-associative arithmetic (SURVEY.md
section 2.9) `mpirun -np P` can produce different bits than P=1, and parity
means matching the reference *at the same P*.  The gather disappears: partial
products are just arrays; the combine is the same pairwise tree (a log-P
reduction, which the reference's report claimed but its code never had --
SURVEY.md section 0 caveat 1).
"""

from __future__ import annotations

from spgemm_tpu.chain import _to_host, chain_product
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix


def partition_chain(n: int, p: int) -> list[tuple[int, int] | None]:
    """Rank r -> inclusive (start, end) into the chain, or None for idle ranks.

    Exact replica of sparse_matrix_mult.cu:438-456 (+ :612 degenerate case).
    """
    q = n // p
    if q == 0:
        return [(0, n - 1)] + [None] * (p - 1)
    parts: list[tuple[int, int] | None] = []
    for r in range(p):
        start = r * q
        end = (r + 1) * q - 1 if r < p - 1 else n - 1
        parts.append((start, end))
    return parts


def chain_product_partitioned(matrices: list[BlockSparseMatrix], num_parts: int,
                              multiply=None, checkpoint_dir: str | None = None,
                              **kwargs) -> BlockSparseMatrix:
    """Chain product with the reference's P-rank partition/combine semantics.

    Equivalent to `mpirun -np num_parts ./a4`: each part reduces its sub-chain
    with the helper2 tree, then the partials are reduced with the same tree
    (the reference's rank-0 combine, :571).  With checkpoint_dir, each rank's
    sub-chain and the combine get their own snapshot subdirectory."""
    import os

    if num_parts < 1:
        raise ValueError("num_parts must be >= 1")

    def sub(name):
        return os.path.join(checkpoint_dir, name) if checkpoint_dir else None

    # With the default device-resident multiply, each part's partial product
    # stays in HBM between the per-part reduction and the combine tree (the
    # reference instead serializes partials through MPI to rank 0, :460-556).
    keep_device = kwargs.pop("keep_device", False)
    keep = {"keep_device": True} if multiply is None else {}
    parts = partition_chain(len(matrices), num_parts)
    partials = [
        chain_product(matrices[start : end + 1], multiply=multiply,
                      checkpoint_dir=sub(f"rank{idx}"), **keep, **kwargs)
        for idx, part in enumerate(parts) if part is not None
        for start, end in [part]
    ]
    if len(partials) == 1:
        return partials[0] if keep_device else _to_host(partials[0])
    return chain_product(partials, multiply=multiply, keep_device=keep_device,
                         checkpoint_dir=sub("combine"), **kwargs)


def chain_product_on_devices(matrices: list[BlockSparseMatrix],
                             devices=None, num_parts: int | None = None,
                             **kwargs) -> BlockSparseMatrix:
    """The reference's MPI data parallelism actually EXECUTING in parallel:
    one device per rank, concurrent sub-chain reductions.

    `chain_product_partitioned` replicates `mpirun -np P` *semantics* on one
    device; here each rank's sub-chain is placed on its own mesh device
    (committed placement, so jit runs each rank's multiplies where its tiles
    live) and JAX's async dispatch overlaps the per-rank reductions across
    the mesh -- the TPU-native version of P MPI processes computing
    concurrently (sparse_matrix_mult.cu:438-456).  Partials then converge to
    devices[0] and reduce with the same helper2 combine tree as rank 0
    (:557-571), so the result is bit-identical to
    `chain_product_partitioned(matrices, P)` at the same P.

    num_parts: P (default len(devices); parity requires matching the
    reference's P, so an explicit P cycles ranks over the devices).  Idle
    ranks (N < P) get no device work, mirroring the reference's :612
    degenerate branch.  NOTE: checkpoint_dir serializes the ranks -- each
    pass snapshot is a blocking D2H, so rank idx finishes before rank idx+1
    dispatches; recoverability costs the overlap.
    """
    import os

    import jax

    from spgemm_tpu.ops.device import DeviceBlockMatrix
    from spgemm_tpu.ops.spgemm import spgemm_device

    if devices is None:
        devices = jax.devices()
    p = num_parts or len(devices)
    checkpoint_dir = kwargs.pop("checkpoint_dir", None)

    def sub(name):
        return os.path.join(checkpoint_dir, name) if checkpoint_dir else None

    parts = partition_chain(len(matrices), p)
    partials = []
    for idx, part in enumerate(parts):
        if part is None:
            continue
        start, end = part
        dev = devices[idx % len(devices)]
        dmats = [DeviceBlockMatrix.from_host(m, device=dev)
                 for m in matrices[start:end + 1]]
        # async dispatch: rank idx's whole reduction enqueues on its device
        # before rank idx+1's begins -- the ranks execute concurrently
        # (unless checkpointing, see docstring)
        partials.append(chain_product(dmats, multiply=spgemm_device,
                                      keep_device=True,
                                      checkpoint_dir=sub(f"rank{idx}"),
                                      **kwargs))
    if len(partials) == 1:
        return _to_host(partials[0])
    if any(not isinstance(d, DeviceBlockMatrix) for d in partials):
        # a rank failed over to the host oracle (failover=True): finish the
        # combine tree on the host too -- the device cannot be trusted
        from spgemm_tpu.chain import oracle_multiply  # noqa: PLC0415

        return chain_product([_to_host(d) for d in partials],
                             multiply=oracle_multiply,
                             checkpoint_dir=sub("combine"))
    # gather: partial slabs converge on devices[0] (the rank-0 combine);
    # coords stay host-side, only tile planes move over ICI/PCIe
    gathered = [
        DeviceBlockMatrix(rows=d.rows, cols=d.cols, k=d.k, coords=d.coords,
                          hi=jax.device_put(d.hi, devices[0]),
                          lo=jax.device_put(d.lo, devices[0]),
                          val_bound=d.val_bound)
        for d in partials
    ]
    return chain_product(gathered, multiply=spgemm_device, keep_device=False,
                         checkpoint_dir=sub("combine"), **kwargs)
