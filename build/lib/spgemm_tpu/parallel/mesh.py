"""Mesh construction helpers."""

from __future__ import annotations

import jax
from jax.sharding import Mesh


def default_mesh(n_devices: int | None = None, axis: str = "keys") -> Mesh:
    """1-D mesh over the first n visible devices (all by default)."""
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return jax.make_mesh((len(devs),), (axis,), devices=devs)
