// Native text-format I/O for tpu-spgemm (the reference's L4 equivalent).
//
// The reference parses matrix files with formatted `ifstream >>` reads, one
// OpenMP task per file over 16 threads (sparse_matrix_mult.cu:334-384), and
// writes the result with ofstream << (:595-608).  This library replaces the
// per-element formatted I/O with a single-pass byte-level tokenizer and a
// single-buffer formatter -- typically 20-50x faster per file -- and exposes a
// C ABI consumed via ctypes.  Cross-file parallelism comes from the Python
// thread pool: these functions release the GIL for their whole duration, so
// the pool achieves real concurrency (the task-per-file pattern, without the
// hardcoded 16 threads).
//
// Build: make native   (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>

extern "C" {

// ---------------------------------------------------------------------------
// Parsing
// ---------------------------------------------------------------------------

static inline const char *skip_ws(const char *p, const char *end) {
  while (p < end && (*p == ' ' || *p == '\n' || *p == '\r' || *p == '\t' ||
                     *p == '\f' || *p == '\v'))
    ++p;
  return p;
}

// Parse one unsigned decimal token.  Valid inputs are < 2^64 so the
// accumulate cannot overflow on well-formed files.
static inline const char *parse_u64(const char *p, const char *end,
                                    uint64_t *out, int *ok) {
  p = skip_ws(p, end);
  if (p >= end || *p < '0' || *p > '9') {
    *ok = 0;
    return p;
  }
  uint64_t v = 0;
  while (p < end && *p >= '0' && *p <= '9') {
    v = v * 10u + (uint64_t)(*p - '0');
    ++p;
  }
  *out = v;
  *ok = 1;
  return p;
}

// Parse a whole matrix file.
//   header_out: [rows, cols, blocks]
//   coords_out: malloc'd int64[blocks * 2]
//   tiles_out : malloc'd uint64[blocks * k * k]
// Returns 0 on success; caller frees with smm_free.
//   -1 open failure, -2 read failure, -3 malformed/truncated, -4 alloc failure
int smm_parse_matrix(const char *path, int64_t k, int64_t header_out[3],
                     int64_t **coords_out, uint64_t **tiles_out) {
  FILE *f = fopen(path, "rb");
  if (!f) return -1;
  fseek(f, 0, SEEK_END);
  long sz = ftell(f);
  fseek(f, 0, SEEK_SET);
  char *buf = (char *)malloc((size_t)sz);
  if (!buf) {
    fclose(f);
    return -4;
  }
  if (sz > 0 && fread(buf, 1, (size_t)sz, f) != (size_t)sz) {
    free(buf);
    fclose(f);
    return -2;
  }
  fclose(f);

  const char *p = buf, *end = buf + sz;
  int ok = 1;
  uint64_t rows, cols, blocks;
  p = parse_u64(p, end, &rows, &ok);
  if (ok) p = parse_u64(p, end, &cols, &ok);
  if (ok) p = parse_u64(p, end, &blocks, &ok);
  if (!ok) {
    free(buf);
    return -3;
  }

  int64_t *coords = (int64_t *)malloc(sizeof(int64_t) * 2u * blocks);
  uint64_t *tiles =
      (uint64_t *)malloc(sizeof(uint64_t) * (size_t)blocks * k * k);
  if ((blocks && (!coords || !tiles))) {
    free(coords);
    free(tiles);
    free(buf);
    return -4;
  }

  const uint64_t kk = (uint64_t)k * (uint64_t)k;
  for (uint64_t b = 0; b < blocks && ok; ++b) {
    uint64_t r, c;
    p = parse_u64(p, end, &r, &ok);
    if (ok) p = parse_u64(p, end, &c, &ok);
    coords[2 * b] = (int64_t)r;
    coords[2 * b + 1] = (int64_t)c;
    uint64_t *t = tiles + b * kk;
    for (uint64_t i = 0; i < kk && ok; ++i) p = parse_u64(p, end, &t[i], &ok);
  }
  free(buf);
  if (!ok) {
    free(coords);
    free(tiles);
    return -3;
  }
  header_out[0] = (int64_t)rows;
  header_out[1] = (int64_t)cols;
  header_out[2] = (int64_t)blocks;
  *coords_out = coords;
  *tiles_out = tiles;
  return 0;
}

void smm_free(void *p) { free(p); }

// ---------------------------------------------------------------------------
// Writing (byte-identical to the reference writer, sparse_matrix_mult.cu:
// 595-608: "R C\n", "blocks\n", per tile "r c\n" + k space-joined rows with
// no trailing space)
// ---------------------------------------------------------------------------

static inline char *fmt_u64(char *dst, uint64_t v) {
  char tmp[20];
  int n = 0;
  do {
    tmp[n++] = (char)('0' + (v % 10u));
    v /= 10u;
  } while (v);
  while (n) *dst++ = tmp[--n];
  return dst;
}

int smm_write_matrix(const char *path, int64_t rows, int64_t cols, int64_t k,
                     int64_t nnzb, const int64_t *coords,
                     const uint64_t *tiles) {
  // worst case 21 bytes per number (20 digits + separator)
  size_t cap = 64 + (size_t)nnzb * (42 + (size_t)k * k * 21);
  char *buf = (char *)malloc(cap);
  if (!buf) return -4;
  char *p = buf;
  p = fmt_u64(p, (uint64_t)rows);
  *p++ = ' ';
  p = fmt_u64(p, (uint64_t)cols);
  *p++ = '\n';
  p = fmt_u64(p, (uint64_t)nnzb);
  *p++ = '\n';
  const uint64_t kk = (uint64_t)k * (uint64_t)k;
  for (int64_t b = 0; b < nnzb; ++b) {
    p = fmt_u64(p, (uint64_t)coords[2 * b]);
    *p++ = ' ';
    p = fmt_u64(p, (uint64_t)coords[2 * b + 1]);
    *p++ = '\n';
    const uint64_t *t = tiles + (uint64_t)b * kk;
    for (int64_t r = 0; r < k; ++r) {
      for (int64_t c = 0; c < k; ++c) {
        if (c) *p++ = ' ';
        p = fmt_u64(p, t[r * k + c]);
      }
      *p++ = '\n';
    }
  }
  FILE *f = fopen(path, "wb");
  if (!f) {
    free(buf);
    return -1;
  }
  size_t len = (size_t)(p - buf);
  int rc = fwrite(buf, 1, len, f) == len ? 0 : -2;
  fclose(f);
  free(buf);
  return rc;
}

}  // extern "C"
