// Native symbolic phase for tpu-spgemm (the reference's C5 equivalent).
//
// The reference's symbolic join is a hash-map build + probe on the host CPU
// (sparse_matrix_mult.cu:141-156) -- its "CPU hot loop #1" (SURVEY.md
// section 3.2).  Here the join over sorted block coordinates is a
// searchsorted range per A-block followed by a stable LSD radix sort of the
// fused output keys, all in one pass-oriented C++ translation unit: the
// framework's host runtime is native where the reference's is, and the
// Python/numpy implementation (ops/symbolic.py) remains as the
// always-available fallback and cross-check.
//
// Contract (mirrors ops/symbolic.symbolic_join exactly):
//   inputs : a_coords (na, 2) int64 lex-sorted; b_coords (nb, 2) lex-sorted
//   outputs: keys (nk, 2) int64 lex-sorted, pair_ptr (nk+1) int64,
//            pair_a / pair_b (total) int32 -- per key in ascending inner
//            block-coordinate order (the std::map traversal order parity
//            depends on, SURVEY.md section 2.9).
//
// Build: make native  (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstdlib>
#include <cstring>

extern "C" {

void smm_sym_free(void *p) { free(p); }

// Stable LSD radix sort of (key, payload-index) by 64-bit key, 16-bit digits.
// idx is permuted; keys_in is read-only.  Skips passes whose digits are
// constant across the live key range (common: high words are mostly zero).
static void radix_sort_idx(const uint64_t *keys, int64_t *idx, int64_t n,
                           int64_t *scratch) {
  if (n <= 1) return;
  uint64_t all_or = 0, all_and = ~0ull;
  for (int64_t i = 0; i < n; ++i) {
    all_or |= keys[i];
    all_and &= keys[i];
  }
  for (int pass = 0; pass < 4; ++pass) {
    const int shift = pass * 16;
    const uint64_t varying = (all_or ^ all_and) >> shift & 0xFFFF;
    if (!varying) continue;  // digit constant across all keys: stable no-op
    int64_t hist[65536];
    memset(hist, 0, sizeof(hist));
    for (int64_t i = 0; i < n; ++i)
      ++hist[(keys[idx[i]] >> shift) & 0xFFFF];
    int64_t sum = 0;
    for (int d = 0; d < 65536; ++d) {
      int64_t c = hist[d];
      hist[d] = sum;
      sum += c;
    }
    for (int64_t i = 0; i < n; ++i)
      scratch[hist[(keys[idx[i]] >> shift) & 0xFFFF]++] = idx[i];
    memcpy(idx, scratch, (size_t)n * sizeof(int64_t));
  }
}

// Lower/upper bound over b's sorted row column.
static int64_t lower_bound_row(const int64_t *b_rows, int64_t nb, int64_t v) {
  int64_t lo = 0, hi = nb;
  while (lo < hi) {
    int64_t mid = (lo + hi) >> 1;
    if (b_rows[mid] < v) lo = mid + 1; else hi = mid;
  }
  return lo;
}
static int64_t upper_bound_row(const int64_t *b_rows, int64_t nb, int64_t v) {
  int64_t lo = 0, hi = nb;
  while (lo < hi) {
    int64_t mid = (lo + hi) >> 1;
    if (b_rows[mid] <= v) lo = mid + 1; else hi = mid;
  }
  return lo;
}

// Returns 0 on success, -4 on allocation failure.
// Outputs are malloc'd; caller frees each with smm_sym_free.
int smm_symbolic_join(const int64_t *a_coords, int64_t na,
                      const int64_t *b_coords, int64_t nb,
                      int64_t **keys_out, int64_t *num_keys_out,
                      int64_t **pair_ptr_out,
                      int32_t **pair_a_out, int32_t **pair_b_out,
                      int64_t *total_out) {
  *keys_out = nullptr;
  *pair_ptr_out = nullptr;
  *pair_a_out = nullptr;
  *pair_b_out = nullptr;
  *num_keys_out = 0;
  *total_out = 0;
  if (na == 0 || nb == 0) {
    *pair_ptr_out = (int64_t *)calloc(1, sizeof(int64_t));
    return *pair_ptr_out ? 0 : -4;
  }

  // b rows as a contiguous array for binary search, and the key span
  int64_t *b_rows = (int64_t *)malloc((size_t)nb * sizeof(int64_t));
  if (!b_rows) return -4;
  int64_t max_c = 0;
  for (int64_t i = 0; i < nb; ++i) {
    b_rows[i] = b_coords[2 * i];
    if (b_coords[2 * i + 1] > max_c) max_c = b_coords[2 * i + 1];
  }
  const uint64_t span = (uint64_t)max_c + 1;

  // per-A-block matching B range; total pair count
  int64_t *lo = (int64_t *)malloc((size_t)na * sizeof(int64_t));
  int64_t *hi = (int64_t *)malloc((size_t)na * sizeof(int64_t));
  if (!lo || !hi) { free(b_rows); free(lo); free(hi); return -4; }
  int64_t total = 0;
  for (int64_t i = 0; i < na; ++i) {
    const int64_t col = a_coords[2 * i + 1];
    lo[i] = lower_bound_row(b_rows, nb, col);
    hi[i] = upper_bound_row(b_rows, nb, col);
    total += hi[i] - lo[i];
  }
  free(b_rows);
  if (total == 0) {
    free(lo); free(hi);
    *pair_ptr_out = (int64_t *)calloc(1, sizeof(int64_t));
    return *pair_ptr_out ? 0 : -4;
  }

  // pair stream in A-traversal order (stable-sort input order)
  uint64_t *fused = (uint64_t *)malloc((size_t)total * sizeof(uint64_t));
  int32_t *sa = (int32_t *)malloc((size_t)total * sizeof(int32_t));
  int32_t *sb = (int32_t *)malloc((size_t)total * sizeof(int32_t));
  int64_t *idx = (int64_t *)malloc((size_t)total * sizeof(int64_t));
  int64_t *scratch = (int64_t *)malloc((size_t)total * sizeof(int64_t));
  if (!fused || !sa || !sb || !idx || !scratch) {
    free(lo); free(hi); free(fused); free(sa); free(sb); free(idx);
    free(scratch);
    return -4;
  }
  int64_t w = 0;
  for (int64_t i = 0; i < na; ++i) {
    const uint64_t row_part = (uint64_t)a_coords[2 * i] * span;
    for (int64_t j = lo[i]; j < hi[i]; ++j, ++w) {
      fused[w] = row_part + (uint64_t)b_coords[2 * j + 1];
      sa[w] = (int32_t)i;
      sb[w] = (int32_t)j;
    }
  }
  free(lo); free(hi);
  for (int64_t i = 0; i < total; ++i) idx[i] = i;
  radix_sort_idx(fused, idx, total, scratch);
  free(scratch);

  // count distinct keys, emit outputs in sorted order
  int64_t nk = 0;
  for (int64_t i = 0; i < total; ++i)
    if (i == 0 || fused[idx[i]] != fused[idx[i - 1]]) ++nk;

  int64_t *keys = (int64_t *)malloc((size_t)nk * 2 * sizeof(int64_t));
  int64_t *ptr = (int64_t *)malloc(((size_t)nk + 1) * sizeof(int64_t));
  int32_t *pa = (int32_t *)malloc((size_t)total * sizeof(int32_t));
  int32_t *pb = (int32_t *)malloc((size_t)total * sizeof(int32_t));
  if (!keys || !ptr || !pa || !pb) {
    free(fused); free(sa); free(sb); free(idx);
    free(keys); free(ptr); free(pa); free(pb);
    return -4;
  }
  int64_t kidx = -1;
  for (int64_t i = 0; i < total; ++i) {
    const int64_t src = idx[i];
    if (i == 0 || fused[src] != fused[idx[i - 1]]) {
      ++kidx;
      keys[2 * kidx] = (int64_t)(fused[src] / span);
      keys[2 * kidx + 1] = (int64_t)(fused[src] % span);
      ptr[kidx] = i;
    }
    pa[i] = sa[src];
    pb[i] = sb[src];
  }
  ptr[nk] = total;
  free(fused); free(sa); free(sb); free(idx);

  *keys_out = keys;
  *num_keys_out = nk;
  *pair_ptr_out = ptr;
  *pair_a_out = pa;
  *pair_b_out = pb;
  *total_out = total;
  return 0;
}

}  // extern "C"
