"""Device-side compute: u64 limb arithmetic, SpGEMM symbolic/numeric phases, Pallas kernels."""
