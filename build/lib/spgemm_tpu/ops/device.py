"""Device-resident block-sparse matrix: tiles live in HBM between multiplies.

The reference round-trips every partial product through host maps — pack,
H2D, kernel, D2H, unpack (sparse_matrix_mult.cu:189-269) — and its report
attributes 27% of total time to those copies (BASELINE.md phase table).  The
TPU-native design keeps tile data in HBM for the *entire* chain product:
only block coordinates (tiny) live on host, because the symbolic phase
(ops/symbolic.py) is host-side index arithmetic.  Tile values cross the
PCIe/tunnel boundary exactly twice per job: input load and final write.

Representation: (hi, lo) uint32 planes of shape (nnzb + 1, k, k) with an
all-zero sentinel tile at index nnzb — the padding target the round planner
(ops/symbolic.plan_rounds) points dead pair slots at.
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from spgemm_tpu.ops import u64
from spgemm_tpu.utils.blockcsr import BlockSparseMatrix


@dataclass
class DeviceBlockMatrix:
    """Block-sparse matrix with host coords and device-resident tile planes.

    rows, cols : element dimensions (carried through, like the reference's).
    k          : tile edge.
    coords     : (nnzb, 2) int64 on HOST, sorted lexicographically.
    hi, lo     : (nnzb + 1, k, k) uint32 on DEVICE; sentinel zero tile last.
    """

    rows: int
    cols: int
    k: int
    coords: np.ndarray
    hi: jax.Array
    lo: jax.Array
    # cached host materialization: repeated to_host (e.g. a partial carried
    # unchanged across checkpointed chain passes) must not re-cross the
    # device boundary
    _host: "BlockSparseMatrix | None" = None
    # inclusive upper bound on element values, when known (python int --
    # may exceed 2^64 for propagated bounds).  None = unknown.  Drives the
    # hybrid backend's proof that MXU field mode is bit-exact here
    # (ops/mxu_spgemm.safe_exact_bound).
    val_bound: "int | None" = None

    @property
    def nnzb(self) -> int:
        return len(self.coords)

    @classmethod
    def from_host(cls, m: BlockSparseMatrix, device=None) -> "DeviceBlockMatrix":
        """Upload a host matrix: one H2D of the (hi, lo) planes + sentinel.

        device: explicit placement (e.g. per-rank devices in
        parallel/chainpart.chain_product_on_devices); default placement
        otherwise."""
        from spgemm_tpu.ops.spgemm import pack_tiles  # noqa: PLC0415

        hi, lo = pack_tiles(m, device=device)
        bound = int(m.tiles.max()) if m.nnzb else 0
        return cls(rows=m.rows, cols=m.cols, k=m.k, coords=m.coords,
                   hi=hi, lo=lo, _host=m, val_bound=bound)

    @classmethod
    def empty(cls, rows: int, cols: int, k: int) -> "DeviceBlockMatrix":
        zero = jnp.zeros((1, k, k), jnp.uint32)
        return cls(rows=rows, cols=cols, k=k,
                   coords=np.zeros((0, 2), np.int64), hi=zero, lo=zero,
                   val_bound=0)

    def to_host(self) -> BlockSparseMatrix:
        """Fetch tiles to host (the one D2H of the pipeline) and reassemble."""
        if self._host is None:
            hi = np.asarray(self.hi[: self.nnzb])
            lo = np.asarray(self.lo[: self.nnzb])
            self._host = BlockSparseMatrix(
                rows=self.rows, cols=self.cols, k=self.k,
                coords=self.coords, tiles=u64.hilo_to_u64(hi, lo))
        return self._host

    def block_until_ready(self) -> "DeviceBlockMatrix":
        """True completion barrier.

        Some transports (the axon tunnel in this environment) acknowledge
        jax.block_until_ready at enqueue time, before the device has executed
        — so timing code must force a value fetch.  An 8-byte digest transfer
        is the cheapest honest barrier.
        """
        _ = int(jnp.sum(self.hi[-1]) + jnp.sum(self.lo[-1])
                + self.hi.ravel()[0] + self.lo.ravel()[0])
        return self


def ensure_device(m) -> DeviceBlockMatrix:
    return DeviceBlockMatrix.from_host(m) if isinstance(m, BlockSparseMatrix) else m
