"""Model-level consumers of the block-sparse machinery.

The reference has no models (it is an SpGEMM program), but the north star's
benchmark configs (BASELINE.json) include a block-sparse Transformer FFN
(d=4096, 90% sparse, 8 chips) -- the float/MXU counterpart of the exact-u64
parity path.  models/ holds that: block-sparse layers whose tiles feed the
MXU in bf16/f32, sharded dp x tp x sp over a mesh.
"""
