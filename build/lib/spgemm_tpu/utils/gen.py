"""Synthetic block-sparse matrix generators for tests and benchmarks.

Zero-egress environment: SuiteSparse matrices (cage12, nd24k, webbase-1M)
cannot be downloaded, so benchmark configs are synthesized with matching
structural statistics (see bench/configs.py); correctness tests use these
generators against the numpy oracle.
"""

from __future__ import annotations

import numpy as np

from spgemm_tpu.utils.blockcsr import BlockSparseMatrix

U64MAX = 0xFFFFFFFFFFFFFFFF

# Values that exercise every wrap/mod corner of SURVEY.md section 2.9.
ADVERSARIAL_VALUES = np.array(
    [0, 1, 2, U64MAX, U64MAX - 1, U64MAX - 2,
     1 << 32, (1 << 32) - 1, (1 << 32) + 1,
     1 << 63, (1 << 63) - 1, (1 << 63) + 1,
     0xDEADBEEFCAFEBABE, 0xFFFFFFFF00000001],
    dtype=np.uint64,
)


def random_values(shape, rng: np.random.Generator, dist: str = "full") -> np.ndarray:
    """uint64 values: 'full' (uniform u64 -- wrap cases fire constantly),
    'small' (< 2^16 -- products never wrap), 'adversarial' (corner values)."""
    if dist == "full":
        return rng.integers(0, 1 << 64, size=shape, dtype=np.uint64)
    if dist == "small":
        return rng.integers(0, 1 << 16, size=shape, dtype=np.uint64)
    if dist == "adversarial":
        idx = rng.integers(0, len(ADVERSARIAL_VALUES), size=shape)
        return ADVERSARIAL_VALUES[idx]
    raise ValueError(dist)


def random_block_sparse(block_rows: int, block_cols: int, k: int,
                        density: float, rng: np.random.Generator,
                        dist: str = "full") -> BlockSparseMatrix:
    """Uniform-random block structure at the given block density."""
    nnzb = max(1, int(round(block_rows * block_cols * density)))
    nnzb = min(nnzb, block_rows * block_cols)
    flat = rng.choice(block_rows * block_cols, size=nnzb, replace=False)
    coords = np.stack([flat // block_cols, flat % block_cols], axis=1).astype(np.int64)
    tiles = random_values((nnzb, k, k), rng, dist)
    return BlockSparseMatrix.from_blocks(block_rows * k, block_cols * k, k, coords, tiles)


def random_chain(n: int, block_dim: int, k: int, density: float,
                 rng: np.random.Generator, dist: str = "full") -> list[BlockSparseMatrix]:
    """A multiplication-compatible chain of n square block-sparse matrices."""
    return [random_block_sparse(block_dim, block_dim, k, density, rng, dist)
            for _ in range(n)]


def banded_block_sparse(block_dim: int, k: int, bandwidth: int,
                        rng: np.random.Generator, dist: str = "full") -> BlockSparseMatrix:
    """Banded structure (nd24k-like: dense band, high SpGEMM fill-in)."""
    coords = []
    for r in range(block_dim):
        for c in range(max(0, r - bandwidth), min(block_dim, r + bandwidth + 1)):
            coords.append((r, c))
    coords = np.array(coords, dtype=np.int64)
    tiles = random_values((len(coords), k, k), rng, dist)
    return BlockSparseMatrix.from_blocks(block_dim * k, block_dim * k, k, coords, tiles)


def powerlaw_block_sparse(block_dim: int, k: int, avg_per_row: float,
                          rng: np.random.Generator, dist: str = "full",
                          alpha: float = 1.5) -> BlockSparseMatrix:
    """Power-law row degrees (webbase-like: a few very heavy rows)."""
    degrees = np.minimum(
        rng.zipf(alpha, size=block_dim), block_dim).astype(np.int64)
    scale = avg_per_row / max(degrees.mean(), 1e-9)
    degrees = np.maximum(1, (degrees * scale).astype(np.int64))
    degrees = np.minimum(degrees, block_dim)
    coords = []
    for r in range(block_dim):
        cols = rng.choice(block_dim, size=degrees[r], replace=False)
        for c in cols:
            coords.append((r, int(c)))
    coords = np.array(coords, dtype=np.int64)
    tiles = random_values((len(coords), k, k), rng, dist)
    return BlockSparseMatrix.from_blocks(block_dim * k, block_dim * k, k, coords, tiles)
