"""Block-sparse matrix container: the TPU-native answer to the reference's C1.

The reference stores a matrix as `std::map<(int,int) -> vector<vector<uint64>>>`
(sparse_matrix_mult.cu:26-32).  A map of heap tiles is hostile to any
accelerator; here a matrix is three flat arrays -- sorted block coordinates plus
one dense (nnzb, k, k) tile slab -- i.e. block-COO whose sorted order makes it
block-CSR on demand.  The tile slab ships to device HBM as two uint32 planes
(hi, lo) since TPUs have no 64-bit integers (see ops/u64.py).

Invariants:
  * coords are lexicographically sorted by (row, col) -- the std::map iteration
    order every downstream phase depends on (SURVEY.md section 2.9 ordering).
  * duplicate coordinates: last occurrence wins (std::map operator[] overwrite,
    sparse_matrix_mult.cu:383).
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np


@dataclass
class BlockSparseMatrix:
    """A block-sparse matrix of dense k x k uint64 tiles.

    rows, cols : element dimensions (as read from the file header -- opaque,
                 only carried through; the reference never validates them).
    k          : tile edge.
    coords     : (nnzb, 2) int64, sorted lexicographically by (row, col).
    tiles      : (nnzb, k, k) uint64, aligned with coords.
    """

    rows: int
    cols: int
    k: int
    coords: np.ndarray = field(default_factory=lambda: np.zeros((0, 2), np.int64))
    tiles: np.ndarray = field(default_factory=lambda: np.zeros((0, 0, 0), np.uint64))

    def __post_init__(self):
        self.coords = np.asarray(self.coords, dtype=np.int64).reshape(-1, 2)
        self.tiles = np.asarray(self.tiles, dtype=np.uint64)
        if self.tiles.size == 0:
            self.tiles = self.tiles.reshape(0, self.k, self.k)

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_blocks(cls, rows: int, cols: int, k: int, coords, tiles,
                    assume_sorted: bool = False) -> "BlockSparseMatrix":
        """Build from parallel coord/tile arrays, sorting and deduplicating."""
        coords = np.asarray(coords, dtype=np.int64).reshape(-1, 2)
        tiles = np.asarray(tiles, dtype=np.uint64).reshape(-1, k, k)
        if not assume_sorted and len(coords) > 0:
            order = np.lexsort((coords[:, 1], coords[:, 0]))  # stable: file order kept
            coords, tiles = coords[order], tiles[order]
            # last occurrence of a duplicate key wins (std::map overwrite)
            if len(coords) > 1:
                same = np.all(coords[1:] == coords[:-1], axis=1)
                keep = np.append(~same, True)
                coords, tiles = coords[keep], tiles[keep]
        return cls(rows=rows, cols=cols, k=k, coords=coords, tiles=tiles)

    @classmethod
    def from_dict(cls, rows: int, cols: int, k: int, blocks: dict) -> "BlockSparseMatrix":
        """From {(r, c): (k,k) array} -- the oracle's working representation."""
        if not blocks:
            return cls(rows=rows, cols=cols, k=k)
        keys = sorted(blocks.keys())
        coords = np.array(keys, dtype=np.int64)
        tiles = np.stack([np.asarray(blocks[key], dtype=np.uint64) for key in keys])
        return cls(rows=rows, cols=cols, k=k, coords=coords, tiles=tiles)

    # -- views --------------------------------------------------------------

    @property
    def nnzb(self) -> int:
        return len(self.coords)

    @property
    def nnz(self) -> int:
        """Count of nonzero *elements* (BASELINE.json parity metric)."""
        return int(np.count_nonzero(self.tiles))

    def to_dict(self) -> dict:
        return {(int(r), int(c)): self.tiles[i] for i, (r, c) in enumerate(self.coords)}

    # -- transforms ---------------------------------------------------------

    def prune_zeros(self) -> "BlockSparseMatrix":
        """Drop all-zero tiles -- the reference's C15 (sparse_matrix_mult.cu:577-592),
        done vectorized instead of map-erase-during-iteration (which is UB there)."""
        if self.nnzb == 0:
            return self
        keep = np.any(self.tiles != 0, axis=(1, 2))
        return BlockSparseMatrix(rows=self.rows, cols=self.cols, k=self.k,
                                 coords=self.coords[keep], tiles=self.tiles[keep])

    def __eq__(self, other) -> bool:
        if not isinstance(other, BlockSparseMatrix):
            return NotImplemented
        return (self.rows == other.rows and self.cols == other.cols
                and self.k == other.k
                and self.coords.shape == other.coords.shape
                and bool(np.all(self.coords == other.coords))
                and bool(np.all(self.tiles == other.tiles)))
