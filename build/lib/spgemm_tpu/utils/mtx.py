"""MatrixMarket (.mtx) -> block text directory converter (north-star tooling).

BASELINE.json's benchmark configs are SuiteSparse matrices (cage12, nd24k,
webbase-1M); this converter tiles a MatrixMarket coordinate file into dense
k x k uint64 blocks and emits a reference-format input directory (size +
matrix1..matrixN).  In this zero-egress environment the actual downloads are
unavailable -- utils/gen.py synthesizes structure-matched stand-ins -- but the
converter is the supported path on any machine that has the .mtx files.

Value mapping (the reference semantics are integer mod 2^64-1; SuiteSparse
values are real): 'pattern' maps every nonzero to 1, 'scale' multiplies by a
fixed factor and rounds into uint64 (documented, deterministic).
"""

from __future__ import annotations

import gzip

import numpy as np

from spgemm_tpu.utils.blockcsr import BlockSparseMatrix


def read_mtx(path: str, value_map: str = "pattern", scale: float = 1000.0) -> tuple:
    """Parse a MatrixMarket coordinate file -> (rows, cols, r, c, v) element COO
    with symmetric storage already mirrored."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        header = f.readline()
        if not header.startswith("%%MatrixMarket"):
            raise ValueError(f"{path!r}: not a MatrixMarket file")
        toks = header.split()
        if toks[2] != "coordinate":
            raise ValueError(f"{path!r}: only coordinate format supported")
        field = toks[3]       # real | integer | pattern
        symmetry = toks[4]    # general | symmetric | skew-symmetric
        line = f.readline()
        while line.startswith("%"):
            line = f.readline()
        rows, cols, nnz = (int(t) for t in line.split())
        data = np.loadtxt(f, ndmin=2) if nnz else np.zeros((0, 3))

    r = data[:, 0].astype(np.int64) - 1  # 1-indexed on disk
    c = data[:, 1].astype(np.int64) - 1
    if field == "pattern" or data.shape[1] < 3 or value_map == "pattern":
        v = np.ones(len(r), np.uint64)
    elif value_map == "scale":
        v = np.abs(data[:, 2] * scale).round().astype(np.uint64)
        v[v == 0] = 1  # keep the sparsity pattern
    else:
        raise ValueError(f"unknown value_map {value_map!r}")

    if symmetry in ("symmetric", "skew-symmetric", "hermitian"):
        off = r != c  # mirror off-diagonal entries
        r, c, v = (np.concatenate([r, c[off]]),
                   np.concatenate([c, r[off]]),
                   np.concatenate([v, v[off]]))
    return rows, cols, r, c, v


def mtx_to_block_matrix(path: str, k: int, value_map: str = "pattern",
                        scale: float = 1000.0) -> BlockSparseMatrix:
    """Tile a .mtx file into a BlockSparseMatrix of k x k uint64 blocks."""
    rows, cols, r, c, v = read_mtx(path, value_map, scale)
    return elements_to_blocks(rows, cols, r, c, v, k)


def elements_to_blocks(rows: int, cols: int, r: np.ndarray, c: np.ndarray,
                       v: np.ndarray, k: int) -> BlockSparseMatrix:
    """Element COO -> block-sparse with dense k x k tiles (vectorized)."""
    if len(r) == 0:
        return BlockSparseMatrix(rows=rows, cols=cols, k=k)
    br, bc = r // k, c // k
    ir, ic = r - br * k, c - bc * k
    nbc = int(bc.max()) + 1 if len(bc) else 1
    block_key = br * nbc + bc
    order = np.argsort(block_key, kind="stable")
    block_key, br, bc = block_key[order], br[order], bc[order]
    ir, ic, v = ir[order], ic[order], v[order]
    uniq, inv = np.unique(block_key, return_inverse=True)
    nnzb = len(uniq)
    tiles = np.zeros((nnzb, k, k), np.uint64)
    tiles[inv, ir, ic] = v
    first = np.searchsorted(block_key, uniq)
    coords = np.stack([br[first], bc[first]], axis=1)
    return BlockSparseMatrix.from_blocks(rows, cols, k, coords, tiles,
                                         assume_sorted=False)


def convert_to_dir(mtx_paths: list[str], out_dir: str, k: int,
                   value_map: str = "pattern", scale: float = 1000.0) -> None:
    """Convert one or more .mtx files into a chain input directory."""
    from spgemm_tpu.utils import io_text

    mats = [mtx_to_block_matrix(p, k, value_map, scale) for p in mtx_paths]
    io_text.write_chain_dir(out_dir, mats, k)


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        description="Convert MatrixMarket files to a reference-format input directory")
    p.add_argument("mtx", nargs="+", help=".mtx or .mtx.gz files (chain order)")
    p.add_argument("out_dir")
    p.add_argument("--k", type=int, default=32)
    p.add_argument("--value-map", choices=["pattern", "scale"], default="pattern")
    p.add_argument("--scale", type=float, default=1000.0)
    args = p.parse_args(argv)
    convert_to_dir(args.mtx, args.out_dir, args.k, args.value_map, args.scale)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
