"""Host-side utilities: containers, text I/O, oracle semantics, timers, config."""
