"""Backend liveness probe + platform pinning (shared by bench.py and the CLI).

The failure mode observed on this environment's TPU tunnel is a HANG inside
backend init or the first device op -- not an exception -- so an in-process
try/except can never fail soft.  The probe runs a tiny matmul in a
SUBPROCESS with a hard timeout; the main process must not touch jax's
backends until a probe has passed (or it has pinned a known-good platform).
"""

from __future__ import annotations

import os
import subprocess
import sys


def probe_default_backend(timeout_s: float | None = None) -> str:
    """Probe outcome: 'ok' (real accelerator computed), 'cpu' (healthy but
    CPU-only -- deterministic, not worth retrying), 'timeout' (hung), or
    'error' (init crashed).  SPGEMM_TPU_PROBE_TIMEOUT overrides the default
    150 s."""
    if timeout_s is None:
        timeout_s = float(os.environ.get("SPGEMM_TPU_PROBE_TIMEOUT", "150"))
    code = ("import jax, jax.numpy as jnp; "
            "x = jnp.ones((64, 64), jnp.bfloat16); "
            "(x @ x).block_until_ready(); "
            "print(jax.devices()[0].platform)")
    try:
        rc = subprocess.run([sys.executable, "-c", code],
                            capture_output=True, text=True, timeout=timeout_s)
        if rc.returncode != 0:
            return "error"
        plat = rc.stdout.strip().splitlines()[-1] if rc.stdout.strip() else ""
        return "cpu" if plat in ("", "cpu") else "ok"
    except subprocess.TimeoutExpired:
        return "timeout"


def pin(platform: str) -> None:
    """Pin the JAX platform in-process.  The env var alone is ineffective
    here: the TPU plugin's sitecustomize imports jax at interpreter start
    and snapshots JAX_PLATFORMS, so the config must be updated before any
    backend initializes."""
    import jax

    os.environ["JAX_PLATFORMS"] = platform
    from jax._src import xla_bridge
    if not xla_bridge._backends:
        jax.config.update("jax_platforms", platform)
