#!/usr/bin/env python
"""Benchmark suite: the five BASELINE.json configs, synthesized.

SuiteSparse downloads are impossible here (zero egress), so each named
matrix is replaced by a generator matching its structural statistics
(BASELINE.md notes this caveat); the configs, parallel strategy, and
correctness metrics (nnz parity + exact value equality vs the oracle,
BASELINE.json "nnz/Frobenius parity") are the north star's:

  1. random-1pct : random block-sparse 32x32-block 1024-block matrix pair,
                   ~1% block density, through FILE I/O on the CPU backend --
                   the reference text-format round-trip config.
  2. cage12      : uniform ~8 blocks/row (cage12's near-uniform ~16 nnz/row
                   profile), single-chip Pallas kernel.
  3. nd24k       : banded, bandwidth 16 (nd24k's dense-block high fill-in
                   profile), single-chip, the fill-in stress test.
  4. webbase-1M  : power-law row degrees (webbase's web-graph skew),
                   row-partitioned over a 4-device mesh (rowshard,
                   bit-exact output sharding) -- runs on a virtual CPU mesh
                   when only one real chip is visible.
  5. ffn         : block-sparse Transformer FFN forward, d=4096, 90% block
                   sparsity, bf16 on the MXU (models/ffn.py).

Each config prints one JSON line; --write-table also refreshes
benchmarks/RESULTS.md.  Run: python benchmarks/run.py [--config NAME]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _digest_barrier(x):
    import jax.numpy as jnp
    _ = int(jnp.asarray(x).ravel()[0])


def _spgemm_config(name, a, b, backend, parity=True):
    """Time one device-resident SpGEMM; optionally verify vs the oracle."""
    import jax
    from spgemm_tpu.ops.device import DeviceBlockMatrix
    from spgemm_tpu.ops.spgemm import spgemm_device
    from spgemm_tpu.ops.symbolic import symbolic_join

    da, db = DeviceBlockMatrix.from_host(a), DeviceBlockMatrix.from_host(b)
    da.block_until_ready()
    db.block_until_ready()
    join = symbolic_join(a.coords, b.coords)
    flops = 2.0 * int(join.pair_ptr[-1]) * a.k ** 3

    spgemm_device(da, db, backend=backend).block_until_ready()  # warm
    t0 = time.perf_counter()
    c = spgemm_device(da, db, backend=backend)
    c.block_until_ready()
    wall = time.perf_counter() - t0

    result = {
        "config": name, "backend": backend,
        "platform": jax.devices()[0].platform,
        "nnzb_a": a.nnzb, "nnzb_b": b.nnzb, "out_keys": join.num_keys,
        "tile_pairs": int(join.pair_ptr[-1]),
        "wall_s": round(wall, 4),
        "effective_gflops": round(flops / wall / 1e9, 2),
    }
    if parity:
        from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
        from spgemm_tpu.utils.semantics import spgemm_oracle
        want = BlockSparseMatrix.from_dict(
            a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))
        got = c.to_host()
        result["nnz_parity"] = bool(got.nnz == want.nnz)
        result["value_parity"] = bool(got == want)
    return result


def config_random_1pct():
    """Reference-format file I/O round-trip on the CPU backend."""
    from spgemm_tpu.utils import io_text
    from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
    from spgemm_tpu.utils.gen import random_block_sparse
    from spgemm_tpu.utils.semantics import chain_oracle

    rng = np.random.default_rng(0)
    k = 32
    mats = [random_block_sparse(32, 32, k, 0.01 * 32, rng, "full")
            for _ in range(2)]
    with tempfile.TemporaryDirectory() as td:
        io_text.write_chain_dir(os.path.join(td, "in"), mats, k)
        t0 = time.perf_counter()
        out = os.path.join(td, "matrix")
        rc = subprocess.run(
            [sys.executable, "-m", "spgemm_tpu.cli", os.path.join(td, "in"),
             "--device", "cpu", "--output", out],
            cwd=REPO, capture_output=True, text=True,
            env={**os.environ, "PYTHONPATH": REPO + ":" + os.environ.get("PYTHONPATH", "")})
        wall = time.perf_counter() - t0
        assert rc.returncode == 0, rc.stderr[-2000:]
        got = io_text.read_matrix(out, k)
    want = BlockSparseMatrix.from_dict(
        mats[0].rows, mats[-1].cols, k,
        chain_oracle([m.to_dict() for m in mats], k)).prune_zeros()
    return {"config": "random-1pct", "backend": "cli+file-io", "platform": "cpu",
            "nnzb_a": mats[0].nnzb, "nnzb_b": mats[1].nnzb,
            "wall_s": round(wall, 4), "end_to_end": True,
            "nnz_parity": bool(got.nnz == want.nnz),
            "value_parity": bool(got == want)}


def config_cage12(backend=None):
    from spgemm_tpu.ops.spgemm import resolve_backend
    from spgemm_tpu.utils.gen import random_block_sparse

    rng = np.random.default_rng(1)
    # cage12 profile: near-uniform row degree; 512 block-rows x ~8 blocks/row
    a = random_block_sparse(512, 512, 32, 8 / 512, rng, "full")
    b = random_block_sparse(512, 512, 32, 8 / 512, rng, "full")
    return _spgemm_config("cage12", a, b, resolve_backend(backend), parity=False)


def config_nd24k(backend=None):
    from spgemm_tpu.ops.spgemm import resolve_backend
    from spgemm_tpu.utils.gen import banded_block_sparse

    rng = np.random.default_rng(2)
    a = banded_block_sparse(720, 32, 16, rng, "full")
    b = banded_block_sparse(720, 32, 16, rng, "full")
    return _spgemm_config("nd24k", a, b, resolve_backend(backend), parity=False)


def config_webbase(n_dev=4):
    """Row-partitioned over a mesh; re-execs onto a virtual CPU mesh when
    fewer than n_dev real chips are visible (the BASELINE config asks for 4)."""
    import jax

    if len(jax.devices()) < n_dev:
        env = {**os.environ,
               "PYTHONPATH": REPO + ":" + os.environ.get("PYTHONPATH", "")}
        rc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--config", "webbase-1M",
             "--device", "cpu", "--virtual-devices", str(n_dev)],
            capture_output=True, text=True, env=env, cwd=REPO)
        assert rc.returncode == 0, rc.stderr[-2000:]
        return json.loads(rc.stdout.strip().splitlines()[-1])

    from spgemm_tpu.parallel.rowshard import spgemm_sharded
    from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
    from spgemm_tpu.utils.gen import powerlaw_block_sparse
    from spgemm_tpu.utils.semantics import spgemm_oracle
    from spgemm_tpu.ops.symbolic import symbolic_join

    rng = np.random.default_rng(3)
    a = powerlaw_block_sparse(256, 32, 3.0, rng, "full")
    b = powerlaw_block_sparse(256, 32, 3.0, rng, "full")
    join = symbolic_join(a.coords, b.coords)
    flops = 2.0 * int(join.pair_ptr[-1]) * a.k ** 3

    spgemm_sharded(a, b)  # warm/compile
    t0 = time.perf_counter()
    got = spgemm_sharded(a, b)
    wall = time.perf_counter() - t0
    want = BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))
    return {"config": "webbase-1M", "backend": f"rowshard x{n_dev}",
            "platform": jax.devices()[0].platform,
            "nnzb_a": a.nnzb, "nnzb_b": b.nnzb, "out_keys": join.num_keys,
            "tile_pairs": int(join.pair_ptr[-1]), "wall_s": round(wall, 4),
            "effective_gflops": round(flops / wall / 1e9, 2),
            "nnz_parity": bool(got.nnz == want.nnz),
            "value_parity": bool(got == want)}


def config_ffn():
    import jax
    import jax.numpy as jnp
    from spgemm_tpu.models.ffn import (
        BlockSparseFFNConfig, ffn_forward, init_params)

    cfg = BlockSparseFFNConfig(d_model=4096, d_ff=16384, k=128,
                               block_density=0.1)
    params = init_params(cfg, jax.random.key(0))
    x = jnp.ones((8, 128, cfg.d_model), jnp.bfloat16)
    fwd = jax.jit(lambda p, x: ffn_forward(p, x, cfg))
    _digest_barrier(fwd(params, x))
    t0 = time.perf_counter()
    _digest_barrier(fwd(params, x))
    wall = time.perf_counter() - t0
    # dense-equivalent flops * density = sparse flops actually done
    tokens = x.shape[0] * x.shape[1]
    sparse_flops = 2 * 2 * tokens * cfg.d_model * cfg.d_ff * cfg.block_density
    return {"config": "ffn-d4096-90pct-sparse", "backend": "bf16-mxu",
            "platform": jax.devices()[0].platform,
            "tokens": tokens, "wall_s": round(wall, 4),
            "sparse_tflops": round(sparse_flops / wall / 1e12, 2)}


CONFIGS = {
    "random-1pct": config_random_1pct,
    "cage12": config_cage12,
    "nd24k": config_nd24k,
    "webbase-1M": config_webbase,
    "ffn": config_ffn,
}


def write_table(rows):
    path = os.path.join(REPO, "benchmarks", "RESULTS.md")
    lines = ["# Benchmark suite results (BASELINE.json configs, synthesized)",
             "",
             "Regenerate: `python benchmarks/run.py --write-table`", "",
             "| config | backend | platform | wall s | eff. GFLOP/s | parity |",
             "|---|---|---|---|---|---|"]
    for r in rows:
        par = ""
        if "value_parity" in r:
            par = "bit-exact" if r["value_parity"] else "MISMATCH"
        gf = r.get("effective_gflops", r.get("sparse_tflops"))
        if "sparse_tflops" in r:
            gf = f"{r['sparse_tflops']} TF/s"
        lines.append(f"| {r['config']} | {r['backend']} | {r['platform']} | "
                     f"{r['wall_s']} | {gf or ''} | {par} |")
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


def _pin_platform(platform: str | None, n_virtual: int = 0) -> None:
    """Pin the JAX platform in-process.  The env var alone is not enough:
    this environment's TPU plugin sitecustomize imports jax at interpreter
    start and snapshots JAX_PLATFORMS, so the config must be updated before
    any backend initializes (same dance as cli.py / tests/conftest.py)."""
    if not platform:
        return
    os.environ["JAX_PLATFORMS"] = platform
    if n_virtual:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_virtual}").strip()
    import jax
    from jax._src import xla_bridge
    if not xla_bridge._backends:
        jax.config.update("jax_platforms", platform)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--config", choices=list(CONFIGS), default=None)
    p.add_argument("--device", default=None, help="force a JAX platform")
    p.add_argument("--virtual-devices", type=int, default=0)
    p.add_argument("--write-table", action="store_true")
    args = p.parse_args()

    _pin_platform(args.device, args.virtual_devices)
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.path.expanduser("~/.cache/jax_bench"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

    names = [args.config] if args.config else list(CONFIGS)
    rows = []
    for name in names:
        row = CONFIGS[name]()
        rows.append(row)
        print(json.dumps(row), flush=True)
    if args.write_table:
        print("wrote", write_table(rows))
    return 0


if __name__ == "__main__":
    sys.exit(main())
