#!/usr/bin/env python
"""Benchmark suite: the five BASELINE.json configs, synthesized.

SuiteSparse downloads are impossible here (zero egress), so each named
matrix is replaced by a generator matching its structural statistics
(BASELINE.md notes this caveat); the configs, parallel strategy, and
correctness metrics (nnz parity + exact value equality vs the oracle,
BASELINE.json "nnz/Frobenius parity") are the north star's:

  1. random-1pct : random block-sparse 32x32-block 1024-block matrix pair,
                   ~1% block density, through FILE I/O on the CPU backend --
                   the reference text-format round-trip config.
  2. cage12      : uniform ~8 blocks/row (cage12's near-uniform ~16 nnz/row
                   profile), single-chip Pallas kernel.
  3. nd24k       : banded, bandwidth 16 (nd24k's dense-block high fill-in
                   profile), single-chip, the fill-in stress test.
  4. webbase-1M  : power-law row degrees (webbase's web-graph skew),
                   row-partitioned over a 4-device mesh (rowshard,
                   bit-exact output sharding) -- runs on a virtual CPU mesh
                   when only one real chip is visible.
  5. ffn         : block-sparse Transformer FFN forward, d=4096, 90% block
                   sparsity, bf16 on the MXU (models/ffn.py).

Plus five rows beyond the five BASELINE configs:

  6. cage12-mxu / 7. nd24k-mxu : the same structures with 16-bit-bounded
                   values through backend='mxu' (ops/pallas_mxu.py on TPU) --
                   field mode is provably bit-exact vs the reference fold at
                   these bounds, so sampled parity still checks 2.9 semantics.
  8. webbase-ring : the power-law structure through the ring strategy
                   (O(1/n) operand memory), bounded values, full parity.
  9. webbase-1Mrow : the webbase structure at its honest 1,000,000-element-
                   row scale, single chip, sampled parity (TPU-gated; run
                   best-effort and isolated by tpu_evidence.sh -- a hang at
                   this never-before-measured scale must not cost the core
                   capture, so the core suite passes --skip webbase-1Mrow
                   and the table merges the row from the evidence dir).
  10. loader-scaling : file-loader thread scaling, the reference report's
                   OpenMP Table 3 analog.

Each config prints one JSON line; --write-table also refreshes
benchmarks/RESULTS.md.  Run: python benchmarks/run.py [--config NAME]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spgemm_tpu.utils import knobs  # noqa: E402 -- jax-free registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _evidence_dir() -> str:
    return (knobs.get("SPGEMM_TPU_EVIDENCE_DIR")
            or os.path.join(REPO, "benchmarks", "evidence"))


def _digest_barrier(x):
    import jax.numpy as jnp
    _ = int(jnp.asarray(x).ravel()[0])


def _spgemm_config(name, a, b, backend, parity=True, sampled_parity=0):
    """Time one device-resident SpGEMM; verify vs the oracle.

    parity=True: full value parity (oracle computes every tile -- feasible
    for small configs).  sampled_parity=N: the oracle evaluates N randomly
    sampled output tiles only (python-int semantics, utils/semantics), which
    scales to the big configs (cage12/nd24k -- BASELINE.json names exactly
    these two for correctness, round-2 VERDICT #4).
    """
    import jax
    from spgemm_tpu.ops.device import DeviceBlockMatrix
    from spgemm_tpu.ops.spgemm import spgemm_device
    from spgemm_tpu.ops.symbolic import symbolic_join
    from spgemm_tpu.utils.timers import ENGINE

    da, db = DeviceBlockMatrix.from_host(a), DeviceBlockMatrix.from_host(b)
    da.block_until_ready()
    db.block_until_ready()
    join = symbolic_join(a.coords, b.coords)
    flops = 2.0 * int(join.pair_ptr[-1]) * a.k ** 3

    pre = ENGINE.counter_snapshot()  # the warm run IS the cold first contact
    spgemm_device(da, db, backend=backend).block_until_ready()  # warm
    # the warm run's counter deltas record how the COLD plan routed
    # (estimated fast-return vs inline exact join vs an earlier config's
    # cache) -- the per-row audit trail for the estimator A/B
    warm = ENGINE.counter_snapshot()
    d_est = warm.get("est_hits", 0) - pre.get("est_hits", 0)
    d_fall = warm.get("est_fallbacks", 0) - pre.get("est_fallbacks", 0)
    d_miss = (warm.get("plan_cache_misses", 0)
              - pre.get("plan_cache_misses", 0))
    d_hit = (warm.get("plan_cache_hits", 0)
             - pre.get("plan_cache_hits", 0))
    # 'cache-hit' only when a hit actually landed -- with the cache
    # disabled (or estimation skipped) no counter moves, and that is a
    # plain cold exact plan, not a hit
    cold_route = ("estimated" if d_est
                  else "exact" if d_fall or d_miss
                  else "cache-hit" if d_hit else "exact")
    # the timed run repeats the warm run's structure, so with the plan
    # cache on it IS the serving-path cache-hit row: phases_s.plan near
    # zero, plan_cache_hits > 0 (the counters make that auditable per row)
    ENGINE.reset()
    t0 = time.perf_counter()
    c = spgemm_device(da, db, backend=backend)
    c.block_until_ready()
    wall = time.perf_counter() - t0
    counters = ENGINE.counter_snapshot()

    result = {
        "config": name, "backend": backend,
        "platform": jax.devices()[0].platform,
        "nnzb_a": a.nnzb, "nnzb_b": b.nnzb, "out_keys": join.num_keys,
        "tile_pairs": int(join.pair_ptr[-1]),
        "wall_s": round(wall, 4),
        "effective_gflops": round(flops / wall / 1e9, 2),
        "phases_s": ENGINE.snapshot(),
        "plan_cache_hits": counters.get("plan_cache_hits", 0),
        "plan_cache_misses": counters.get("plan_cache_misses", 0),
        "cold_plan_route": cold_route,
    }
    if parity:
        from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
        from spgemm_tpu.utils.semantics import spgemm_oracle
        want = BlockSparseMatrix.from_dict(
            a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))
        got = c.to_host()
        result["nnz_parity"] = bool(got.nnz == want.nnz)
        result["value_parity"] = bool(got == want)
    elif sampled_parity:
        got = c.to_host()
        ok, n_checked = _sampled_value_parity(a, b, got, sampled_parity)
        result["value_parity_sampled"] = bool(ok)
        result["parity_tiles_checked"] = n_checked
        # at-scale FULL parity: the native uint64 wrap-then-mod fold
        # recomputes every output key (native/parityfold.cpp) -- the
        # python-int oracle stays as the sampled, structure-independent
        # cross-check; this one covers all keys
        from spgemm_tpu.utils import native

        full = native.parity_fold_check(a.tiles, b.tiles, join.pair_ptr,
                                        join.pair_a, join.pair_b, got.tiles)
        if full is not None:
            n_bad, first_bad = full
            result["value_parity_all_keys"] = bool(n_bad == 0)
            result["parity_keys_checked"] = join.num_keys
            if n_bad:
                result["parity_bad_keys"] = n_bad
                result["parity_first_bad"] = first_bad
    return result


def _sampled_value_parity(a, b, got, n_tiles, seed=1234):
    """Exact oracle on randomly sampled output ROWS, fully independent of
    the engine: structure AND pair lists are re-derived here from the raw
    operand coordinates (sorted-coords binary search), never from the
    engine's symbolic join -- a join bug shows up as a structure or value
    mismatch instead of being folded into the expectation.  Values fold with
    the reference's wrap-then-mod semantics in j-ascending order
    (SURVEY.md section 2.9).  Checks whole rows (the engine keeps all-zero
    output tiles, so row structure must match exactly) until n_tiles tiles
    have been verified.
    """
    from spgemm_tpu.utils.semantics import tile_mac_oracle

    rng = np.random.default_rng(seed)
    a_rows = a.coords[:, 0]  # sorted (lex order invariant)
    b_rows = b.coords[:, 0]
    got_rows = got.coords[:, 0]
    rows = np.unique(a_rows)
    picks = rng.permutation(rows)
    checked = 0
    for r in picks:
        if checked >= n_tiles:
            break
        # A blocks of row r, ascending j (lex-sorted coords)
        a_s, a_e = np.searchsorted(a_rows, [r, r + 1])
        # expected pair lists per output col c, j-ascending (A traversal order)
        expect: dict = {}
        for ai in range(a_s, a_e):
            j = a.coords[ai, 1]
            b_s, b_e = np.searchsorted(b_rows, [j, j + 1])
            for bi in range(b_s, b_e):
                expect.setdefault(int(b.coords[bi, 1]), []).append((ai, bi))
        # structural row parity: the engine keeps zero tiles, so got's row-r
        # columns must equal the expected structure exactly
        g_s, g_e = np.searchsorted(got_rows, [r, r + 1])
        got_cols = got.coords[g_s:g_e, 1].tolist()
        if sorted(expect.keys()) != got_cols:
            return False, checked
        for gi, c_col in zip(range(g_s, g_e), got_cols):
            pairs = expect[c_col]
            want = tile_mac_oracle(a.tiles[[p[0] for p in pairs]],
                                   b.tiles[[p[1] for p in pairs]])
            if not np.array_equal(got.tiles[gi], want):
                return False, checked
            checked += 1
            if checked >= n_tiles:
                break
    return True, checked


def config_random_1pct():
    """Reference-format file I/O round-trip on the CPU backend."""
    from spgemm_tpu.utils import io_text
    from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
    from spgemm_tpu.utils.gen import random_block_sparse
    from spgemm_tpu.utils.semantics import chain_oracle

    rng = np.random.default_rng(0)
    k = 32
    mats = [random_block_sparse(32, 32, k, 0.01 * 32, rng, "full")
            for _ in range(2)]
    with tempfile.TemporaryDirectory() as td:
        io_text.write_chain_dir(os.path.join(td, "in"), mats, k)
        t0 = time.perf_counter()
        out = os.path.join(td, "matrix")
        rc = subprocess.run(
            [sys.executable, "-m", "spgemm_tpu.cli", os.path.join(td, "in"),
             "--device", "cpu", "--output", out],
            cwd=REPO, capture_output=True, text=True,
            env={**os.environ, "PYTHONPATH": REPO + ":" + os.environ.get("PYTHONPATH", "")})
        wall = time.perf_counter() - t0
        assert rc.returncode == 0, rc.stderr[-2000:]
        got = io_text.read_matrix(out, k)
    want = BlockSparseMatrix.from_dict(
        mats[0].rows, mats[-1].cols, k,
        chain_oracle([m.to_dict() for m in mats], k)).prune_zeros()
    return {"config": "random-1pct", "backend": "cli+file-io", "platform": "cpu",
            "nnzb_a": mats[0].nnzb, "nnzb_b": mats[1].nnzb,
            "wall_s": round(wall, 4), "end_to_end": True,
            "nnz_parity": bool(got.nnz == want.nnz),
            "value_parity": bool(got == want)}


def _cage12_mats(dist="full"):
    from spgemm_tpu.utils.gen import random_block_sparse

    rng = np.random.default_rng(1)
    # cage12 profile: near-uniform row degree; 512 block-rows x ~8 blocks/row
    a = random_block_sparse(512, 512, 32, 8 / 512, rng, dist)
    b = random_block_sparse(512, 512, 32, 8 / 512, rng, dist)
    return a, b


def _nd24k_mats(dist="full"):
    from spgemm_tpu.utils.gen import banded_block_sparse

    rng = np.random.default_rng(2)
    a = banded_block_sparse(720, 32, 16, rng, dist)
    b = banded_block_sparse(720, 32, 16, rng, dist)
    return a, b


def config_cage12(backend=None):
    from spgemm_tpu.ops.spgemm import resolve_backend

    a, b = _cage12_mats()
    return _spgemm_config("cage12", a, b, resolve_backend(backend),
                          parity=False, sampled_parity=64)


def config_nd24k(backend=None):
    from spgemm_tpu.ops.spgemm import resolve_backend

    a, b = _nd24k_mats()
    return _spgemm_config("nd24k", a, b, resolve_backend(backend),
                          parity=False, sampled_parity=64)


def config_cage12_mxu():
    """cage12 with 32-bit-bounded values through the MXU limb kernel --
    field mode == reference mode at these bounds (safe_exact_bound), so
    sampled parity still checks the reference fold."""
    a, b = _cage12_mats("small")
    return _spgemm_config("cage12-mxu", a, b, "mxu",
                          parity=False, sampled_parity=64)


def config_nd24k_mxu():
    a, b = _nd24k_mats("small")
    return _spgemm_config("nd24k-mxu", a, b, "mxu",
                          parity=False, sampled_parity=64)


def config_k64():
    """k = 64 tiles, full-range values -- a scale the reference physically
    cannot run: its CUDA launch assigns one thread per tile element
    (block(k,k)), so the 1024-thread block limit caps it at k = 32
    (SURVEY.md section 3.3).  The u64 engine is shape-polymorphic in k
    (G auto-clamps to 512/k lanes); exact wrap-then-mod parity is
    sampled-verified like the other big configs."""
    from spgemm_tpu.ops.spgemm import resolve_backend
    from spgemm_tpu.utils.gen import random_block_sparse

    rng = np.random.default_rng(64)
    a = random_block_sparse(128, 128, 64, 6 / 128, rng, "full")
    b = random_block_sparse(128, 128, 64, 6 / 128, rng, "full")
    return _spgemm_config("k64-beyond-ref", a, b, resolve_backend(None),
                          parity=False, sampled_parity=32)


def _webbase_config(config_name, dist, strategy, backend_label, n_dev=4):
    """Shared scaffold for the power-law (webbase-like) mesh configs:
    re-exec onto a virtual CPU mesh when fewer than n_dev chips are visible,
    generate the matrix pair, run the strategy, check full value parity.

    strategy(a, b, devices) -> result BlockSparseMatrix.
    """
    import jax

    if len(jax.devices()) < n_dev:
        env = {**os.environ,
               "PYTHONPATH": REPO + ":" + os.environ.get("PYTHONPATH", "")}
        rc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--config", config_name,
             "--device", "cpu", "--virtual-devices", str(n_dev)],
            capture_output=True, text=True, env=env, cwd=REPO)
        assert rc.returncode == 0, rc.stderr[-2000:]
        return json.loads(rc.stdout.strip().splitlines()[-1])

    from spgemm_tpu.ops.symbolic import symbolic_join
    from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
    from spgemm_tpu.utils.gen import powerlaw_block_sparse
    from spgemm_tpu.utils.semantics import spgemm_oracle

    rng = np.random.default_rng(3)
    a = powerlaw_block_sparse(256, 32, 3.0, rng, dist)
    b = powerlaw_block_sparse(256, 32, 3.0, rng, dist)
    join = symbolic_join(a.coords, b.coords)
    flops = 2.0 * int(join.pair_ptr[-1]) * a.k ** 3
    devices = jax.devices()[:n_dev]

    from spgemm_tpu.utils.timers import ENGINE

    strategy(a, b, devices)  # warm/compile
    ENGINE.reset()
    t0 = time.perf_counter()
    got = strategy(a, b, devices)
    wall = time.perf_counter() - t0
    phases = ENGINE.snapshot()  # ring_plan/ring_hop/ring_fold for the ring row
    want = BlockSparseMatrix.from_dict(
        a.rows, b.cols, a.k, spgemm_oracle(a.to_dict(), b.to_dict(), a.k))
    return {"config": config_name, "backend": f"{backend_label} x{n_dev}",
            "platform": jax.devices()[0].platform,
            "nnzb_a": a.nnzb, "nnzb_b": b.nnzb, "out_keys": join.num_keys,
            "tile_pairs": int(join.pair_ptr[-1]), "wall_s": round(wall, 4),
            "effective_gflops": round(flops / wall / 1e9, 2),
            **({"phases_s": phases} if phases else {}),
            "nnz_parity": bool(got.nnz == want.nnz),
            "value_parity": bool(got == want)}


def config_webbase(n_dev=4):
    """Row-partitioned over a mesh (bit-exact output sharding, full-range
    values); the BASELINE config asks for 4 chips."""
    def rowshard(a, b, devices):
        from spgemm_tpu.parallel.rowshard import spgemm_sharded
        return spgemm_sharded(a, b)

    return _webbase_config("webbase-1M", "full", rowshard, "rowshard", n_dev)


def config_webbase_ring(n_dev=4):
    """The webbase structure through the ring strategy (B rotates around the
    mesh, O(1/n) operand memory).  Ring arithmetic is field mode, which is
    reference-bit-exact for bounded values (safe_exact_bound) -- so this
    config uses the 'small' distribution and still checks full value parity."""
    def ring(a, b, devices):
        import jax

        from spgemm_tpu.parallel.ring import spgemm_ring
        mesh = jax.make_mesh((len(devices),), ("ring",), devices=devices)
        return spgemm_ring(a, b, mesh=mesh)

    return _webbase_config("webbase-ring", "small", ring, "ring", n_dev)


def config_webbase_1mrow():
    """The webbase structure at its HONEST scale: 1,000,000 element rows
    (31250 block-rows x k=32, ~119k tiles, ~30 GFLOP of join work),
    single-chip device-resident pipeline, full-range values, sampled exact
    parity.  TPU-gated in the suite (the CPU backend's exact-kernel rate
    makes it a multi-minute row, too slow for the fail-gated core run);
    SPGEMM_TPU_FORCE_1MROW=1 runs it anyway -- the honest-scale execution
    evidence matters even when only the CPU backend is reachable."""
    import jax

    if (jax.devices()[0].platform != "tpu"
            and not knobs.get("SPGEMM_TPU_FORCE_1MROW")):
        return {"config": "webbase-1Mrow", "skipped":
                "needs TPU (1M-row scale impractical at CPU kernel rates)"}
    from spgemm_tpu.ops.spgemm import resolve_backend
    from spgemm_tpu.utils.gen import powerlaw_block_sparse

    rng = np.random.default_rng(3)
    a = powerlaw_block_sparse(31250, 32, 3.0, rng, "full")
    b = powerlaw_block_sparse(31250, 32, 3.0, rng, "full")
    return _spgemm_config("webbase-1Mrow", a, b, resolve_backend(None),
                          parity=False, sampled_parity=64)


def config_ffn():
    import jax
    import jax.numpy as jnp
    from spgemm_tpu.models.ffn import (
        BlockSparseFFNConfig, ffn_forward, init_params)

    cfg = BlockSparseFFNConfig(d_model=4096, d_ff=16384, k=128,
                               block_density=0.1)
    params = init_params(cfg, jax.random.key(0))
    x = jnp.ones((8, 128, cfg.d_model), jnp.bfloat16)
    fwd = jax.jit(lambda p, x: ffn_forward(p, x, cfg))
    _digest_barrier(fwd(params, x))
    t0 = time.perf_counter()
    _digest_barrier(fwd(params, x))
    wall = time.perf_counter() - t0
    # dense-equivalent flops * density = sparse flops actually done
    tokens = x.shape[0] * x.shape[1]
    sparse_flops = 2 * 2 * tokens * cfg.d_model * cfg.d_ff * cfg.block_density
    return {"config": "ffn-d4096-90pct-sparse", "backend": "bf16-mxu",
            "platform": jax.devices()[0].platform,
            "tokens": tokens, "wall_s": round(wall, 4),
            "sparse_tflops": round(sparse_flops / wall / 1e12, 2)}


def config_loader_scaling():
    """Loader thread scaling -- the analog of the reference's OpenMP Table 3
    (report.pdf p.3: 1.8x/2.9x/4.1x/4.3x at 4/8/16/32 threads for its
    omp-task file loads).  Times read_chain over a generated on-disk chain
    at 1/4/16 threads; the native GIL-released tokenizer is what makes
    thread scaling real."""
    from spgemm_tpu.utils import io_text
    from spgemm_tpu.utils.gen import random_chain

    rng = np.random.default_rng(4)
    k = 32
    # ~20k tiles over 16 files: big enough that parse time dominates the
    # pool overhead (the reference's Table 3 ran at its 100k-tile scale)
    mats = random_chain(16, 64, k, 0.3, rng, "full")
    with tempfile.TemporaryDirectory() as td:
        folder = os.path.join(td, "in")
        io_text.write_chain_dir(folder, mats, k)
        # warmup: native-library ctypes load, page cache, pool code paths --
        # must not land inside the first timed point
        io_text.read_chain(folder, 0, len(mats) - 1, k, max_workers=2)
        times = {}
        for threads in (1, 4, 16):
            t0 = time.perf_counter()
            got = io_text.read_chain(folder, 0, len(mats) - 1, k,
                                     max_workers=threads)
            times[threads] = time.perf_counter() - t0
            assert len(got) == len(mats)
    best = min(times.values())
    return {"config": "loader-scaling", "backend": "native+threads",
            "platform": "host", "files": len(mats),
            "host_cores": os.cpu_count(),
            "wall_s": round(best, 4),
            "wall_s_by_threads": {str(t): round(s, 4) for t, s in times.items()},
            "speedup_best_vs_1": round(times[1] / best, 2)}


def config_pool_scaling():
    """Device-pool serving throughput (benchmarks/pool_bench.py): a mixed
    batch (small chains + one large structure) through a 1-slice vs
    N-slice spgemmd on the 8-vdev CPU config, every result bit-exact vs
    the oracle in both legs.  The row carries the pool leg's makespan and
    jobs/minute plus the speedup over the single-executor daemon -- the
    RESULTS.md view of pool scaling alongside single-job wall.  Runs in
    subprocesses (pool_bench spawns one cold child per leg), so the
    suite process's own jax state never warms either side."""
    child = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "pool_bench.py"),
         "--small", "3", "--chain", "3", "--small-dim", "6",
         "--large-dim", "12", "--k", "8"],
        capture_output=True, text=True, timeout=1800)
    last = next((ln for ln in reversed(child.stdout.strip().splitlines())
                 if ln.startswith("{")), None)
    if child.returncode != 0 or last is None:
        raise RuntimeError(f"pool_bench failed (rc {child.returncode}): "
                           f"{child.stderr[-500:]}")
    row = json.loads(last)
    if "error" in row:
        raise RuntimeError(f"pool_bench error: {row['error']}")
    det = row["detail"]
    return {"config": "pool-scaling", "backend": "spgemmd-pool",
            "platform": "cpu",
            "wall_s": det["makespan_pool_s"],
            "jobs": det["jobs"],
            "jobs_per_min": det["jobs_per_min_pool"],
            "jobs_per_min_1slice": det["jobs_per_min_1slice"],
            "speedup_vs_1slice": det["speedup_vs_1slice"],
            "slices": det["slices"],
            "core_limited": det["core_limited"],
            "host_cores": det["cores"],
            "value_parity": det["parity"]}


def config_serve_batching():
    """Cross-job batched dispatch throughput (benchmarks/pool_bench.py
    --queue-depth-sweep): same-structure submits at queue depths 1/4/16
    through a single-slice spgemmd, the batched leg (admission window
    armed, the executor fuses the queue into mega-launches along the
    round axis) against the window=0 A/B leg, every output bit-exact vs
    the oracle in both legs.  The row carries the deepest depth's
    batched jobs/minute plus the speedup over the unbatched daemon --
    the RESULTS.md view of cross-job batching next to pool scaling."""
    child = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "pool_bench.py"),
         "--queue-depth-sweep", "--depths", "1,4,16",
         "--chain", "3", "--small-dim", "6", "--k", "8"],
        capture_output=True, text=True, timeout=1800)
    last = next((ln for ln in reversed(child.stdout.strip().splitlines())
                 if ln.startswith("{")), None)
    if child.returncode != 0 or last is None:
        raise RuntimeError(f"pool_bench sweep failed (rc {child.returncode}):"
                           f" {child.stderr[-500:]}")
    row = json.loads(last)
    if "error" in row:
        raise RuntimeError(f"pool_bench sweep error: {row['error']}")
    det = row["detail"]
    deepest = det["depths"][max(det["depths"], key=int)]
    return {"config": "serve-batching", "backend": "spgemmd-batch",
            "platform": "cpu",
            "wall_s": deepest["batched"]["makespan_s"],
            "jobs": det["serve_batched_jobs"],
            "jobs_per_min": det["jobs_per_min_batched"],
            "jobs_per_min_window0": det["jobs_per_min_window0"],
            "speedup_vs_window0": det["speedup_deepest"],
            "serve_batches": det["serve_batches"],
            "batch_window_s": det["batch_window_s"],
            "value_parity": det["parity"]}


def config_fleet_scaling():
    """Federation-router serving throughput (benchmarks/pool_bench.py
    --fleet): a batch of distinct small chains submitted through one
    spgemm-router fronting 1 vs 2 spgemmd subprocess backends, each on
    its own TCP front-end (spgemm_tpu/fleet), every result bit-exact vs
    the oracle in both legs and zero failovers on the healthy run.  The
    row carries the fleet leg's makespan and jobs/minute plus the
    speedup over the single-backend daemon -- the RESULTS.md view of
    horizontal (multi-daemon) scaling next to the in-daemon pool row."""
    child = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "pool_bench.py"),
         "--fleet", "--small", "4", "--chain", "3", "--small-dim", "6",
         "--k", "8"],
        capture_output=True, text=True, timeout=1800)
    last = next((ln for ln in reversed(child.stdout.strip().splitlines())
                 if ln.startswith("{")), None)
    if child.returncode != 0 or last is None:
        raise RuntimeError(f"pool_bench --fleet failed "
                           f"(rc {child.returncode}): {child.stderr[-500:]}")
    row = json.loads(last)
    if "error" in row:
        raise RuntimeError(f"pool_bench --fleet error: {row['error']}")
    det = row["detail"]
    return {"config": "fleet-scaling", "backend": "spgemm-router",
            "platform": "cpu",
            "wall_s": det["makespan_fleet_s"],
            "jobs": det["jobs"],
            "jobs_per_min": det["jobs_per_min_fleet"],
            "jobs_per_min_1backend": det["jobs_per_min_1backend"],
            "speedup_vs_1backend": det["speedup_vs_1backend"],
            "fleet_backends": det["backends_used"],
            "fleet_failovers": det["failovers"],
            "core_limited": det["core_limited"],
            "host_cores": det["cores"],
            "value_parity": det["parity"]}


def config_accum_route():
    """Dense vs ladder accumulator-route A/B (SPGEMM_TPU_ACCUM_ROUTE):
    a hub-skew structure whose single deep fanout class pays the ladder's
    worst-case padded-MAC tax (fanout one past a pow2 boundary), multiplied
    once per forced route leg in-process -- plan cache cleared between legs
    (the knob is jit-static, each leg compiles its own executable).  Both
    legs must be byte-identical to each other and to the oracle; the row
    feeds the RESULTS.md padded-MAC column with both legs' ratios and the
    dense leg's wall speedup."""
    import jax
    from spgemm_tpu.ops import plancache
    from spgemm_tpu.ops.spgemm import plan as build_plan
    from spgemm_tpu.ops.spgemm import resolve_backend, spgemm
    from spgemm_tpu.utils.blockcsr import BlockSparseMatrix
    from spgemm_tpu.utils.semantics import spgemm_oracle

    rng = np.random.default_rng(17)
    k, K, f = 16, 5, 513  # fanout 513 -> class 768: ~1.5x pair padding
    a_coords = np.array([(i, i * f + j) for i in range(K)
                         for j in range(f)], np.int64)
    b_coords = np.array([(m, 0) for m in range(K * f)], np.int64)
    a = BlockSparseMatrix(
        rows=K, cols=K * f, k=k, coords=a_coords,
        tiles=rng.integers(0, 1 << 64, size=(len(a_coords), k, k),
                           dtype=np.uint64))
    b = BlockSparseMatrix(
        rows=K * f, cols=1, k=k, coords=b_coords,
        tiles=rng.integers(0, 1 << 64, size=(len(b_coords), k, k),
                           dtype=np.uint64))
    want = BlockSparseMatrix.from_dict(
        a.rows, b.cols, k, spgemm_oracle(a.to_dict(), b.to_dict(), k))
    backend = resolve_backend(None)
    platform = jax.devices()[0].platform
    legs = {}
    # restore target read through the registry (KNB): the default is
    # "auto", so re-exporting the resolved value is equivalent to unset
    prev = knobs.get("SPGEMM_TPU_ACCUM_ROUTE")
    try:
        for route in ("ladder", "dense"):
            os.environ["SPGEMM_TPU_ACCUM_ROUTE"] = route
            plancache.clear()
            plan = build_plan(a, b, backend=backend, platform=platform)
            spgemm(a, b, backend=backend)  # warm/compile
            t0 = time.perf_counter()
            got = spgemm(a, b, backend=backend)
            legs[route] = {"wall": time.perf_counter() - t0,
                           "ratio": plan.padded_mac_ratio(), "got": got}
    finally:
        os.environ["SPGEMM_TPU_ACCUM_ROUTE"] = prev
        plancache.clear()  # forced-route plans must not leak to later configs
    lad, den = legs["ladder"], legs["dense"]
    parity = bool(lad["got"] == want and den["got"] == want
                  and np.array_equal(lad["got"].tiles, den["got"].tiles))
    return {"config": "accum-route", "backend": backend,
            "platform": platform,
            "nnzb_a": a.nnzb, "nnzb_b": b.nnzb,
            "wall_s": round(den["wall"], 4),
            "wall_s_ladder": round(lad["wall"], 4),
            "padded_mac_ratio": round(lad["ratio"], 3),
            "padded_mac_ratio_dense": round(den["ratio"], 3),
            "speedup_vs_ladder": round(lad["wall"] / den["wall"], 2),
            "value_parity": parity}


def config_autotune():
    """Telemetry-driven autotune A/B (benchmarks/autotune_bench.py): the
    mixed structure suite through the real tuner state machine -- the
    deep-fanout class must promote a forced-dense override past the
    canary margin while the banded control settles untuned, every leg
    bit-exact.  Runs in a subprocess (the bench pins its own backend and
    mutates the process-global tuned overlay), --check armed so a
    regression in the tuner's promotion or parity fails the row."""
    child = subprocess.run(
        [sys.executable,
         os.path.join(REPO, "benchmarks", "autotune_bench.py"), "--check"],
        capture_output=True, text=True, timeout=1800)
    last = next((ln for ln in reversed(child.stdout.strip().splitlines())
                 if ln.startswith("{")), None)
    if child.returncode != 0 or last is None:
        raise RuntimeError(f"autotune_bench failed (rc {child.returncode}): "
                           f"{child.stderr[-500:]}")
    row = json.loads(last)
    det = row["detail"]
    deep = det["classes"]["deep-fanout"]
    return {"config": "autotune", "backend": "tuner",
            "platform": det["device"],
            "wall_s": deep.get("tuned_s"),
            "wall_s_cold": deep["cold_s"],
            "speedup_tuned": row["value"],
            "trial_legs": det["trial_legs"],
            "trial_wall_s": det["trial_wall_s"],
            "winning_classes": det["winning_classes"],
            "tuned_knobs": deep["knobs"],
            "value_parity": det["parity"]}


CONFIGS = {
    "random-1pct": config_random_1pct,
    "cage12": config_cage12,
    "nd24k": config_nd24k,
    "cage12-mxu": config_cage12_mxu,
    "nd24k-mxu": config_nd24k_mxu,
    "k64-beyond-ref": config_k64,
    "webbase-1M": config_webbase,
    "webbase-ring": config_webbase_ring,
    "webbase-1Mrow": config_webbase_1mrow,
    "ffn": config_ffn,
    "loader-scaling": config_loader_scaling,
    "pool-scaling": config_pool_scaling,
    "serve-batching": config_serve_batching,
    "fleet-scaling": config_fleet_scaling,
    "accum-route": config_accum_route,
    "autotune": config_autotune,
}


def _extra_rows():
    """Best-effort rows captured separately by tpu_evidence.sh (extras.jsonl
    in the evidence dir, one suite-schema JSON row per line).  Isolating
    unproven big-scale configs there means their hang/failure can never
    cost the fail-gated core capture; the table still shows their rows."""
    ev_dir = _evidence_dir()
    path = os.path.join(ev_dir, "extras.jsonl")
    by_config: dict = {}
    if os.path.exists(path):
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if ln.startswith("{"):
                    try:
                        row = json.loads(ln)
                    except json.JSONDecodeError:
                        continue
                    # appended file, newest capture last: last row per
                    # config wins, so a re-capture supersedes stale rows
                    by_config[row.get("config")] = row
    return list(by_config.values())


def write_table(rows, path=None):
    # merge best-effort evidence rows: a real captured row replaces the
    # core run's --skip placeholder for the same config
    rows = list(rows)
    for extra in _extra_rows():
        for i, r in enumerate(rows):
            if r.get("config") == extra.get("config"):
                # replace PLACEHOLDERS only: a freshly measured (or error)
                # row must never be overwritten by stale evidence
                if "skipped" in r:
                    rows[i] = extra
                break
        else:
            rows.append(extra)
    if path is None:
        path = os.path.join(REPO, "benchmarks", "RESULTS.md")
    # ring-vs-rowshard ratio column: the overlap layer's standing regression
    # guard (round 7) -- ring is the only operand-exceeds-HBM multi-chip
    # path, so its distance from the rowshard strategy on the same webbase
    # structure must stay visible in RESULTS.md (target <= ~2.0x).  Same
    # metric as ROUND5's standing 2.9x: ring rides bounded 'small' values
    # (b32 field MAC) vs rowshard's full-width exact fold, so this tracks
    # the end-to-end strategy gap, not equal-arithmetic kernel overhead.
    # Only rows from the same capture (same platform) are comparable -- an
    # extras-merged TPU row must not divide by a CPU-host core-suite row.
    rowshard_row = next((r for r in rows
                         if r.get("config") == "webbase-1M"), None)
    lines = ["# Benchmark suite results (BASELINE.json configs, synthesized)",
             "",
             "Regenerate: `python benchmarks/run.py --write-table`",
             "",
             "Wall-clock rows are from whatever host ran the capture (each "
             "row's `platform` names the backend, not the host speed): "
             "compare across regenerations only on the same host -- the "
             "round's `benchmarks/ROUND*_NOTES.md` records the capture "
             "context.",
             "",
             "| config | backend | platform | wall s | eff. GFLOP/s | plan s (wait) | jobs/min | padded-MAC | vs rowshard | parity |",
             "|---|---|---|---|---|---|---|---|---|---|"]
    for r in rows:
        if "error" in r:
            err = r["error"][:60].replace("|", "\\|")
            lines.append(f"| {r['config']} | — | — | — | — | — | — | — | — | ERROR: {err} |")
            continue
        if "skipped" in r:
            note = r["skipped"][:60].replace("|", "\\|")
            lines.append(f"| {r['config']} | — | — | — | — | — | — | — | — | skipped: {note} |")
            continue
        par = ""
        if "value_parity" in r:
            par = "bit-exact" if r["value_parity"] else "MISMATCH"
        elif "value_parity_all_keys" in r:
            # native full fold (parityfold.cpp): every output key recomputed
            nk = r.get("parity_keys_checked", 0)
            par = (f"bit-exact (all {nk} keys)"
                   if (r["value_parity_all_keys"]
                       and r.get("value_parity_sampled", True))
                   else "MISMATCH")
        elif "value_parity_sampled" in r:
            n = r.get("parity_tiles_checked", 0)
            par = (f"bit-exact ({n} tiles sampled)"
                   if r["value_parity_sampled"] else "MISMATCH")
        gf = r.get("effective_gflops", r.get("sparse_tflops"))
        if "sparse_tflops" in r:
            gf = f"{r['sparse_tflops']} TF/s"
        ratio = ""
        if (r.get("config") == "webbase-ring" and rowshard_row
                and rowshard_row.get("wall_s") and r.get("wall_s")
                and r.get("platform") == rowshard_row.get("platform")):
            ratio = (f"{r['wall_s'] / rowshard_row['wall_s']:.2f}x "
                     "(target <=2.0x)")
        # planner observability column: the timed run's host planning cost
        # and how long dispatch blocked on it -- a cache-hit row (repeated
        # structure) shows plan near zero with hits > 0
        plan_col = ""
        ph = r.get("phases_s") or {}
        if "plan" in ph:
            plan_col = f"{ph['plan']:.4g} ({ph.get('plan_wait', 0.0):.4g})"
            if r.get("plan_cache_hits"):
                plan_col += f", {r['plan_cache_hits']} cache hit(s)"
        # pool-scaling throughput column (benchmarks/pool_bench.py): batch
        # jobs/minute through the sliced daemon + the speedup over the
        # single-executor A/B -- pool scaling next to single-job wall
        jobs_col = ""
        if r.get("jobs_per_min") is not None:
            jobs_col = f"{r['jobs_per_min']:g}"
            if r.get("speedup_vs_1slice") is not None:
                jobs_col += f" ({r['speedup_vs_1slice']:g}x vs 1-slice"
                if r.get("core_limited"):
                    jobs_col += f", {r.get('host_cores')}-core host"
                jobs_col += ")"
            # serve-batching row (pool_bench --queue-depth-sweep): fused
            # mega-launch throughput vs the window=0 unbatched A/B
            if r.get("speedup_vs_window0") is not None:
                jobs_col += (f" ({r['speedup_vs_window0']:g}x vs "
                             "window=0)")
            # fleet-scaling row (pool_bench --fleet): router-fronted
            # multi-daemon throughput vs the single-backend A/B
            if r.get("speedup_vs_1backend") is not None:
                jobs_col += (f" ({r['speedup_vs_1backend']:g}x vs "
                             "1-backend")
                if r.get("core_limited"):
                    jobs_col += f", {r.get('host_cores')}-core host"
                jobs_col += ")"
        # padded-MAC column (accum-route A/B + any row that reports the
        # ratio): shipped/real MAC tax under ladder, the dense route's
        # residual stream-tail ratio, and the dense leg's wall speedup
        mac_col = ""
        if r.get("padded_mac_ratio") is not None:
            mac_col = f"{r['padded_mac_ratio']:g}x"
            if r.get("padded_mac_ratio_dense") is not None:
                mac_col += f" → {r['padded_mac_ratio_dense']:g}x dense"
            if r.get("speedup_vs_ladder") is not None:
                mac_col += f" ({r['speedup_vs_ladder']:g}x faster)"
        lines.append(f"| {r['config']} | {r['backend']} | {r['platform']} | "
                     f"{r['wall_s']} | {gf or ''} | {plan_col} | {jobs_col} "
                     f"| {mac_col} | {ratio} | {par} |")
    sweep = _sweep_section()
    if not sweep:
        # no sweep capture on disk (the evidence dir's sweep.txt is
        # transient): PRESERVE the previous table's kernel-variants section
        # instead of silently dropping hard-won on-chip evidence -- a
        # CPU-host suite regeneration must never destroy the TPU sweep
        sweep = _existing_sweep_section(path)
    if sweep:
        lines += [""] + sweep
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path


def _existing_sweep_section(path):
    """The '## Kernel variants' section of the table being overwritten, if
    any (kept verbatim when the current capture has no sweep of its own)."""
    try:
        with open(path) as f:
            text = f.read()
    except OSError:
        return []
    marker = "## Kernel variants"
    if marker not in text:
        return []
    section = text[text.index(marker):].rstrip("\n")
    return section.split("\n")


def _sweep_section():
    """Kernel-variant table from the newest kernel_sweep evidence, if any
    (written by tpu_evidence.sh, which runs the sweep BEFORE the suite so
    this table is from the same capture; SPGEMM_TPU_EVIDENCE_DIR overrides
    the directory for custom-outdir runs)."""
    ev_dir = _evidence_dir()
    rows = []
    # sweep_k64.txt: the best-effort beyond-reference tile-size sweep --
    # same row schema (each row carries its k), one shared table
    for name in ("sweep.txt", "sweep_k64.txt"):
        path = os.path.join(ev_dir, name)
        if not os.path.exists(path):
            continue
        with open(path) as f:
            for ln in f:
                ln = ln.strip()
                if ln.startswith("{"):
                    try:
                        rows.append(json.loads(ln))
                    except json.JSONDecodeError:
                        pass
    if not rows:
        return []
    lines = ["## Kernel variants (benchmarks/kernel_sweep.py)",
             "",
             "| variant | k | K | P | G | platform | wall ms | eff. GFLOP/s |",
             "|---|---|---|---|---|---|---|---|"]
    for r in rows:
        if "error" in r:
            err = r["error"][:50].replace("|", "\\|")
            lines.append(f"| {r['variant']} | {r.get('k', '')} | {r['K']} | "
                         f"{r['P']} | {r.get('G', '')} | {r['platform']} | "
                         f"ERROR | {err} |")
        else:
            lines.append(f"| {r['variant']} | {r.get('k', '')} | {r['K']} | "
                         f"{r['P']} | {r.get('G', '')} | {r['platform']} | "
                         f"{r['wall_ms']} | {r['effective_gflops']} |")
    return lines


def _pin_platform(platform: str | None, n_virtual: int = 0) -> None:
    """Pin the JAX platform in-process.  The env var alone is not enough:
    this environment's TPU plugin sitecustomize imports jax at interpreter
    start and snapshots JAX_PLATFORMS, so the config must be updated before
    any backend initializes (same dance as cli.py / tests/conftest.py)."""
    if not platform:
        return
    os.environ["JAX_PLATFORMS"] = platform
    if n_virtual:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n_virtual}").strip()
    import jax
    from jax._src import xla_bridge
    if not xla_bridge._backends:
        jax.config.update("jax_platforms", platform)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--config", choices=list(CONFIGS), default=None)
    p.add_argument("--skip", action="append", default=[],
                   choices=list(CONFIGS), metavar="NAME",
                   help="mark a config skipped instead of running it "
                        "(repeatable; used by tpu_evidence.sh to isolate "
                        "best-effort configs from the fail-gated core run)")
    p.add_argument("--device", default=None, help="force a JAX platform")
    p.add_argument("--virtual-devices", type=int, default=0)
    p.add_argument("--write-table", action="store_true")
    args = p.parse_args()

    # the suite's timed run deliberately REPEATS the warm run's structure
    # to measure the serving cache-hit path; delta memoization (ops/delta)
    # would answer it from the retained result (wall ~0), so the knob
    # defaults OFF for suite rows unless the operator exported it
    # explicitly (process-scoped, no restore needed)
    knobs.pin_unless_exported("SPGEMM_TPU_DELTA", "0")
    _pin_platform(args.device, args.virtual_devices)
    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.path.expanduser("~/.cache/jax_bench"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.3)

    names = [args.config] if args.config else list(CONFIGS)
    rows = []
    for name in names:
        try:
            if name in args.skip:
                row = {"config": name, "skipped": "via --skip (run separately)"}
            else:
                row = CONFIGS[name]()
        except Exception as e:  # noqa: BLE001 -- keep sweeping, record the row
            import traceback
            traceback.print_exc()
            row = {"config": name, "error": repr(e)[:300]}
        rows.append(row)
        print(json.dumps(row), flush=True)
    if args.write_table:
        print("wrote", write_table(rows))
    # error rows are recorded AND surfaced in the exit code, so automation
    # checking only rc still detects a broken config
    return 1 if any("error" in r for r in rows) else 0


if __name__ == "__main__":
    sys.exit(main())
